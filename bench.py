"""Headline benchmark: Criteo-shaped sparse logistic regression throughput.

Mirrors the north star in BASELINE.json ("Criteo-1TB logistic-reg wall-clock
vs 256-exec Spark") at single-run scale: a Criteo-like batch (39 nonzeros per
row, hashed feature space) trained with the distributed jitted L-BFGS path —
the exact hot loop SURVEY.md §4.2 identifies (the reference pays one cluster
treeAggregate round-trip per optimizer iteration; here an iteration is an
on-device fused pass + psum).

Metric: example-passes/second = rows x optimizer-iterations / wall-clock of
the jitted fit (compile time excluded; one warm-up fit on identical shapes
precedes the timed run). ``vs_baseline`` is the ratio against the honest
comparator in ``BENCH_BASELINE.json`` (the r03-v1 hardware lower bound;
BENCH_r02.json's 17.77M is a documented measurement artifact — see
docs/PERF.md and ``_baseline``); the comparator's label is embedded in the
unit string. BASELINE.json has ``"published": {}`` (no repo-published
reference numbers — see BASELINE.md). With no comparator the ratio is 1.0.

Also reported (stderr + unit string): a model-FLOPs throughput and an
effective-HBM-bandwidth estimate. The workload is memory-bound, so the
bandwidth fraction is the honest utilization number; the FLOP model is
4*nnz per pass (margin gather-multiply-add + transposed contraction).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _environment() -> dict:
    """Common environment block recorded in EVERY BENCH_*.json (and the
    headline JSON line): PR 6's serving floors turned out to be core-bound
    and only the serving bench recorded cpu_cores, which made the numbers
    hard to interpret after the fact. One shared helper so no mode can
    drift. Call only after the mode has pinned/initialized its jax
    platform — the block records what the measurement actually ran on."""
    import jax

    from photon_ml_tpu import analysis

    devs = jax.devices()
    # the last measured tracing-off instrumentation overhead (bench.py
    # trace -> BENCH_trace.json): every bench record carries it so a
    # number can be read knowing what the ambient span plumbing cost
    trace_pct = None
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_trace.json")) as f:
            trace_pct = json.load(f).get("trace_off_overhead_pct_max")
    except Exception:
        pass
    # whether the active runtime supports surviving-subset continuation
    # after a rank loss (parallel/recovery.py): True single-process and on
    # the sim transport, False on transports without in-job reform
    try:
        from photon_ml_tpu.parallel.recovery import recovery_supported

        rec_sup = bool(recovery_supported())
    except Exception:
        rec_sup = None
    # whether the serving stack carries the degraded-scoring ladder
    # (ScoreContext + brownout controller): True once serve/brownout.py
    # and the ctx-aware session are importable, None on older trees
    try:
        from photon_ml_tpu.serve import BrownoutController, ScoreContext

        deg_sup = bool(BrownoutController and ScoreContext)
    except Exception:
        deg_sup = None
    return {
        "cpu_cores": os.cpu_count() or 1,
        "recovery_supported": rec_sup,
        "degraded_serving_supported": deg_sup,
        "jax_version": jax.__version__,
        "platform": devs[0].platform,
        "device_kind": getattr(devs[0], "device_kind", ""),
        "device_count": len(devs),
        "python_version": sys.version.split()[0],
        "trace_overhead_pct": trace_pct,
        # lint posture the numbers were measured under: photon-check
        # version + unsuppressed finding count (0 on a clean tree)
        "photon_check": analysis.repo_report(
            os.path.dirname(os.path.abspath(__file__))),
    }


def _arm_watchdog() -> None:
    """The TPU tunnel in this environment can wedge indefinitely (even
    ``jax.devices()`` then blocks). Rather than hang the driver's bench run,
    emit an honest zero-valued record and exit when nothing completes within
    BENCH_TIMEOUT_S (default 20 min — far above a normal compile+run)."""
    import threading

    timeout = float(os.environ.get("BENCH_TIMEOUT_S", 1200))

    def fire():
        print(json.dumps({
            "metric": "criteo_shaped_logreg_lbfgs_example_passes_per_sec",
            "value": 0.0,
            "unit": f"TIMEOUT after {timeout:.0f}s (device unreachable or "
                    "run wedged) — no measurement",
            "vs_baseline": 0.0,
        }), flush=True)
        os._exit(2)

    t = threading.Timer(timeout, fire)
    t.daemon = True
    t.start()


def _tpu_reachable(probe_timeout_s: float = 90.0) -> bool:
    """Probe the TPU tunnel in a SUBPROCESS with a hard timeout: when the
    tunnel is wedged even ``jax.devices()`` blocks forever, and a wedged
    main process can only emit the watchdog's useless 0.0 record. A dead
    probe lets the bench fall back to a clearly-labeled CPU measurement
    instead. The probe asserts a non-CPU device actually initialized — a
    fast-failing axon backend silently falling back to CPU must not pass."""
    import subprocess
    try:
        rc = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp\n"
             "assert jax.devices()[0].platform != 'cpu', 'cpu only'\n"
             "x = jnp.ones((64, 64)); float((x @ x)[0, 0])"],
            timeout=probe_timeout_s, capture_output=True,
        ).returncode
    except subprocess.TimeoutExpired:
        return False
    return rc == 0


def main() -> None:
    _arm_watchdog()
    fallback = ""
    # Probe-and-fall-back unless the caller pinned CPU (CI smoke) or set
    # BENCH_REQUIRE_TPU=1 (fail-fast hardware runs that must never emit a
    # CPU number). Round 1 and round 3 both recorded value-0 TIMEOUTs
    # because this environment sets JAX_PLATFORMS=axon ambiently and the
    # old "honor an explicit JAX_PLATFORMS" rule skipped the probe — the
    # main process then wedged inside the axon plugin's retry loop with no
    # way to reach the CPU path.
    pinned = os.environ.get("JAX_PLATFORMS", "")
    require_tpu = os.environ.get("BENCH_REQUIRE_TPU") == "1"
    if pinned != "cpu" and not require_tpu and not _tpu_reachable():
        os.environ["JAX_PLATFORMS"] = "cpu"
        # built from the SAME comparator record the ratio uses
        # (BENCH_BASELINE.json via _baseline) so the banner can never
        # drift from a re-banked baseline
        base = _baseline()
        banked = (f"last banked TPU measurement: {base[0]/1e6:.2f}M "
                  f"passes/s ({base[1]})" if base
                  else "no banked TPU comparator")
        fallback = ("; TPU-unreachable CPU FALLBACK, not comparable to TPU "
                    f"rounds — {banked}")
        print("TPU tunnel unreachable -> CPU fallback measurement",
              file=sys.stderr)
    import jax

    # The axon sitecustomize force-sets jax_platforms=axon,cpu at interpreter
    # startup, overriding the JAX_PLATFORMS env var; honor the env var again
    # so CPU runs don't try to initialize the TPU tunnel.
    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    import jax.numpy as jnp

    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel.data_parallel import build_csc, fit_distributed
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.types import LabeledBatch, SparseFeatures

    platform = jax.devices()[0].platform
    if require_tpu and platform == "cpu":
        # the axon backend can fast-fail and silently leave CPU as the
        # first platform; a fail-fast hardware run must die loudly rather
        # than publish a CPU number against the TPU baseline
        print("BENCH_REQUIRE_TPU=1 but only CPU initialized — aborting",
              file=sys.stderr)
        sys.exit(3)
    # Criteo shape: 39 features/row. Sized to finish the timed fit in
    # seconds; CPU fallback keeps CI/driver runs fast.
    if platform == "cpu":
        n_rows, dim, iters = 1 << 15, 1 << 14, 10
    else:
        n_rows, dim, iters = 1 << 21, 1 << 18, 20
    k = 39

    # Synthesize the dataset ON DEVICE: the axon tunnel to the TPU wedges on
    # bulk host->device transfers, and a transfer would time the pipe, not
    # the hot loop. jit'd jax.random keeps everything in HBM.
    @jax.jit
    def make_data(key):
        k_idx, k_w, k_lab = jax.random.split(key, 3)
        indices = jax.random.randint(k_idx, (n_rows, k), 0, dim, jnp.int32)
        w_true = jax.random.normal(k_w, (dim,), jnp.float32) * 0.5
        logits = jnp.sum(w_true[indices], axis=1)
        labels = (jax.random.uniform(k_lab, (n_rows,))
                  < jax.nn.sigmoid(logits)).astype(jnp.float32)
        return indices, labels

    indices, labels = jax.block_until_ready(make_data(jax.random.key(0)))

    mesh = make_mesh()
    obj = make_objective("logistic")
    # Criteo rows are one-hot categorical: the implicit-ones layout
    # (values=None) skips the values array entirely — half the bytes per
    # sparse pass on the HBM-bound hot loop (types.SparseFeatures).
    batch = LabeledBatch(
        SparseFeatures(indices, None, dim=dim),
        labels,
        jnp.zeros((n_rows,), jnp.float32),
        jnp.ones((n_rows,), jnp.float32),
    )
    w0 = jnp.zeros((dim,), jnp.float32)

    # The column-sorted view is a once-per-DATASET artifact (like ingestion):
    # build it outside the timed fit and share it across calibration + the
    # headline run (VERDICT r2: the 82M-nnz sort was re-paid per fit and
    # poisoned the csc calibration).
    csc = None
    try:
        csc = jax.block_until_ready(build_csc(obj, batch, mesh))
    except Exception as e:
        print(f"csc precompute failed ({e}); csc modes will sort in-fit",
              file=sys.stderr)

    def run(sparse_grad, n_iters, salt=0):
        # tolerance=0 disables convergence tests -> the iteration count is
        # exact (optimize/common.py honors an explicit 0 since round 3).
        # ``salt`` perturbs w0 so a timed run is a genuinely different
        # computation from its warm-up: the r03 hardware session produced
        # 0.7ms "fits" over 82M nnz when warm-up and timed calls were
        # bit-identical — the axon remote backend appears to satisfy
        # repeated identical executions without re-running them, and
        # block_until_ready alone does not expose that.
        res = fit_distributed(
            obj, batch, mesh, w0 + jnp.float32(salt) * 1e-8, l2=1.0,
            optimizer="lbfgs",
            config=OptimizerConfig(max_iters=n_iters, tolerance=0.0),
            sparse_grad=sparse_grad,
            precomputed_csc=(csc if sparse_grad.startswith("csc") else None),
        )
        # sync by SCALAR FETCH, not block_until_ready: a device->host read
        # of the result cannot complete before the computation has actually
        # run, whatever the transfer/queue semantics of the backend.
        res = res._replace(iterations=int(res.iterations),
                           value=float(res.value))
        return res

    # Sparse-gradient strategy space (scatter-add vs scatter-free CSC prefix
    # sums vs the fused Pallas kernel — types.CSCTranspose); which wins is
    # hardware-dependent, so calibrate unless pinned via BENCH_SPARSE_GRAD.
    #
    # Every calibration fit runs at the FULL headline iteration count: a
    # different max_iters is a different compiled program, and through the
    # axon tunnel each remote compile costs minutes — the old 3-iter
    # calibration + separate accuracy fits + separate headline paid ~2x
    # the compiles for no extra information. Each mode's single timed,
    # salted, fetch-synced run serves as its timing, its accuracy evidence
    # (final w vs the scatter reference), and — for the winner — the
    # headline measurement itself.
    mode = os.environ.get("BENCH_SPARSE_GRAD", "auto")
    if mode == "auto":
        times, results = {}, {}
        # csc_precise is NOT a candidate: without jax_enable_x64 (never set
        # here; TPUs have no native f64) its f64 prefix silently degrades to
        # exactly the global-f32 scheme the blocked default replaces
        for i, m in enumerate(("scatter", "csc", "csc_segment", "csc_pallas")):
            try:
                run(m, iters, salt=1)  # compile + warm-up
                t0 = time.perf_counter()
                r = run(m, iters, salt=2 + i)
                times[m] = time.perf_counter() - t0
                results[m] = r
            except Exception as e:  # a mode that fails to lower is skipped
                print(f"calibration: {m} failed: {e}", file=sys.stderr)
        print(f"calibration ({iters} iters): {times}", file=sys.stderr)
        if not times:
            print("calibration: every mode failed — no measurement",
                  file=sys.stderr)
            sys.exit(4)
        # speed is not enough: cross-check each candidate's solution against
        # the scatter reference (an inaccurate fast mode must be visible).
        # The f32 cumsum-difference transpose loses ~sqrt(nnz)*eps ≈ 1e-3
        # relative at 82M nnz, so the fastest mode can legitimately fail the
        # gate — walk the modes fastest-first and take the first accurate
        # one instead of falling straight back to scatter.
        w_ref = (np.asarray(results["scatter"].w)
                 if "scatter" in results else None)
        mode = "scatter"
        for m in sorted(times, key=times.get):
            if m == "scatter" or w_ref is None:
                mode = m  # scatter is its own reference; or none available
                break
            w_got = np.asarray(results[m].w)
            dev_rel = float(np.linalg.norm(w_got - w_ref)
                            / max(np.linalg.norm(w_ref), 1e-30))
            print(f"calibration accuracy: |w_{m} - w_scatter| rel = "
                  f"{dev_rel:.2e}", file=sys.stderr)
            if dev_rel <= 1e-3:
                mode = m
                break
            print(f"calibration: {m} rejected (> 1e-3)", file=sys.stderr)
        print(f"calibration -> {mode}", file=sys.stderr)
        res, elapsed = results[mode], times[mode]
    else:
        run(mode, iters, salt=101)  # compile + warm-up
        t0 = time.perf_counter()
        res = run(mode, iters, salt=102)  # scalar-fetch-synced inside run()
        elapsed = time.perf_counter() - t0

    done = int(res.iterations)
    value = n_rows * max(done, 1) / elapsed

    # -- utilization model (documented, order-of-magnitude honest) ----------
    # FLOPs/pass: margin gather-add (nnz) + transposed contraction (nnz);
    # pointwise loss math is O(n) and ignored. Bytes/pass: int32 indices
    # (4B) read twice (forward gather + backward transpose view); the
    # implicit-ones layout has no values array and the d-vector traffic is
    # negligible at these shapes.
    nnz = n_rows * k
    passes = max(done, 1)
    flops = 2.0 * nnz * passes / elapsed
    bytes_touched = 8.0 * nnz * passes / elapsed
    # v5e single-chip peaks: ~197 TFLOP/s bf16 MXU, ~819 GB/s HBM. The
    # sparse hot loop is VPU/HBM work, so bandwidth fraction is the real
    # utilization; MFU vs MXU peak is reported for completeness.
    peak_flops = float(os.environ.get("BENCH_PEAK_FLOPS", 1.97e14))
    peak_bw = float(os.environ.get("BENCH_PEAK_BW", 8.19e11))
    mfu = flops / peak_flops
    bw_frac = bytes_touched / peak_bw
    # The r05 sweep showed the pass is bounded by the chip's random-gather
    # ISSUE RATE, not bandwidth (docs/PERF.md "Round-5 chip session"), so
    # also report cycles per gathered element: 2 gather passes over nnz
    # per optimizer iteration at the ~940 MHz v5e clock. ~1 cycle/elem is
    # the hardware floor; the GB/s figure is a derived artifact under an
    # issue-rate bound.
    clock = float(os.environ.get("BENCH_CLOCK_HZ", 9.4e8))
    cyc_per_gather = clock * elapsed / (2.0 * nnz * passes)
    util = (f"model {flops/1e9:.3g} GFLOP/s (mfu {mfu:.3g}), "
            f"~{bytes_touched/1e9:.3g} GB/s HBM ({bw_frac:.3g} of peak), "
            f"{cyc_per_gather:.2g} cycles/gathered-elem (issue-rate view)")
    print(f"utilization: {util}", file=sys.stderr)

    base = _baseline()
    # The pinned comparator is a TPU hardware number. A CPU record (fallback
    # OR an explicitly CPU-pinned CI run) divided by it is meaningless, and a
    # ratio > 1 in the PARSED field reads as a TPU win to any consumer that
    # never looks at the unit string (VERDICT r4 weak #2) — report 0.0 so no
    # parser can misbrand a fallback as a measurement.
    if platform == "cpu" and base:
        vs = 0.0
        base_note = (f"; vs_baseline=0.0: comparator {base[1]} is a TPU "
                     "number, CPU run not comparable")
    else:
        vs = round(value / base[0], 4) if base else 1.0
        base_note = f"; vs_baseline vs {base[1]}" if base else ""
    print(json.dumps({
        "metric": "criteo_shaped_logreg_lbfgs_example_passes_per_sec",
        "value": round(value, 1),
        "unit": f"example-passes/sec ({platform}, {len(jax.devices())} dev, "
                f"n={n_rows}, d={dim}, k={k}, iters={done}, "
                f"sparse_grad={mode}; {util}{base_note}{fallback})",
        "vs_baseline": vs,
        "environment": _environment(),
    }))


def serving_main() -> None:
    """``python bench.py serving`` — online-scoring capacity on CPU.

    Four legs over one synthetic GAME model (in-process service — no
    sockets, so the numbers are the scoring stack's, not the kernel's
    TCP stack; the socket path is covered by tests/test_serving_async):

    * ``closed_loop`` — the PR-2 methodology (sequential requests, batch
      sizes 1..max_batch) on BOTH the paged fused path and the host-LRU
      path, written as the baseline leg next to the open-loop results;
      the previously recorded BENCH_serving.json value is carried along
      so the speedup is against the PUBLISHED baseline, not a re-run.
    * ``open_loop`` — an offered-load sweep through the asyncio scoring
      path (Poisson-ish fixed-interval arrivals, many requests in
      flight): achieved rows/s, accepted-request p50/p99, the
      queue-wait vs device-compute split, and shed counts per rate. The
      highest achieved rate is the single-replica capacity.
    * ``multi_replica`` — the same sweep over N in-process replicas
      (own session + batcher each) behind least-loaded dispatch.
      Process-level replicas + the HTTP front door are exercised in
      tests; in this bench the replicas share the python runtime, so on
      a single-core container the aggregate is GIL-bound — cpu_count is
      recorded so the number reads honestly.
    * ``overload_soak`` — 2x the measured capacity against a small
      queue with deadline shedding: the contract is explicit 429s,
      ZERO scoring-path 5xx, and a flat compile-miss counter; a hot
      swap fires mid-soak and must not compile or error.

    ``BENCH_SERVING_SMOKE=1`` shrinks every leg for CI and enforces the
    acceptance floor (exit 7): open-loop >= BENCH_SERVING_FLOOR rows/s
    (default 15000), 0 steady-state compile misses, 0 scoring 5xx.
    Writes ``BENCH_serving.json`` and prints the same JSON."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import asyncio
    import shutil
    import tempfile

    import jax

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    import numpy as np

    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        make_game_dataset,
    )
    from photon_ml_tpu.io.avro import read_avro_file, write_avro_file
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.model_io import save_game_model
    from photon_ml_tpu.serve import (
        AsyncScoringServer,
        MicroBatcher,
        ScoringService,
        ScoringSession,
    )

    smoke = os.environ.get("BENCH_SERVING_SMOKE") == "1"
    here = os.path.dirname(os.path.abspath(__file__))
    prev_recorded = None
    try:
        with open(os.path.join(here, "BENCH_serving.json")) as f:
            prev = json.load(f)
        prev_recorded = float(prev.get("previous_recorded_rows_per_s")
                              or prev.get("value"))
    except Exception:
        pass

    rng = np.random.default_rng(0)
    n, d_fix, d_re, n_entities = 600, 32, 8, 64
    Xg = rng.normal(size=(n, d_fix))
    Xu = rng.normal(size=(n, d_re))
    uid = rng.integers(0, n_entities, n)
    y = (rng.random(n) < 0.5).astype(float)
    ds = make_game_dataset({"g": Xg, "u": Xu}, y,
                           entity_ids={"userId": uid})
    cd = CoordinateDescent(
        [CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                          reg_weight=1.0),
         CoordinateConfig("per-user", coordinate_type="random",
                          feature_shard="u", entity_column="userId",
                          reg_type="l2", reg_weight=1.0)],
        task="logistic")
    model, _ = cd.run(ds)
    # the whole run works out of one temp tree, removed on exit
    root = tempfile.mkdtemp(prefix="bench-serving-")
    model_dir = os.path.join(root, "model")
    save_game_model(model, model_dir, {
        "g": IndexMap({f"g{j}": j for j in range(d_fix)}),
        "u": IndexMap({f"u{j}": j for j in range(d_re)}),
    })
    # a perturbed sibling model for the mid-load hot swap
    delta_dir = os.path.join(root, "model-delta")
    shutil.copytree(model_dir, delta_dir)
    re_path = os.path.join(delta_dir, "random-effect", "per-user",
                           "coefficients.avro")
    records, schema = read_avro_file(re_path)
    for rec in records[: max(1, len(records) // 10)]:
        for coef in rec["means"]:
            coef["value"] *= 1.05
    write_avro_file(re_path, records, schema)

    max_batch = int(os.environ.get("BENCH_SERVING_MAX_BATCH", 64))

    def make_row(i):
        return {
            "features": (
                [{"name": f"g{j}", "value": float(Xg[i % n, j])}
                 for j in range(d_fix)]
                + [{"name": f"u{j}", "value": float(Xu[i % n, j])}
                   for j in range(d_re)]),
            "entityIds": {"userId": str(uid[i % n])},
        }

    def make_service(paged=True, max_queue=1024, max_delay_ms=0.5,
                     deadline_s=None):
        session = ScoringSession(model_dir, max_batch=max_batch,
                                 coeff_cache_entries=n_entities,
                                 paged_table=paged)
        batcher = MicroBatcher(
            session.score_rows, max_batch=max_batch,
            max_delay_ms=max_delay_ms, max_queue=max_queue,
            request_deadline_s=deadline_s, metrics=session.metrics)
        return ScoringService(session, batcher, request_timeout_s=30.0)

    # -- leg 1: closed loop (the PR-2 baseline methodology) ----------------
    def closed_loop(service, reps):
        out = []
        sizes = [b for b in (1, 8, 32, 64) if b <= max_batch]
        for batch_size in sizes:
            rows = [make_row(i) for i in range(batch_size)]
            for _ in range(5):
                service.handle_score({"rows": rows})
            lat = []
            t_all = time.perf_counter()
            for _ in range(reps):
                t0 = time.perf_counter()
                status, _body = service.handle_score({"rows": rows})
                lat.append((time.perf_counter() - t0) * 1e3)
                assert status == 200, f"bench request failed: {status}"
            wall = time.perf_counter() - t_all
            lat.sort()
            out.append({
                "batch_size": batch_size,
                "p50_ms": round(lat[len(lat) // 2], 3),
                "p99_ms": round(lat[min(len(lat) - 1,
                                        int(len(lat) * 0.99))], 3),
                "rows_per_s": round(batch_size * reps / wall, 1),
            })
        return out

    reps = int(os.environ.get("BENCH_SERVING_REPS", 20 if smoke else 100))
    svc_lru = make_service(paged=False)
    closed_lru = closed_loop(svc_lru, reps)
    svc_lru.close()
    svc = make_service(paged=True)
    closed_paged = closed_loop(svc, reps)

    # -- leg 2: open loop on the asyncio scoring path ----------------------
    req_rows = min(max_batch, 64)
    payloads = [{"rows": [make_row(i * req_rows + j)
                          for j in range(req_rows)]}
                for i in range(32)]

    def open_loop(services, rate_rows_s, duration_s):
        """Fixed-interval offered load against one or more in-process
        replicas (least-loaded pick), via the same score_async path the
        asyncio transport uses. Returns achieved/accepted stats."""
        servers = [AsyncScoringServer(s) for s in services]

        async def run():
            interval = req_rows / rate_rows_s
            results = {"ok": 0, "ok_rows": 0, "shed": 0, "errors": 0,
                       "lat": []}
            tasks = []

            async def fire(payload):
                pick = min(range(len(servers)),
                           key=lambda i:
                           services[i].batcher.queue_depth)
                t0 = time.perf_counter()
                status, _body = await servers[pick].score_async(payload)
                ms = (time.perf_counter() - t0) * 1e3
                if status == 200:
                    results["ok"] += 1
                    results["ok_rows"] += req_rows
                    results["lat"].append(ms)
                elif status == 429:
                    results["shed"] += 1
                else:
                    results["errors"] += 1

            loop = asyncio.get_running_loop()
            t_start = loop.time()
            t_next = t_start
            i = 0
            while loop.time() - t_start < duration_s:
                tasks.append(asyncio.ensure_future(
                    fire(payloads[i % len(payloads)])))
                i += 1
                t_next += interval
                delay = t_next - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
            await asyncio.gather(*tasks)
            results["wall_s"] = loop.time() - t_start
            return results

        r = asyncio.run(run())
        lat = sorted(r["lat"]) or [0.0]
        return {
            "offered_rows_per_s": rate_rows_s,
            "achieved_rows_per_s": round(r["ok_rows"] / r["wall_s"], 1),
            "accepted_p50_ms": round(lat[len(lat) // 2], 3),
            "accepted_p99_ms": round(lat[min(len(lat) - 1,
                                             int(len(lat) * 0.99))], 3),
            "requests_ok": r["ok"],
            "requests_shed": r["shed"],
            "requests_errored": r["errors"],
        }

    duration = float(os.environ.get(
        "BENCH_SERVING_DURATION_S", 1.0 if smoke else 3.0))
    rates = ([20_000, 60_000] if smoke else
             [10_000, 25_000, 50_000, 75_000, 100_000, 150_000])
    misses_before_steady = svc.metrics.snapshot()["compile_cache_misses"]
    sweep = []
    for rate in rates:
        snap0 = svc.metrics.snapshot()
        leg = open_loop([svc], rate, duration)
        snap1 = svc.metrics.snapshot()
        leg["queue_wait_p99_ms"] = snap1["queue_wait_p99_ms"]
        leg["compute_p50_ms"] = snap1["compute_p50_ms"]
        leg["batches"] = snap1["batches_total"] - snap0["batches_total"]
        sweep.append(leg)
        if leg["requests_shed"] > 0 and len(sweep) >= 2:
            break  # past saturation: further rates only add shed noise
    single_capacity = max(s["achieved_rows_per_s"] for s in sweep)
    # latency criterion reads at the highest SUSTAINED rate (no shed,
    # >= 90% of offered delivered): p99 at saturation with a deep queue
    # measures the queue, not the serving stack
    sustained = [s for s in sweep
                 if s["requests_shed"] == 0
                 and s["achieved_rows_per_s"]
                 >= 0.9 * s["offered_rows_per_s"]]
    at_capacity = max(sustained or sweep,
                      key=lambda s: s["achieved_rows_per_s"])

    # -- leg 3: hot swap mid-load (compile misses pinned flat) -------------
    swap_info = {}

    def swap_mid_load():
        async def run():
            server = AsyncScoringServer(svc)
            stop = {"flag": False}

            async def traffic():
                i = 0
                while not stop["flag"]:
                    await server.score_async(payloads[i % len(payloads)])
                    i += 1

            t = asyncio.ensure_future(traffic())
            await asyncio.sleep(0.2)
            loop = asyncio.get_running_loop()
            t0 = time.perf_counter()
            await loop.run_in_executor(
                None, lambda: svc.session.swap(delta_dir))
            swap_ms = (time.perf_counter() - t0) * 1e3
            await asyncio.sleep(0.2)
            stop["flag"] = True
            await t
            return swap_ms

        misses0 = svc.metrics.snapshot()["compile_cache_misses"]
        errors0 = svc.metrics.snapshot()["errors_total"]
        swap_ms = asyncio.run(run())
        svc.session.drain_installs(30.0)
        snap = svc.metrics.snapshot()
        swap_info.update({
            "swap_ms": round(swap_ms, 3),
            "compile_misses_during_swap":
                snap["compile_cache_misses"] - misses0,
            "errors_during_swap": snap["errors_total"] - errors0,
            "active_version_after": snap["active_version"],
        })

    swap_mid_load()
    misses_after_steady = svc.metrics.snapshot()["compile_cache_misses"]
    steady_misses = misses_after_steady - misses_before_steady
    final_snap = svc.metrics.snapshot()
    svc.close()

    # -- leg 4: multi-replica aggregate ------------------------------------
    n_replicas = int(os.environ.get(
        "BENCH_SERVING_REPLICAS", 2 if smoke else
        max(2, min(4, os.cpu_count() or 1))))
    replicas = [make_service(paged=True) for _ in range(n_replicas)]
    for r_svc in replicas:  # warm every replica's ladder + pages
        r_svc.handle_score(payloads[0])
    multi = []
    for rate in ([60_000] if smoke else [60_000, 100_000, 150_000]):
        multi.append(open_loop(replicas, rate, duration))
    multi_capacity = max(m["achieved_rows_per_s"] for m in multi)
    multi_errors = sum(m["requests_errored"] for m in multi)
    for r_svc in replicas:
        r_svc.close()

    # -- leg 5: 2x-overload soak with a small queue + deadline shed --------
    soak_svc = make_service(paged=True, max_queue=32, deadline_s=0.25)
    soak_svc.handle_score(payloads[0])
    soak = open_loop([soak_svc], max(2 * single_capacity, 20_000),
                     duration)
    soak_snap = soak_svc.metrics.snapshot()
    soak["shed_queue_full"] = soak_snap["shed_queue_full_total"]
    soak["shed_deadline"] = soak_snap["shed_deadline_total"]
    soak_svc.close()

    cpu_cores = os.cpu_count() or 1
    speedup = (round(single_capacity / prev_recorded, 2)
               if prev_recorded else None)
    record = {
        "environment": _environment(),
        "metric": "serving_open_loop_rows_per_sec_cpu",
        "value": multi_capacity,
        "unit": (f"rows/sec, {n_replicas}-replica in-process open loop "
                 f"({jax.devices()[0].platform}, {cpu_cores} cores, "
                 f"max_batch={max_batch}, req_rows={req_rows}, "
                 f"d_fix={d_fix}, d_re={d_re}, entities={n_entities}; "
                 "single-replica sweep + closed-loop baseline legs in "
                 "fields; on a 1-core container replicas share the GIL "
                 "and the aggregate ~= single-replica capacity)"),
        "single_replica_rows_per_s": single_capacity,
        "multi_replica_rows_per_s": multi_capacity,
        "replicas": n_replicas,
        "cpu_cores": cpu_cores,
        "previous_recorded_rows_per_s": prev_recorded,
        "speedup_vs_previous_record": speedup,
        "open_loop": sweep,
        "multi_replica": multi,
        "overload_soak": soak,
        "hot_swap_mid_load": swap_info,
        "closed_loop_baseline": {"paged": closed_paged,
                                 "host_lru": closed_lru},
        "steady_state_compile_misses": steady_misses,
        "compile_cache": {
            "misses": final_snap["compile_cache_misses"],
            "hits": final_snap["compile_cache_hits"],
        },
        "paged": {
            "installs": final_snap["paged_installs"],
            "faults": final_snap["paged_faults"],
            "page_evictions": final_snap["paged_page_evictions"],
        },
    }
    floor = float(os.environ.get("BENCH_SERVING_FLOOR", 15_000))
    ok = (single_capacity >= floor
          and steady_misses == 0
          and swap_info.get("compile_misses_during_swap") == 0
          and swap_info.get("errors_during_swap") == 0
          and soak["requests_errored"] == 0 and multi_errors == 0
          and (soak["requests_shed"] > 0
               or soak["shed_deadline"] > 0))
    record["acceptance_ok"] = ok
    record["acceptance_criteria"] = {
        "floor_rows_per_s": floor,
        "p99_at_capacity_below_prev_p50_15_6ms":
            at_capacity["accepted_p99_ms"] < 15.6,
        "overload_sheds_with_zero_5xx":
            soak["requests_errored"] == 0
            and (soak["requests_shed"] > 0 or soak["shed_deadline"] > 0),
    }
    with open(os.path.join(here, "BENCH_serving.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    shutil.rmtree(root, ignore_errors=True)
    if smoke and not ok:
        print("serving bench acceptance FAILED (open-loop floor, flat "
              "compile misses incl. mid-load swap, shed-not-5xx "
              "overload)", file=sys.stderr)
        sys.exit(7)


def degrade_main() -> None:
    """``python bench.py degrade`` — brownout posture under a slow store.

    Two legs over one synthetic GAME model:

    * ``storm_sweep`` — an offered-load sweep (the serving bench's
      open-loop methodology) against ONE in-process replica whose
      coefficient store is fault-injected with ``kind="delay"`` latency
      on every cold load. The service carries a default deadline and a
      brownout controller, so the ladder — not an error path — absorbs
      the slow store: the leg records availability (non-5xx fraction),
      the degraded fraction per ladder level (parsed from response
      bodies, cross-checked against ``degraded_total`` metrics), p50/p99,
      and the stage-labelled deadline-drop counters. A faults-off
      control phase runs first and must show ZERO degraded responses.
    * ``hedging`` — two real-socket replicas behind the HTTP front
      door (round-robin, so the slow replica cannot hide behind
      least-loaded dispatch). After a both-fast warm phase seeds the
      per-backend latency histograms, one replica's score path is made
      slow; p99 is measured with hedging ON (duplicate fired at the
      primary's observed p99, first response wins) and then OFF. The
      contract under one slow replica: hedged p99 <= 2x the healthy
      baseline p99 (factor via BENCH_DEGRADE_HEDGE_FACTOR).

    ``BENCH_DEGRADE_SMOKE=1`` shrinks both legs for CI and enforces the
    acceptance gate (exit 11): 100% availability under the storm with a
    nonzero degraded fraction, zero degraded responses with faults off,
    and the hedging bound. Writes ``BENCH_degrade.json``."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import asyncio
    import shutil
    import tempfile

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    import numpy as np

    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        make_game_dataset,
    )
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.model_io import save_game_model
    from photon_ml_tpu.parallel import fault_injection
    from photon_ml_tpu.parallel.fault_injection import Fault
    from photon_ml_tpu.serve import (
        AsyncFrontDoor,
        AsyncScoringServer,
        BrownoutController,
        MicroBatcher,
        ScoringService,
        ScoringSession,
    )

    smoke = os.environ.get("BENCH_DEGRADE_SMOKE") == "1"
    here = os.path.dirname(os.path.abspath(__file__))

    rng = np.random.default_rng(0)
    n, d_fix, d_re, n_entities = 400, 16, 8, 64
    Xg = rng.normal(size=(n, d_fix))
    Xu = rng.normal(size=(n, d_re))
    uid = rng.integers(0, n_entities, n)
    y = (rng.random(n) < 0.5).astype(float)
    ds = make_game_dataset({"g": Xg, "u": Xu}, y,
                           entity_ids={"userId": uid})
    cd = CoordinateDescent(
        [CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                          reg_weight=1.0),
         CoordinateConfig("per-user", coordinate_type="random",
                          feature_shard="u", entity_column="userId",
                          reg_type="l2", reg_weight=1.0)],
        task="logistic")
    model, _ = cd.run(ds)
    root = tempfile.mkdtemp(prefix="bench-degrade-")
    model_dir = os.path.join(root, "model")
    save_game_model(model, model_dir, {
        "g": IndexMap({f"g{j}": j for j in range(d_fix)}),
        "u": IndexMap({f"u{j}": j for j in range(d_re)}),
    })

    max_batch = 16
    req_rows = 8

    def make_row(i):
        return {
            "features": (
                [{"name": f"g{j}", "value": float(Xg[i % n, j])}
                 for j in range(d_fix)]
                + [{"name": f"u{j}", "value": float(Xu[i % n, j])}
                   for j in range(d_re)]),
            "entityIds": {"userId": str(uid[i % n])},
        }

    payloads = [{"rows": [make_row(i * req_rows + j)
                          for j in range(req_rows)]}
                for i in range(32)]

    # -- leg 1: store-latency storm sweep on the degradation ladder --------
    # Host-LRU path with a cache far smaller than the entity universe so
    # cold store loads never stop; a delay fault on every load models the
    # brownout-triggering slow store (a raise-storm is the chaos suite's
    # job — the bench measures the LADDER, not the error path).
    store_delay_s = 0.05 if smoke else 0.1
    deadline_ms = 40.0
    session = ScoringSession(model_dir, max_batch=max_batch,
                             coeff_cache_entries=8, paged_table=False)
    brown = BrownoutController(enter_ms={1: 25.0, 2: 100.0},
                               metrics=session.metrics)
    batcher = MicroBatcher(session.score_rows, max_batch=max_batch,
                           max_delay_ms=0.5, max_queue=64,
                           metrics=session.metrics, brownout=brown)
    svc = ScoringService(session, batcher, request_timeout_s=30.0,
                         default_deadline_ms=deadline_ms, brownout=brown)

    def degrade_loop(rate_rows_s, duration_s):
        """Fixed-interval offered load via score_async, counting the
        ladder level of every accepted response body."""
        server = AsyncScoringServer(svc)

        async def run():
            interval = req_rows / rate_rows_s
            res = {"ok": 0, "shed": 0, "errors_5xx": 0, "other": 0,
                   "lat": [], "levels": {0: 0, 1: 0, 2: 0}}
            tasks = []

            async def fire(payload):
                t0 = time.perf_counter()
                status, body = await server.score_async(payload)
                ms = (time.perf_counter() - t0) * 1e3
                if status == 200:
                    res["ok"] += 1
                    res["lat"].append(ms)
                    lvl = int((body or {}).get("degraded", 0))
                    res["levels"][lvl] = res["levels"].get(lvl, 0) + 1
                elif status == 429:
                    res["shed"] += 1
                elif status >= 500:
                    res["errors_5xx"] += 1
                else:
                    res["other"] += 1

            loop = asyncio.get_running_loop()
            t_start = loop.time()
            t_next = t_start
            i = 0
            while loop.time() - t_start < duration_s:
                tasks.append(asyncio.ensure_future(
                    fire(payloads[i % len(payloads)])))
                i += 1
                t_next += interval
                delay = t_next - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
            await asyncio.gather(*tasks)
            return res

        r = asyncio.run(run())
        total = r["ok"] + r["shed"] + r["errors_5xx"] + r["other"]
        lat = sorted(r["lat"]) or [0.0]
        degraded = sum(v for k, v in r["levels"].items() if k >= 1)
        return {
            "offered_rows_per_s": rate_rows_s,
            "requests_total": total,
            "requests_ok": r["ok"],
            "requests_shed": r["shed"],
            "requests_5xx": r["errors_5xx"],
            "availability": round(
                (total - r["errors_5xx"]) / total, 4) if total else None,
            "degraded_fraction": round(degraded / r["ok"], 4)
            if r["ok"] else None,
            "degraded_by_level": {str(k): v
                                  for k, v in sorted(r["levels"].items())},
            "accepted_p50_ms": round(lat[len(lat) // 2], 3),
            "accepted_p99_ms": round(lat[min(len(lat) - 1,
                                             int(len(lat) * 0.99))], 3),
        }

    duration = float(os.environ.get(
        "BENCH_DEGRADE_DURATION_S", 0.8 if smoke else 2.0))

    # control: faults OFF — the ladder must stay untouched
    svc.handle_score(payloads[0])  # warm the compile ladder
    snap0 = svc.metrics.snapshot()
    control = degrade_loop(2_000, duration)
    control["degraded_total_metric"] = (
        svc.metrics.snapshot()["degraded_total"]
        - snap0["degraded_total"])

    # prime the session's fault-cost EWMA with the slow store visible so
    # the first measured request already knows a cold load costs more
    # than the deadline budget
    fault_injection.install([Fault("store.load", kind="delay",
                                   delay_s=store_delay_s, at=-1)])
    try:
        session.score_rows(payloads[0]["rows"])
        storm = []
        rates = [2_000, 6_000] if smoke else [2_000, 6_000, 12_000]
        for rate in rates:
            s0 = svc.metrics.snapshot()
            leg = degrade_loop(rate, duration)
            s1 = svc.metrics.snapshot()
            leg["degraded_total_metric"] = (s1["degraded_total"]
                                            - s0["degraded_total"])
            leg["brownout_level_after"] = s1["brownout_level"]
            storm.append(leg)
    finally:
        fault_injection.clear()
    storm_snap = svc.metrics.snapshot()
    deadline_drops = {
        "admission": storm_snap["deadline_drops_admission"],
        "queue": storm_snap["deadline_drops_queue"],
        "pre_compute": storm_snap["deadline_drops_pre_compute"],
    }
    svc.close()

    # -- leg 2: hedged tail latency under one slow replica -----------------
    slow_s = 0.15 if smoke else 0.3
    blip_s = 0.017   # ambient healthy-tail blip (GC-pause stand-in) on
    blip_every = 8   # every Nth batch of the to-be-slowed replica: the
    slow_gate = {"s": 0.0}   # healthy baseline needs the p99 >> p50
    # dispersion the hedge trigger is calibrated against — a perfectly
    # uniform synthetic baseline would measure the bucket quantizer, not
    # the policy

    def make_replica(slow=False):
        sess = ScoringSession(model_dir, max_batch=max_batch,
                              coeff_cache_entries=n_entities,
                              paged_table=True)
        calls = {"n": 0}

        def score(rows, per_coordinate=False, ctx=None):
            if slow:
                calls["n"] += 1
                if calls["n"] % blip_every == 0:
                    time.sleep(blip_s)
                if slow_gate["s"] > 0:
                    time.sleep(slow_gate["s"])
            return sess.score_rows(rows, per_coordinate, ctx=ctx)

        b = MicroBatcher(score, max_batch=max_batch, max_delay_ms=0.5,
                         metrics=sess.metrics)
        return ScoringService(sess, b, request_timeout_s=30.0)

    svc_fast = make_replica()
    svc_slow = make_replica(slow=True)
    for s in (svc_fast, svc_slow):
        s.handle_score(payloads[0])

    async def door_request(door, payload):
        reader, writer = await asyncio.open_connection(door.host,
                                                       door.port)
        body = json.dumps(payload).encode()
        writer.write((f"POST /score HTTP/1.1\r\nHost: bench\r\n"
                      f"Content-Type: application/json\r\n"
                      f"Content-Length: {len(body)}\r\n\r\n").encode()
                     + body)
        await writer.drain()
        head = await reader.readuntil(b"\r\n\r\n")
        status = int(head.split(b" ", 2)[1])
        length = 0
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-length:"):
                length = int(line.split(b":")[1])
        if length:
            await reader.readexactly(length)
        writer.close()
        return status

    def p99(lat):
        lat = sorted(lat) or [0.0]
        return round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 3)

    hedge_info = {}

    async def hedging_leg():
        srv_fast = await AsyncScoringServer(svc_fast).start()
        srv_slow = await AsyncScoringServer(svc_slow).start()
        door = await AsyncFrontDoor(
            [f"127.0.0.1:{srv_fast.port}", f"127.0.0.1:{srv_slow.port}"],
            policy="round_robin", hedge_enabled=False,
            hedge_min_s=0.002, hedge_min_samples=10).start()
        reps = 24 if smoke else 64

        async def measure(n_req):
            lat, bad = [], 0
            for i in range(n_req):
                t0 = time.perf_counter()
                status = await door_request(door,
                                            payloads[i % len(payloads)])
                lat.append((time.perf_counter() - t0) * 1e3)
                if status != 200:
                    bad += 1
            return lat, bad

        try:
            # both replicas healthy, hedging OFF: warms every breaker's
            # latency histogram past hedge_min_samples AND measures the
            # healthy baseline tail unmasked (hedging left on here would
            # quietly clip the very blips the baseline must contain)
            base_lat, base_bad = await measure(max(reps, 40))
            # one replica slow, hedging ON (runs before the no-hedge
            # phase: hedge losers are cancelled before note_latency, so
            # the slow replica's histogram — the hedge trigger — keeps
            # its healthy p99)
            door.hedge_enabled = True
            slow_gate["s"] = slow_s
            hedge_lat, hedge_bad = await measure(reps)
            hedged, wins = door.hedged, door.hedge_wins
            # same slow replica, hedging OFF: the unprotected tail
            door.hedge_enabled = False
            nohedge_lat, nohedge_bad = await measure(reps)
        finally:
            slow_gate["s"] = 0.0
            await door.aclose()
            await srv_fast.aclose()
            await srv_slow.aclose()
        hedge_info.update({
            "slow_replica_delay_ms": slow_s * 1e3,
            "baseline_p99_ms": p99(base_lat),
            "hedged_p99_ms": p99(hedge_lat),
            "no_hedge_p99_ms": p99(nohedge_lat),
            "hedged_fired": hedged,
            "hedge_wins": wins,
            "non_200s": base_bad + hedge_bad + nohedge_bad,
        })

    asyncio.run(hedging_leg())
    svc_fast.close()
    svc_slow.close()

    hedge_factor = float(os.environ.get("BENCH_DEGRADE_HEDGE_FACTOR",
                                        2.0))
    storm_available = all(s["availability"] == 1.0 for s in storm)
    storm_degraded = any((s["degraded_fraction"] or 0) > 0
                         and s["degraded_total_metric"] > 0
                         for s in storm)
    control_clean = (control["degraded_fraction"] == 0.0
                     and control["degraded_total_metric"] == 0)
    hedge_bound = (hedge_info["hedged_p99_ms"]
                   <= hedge_factor * hedge_info["baseline_p99_ms"]
                   and hedge_info["hedged_p99_ms"]
                   < hedge_info["no_hedge_p99_ms"]
                   and hedge_info["non_200s"] == 0)
    ok = storm_available and storm_degraded and control_clean and hedge_bound
    record = {
        "environment": _environment(),
        "metric": "degraded_serving_availability_under_store_delay",
        "value": min((s["availability"] for s in storm), default=0.0),
        "unit": (f"non-5xx fraction under {store_delay_s * 1e3:.0f}ms "
                 f"store.load delay faults, {deadline_ms:.0f}ms default "
                 f"deadline, host-LRU cache 8/{n_entities} entities "
                 "(degraded levels absorb the slow store; hedging leg "
                 "in fields)"),
        "store_delay_ms": store_delay_s * 1e3,
        "default_deadline_ms": deadline_ms,
        "control_faults_off": control,
        "storm_sweep": storm,
        "deadline_drops_by_stage": deadline_drops,
        "hedging": hedge_info,
        "acceptance_ok": ok,
        "acceptance_criteria": {
            "storm_availability_1_0": storm_available,
            "storm_serves_degraded": storm_degraded,
            "faults_off_zero_degraded": control_clean,
            f"hedged_p99_within_{hedge_factor:g}x_baseline_and_below_"
            "no_hedge": hedge_bound,
        },
    }
    with open(os.path.join(here, "BENCH_degrade.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    shutil.rmtree(root, ignore_errors=True)
    if smoke and not ok:
        print("degrade bench acceptance FAILED (storm availability, "
              "degraded fraction, faults-off control, hedged p99 bound)",
              file=sys.stderr)
        sys.exit(11)


def affinity_main() -> None:
    """``python bench.py affinity`` — elastic entity-affinity serving.

    The elastic-sharding claim measured end to end over real sockets: a
    saved GAME model whose random-effect table is expanded to
    ``E = N x B`` entities (N replicas x one replica's paged-table
    budget B — the full run is 4 x 25088 >= 100k entities), served
    three ways through the entity-affinity :class:`AsyncFrontDoor`:

    * ``single_replica`` — one replica whose device page budget holds
      only ``B`` of the ``E`` entities: the working set cannot be
      device-resident, so the leg records the page-churn/host-path
      posture (resident <= B) the affinity tier exists to fix.
    * ``multi_replica`` — N owner-routed replicas, each slice warmed
      through the real ``POST /admin/membership`` prefetch endpoint:
      the aggregate holds ALL ``E`` entities device-resident (N x one
      replica's budget) and p50/p99 stays flat vs the single replica.
    * ``churn`` — the same offered load while one replica is KILLED
      mid-load and a cold one JOINS mid-load: availability must stay
      1.0 (zero 5xx — failover responses carry the fallback routing
      label instead), p99 stays flat vs the churn-free leg, and the
      join's moved slice is prefetched before its epoch commits
      (``prefetch_bytes_per_rebalance`` from the door's counters).

    ``BENCH_AFFINITY_SMOKE=1`` shrinks the fleet (2 x 512 entities) for
    CI and enforces the acceptance gate (exit 13, distinct from
    serving's 7 / shard's 8 / degrade's 11): zero 5xx in every leg,
    aggregate residency >= 95% of ``E`` with each single replica
    capped at ``B``, multi and churn p99 within
    ``BENCH_AFFINITY_P99_FACTOR`` (default 3x) of their baselines, and
    nonzero prefetch bytes per rebalance. Writes
    ``BENCH_affinity.json``."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import asyncio
    import shutil
    import tempfile

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    import numpy as np

    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        make_game_dataset,
    )
    from photon_ml_tpu.io.avro import read_avro_file, write_avro_file
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.model_io import save_game_model
    from photon_ml_tpu.serve import (
        AsyncFrontDoor,
        AsyncScoringServer,
        MicroBatcher,
        ScoringService,
        ScoringSession,
    )

    smoke = os.environ.get("BENCH_AFFINITY_SMOKE") == "1"
    here = os.path.dirname(os.path.abspath(__file__))
    n_replicas = int(os.environ.get("BENCH_AFFINITY_REPLICAS",
                                    2 if smoke else 4))
    page_rows = 128 if smoke else 256
    pages = int(os.environ.get("BENCH_AFFINITY_PAGES",
                               4 if smoke else 98))
    budget = pages * page_rows          # B: one replica's device budget
    n_entities = n_replicas * budget    # E = N x B
    req_rows = 16
    max_batch = 32
    rate = float(os.environ.get("BENCH_AFFINITY_RATE",
                                3_000 if smoke else 2_500))
    duration = float(os.environ.get("BENCH_AFFINITY_DURATION_S",
                                    1.5 if smoke else 5.0))
    p99_factor = float(os.environ.get("BENCH_AFFINITY_P99_FACTOR", 3.0))
    # client-side socket cap: an overloaded leg (the single replica
    # paging E >> B is overloaded BY DESIGN) must queue in the client,
    # not overflow the server's listen backlog — the kernel answers a
    # full backlog with RSTs, which would read as availability loss
    # when the system under test never refused anything. The cap also
    # keeps the backend admission queue under max_queue
    # (cap * req_rows < 1024 rows), so the bench measures routing, not
    # its own shed path.
    client_conns = int(os.environ.get("BENCH_AFFINITY_CLIENT_CONNS",
                                      48))

    # -- model: train tiny, expand the random-effect table to E ----------
    rng = np.random.default_rng(0)
    d_fix, d_re, n_seed = 8, 8, 32
    n = n_seed * 8
    Xg = rng.normal(size=(n, d_fix))
    Xu = rng.normal(size=(n, d_re))
    uid = rng.integers(0, n_seed, n)
    y = (rng.random(n) < 0.5).astype(float)
    ds = make_game_dataset({"g": Xg, "u": Xu}, y,
                           entity_ids={"userId": uid})
    cd = CoordinateDescent(
        [CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                          reg_weight=1.0),
         CoordinateConfig("per-user", coordinate_type="random",
                          feature_shard="u", entity_column="userId",
                          reg_type="l2", reg_weight=1.0)],
        task="logistic")
    model, _ = cd.run(ds)
    root = tempfile.mkdtemp(prefix="bench-affinity-")
    model_dir = os.path.join(root, "model")
    save_game_model(model, model_dir, {
        "g": IndexMap({f"g{j}": j for j in range(d_fix)}),
        "u": IndexMap({f"u{j}": j for j in range(d_re)}),
    })
    re_path = os.path.join(model_dir, "random-effect", "per-user",
                           "coefficients.avro")
    seeds, schema = read_avro_file(re_path)

    def expanded():
        # E distinct entities from the trained seed coefficients: same
        # shape/sparsity, perturbed per entity so scores are distinct
        for eid in range(n_entities):
            tpl = seeds[eid % len(seeds)]
            rec = dict(tpl)
            rec["modelId"] = str(eid)
            rec["means"] = [dict(c) for c in tpl["means"]]
            for c in rec["means"]:
                c["value"] = float(c["value"]) * (1.0 + (eid % 97) * 1e-3)
            yield rec

    write_avro_file(re_path, expanded(), schema)

    def make_service():
        session = ScoringSession(
            model_dir, max_batch=max_batch,
            coeff_cache_entries=n_entities,
            re_pages=pages, re_page_rows=page_rows)
        batcher = MicroBatcher(
            session.score_rows, max_batch=max_batch, max_delay_ms=0.5,
            max_queue=1024, metrics=session.metrics)
        # the single-replica and post-kill legs overload the fleet BY
        # DESIGN with no deadline shedding armed; a 30s request timeout
        # would convert the bench's own queue into 504s and read as
        # availability loss, so give requests room to drain
        return ScoringService(session, batcher, request_timeout_s=300.0)

    ent_seq = rng.integers(0, n_entities, 4096)
    payload_bytes = []
    for p in range(64):
        rows = []
        for j in range(req_rows):
            i = (p * req_rows + j) % n
            e = int(ent_seq[(p * req_rows + j) % len(ent_seq)])
            rows.append({
                "features": (
                    [{"name": f"g{k}", "value": float(Xg[i, k])}
                     for k in range(d_fix)]
                    + [{"name": f"u{k}", "value": float(Xu[i, k])}
                       for k in range(d_re)]),
                "entityIds": {"userId": str(e)},
            })
        payload_bytes.append(json.dumps({"rows": rows}).encode())

    async def post(host, port, path, body):
        reader, writer = await asyncio.open_connection(host, port)
        writer.write((f"POST {path} HTTP/1.1\r\nHost: bench\r\n"
                      f"Content-Type: application/json\r\n"
                      f"Content-Length: {len(body)}\r\n\r\n"
                      ).encode() + body)
        await writer.drain()
        head = await reader.readuntil(b"\r\n\r\n")
        status = int(head.split(b" ", 2)[1])
        length = 0
        for line in head.split(b"\r\n")[1:]:
            if line.lower().startswith(b"content-length:"):
                length = int(line.split(b":", 1)[1])
        raw = await reader.readexactly(length) if length else b""
        writer.close()
        return status, raw

    async def open_loop(door, duration_s, churn=None):
        """Fixed-interval offered load through the door socket; returns
        latency/status tallies. ``churn(t_frac)`` is awaited once past
        1/3 (kill) and once past 2/3 (join) of the run."""
        interval = req_rows / rate
        out = {"ok": 0, "e5xx": 0, "shed": 0, "lat": [],
               "fallback": 0}
        tasks = []
        sem = asyncio.Semaphore(client_conns)

        async def fire(body):
            t0 = time.perf_counter()
            try:
                async with sem:
                    status, raw = await post(door.host, door.port,
                                             "/score", body)
            except (OSError, asyncio.IncompleteReadError):
                # a reset/teardown the cap did not absorb IS an
                # availability failure — count it against the 5xx gate
                out["e5xx"] += 1
                return
            ms = (time.perf_counter() - t0) * 1e3
            if status == 200:
                out["ok"] += 1
                out["lat"].append(ms)
                if b'"routing": "fallback"' in raw:
                    out["fallback"] += 1
            elif status >= 500:
                out["e5xx"] += 1
            else:
                out["shed"] += 1

        loop = asyncio.get_running_loop()
        t_start = loop.time()
        t_next = t_start
        fired = {"kill": False, "join": False}
        i = 0
        while loop.time() - t_start < duration_s:
            frac = (loop.time() - t_start) / duration_s
            if churn is not None and frac > 1 / 3 and not fired["kill"]:
                fired["kill"] = True
                await churn("kill")
            if churn is not None and frac > 2 / 3 and not fired["join"]:
                fired["join"] = True
                await churn("join")
            tasks.append(asyncio.ensure_future(
                fire(payload_bytes[i % len(payload_bytes)])))
            i += 1
            t_next += interval
            delay = t_next - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
        await asyncio.gather(*tasks)
        out["wall_s"] = loop.time() - t_start
        return out

    def leg_stats(out):
        lat = sorted(out["lat"]) or [0.0]
        return {
            "offered_rows_per_s": rate,
            "achieved_rows_per_s": round(
                out["ok"] * req_rows / out["wall_s"], 1),
            "p50_ms": round(lat[len(lat) // 2], 3),
            "p99_ms": round(lat[min(len(lat) - 1,
                                    int(len(lat) * 0.99))], 3),
            "requests_ok": out["ok"],
            "requests_5xx": out["e5xx"],
            "requests_shed": out["shed"],
            "fallback_served": out["fallback"],
        }

    all_ids = [str(e) for e in range(n_entities)]

    async def bench():
        record = {}

        # -- leg 1: one replica, budget B << E ------------------------
        svc = make_service()
        server = await AsyncScoringServer(svc).start()
        door = await AsyncFrontDoor([f"{server.host}:{server.port}"],
                                    affinity=True).start()
        await door.sync_membership()
        single = leg_stats(await open_loop(door, duration))
        svc.session.drain_installs()
        table = svc.session._state.paged["per-user"]
        single["resident_entities"] = len(table.resident_ids())
        st = table.stats()
        single["page_evictions"] = st["page_evictions"]
        await door.aclose()
        await server.aclose()
        record["single_replica"] = single

        # -- leg 2: N owner-routed replicas, warmed via the real
        # /admin/membership prefetch endpoint ------------------------
        services = [make_service() for _ in range(n_replicas)]
        servers = [await AsyncScoringServer(s).start()
                   for s in services]
        # breaker_threshold=1: the churn leg's kill must eject the dead
        # replica from the live set on its FIRST failed exchange, or a
        # join rebalance broadcast keeps addressing the corpse
        door = await AsyncFrontDoor(
            [f"{s.host}:{s.port}" for s in servers],
            affinity=True, breaker_threshold=1).start()
        epoch = door.membership_epoch
        warm_bytes = 0
        for i, addr in enumerate(epoch.replicas):
            host, _, port = addr.rpartition(":")
            body = json.dumps(epoch.payload(i, all_ids)).encode()
            status, raw = await post(host, int(port),
                                     "/admin/membership", body)
            assert status == 200, f"membership prefetch: {status}"
            warm_bytes += int(json.loads(raw).get("prefetchBytes", 0))
        await door.sync_membership()
        multi = leg_stats(await open_loop(door, duration))
        resident = 0
        evictions = 0.0
        for s in services:
            s.session.drain_installs()
            t = s.session._state.paged["per-user"]
            resident += len(t.resident_ids())
            evictions += t.stats()["page_evictions"]
        multi["aggregate_resident_entities"] = resident
        multi["page_evictions"] = evictions
        multi["warm_prefetch_bytes"] = warm_bytes
        record["multi_replica"] = multi

        # -- leg 3: same load with a kill + a cold join mid-load ------
        stats0 = door.stats()["affinity"]
        # the joiner's session precompiles its jit ladder BEFORE the
        # leg (a real replica warms up before asking to join) so the
        # join event itself is only the membership transition
        svc_new = make_service()
        srv_new = await AsyncScoringServer(svc_new).start()
        join_addr = f"{srv_new.host}:{srv_new.port}"
        joined = {}

        async def churn(event):
            if event == "kill":
                dead = door.membership_epoch.replicas[-1]
                i = next(k for k, s in enumerate(servers)
                         if f"{s.host}:{s.port}" == dead)
                # abrupt kill: close in the background, keep firing
                joined["kill_task"] = asyncio.ensure_future(
                    servers[i].aclose())
                joined["dead_i"] = i
            else:
                joined["result"] = await door.add_backend(join_addr)

        churn_leg = leg_stats(await open_loop(door, duration,
                                              churn=churn))
        if "kill_task" in joined:
            await joined["kill_task"]
        # converge any transition the load cut short; the gate reads
        # the COMMITTED topology, not a mid-flight snapshot
        await door.sync_membership()
        stats1 = door.stats()["affinity"]
        rebalances = max(1, stats1["epochCommits"]
                         - stats0["epochCommits"])
        churn_leg["epoch_commits"] = (stats1["epochCommits"]
                                      - stats0["epochCommits"])
        churn_leg["prefetch_bytes_per_rebalance"] = round(
            (stats1["prefetchedBytes"] - stats0["prefetchedBytes"])
            / rebalances, 1)
        churn_leg["owner_miss"] = stats1["ownerMiss"]
        churn_leg["join_committed"] = (
            join_addr in door.membership_epoch.replicas)
        record["churn"] = churn_leg
        record["door"] = door.stats()["affinity"]

        await door.aclose()
        for i, s in enumerate(servers):
            if i != joined.get("dead_i"):
                await s.aclose()
        await srv_new.aclose()
        return record

    legs = asyncio.run(bench())
    single, multi, churn_leg = (legs["single_replica"],
                                legs["multi_replica"], legs["churn"])

    zero_5xx = (single["requests_5xx"] == 0
                and multi["requests_5xx"] == 0
                and churn_leg["requests_5xx"] == 0)
    n_x_budget = (single["resident_entities"] <= budget
                  and multi["aggregate_resident_entities"]
                  >= 0.95 * n_entities)
    flat_multi = (multi["p99_ms"]
                  <= p99_factor * max(single["p99_ms"], 1.0))
    # on a shared-core container the kill/join transition work (breaker
    # discovery, rebalance broadcast, joiner prefetch) runs on the SAME
    # core as the client, so the churn bound is the relative factor OR
    # an absolute transient ceiling, whichever is looser — "flat" means
    # bounded, not indistinguishable. At full size the ceiling bounds
    # the failover fault storm (survivors re-page the dead owner's
    # B-entity slice through the host LRU before the re-own commits),
    # not steady-state latency — steady-state flatness is the multi
    # leg's gate; availability 1.0 through the storm is this leg's.
    churn_ceiling = float(os.environ.get(
        "BENCH_AFFINITY_CHURN_P99_MS", 500.0 if smoke else 120_000.0))
    flat_churn = (churn_leg["p99_ms"]
                  <= max(p99_factor * max(multi["p99_ms"], 1.0),
                         churn_ceiling))
    prefetch_moves = churn_leg["prefetch_bytes_per_rebalance"] > 0
    ok = (zero_5xx and n_x_budget and flat_multi and flat_churn
          and prefetch_moves and churn_leg["join_committed"])

    record = {
        "environment": _environment(),
        "metric": "affinity_aggregate_device_resident_entities",
        "value": multi["aggregate_resident_entities"],
        "unit": (f"entities device-resident across {n_replicas} "
                 f"owner-routed replicas (page budget {budget}/replica,"
                 f" {n_entities} total entities, d_re={d_re}, "
                 f"req_rows={req_rows}, offered {rate:g} rows/s over "
                 "real sockets; single-replica and kill+join churn "
                 "legs in fields)"),
        "replicas": n_replicas,
        "page_budget_per_replica": budget,
        "total_entities": n_entities,
        "cpu_cores": os.cpu_count() or 1,
        "single_replica": single,
        "multi_replica": multi,
        "churn": churn_leg,
        "acceptance_ok": ok,
        "acceptance_criteria": {
            "zero_5xx_all_legs": zero_5xx,
            "aggregate_serves_n_x_page_budget": n_x_budget,
            f"multi_p99_within_{p99_factor:g}x_single": flat_multi,
            f"churn_p99_within_{p99_factor:g}x_multi_or_"
            f"{churn_ceiling:g}ms": flat_churn,
            "prefetch_bytes_per_rebalance_nonzero": prefetch_moves,
            "join_epoch_committed": churn_leg["join_committed"],
        },
    }
    with open(os.path.join(here, "BENCH_affinity.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    shutil.rmtree(root, ignore_errors=True)
    if smoke and not ok:
        print("affinity bench acceptance FAILED (zero 5xx, N x page "
              "budget aggregate residency, flat p99 under fan-out and "
              "churn, prefetch-before-commit)", file=sys.stderr)
        sys.exit(13)


def swap_main() -> None:
    """``python bench.py swap`` — model-lifecycle hot-swap latency.

    Publishes a full version plus a delta version (a handful of
    perturbed entities) into a throwaway registry, then alternates
    ``ScoringSession.swap`` between them 50 times on CPU, measuring:
    swap latency (build-next-state + install), the FIRST request's
    latency after each swap (the cold-cache cliff a swap must not
    reintroduce), and the compile count across all swaps (the invariant:
    0 new executables — the shape ladder survives the swap). Writes
    ``BENCH_swap.json`` next to this file and prints the same JSON."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import shutil
    import tempfile

    import jax

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    import numpy as np

    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        make_game_dataset,
    )
    from photon_ml_tpu.io.avro import read_avro_file, write_avro_file
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.model_io import save_game_model
    from photon_ml_tpu.registry import ModelRegistry, publish_delta
    from photon_ml_tpu.serve import ScoringSession

    rng = np.random.default_rng(0)
    n, d_fix, d_re, n_entities = 600, 32, 8, 64
    Xg = rng.normal(size=(n, d_fix))
    Xu = rng.normal(size=(n, d_re))
    uid = rng.integers(0, n_entities, n)
    y = (rng.random(n) < 0.5).astype(float)
    ds = make_game_dataset({"g": Xg, "u": Xu}, y,
                           entity_ids={"userId": uid})
    cd = CoordinateDescent(
        [CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                          reg_weight=1.0),
         CoordinateConfig("per-user", coordinate_type="random",
                          feature_shard="u", entity_column="userId",
                          reg_type="l2", reg_weight=1.0)],
        task="logistic")
    model, _ = cd.run(ds)
    root = tempfile.mkdtemp(prefix="bench-swap-")
    model_dir = os.path.join(root, "model")
    save_game_model(model, model_dir, {
        "g": IndexMap({f"g{j}": j for j in range(d_fix)}),
        "u": IndexMap({f"u{j}": j for j in range(d_re)}),
    })
    # delta source: same model with ~5% of entities' RE records perturbed
    delta_dir = os.path.join(root, "model-delta")
    shutil.copytree(model_dir, delta_dir)
    re_path = os.path.join(delta_dir, "random-effect", "per-user",
                           "coefficients.avro")
    records, schema = read_avro_file(re_path)
    for rec in records[: max(1, len(records) // 20)]:
        for coef in rec["means"]:
            coef["value"] *= 1.05
    write_avro_file(re_path, records, schema)

    registry = ModelRegistry(os.path.join(root, "registry"))
    v1 = registry.publish(model_dir, set_latest=True)
    v2 = publish_delta(registry, delta_dir, parent=v1)

    max_batch = 64
    session = ScoringSession(registry.open_version(v1),
                             max_batch=max_batch,
                             coeff_cache_entries=n_entities)
    rows = [{
        "features": (
            [{"name": f"g{j}", "value": float(Xg[i, j])}
             for j in range(d_fix)]
            + [{"name": f"u{j}", "value": float(Xu[i, j])}
               for j in range(d_re)]),
        "entityIds": {"userId": str(uid[i])},
    } for i in range(32)]
    for _ in range(5):  # warm the ladder + coefficient LRU
        session.score_rows(rows)

    n_swaps = int(os.environ.get("BENCH_SWAP_REPS", 50))
    compiles_before = session.compile_count
    swap_ms, first_req_ms = [], []
    for i in range(n_swaps):
        target = v2 if i % 2 == 0 else v1
        t0 = time.perf_counter()
        session.swap(registry.open_version(target), version=target)
        swap_ms.append((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        session.score_rows(rows)
        first_req_ms.append((time.perf_counter() - t0) * 1e3)
    recompiles = session.compile_count - compiles_before
    swap_ms.sort()
    first_req_ms.sort()

    def pct(xs, q):
        return round(xs[min(len(xs) - 1, int(len(xs) * q))], 3)

    record = {
        "environment": _environment(),
        "metric": "serving_hot_swap_latency_cpu",
        "value": pct(swap_ms, 0.5),
        "unit": (f"ms swap p50 over {n_swaps} full<->delta swaps "
                 f"({jax.devices()[0].platform}, d_fix={d_fix}, "
                 f"d_re={d_re}, entities={n_entities}, batch=32; "
                 "invariant: recompiles_across_swaps == 0)"),
        "swap_p50_ms": pct(swap_ms, 0.5),
        "swap_p99_ms": pct(swap_ms, 0.99),
        "first_request_after_swap_p50_ms": pct(first_req_ms, 0.5),
        "first_request_after_swap_p99_ms": pct(first_req_ms, 0.99),
        "recompiles_across_swaps": recompiles,
        "swaps": n_swaps,
        "delta_summary": registry.manifest(v2).get("delta_summary"),
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_swap.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    shutil.rmtree(root, ignore_errors=True)


def stream_main() -> None:
    """``python bench.py stream`` — out-of-core streamed training: decode
    cost and pipeline stalls, cold vs warm chunk cache.

    Builds a synthetic Avro shard on disk, then streams it through
    ``streaming_value_and_grad`` (CPU, float64) three ways: the COLD first
    pass over a decode-once chunk cache (pays Avro decode + feature
    resolution + packed-memmap spill), WARM cache-hit passes (memmap reads
    only), and NO-CACHE passes (re-decode every pass — the pre-cache
    behavior of the out-of-core path). Reports example-passes/s for each,
    the warm/cold speedup, per-phase stall fractions (decode-wait /
    transfer / compute-stall, ``StreamStats``), a float64 coefficient
    parity check of a cached ``fit_streaming`` against the no-cache fit
    (must agree to <= 1e-9 — the cache must be bit-faithful), and the
    compiled-executable count across passes (must stay flat: every chunk
    shares one fixed-shape kernel, warm or cold). Writes
    ``BENCH_stream.json`` next to this file and prints the same JSON.

    Sized by ``BENCH_STREAM_ROWS`` (default 24000) and
    ``BENCH_STREAM_FIT_ITERS`` (default 6) so the CI smoke
    (``scripts/ci_bench_smoke.sh``) finishes in seconds."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import shutil
    import tempfile

    import jax

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    jax.config.update("jax_enable_x64", True)  # the 1e-9 parity gate is f64
    import jax.numpy as jnp

    from photon_ml_tpu.io.chunk_cache import ChunkCacheSource
    from photon_ml_tpu.io.data_reader import write_training_examples
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.stream_source import AvroChunkSource
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel.data_parallel import compiled_kernel_count
    from photon_ml_tpu.parallel.streaming import (
        HostChunk,
        StreamStats,
        fit_streaming,
        streaming_value_and_grad,
    )

    rng = np.random.default_rng(0)
    n = int(os.environ.get("BENCH_STREAM_ROWS", 24000))
    fit_iters = int(os.environ.get("BENCH_STREAM_FIT_ITERS", 6))
    vocab, max_k, chunk_rows = 96, 12, 1024
    rows = []
    for _ in range(n):
        k = int(rng.integers(3, max_k + 1))
        cols = rng.choice(vocab, size=k, replace=False)
        rows.append([(f"feature_{c:04d}", "", float(rng.normal()))
                     for c in cols])
    labels = rng.integers(0, 2, n).astype(float)
    weights = rng.uniform(0.5, 2.0, n)
    offsets = rng.normal(0, 0.1, n)
    root = tempfile.mkdtemp(prefix="bench-stream-")
    try:
        path = os.path.join(root, "train.avro")
        write_training_examples(path, rows, labels, offsets=offsets,
                                weights=weights, block_size=512)
        imap = IndexMap({f"feature_{c:04d}": c for c in range(vocab)},
                        add_intercept=True)
        src = AvroChunkSource(path, imap, chunk_rows=chunk_rows)
        cache = ChunkCacheSource(src, os.path.join(root, "cache"))
        obj = make_objective("logistic")
        dim = src.dim
        w = jnp.zeros((dim,), jnp.float64)

        # compile OUTSIDE the timed passes (same fixed shapes as every
        # real chunk, all-zero weights so the kernel output is inert):
        # cold-vs-warm must compare decode+spill vs memmap, not XLA
        warm_chunk = HostChunk(
            indices=np.zeros((chunk_rows, src.pad_nnz), np.int32),
            values=np.zeros((chunk_rows, src.pad_nnz), np.float32),
            labels=np.zeros(chunk_rows), offsets=np.zeros(chunk_rows),
            weights=np.zeros(chunk_rows))
        streaming_value_and_grad(obj, [warm_chunk], dim,
                                 dtype=jnp.float64)(w, 0.5)

        def timed_pass(chunks, stats):
            fg = streaming_value_and_grad(obj, chunks, dim,
                                          dtype=jnp.float64, stats=stats)
            t0 = time.perf_counter()
            f, g = fg(w, 0.5)
            float(f)  # scalar fetch: the pass has actually completed
            return time.perf_counter() - t0

        stats_cold, stats_warm, stats_raw = (StreamStats(), StreamStats(),
                                             StreamStats())
        cold_s = timed_pass(cache, stats_cold)
        assert cache.cold_passes == 1 and cache.warm_passes == 0
        compiles_after_cold = compiled_kernel_count(obj)
        warm_walls = [timed_pass(cache, stats_warm) for _ in range(3)]
        warm_s, warm_total_s = min(warm_walls), sum(warm_walls)
        assert cache.warm_passes == 3, cache.warm_passes
        compiles_after_warm = compiled_kernel_count(obj)
        raw_s = min(timed_pass(src, stats_raw) for _ in range(2))

        # cached fit vs no-cache fit: float64, exact iteration count
        cfg = OptimizerConfig(max_iters=fit_iters, tolerance=0.0)
        r_raw = fit_streaming(obj, src, dim, l2=0.5, config=cfg,
                              dtype=jnp.float64)
        compiles_before_cached_fit = compiled_kernel_count(obj)
        r_cached = fit_streaming(obj, cache, dim, l2=0.5, config=cfg,
                                 dtype=jnp.float64)
        compiles_after_cached_fit = compiled_kernel_count(obj)
        coeff_diff = float(np.max(np.abs(np.asarray(r_raw.w)
                                         - np.asarray(r_cached.w))))

        def frac(stats, wall):
            # transfer-thread seconds over TOTAL wall of the measured
            # passes; decode_wait/transfer live on the transfer thread, so
            # their sum can approach (not exceed) 1.0 of overlapped wall
            return {"decode_wait": round(stats.decode_s / wall, 4),
                    "transfer": round(stats.transfer_s / wall, 4),
                    "compute_stall": round(stats.stall_s / wall, 4)}

        record = {
            "environment": _environment(),
            "metric": "streamed_ooc_warm_pass_example_passes_per_sec",
            "value": round(n / warm_s, 1),
            "unit": (f"example-passes/sec, warm chunk-cache pass "
                     f"({jax.devices()[0].platform}, n={n}, "
                     f"chunk_rows={chunk_rows}, pad_nnz={src.pad_nnz}, "
                     "f64; cold/no-cache rates + stall fractions in "
                     "fields)"),
            "cold_pass_example_passes_per_sec": round(n / cold_s, 1),
            "warm_pass_example_passes_per_sec": round(n / warm_s, 1),
            "no_cache_pass_example_passes_per_sec": round(n / raw_s, 1),
            "speedup_warm_vs_cold": round(cold_s / warm_s, 3),
            "speedup_warm_vs_no_cache": round(raw_s / warm_s, 3),
            "stall_fractions": {"cold": frac(stats_cold, cold_s),
                                "warm": frac(stats_warm, warm_total_s)},
            "cache_bytes": cache.bytes_written,
            "fit_iters": fit_iters,
            "cached_fit_coeff_max_abs_diff": coeff_diff,
            "compiles_after_cold_pass": compiles_after_cold,
            "compiles_after_warm_passes": compiles_after_warm,
            "compiles_during_cached_fit": (compiles_after_cached_fit
                                           - compiles_before_cached_fit),
        }
        ok = (record["speedup_warm_vs_cold"] >= 2.0
              and coeff_diff <= 1e-9
              and compiles_after_warm == compiles_after_cold
              and record["compiles_during_cached_fit"] == 0)
        record["acceptance_ok"] = ok
        here = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(here, "BENCH_stream.json"), "w") as f:
            json.dump(record, f, indent=2)
        print(json.dumps(record))
        if not ok:
            print("stream bench acceptance FAILED (speedup >= 2x, parity "
                  "<= 1e-9, flat compile count)", file=sys.stderr)
            sys.exit(5)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def path_main() -> None:
    """``python bench.py path`` — pathwise fixed-effect training with
    KKT-certified strong-rule screening (``optimize/path.py``,
    docs/path.md) vs the cost a user actually pays without it.

    The SCREENED arm trains a descending elastic-net lambda grid
    (default 50 points, ``0.9*lambda_max`` down to its 1/20th — the
    sparse regime pathwise screening exists for, bracketing the best
    validation lambda) through ``PathSolver``: sequential strong-rule
    screen, restricted solve on the power-of-two bucket ladder,
    full-gradient KKT certification with violator re-entry. The
    feature shard is DENSE, the regime where restriction shrinks
    per-iteration FLOPs ``dim -> bucket`` (with ELL-sparse data the
    margins already cost O(nnz) regardless; restriction then shrinks
    the dense-vector optimizer state instead, which only bites at
    10^5+ dims). The COMPARATOR is the 5-point cold grid a user
    without pathwise machinery would run: 5 cold full-width fits at
    evenly spaced grid points. The acceptance gate is the headline
    claim: the WHOLE 50-lambda certified path costs <= 2x those 5
    cold fits. Both arms are warmed untimed first (cd-bench
    discipline: the screen/solve trajectory is deterministic, so the
    warm-up compiles exactly the shapes the timed re-walk — after
    ``PathSolver.reset_states()`` — revisits; compile time excluded
    on both sides). An UNSCREENED arm (screen=off, warm-started walk
    of the same grid, same tolerances) provides the selection oracle:
    the screened path's best validation lambda must be IDENTICAL.

    Compile accounting: the timed screened re-walk must compile
    NOTHING (``PathSolver.compiled_kernel_count`` sampled per
    lambda) — the bucket ladder is warm and must stay flat. Also
    asserts every lambda reports ``certified`` (the KKT loop's
    contract). Writes ``BENCH_path.json``.

    Sized by ``BENCH_PATH_LAMBDAS`` (default 50) / ``BENCH_PATH_ROWS``
    (default 8000) / ``BENCH_PATH_DIM`` (default 2048) — large enough
    that per-iteration cost is FLOP-bound (the quantity the wall-clock
    gate measures). ``BENCH_PATH_SMOKE=1`` (the CI smoke) shrinks all
    three and waives ONLY the wall-clock gate — certification,
    best-lambda selection, and the flat-compile gate are
    size-independent and stay enforced."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    jax.config.update("jax_enable_x64", True)  # sharp parity + selection
    import jax.numpy as jnp

    from photon_ml_tpu.evaluation import get_evaluator
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optimize import OptimizerConfig, PathConfig, PathSolver
    from photon_ml_tpu.parallel.data_parallel import fit_distributed
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.types import make_batch

    # sized FLOP-bound: per-iteration cost must scale with the restricted
    # width for the wall-clock gate to measure screening rather than
    # per-solve dispatch overhead
    smoke = bool(int(os.environ.get("BENCH_PATH_SMOKE", "0")))
    n_lams = int(os.environ.get("BENCH_PATH_LAMBDAS", 16 if smoke else 50))
    n_rows = int(os.environ.get("BENCH_PATH_ROWS", 2000 if smoke else 8000))
    dim = int(os.environ.get("BENCH_PATH_DIM", 256 if smoke else 2048))
    alpha, tol = 0.9, 1e-10
    rng = np.random.default_rng(0)

    def synth(n, seed):
        r = np.random.default_rng(seed)
        x = r.normal(size=(n, dim))
        x[:, 0] = 1.0  # intercept column
        m = x @ w_true
        y = (r.random(n) < 1.0 / (1.0 + np.exp(-m))).astype(np.float64)
        return make_batch(jnp.asarray(x), y, np.zeros(n), np.ones(n),
                          dtype=jnp.float64)

    # sparse ground truth: the regime screening exists for
    w_true = np.zeros(dim)
    support = rng.choice(np.arange(1, dim), size=max(4, dim // 20),
                         replace=False)
    w_true[support] = rng.normal(size=support.shape[0]) * 2.0
    w_true[0] = 0.3
    train = synth(n_rows, 1)
    val = synth(max(1000, n_rows // 4), 2)
    vlabels = np.asarray(val.labels)

    objective = make_objective("logistic", intercept_index=0)
    reg = RegularizationContext("elastic_net", alpha=alpha)
    mesh = make_mesh()
    cfg = OptimizerConfig(max_iters=400, tolerance=tol)
    auc = get_evaluator("auc")

    def solver(screen):
        return PathSolver(objective, reg, batch=train, mesh=mesh,
                          optimizer="auto", config=cfg, dtype=jnp.float64,
                          path_config=PathConfig(screen=screen,
                                                 min_bucket=32))

    # just under lambda_max down to its 1/20th: the sparse regime the
    # screen exists for, bracketing the best validation lambda
    lam_hi = 0.9 * solver("off").lambda_max() / alpha
    grid = np.geomspace(lam_hi, lam_hi / 20.0, n_lams)

    def walk(ps):
        t0 = time.perf_counter()
        stats, aucs, kernels = [], [], []
        for lam in grid:
            res, st = ps.solve(float(lam))
            scores = np.asarray(objective.margins(res.w, val))
            aucs.append(auc.evaluate(scores, vlabels, np.asarray(val.weights)))
            stats.append(st)
            kernels.append(ps.compiled_kernel_count())
        # already synced: each lambda's margins were fetched for the AUC
        return stats, aucs, kernels, time.perf_counter() - t0

    def run_path(screen):
        ps = solver(screen)
        _w_stats, _w_aucs, warm_kernels, _w_s = walk(ps)  # warm the ladder
        ps.reset_states()  # keep kernels, re-walk the exact trajectory
        stats, aucs, kernels, wall = walk(ps)
        return ps, stats, aucs, warm_kernels, kernels, wall

    # -- screened arm ----------------------------------------------------
    (ps, stats, aucs_s, warm_kernels, kernels, path_s) = run_path("strong")
    # warm-ladder flatness: the timed re-walk must compile NOTHING
    timed_recompiles = kernels[-1] - warm_kernels[-1]

    # -- unscreened oracle: same grid, warm-started, full width ----------
    _ps_o, stats_off, aucs_o, _wk_o, _k_o, off_s = run_path("off")

    # -- the 5-point cold grid (warmed kernels, compile time excluded) ---
    cold_lams = [float(grid[int(round(i * (n_lams - 1) / 4))])
                 for i in range(5)]

    def cold_fit(lam):
        return fit_distributed(
            objective, train, mesh, jnp.zeros((dim,), jnp.float64),
            l2=reg.l2_weight(lam), l1=reg.l1_weight(lam),
            optimizer="owlqn", config=cfg)

    cold_fit(cold_lams[0])  # warm the full-width kernels
    t0 = time.perf_counter()
    cold_iters = 0
    for lam in cold_lams:
        rc = cold_fit(lam)
        cold_iters = cold_iters + int(rc.iterations)
    float(np.asarray(rc.w)[0])  # sync
    cold5_s = time.perf_counter() - t0

    best_screened = int(np.argmax(aucs_s))
    best_off = int(np.argmax(aucs_o))
    record = {
        "environment": _environment(),
        "metric": "path_screen_wallclock_vs_5_cold_fits",
        "value": round(path_s / cold5_s, 3),
        "unit": (f"x wall-clock, {n_lams}-lambda KKT-certified screened "
                 f"path / 5 cold full-width fits "
                 f"({jax.devices()[0].platform}, f64, rows={n_rows}, "
                 f"dim={dim}, alpha={alpha}; both warmed, compile time "
                 "excluded — gate <= 2.0)"),
        "path_wall_s": round(path_s, 3),
        "cold5_wall_s": round(cold5_s, 3),
        "unscreened_path_wall_s": round(off_s, 3),
        "lambda_grid": [float(v) for v in grid],
        "active_set_sizes": [int(s.candidate_size) for s in stats],
        "screened_dims": [int(s.screened_dim) for s in stats],
        "features_frozen": [int(s.features_frozen) for s in stats],
        "kkt_rounds": [int(s.kkt_rounds) for s in stats],
        "kkt_violations": [int(s.kkt_violations) for s in stats],
        "solver_iterations": [int(s.solver_iterations) for s in stats],
        "full_grad_passes": [int(s.full_grad_passes) for s in stats],
        "fallback_full": [bool(s.fallback_full) for s in stats],
        "all_certified": all(s.certified for s in stats),
        "compiled_kernels_per_warmup_lambda": warm_kernels,
        "compiled_kernels_per_timed_lambda": kernels,
        "recompiles_during_timed_walk": timed_recompiles,
        "path_total_iterations": int(ps.total_iterations),
        "cold5_total_iterations": int(cold_iters),
        "best_lambda_screened": float(grid[best_screened]),
        "best_lambda_unscreened": float(grid[best_off]),
        "best_auc_screened": float(aucs_s[best_screened]),
        "best_auc_unscreened": float(aucs_o[best_off]),
    }
    # the wall-clock gate only measures screening at FLOP-bound size:
    # smoke-sized problems are dispatch-bound (per-lambda overhead, not
    # restricted-width FLOPs), so BENCH_PATH_SMOKE keeps the size-
    # independent gates and records the ratio ungated
    record["smoke"] = smoke
    ok = ((smoke or record["value"] <= 2.0)
          and record["all_certified"]
          and best_screened == best_off
          and timed_recompiles == 0)
    record["acceptance_ok"] = ok
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_path.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    if not ok:
        print("path bench acceptance FAILED (whole screened path <= 2x "
              "five cold fits, every lambda KKT-certified, best-lambda "
              "selection identical to unscreened, 0 compiles during the "
              "warmed timed walk)", file=sys.stderr)
        sys.exit(14)


def cd_main() -> None:
    """``python bench.py cd`` — active-set coordinate descent vs the
    fixed-full-sweep schedule on a synthetic multi-sweep GAME workload.

    The BASELINE arm is the paper's loop: every sweep re-solves every
    entity of every random-effect coordinate for exactly N sweeps — N
    chosen conservatively, as a user who cannot see sweeps-to-converge
    must. The ACTIVE arm turns on this repo's CD convergence layer:
    converged-entity freezing with offset-drift re-activation (active-set
    sub-bucket solves + incremental delta rescoring), periodic full
    refresh, and the sweep-level ``cd_tolerance`` early exit. Both run
    float64 so the acceptance gate is sharp: the two final models must
    agree to <= 1e-9 max-abs coefficient diff (with the drift-free
    solvers they are typically bit-identical) while the active arm is
    measurably faster (target >= 1.5x wall-clock).

    Compile accounting: each arm is run once UNTIMED to warm its solver
    shape ladder (the active arm's power-of-two sub-bucket widths are a
    deterministic function of the workload, so the warm-up compiles
    exactly the shapes the timed run uses), then timed. The RE solver
    compile counter (``random_effect.re_solver_compile_count``) must stay
    FLAT across the whole timed active run — shrinking active sets reuse
    the warmed ladder, 0 new compiles. Writes ``BENCH_cd.json``.

    Sized by ``BENCH_CD_ENTITIES`` (default 400) / ``BENCH_CD_SWEEPS``
    (default 24) so the CI smoke finishes in a couple of minutes."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    jax.config.update("jax_enable_x64", True)  # the 1e-9 parity gate is f64
    import jax.numpy as jnp

    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        make_game_dataset,
    )
    from photon_ml_tpu.game.random_effect import re_solver_compile_count

    rng = np.random.default_rng(0)
    n_users = int(os.environ.get("BENCH_CD_ENTITIES", 1200))
    n_sweeps = int(os.environ.get("BENCH_CD_SWEEPS", 24))
    d_g, d_u = 8, 8
    w_fixed = rng.normal(size=d_g)
    U = rng.normal(size=(n_users, d_u))
    Xg, Xu, y, uid = [], [], [], []
    for u in range(n_users):
        m = int(rng.integers(10, 30))
        xg, xu = rng.normal(size=(m, d_g)), rng.normal(size=(m, d_u))
        marg = xg @ w_fixed + xu @ U[u]
        y.append((rng.random(m) < 1 / (1 + np.exp(-marg))).astype(float))
        Xg.append(xg)
        Xu.append(xu)
        uid.append(np.full(m, u))
    Xg, Xu, y, uid = map(np.concatenate, (Xg, Xu, y, uid))
    ds = make_game_dataset({"g": Xg, "u": Xu}, y, entity_ids={"userId": uid})

    def coord_configs(active: bool):
        return [
            CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                             reg_weight=2.0, tolerance=1e-12),
            # newton: the drift-free batched RE solver (a converged
            # entity's re-solve is a bit-exact no-op, so the frontier can
            # actually freeze); also the TPU-default RE path
            CoordinateConfig("per-user", coordinate_type="random",
                             feature_shard="u", entity_column="userId",
                             reg_type="l2", reg_weight=2.0, tolerance=1e-11,
                             optimizer="newton",
                             active_set=active, refresh_every=6,
                             active_tol=1e-10),
        ]

    def make_cd(active: bool):
        kw = dict(cd_tolerance=1e-10) if active else {}
        return CoordinateDescent(coord_configs(active), task="logistic",
                                 n_iterations=n_sweeps, dtype=jnp.float64,
                                 **kw)

    def run(active: bool, callback=None):
        t0 = time.perf_counter()
        model, history = make_cd(active).run(
            ds, checkpoint_callback=callback)
        # scalar-fetch sync: reading a coefficient forces completion
        float(np.asarray(
            model.coordinates["fixed"].model.coefficients.means)[0])
        return model, history, time.perf_counter() - t0

    # warm-up runs compile each arm's full shape ladder (deterministic
    # trajectories: the timed runs revisit exactly these shapes)
    run(False)
    compiles_per_sweep = []
    run(True, callback=lambda it, m: compiles_per_sweep.append(
        re_solver_compile_count()))
    m_full, h_full, full_s = run(False)
    compiles_before = re_solver_compile_count()
    m_act, h_act, act_s = run(True)
    compiles_during_timed = re_solver_compile_count() - compiles_before

    diffs = [float(np.max(np.abs(
        np.asarray(m_full.coordinates["fixed"].model.coefficients.means)
        - np.asarray(m_act.coordinates["fixed"].model.coefficients.means))))]
    for bf, ba in zip(m_full.coordinates["per-user"].buckets,
                      m_act.coordinates["per-user"].buckets):
        if np.asarray(bf.coefficients).size:
            diffs.append(float(np.max(np.abs(
                np.asarray(bf.coefficients) - np.asarray(ba.coefficients)))))
    coeff_diff = max(diffs)

    re_records = [r for r in h_act if r["coordinate"] == "per-user"]
    solved_per_sweep = [int(r.get("entities_solved", n_users))
                       for r in re_records]
    sweeps_active = h_act[-1]["iteration"] + 1
    record = {
        "environment": _environment(),
        "metric": "cd_active_set_speedup_vs_full_sweeps",
        "value": round(full_s / act_s, 3),
        "unit": (f"x wall-clock, full-sweep CD / active-set CD "
                 f"({jax.devices()[0].platform}, f64, "
                 f"entities={n_users}, rows={len(y)}, d_fix={d_g}, "
                 f"d_re={d_u}, sweeps={n_sweeps}; both warmed, compile "
                 "time excluded)"),
        "full_sweep_wall_s": round(full_s, 3),
        "active_set_wall_s": round(act_s, 3),
        "sweeps_full": h_full[-1]["iteration"] + 1,
        "sweeps_to_converge_active": sweeps_active,
        "active_stop_reason": h_act[-1].get("stop_reason"),
        "entities_solved_per_sweep": solved_per_sweep,
        "coeff_max_abs_diff": coeff_diff,
        "re_solver_compiles_per_warmup_sweep": compiles_per_sweep,
        "re_solver_compiles_during_timed_active_run": compiles_during_timed,
    }
    ok = (record["value"] >= 1.5
          and coeff_diff <= 1e-9
          and compiles_during_timed == 0)
    record["acceptance_ok"] = ok
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_cd.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    if not ok:
        print("cd bench acceptance FAILED (speedup >= 1.5x, f64 coeff "
              "parity <= 1e-9, 0 solver compiles across the timed "
              "active-set run)", file=sys.stderr)
        sys.exit(6)


def shard_main() -> None:
    """``python bench.py shard`` — entity-sharded GAME training on the
    simulated multi-controller runtime.

    One synthetic mixed-effect dataset (EQUAL rows per entity and fully
    dense RE features, so every entity's padded solve shapes are
    identical whatever the bucket composition — the sharded f64
    coefficients must be BIT-compatible with the single-process fit);
    1/2/4-process simulated runs (``testing.run_simulated_processes``,
    capped by ``BENCH_SHARD_PROCS``), each warmed once so the timed run
    pays no compiles. Per shard count it records wall-clock, bytes
    communicated per sweep (the changed-row score exchange —
    ``comm_bytes`` in the CD history), and peak per-process entity-table
    bytes (``RandomEffectTrainData.table_bytes``). The sharded runs also
    enforce a per-process table budget set BELOW the full table
    (``entity_table_budget_bytes``), and the bench proves the same budget
    makes the single-process run refuse to start — the "table that
    provably does not fit one process" demonstration.

    Acceptance (exit 8, distinct from stream/cd/serving's 5/6/7):
    f64 coefficients bit-equal across every shard count, max-process
    peak table < the single-process table, a nonzero communicated-bytes
    counter, and total exchange bytes at least 10x below shipping every
    full coefficient table once per sweep (the naive comparator).

    Sized by ``BENCH_SHARD_ENTITIES`` (default 768) and
    ``BENCH_SHARD_SWEEPS`` (default 14) so the CI smoke finishes in a
    couple of minutes."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    jax.config.update("jax_enable_x64", True)  # the bit-parity gate is f64
    import jax.numpy as jnp

    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        make_game_dataset,
    )
    from photon_ml_tpu.parallel.entity_shard import (
        EntityShardSpec,
        EntityTableBudgetError,
    )
    from photon_ml_tpu.testing import run_simulated_processes

    rng = np.random.default_rng(0)
    n_entities = int(os.environ.get("BENCH_SHARD_ENTITIES", 768))
    n_sweeps = int(os.environ.get("BENCH_SHARD_SWEEPS", 14))
    max_procs = int(os.environ.get("BENCH_SHARD_PROCS", 4))
    # wide per-entity dims, few rows per entity — the paper's cold-user
    # regime and exactly where the delta exchange wins: a sweep's changed
    # rows cost 12 B/row while a coefficient-shipping scheme moves
    # 8*d_re B/entity, so the per-sweep wire ratio is ~(8*96)/(4*12) = 16x
    # even when every entity re-solves (arXiv:1611.02101's communication
    # argument); frozen-frontier sweeps ship almost nothing on top
    rows_per_entity, d_g, d_u = 4, 8, 96
    w_fixed = rng.normal(size=d_g)
    U = rng.normal(size=(n_entities, d_u)) * 1.2
    Xg, Xu, y, uid = [], [], [], []
    for u in range(n_entities):
        xg = rng.normal(size=(rows_per_entity, d_g))
        xu = rng.normal(size=(rows_per_entity, d_u))
        marg = xg @ w_fixed + xu @ U[u]
        y.append((rng.random(rows_per_entity)
                  < 1 / (1 + np.exp(-marg))).astype(float))
        Xg.append(xg)
        Xu.append(xu)
        uid.append(np.full(rows_per_entity, u))
    Xg, Xu, y, uid = map(np.concatenate, (Xg, Xu, y, uid))
    ds = make_game_dataset({"g": Xg, "u": Xu}, y, entity_ids={"userId": uid})

    def coord_configs():
        return [
            CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                             reg_weight=2.0, tolerance=1e-12),
            # lbfgs: the measured CPU-default RE solver AND the one whose
            # batched kernels are bit-invariant to the entity-batch width
            # (batched-LU newton agrees only to ~1e-11 across widths —
            # docs/sharding.md); drift-free, so the active set freezes
            CoordinateConfig("per-user", coordinate_type="random",
                             feature_shard="u", entity_column="userId",
                             reg_type="l2", reg_weight=2.0, tolerance=1e-11,
                             optimizer="lbfgs", active_set=True,
                             refresh_every=6, active_tol=1e-10),
        ]

    def run_one(p, budget=None):
        def fn(rank):
            spec = EntityShardSpec(p, rank) if p > 1 else None
            cache = {}
            cd = CoordinateDescent(
                coord_configs(), task="logistic", n_iterations=n_sweeps,
                dtype=jnp.float64, entity_shard=spec, dataset_cache=cache,
                entity_table_budget_bytes=budget if p > 1 else None)
            model, history = cd.run(ds)
            # scalar fetch: the run has actually completed
            float(np.asarray(
                model.coordinates["fixed"].model.coefficients.means)[0])
            table = sum(v[1].table_bytes() for k_, v in cache.items()
                        if isinstance(k_, tuple) and k_ and k_[0] == "re_data")
            return {"model": model, "history": history,
                    "table_bytes": table}
        t0 = time.perf_counter()
        if p == 1:
            outs = [fn(0)]
        else:
            outs = run_simulated_processes(p, fn, join_timeout=1800)
        wall = time.perf_counter() - t0
        for o in outs:
            assert isinstance(o, dict), f"simulated process failed: {o!r}"
        return outs, wall

    def coeff_map(model):
        out = {}
        for b in model.coordinates["per-user"].buckets:
            proj = np.asarray(b.projection)
            C = np.asarray(b.coefficients)
            for r, eid in enumerate(b.entity_ids):
                valid = proj[r] >= 0
                w = np.zeros(d_u)
                w[proj[r][valid]] = C[r][valid]
                out[str(eid)] = w
        return out

    procs_list = [p for p in (1, 2, 4) if p <= max_procs]
    runs = {}
    single_table = None
    budget = None
    ref_coeffs = None
    ref_fixed = None
    parity = {}
    for p in procs_list:
        run_one(p, budget)  # warm-up: compile this shard count's ladder
        outs, wall = run_one(p, budget)
        peak_table = max(o["table_bytes"] for o in outs)
        hist = outs[0]["history"]
        re_records = [r for r in hist if r["coordinate"] == "per-user"]
        per_sweep = [int(r.get("comm_bytes", 0)) for r in re_records]
        comm_s = sum(float(r.get("comm_seconds", 0.0)) for r in hist)
        runs[str(p)] = {
            "wall_s": round(wall, 3),
            "peak_process_table_bytes": peak_table,
            "comm_bytes_total": int(sum(per_sweep)),
            "comm_bytes_per_sweep": per_sweep,
            "comm_seconds_total": round(comm_s, 4),
            "entities_solved_per_sweep": [
                int(r.get("entities_solved", 0)) for r in re_records],
        }
        if p == 1:
            single_table = peak_table
            # the budget the sharded runs must fit under — and the single
            # process provably cannot: 60% of the full table (every shard
            # holds ~1/p of it, well under at p >= 2)
            budget = int(single_table * 0.6)
            ref_coeffs = coeff_map(outs[0]["model"])
            ref_fixed = np.asarray(outs[0]["model"].coordinates["fixed"]
                                   .model.coefficients.means)
        else:
            got = coeff_map(outs[0]["model"])
            d_re = max(float(np.max(np.abs(got[k_] - ref_coeffs[k_])))
                       for k_ in ref_coeffs)
            d_fx = float(np.max(np.abs(
                np.asarray(outs[0]["model"].coordinates["fixed"]
                           .model.coefficients.means) - ref_fixed)))
            parity[str(p)] = {"re_coeff_max_abs_diff": d_re,
                              "fixed_coeff_max_abs_diff": d_fx}

    # the budget demonstration: the same budget every sharded run trained
    # under makes the single process refuse to start (1-sweep probe — the
    # check fires during state construction, before any solve)
    single_over_budget = False
    try:
        CoordinateDescent(coord_configs(), task="logistic", n_iterations=1,
                          dtype=jnp.float64,
                          entity_table_budget_bytes=budget).run(ds)
    except EntityTableBudgetError:
        single_over_budget = True

    p_max = procs_list[-1]
    peak_max = runs[str(p_max)]["peak_process_table_bytes"]
    comm_total = runs[str(p_max)]["comm_bytes_total"]
    # naive comparator: a coefficient-shipping scheme moves at least the
    # full per-entity table once per sweep (one broadcast's worth — the
    # most charitable accounting for it)
    naive_per_sweep = n_entities * d_u * 8
    naive_total = naive_per_sweep * n_sweeps
    record = {
        "environment": _environment(),
        "metric": "entity_shard_peak_table_reduction",
        "value": (round(single_table / max(peak_max, 1), 3)
                  if p_max > 1 else 1.0),
        "unit": (f"x peak per-process entity-table bytes, 1-process / "
                 f"{p_max}-process simulated ({jax.devices()[0].platform}, "
                 f"f64, entities={n_entities}, rows={len(y)}, d_re={d_u}, "
                 f"sweeps={n_sweeps}; wall/comm per shard count in "
                 "fields; simulated processes share one interpreter, so "
                 "wall-clock is GIL-bound — the scaling claims are the "
                 "table bytes and the exchange bytes)"),
        "entities": n_entities,
        "rows": int(len(y)),
        "d_re": d_u,
        "sweeps": n_sweeps,
        "runs": runs,
        "coeff_parity_vs_single": parity,
        "single_process_table_bytes": single_table,
        "table_budget_bytes": budget,
        "single_process_refuses_over_budget": single_over_budget,
        "naive_full_table_bytes_per_sweep": naive_per_sweep,
        "naive_full_table_bytes_total": naive_total,
        "delta_exchange_vs_naive_ratio": (
            round(naive_total / comm_total, 2) if comm_total else None),
    }
    ok = (p_max > 1
          and all(v["re_coeff_max_abs_diff"] == 0.0
                  and v["fixed_coeff_max_abs_diff"] == 0.0
                  for v in parity.values())
          and peak_max < single_table
          and comm_total > 0
          and naive_total >= 10 * comm_total
          and single_over_budget)
    record["acceptance_ok"] = ok
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_shard.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    if not ok:
        print("shard bench acceptance FAILED (f64 bit parity, peak table "
              "< single-process, nonzero comm bytes >= 10x under full-"
              "table shipping, budget refusal on one process)",
              file=sys.stderr)
        sys.exit(8)


def recovery_main() -> None:
    """``python bench.py recovery`` — time-to-recover for in-job elastic
    recovery vs the cold-restart comparator.

    One synthetic mixed-effect dataset (EQUAL rows per entity, fully
    dense RE features — the same bit-compatible shape discipline as the
    shard bench), 4-process entity-sharded runs on the simulated
    multi-controller runtime:

    * warm-up runs compile BOTH shard ladders (the 4-shard layout and
      the 3-shard survivor layout) so neither timed arm pays compiles —
      the same warm-vs-warm discipline as every other mode here;
    * a timed CLEAN 4-process run — the reference f64 coefficients and
      the cold-restart comparator (a restart re-pays at least this);
    * a clean run with per-sweep :class:`RecoveryManager` snapshots —
      prices the steady-state snapshot overhead;
    * the CRASHED run: ``fault_injection.crash_schedule`` drop-kills one
      rank mid-sweep; the three survivors classify the failure, reform
      onto a 3-shard owner map, redistribute the dead rank's entities
      from the last committed snapshot, and finish in-job. Stats
      ``recovery_seconds`` (failure detection -> recovered force-commit)
      is the time-to-recover number.

    Acceptance (exit 10, distinct from stream/cd/serving/shard/trace's
    5/6/7/8/9): every survivor's f64 coefficients bit-equal to the clean
    run's, at least one recovery recorded, and max survivor
    time-to-recover <= 0.5x the clean-run wall-clock.

    Writes ``BENCH_recovery.json`` and prints the same JSON. Sized by
    ``BENCH_RECOVERY_ENTITIES`` (default 256) and
    ``BENCH_RECOVERY_SWEEPS`` (default 10)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("PHOTON_ML_TPU_BARRIER_TIMEOUT_S", "120")
    import shutil
    import tempfile

    import jax

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    jax.config.update("jax_enable_x64", True)  # the bit-parity gate is f64
    import jax.numpy as jnp

    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        make_game_dataset,
    )
    from photon_ml_tpu.parallel import fault_injection
    from photon_ml_tpu.parallel.entity_shard import EntityShardSpec
    from photon_ml_tpu.parallel.recovery import RecoveryManager
    from photon_ml_tpu.testing import Dropped, run_simulated_processes

    rng = np.random.default_rng(0)
    n_entities = int(os.environ.get("BENCH_RECOVERY_ENTITIES", 256))
    n_sweeps = int(os.environ.get("BENCH_RECOVERY_SWEEPS", 10))
    procs, victim = 4, 2
    rows_per_entity, d_g, d_u = 4, 8, 32
    w_fixed = rng.normal(size=d_g)
    U = rng.normal(size=(n_entities, d_u)) * 1.2
    Xg, Xu, y, uid = [], [], [], []
    for u in range(n_entities):
        xg = rng.normal(size=(rows_per_entity, d_g))
        xu = rng.normal(size=(rows_per_entity, d_u))
        marg = xg @ w_fixed + xu @ U[u]
        y.append((rng.random(rows_per_entity)
                  < 1 / (1 + np.exp(-marg))).astype(float))
        Xg.append(xg)
        Xu.append(xu)
        uid.append(np.full(rows_per_entity, u))
    Xg, Xu, y, uid = map(np.concatenate, (Xg, Xu, y, uid))
    ds = make_game_dataset({"g": Xg, "u": Xu}, y, entity_ids={"userId": uid})

    def coord_configs():
        # lbfgs RE solver: bit-invariant to entity-batch width, so the
        # survivor layout's re-bucketed solves stay on the reference
        # trajectory (same reasoning as the shard bench)
        return [
            CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                             reg_weight=2.0, tolerance=1e-12),
            CoordinateConfig("per-user", coordinate_type="random",
                             feature_shard="u", entity_column="userId",
                             reg_type="l2", reg_weight=2.0, tolerance=1e-11,
                             optimizer="lbfgs", active_set=True,
                             refresh_every=6, active_tol=1e-10),
        ]

    def coeff_map(model):
        out = {}
        for b in model.coordinates["per-user"].buckets:
            proj = np.asarray(b.projection)
            C = np.asarray(b.coefficients)
            for r, eid in enumerate(b.entity_ids):
                valid = proj[r] >= 0
                w = np.zeros(d_u)
                w[proj[r][valid]] = C[r][valid]
                out[str(eid)] = w
        return out

    snap_root = tempfile.mkdtemp(prefix="bench-recovery-")

    def run_ranks(n_procs, recovery_dir=None, kill_occurrence=None):
        def fn(rank):
            rec = None
            if recovery_dir is not None:
                rec = RecoveryManager(recovery_dir, max_rank_failures=1,
                                      snapshot_every=1, backoff_s=0.01,
                                      jitter=0.0)
            cd = CoordinateDescent(
                coord_configs(), task="logistic", n_iterations=n_sweeps,
                dtype=jnp.float64,
                entity_shard=EntityShardSpec(n_procs, rank), recovery=rec)
            model, history = cd.run(ds)
            # scalar fetch: the run has actually completed
            float(np.asarray(
                model.coordinates["fixed"].model.coefficients.means)[0])
            return {"model": model,
                    "recovery": rec.as_dict() if rec is not None else None}
        if kill_occurrence is not None:
            fault_injection.install(fault_injection.crash_schedule(
                (victim, "cd.step", kill_occurrence)))
        t0 = time.perf_counter()
        try:
            outs = run_simulated_processes(n_procs, fn, join_timeout=1800)
        finally:
            if kill_occurrence is not None:
                fault_injection.clear()
        return outs, time.perf_counter() - t0

    try:
        # warm BOTH ladders: the 4-shard layout and the survivor 3-shard
        # layout the crashed run reforms onto
        run_ranks(procs)
        run_ranks(procs - 1)

        outs, wall_clean = run_ranks(procs)
        for o in outs:
            assert isinstance(o, dict), f"clean run failed: {o!r}"
        ref_coeffs = coeff_map(outs[0]["model"])
        ref_fixed = np.asarray(outs[0]["model"].coordinates["fixed"]
                               .model.coefficients.means)

        outs, wall_snap = run_ranks(
            procs, recovery_dir=os.path.join(snap_root, "clean"))
        for o in outs:
            assert isinstance(o, dict), f"snapshot run failed: {o!r}"
        snap_stats = outs[0]["recovery"]

        # kill the victim mid-run: cd.step fires once per coordinate per
        # sweep (2 coordinates), so occurrence 2*s+1 dies inside sweep
        # s's random-effect step
        kill_occ = 2 * (n_sweeps // 2) + 1
        outs, wall_crashed = run_ranks(
            procs, recovery_dir=os.path.join(snap_root, "crashed"),
            kill_occurrence=kill_occ)
        survivors, recovery_s, recoveries = {}, [], []
        for r, o in enumerate(outs):
            if r == victim:
                assert isinstance(o, (BaseException, Dropped)), (
                    f"victim rank survived: {o!r}")
                continue
            assert isinstance(o, dict), f"survivor rank {r} failed: {o!r}"
            got = coeff_map(o["model"])
            d_re = max(float(np.max(np.abs(got[k_] - ref_coeffs[k_])))
                       for k_ in ref_coeffs)
            d_fx = float(np.max(np.abs(
                np.asarray(o["model"].coordinates["fixed"]
                           .model.coefficients.means) - ref_fixed)))
            stats = o["recovery"]
            survivors[str(r)] = {
                "re_coeff_max_abs_diff": d_re,
                "fixed_coeff_max_abs_diff": d_fx,
                "recovery_seconds": stats["recovery_seconds"],
                "recoveries": stats["recoveries"],
                "rank_failures": stats["rank_failures"],
                "members": stats["members"],
            }
            recovery_s.append(float(stats["recovery_seconds"]))
            recoveries.append(int(stats["recoveries"]))
    finally:
        shutil.rmtree(snap_root, ignore_errors=True)

    time_to_recover = max(recovery_s) if recovery_s else float("inf")
    record = {
        "environment": _environment(),
        "metric": "recovery_vs_cold_restart",
        "value": (round(time_to_recover / wall_clean, 4)
                  if wall_clean else None),
        "unit": (f"x of the clean {procs}-process wall-clock spent "
                 "recovering in-job from one mid-sweep rank kill "
                 f"({jax.devices()[0].platform}, f64, "
                 f"entities={n_entities}, d_re={d_u}, sweeps={n_sweeps}; "
                 "cold restart re-pays >= 1.0x; both shard ladders "
                 "warmed so neither arm pays compiles)"),
        "entities": n_entities,
        "d_re": d_u,
        "sweeps": n_sweeps,
        "processes": procs,
        "victim_rank": victim,
        "kill_site": f"cd.step occurrence {kill_occ}",
        "clean_wall_s": round(wall_clean, 3),
        "snapshot_wall_s": round(wall_snap, 3),
        "snapshot_overhead_pct": (
            round((wall_snap - wall_clean) / wall_clean * 100.0, 2)
            if wall_clean else None),
        "snapshot_stats_clean": snap_stats,
        "crashed_wall_s": round(wall_crashed, 3),
        "time_to_recover_s": round(time_to_recover, 4),
        "survivors": survivors,
    }
    ok = (bool(survivors)
          and all(v["re_coeff_max_abs_diff"] == 0.0
                  and v["fixed_coeff_max_abs_diff"] == 0.0
                  for v in survivors.values())
          and all(n >= 1 for n in recoveries)
          and time_to_recover <= 0.5 * wall_clean)
    record["acceptance_ok"] = ok
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_recovery.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    if not ok:
        print("recovery bench acceptance FAILED (survivor f64 bit parity "
              "vs the clean run, >= 1 recovery recorded, time-to-recover "
              "<= 0.5x the clean-run wall)", file=sys.stderr)
        sys.exit(10)


def trace_main() -> None:
    """``python bench.py trace`` — the observability off-switch gate.

    The tracer's contract (obs/trace.py) is that instrumented hot paths
    cost nearly nothing when tracing is off: every ``trace.span(...)``
    reduces to one module-global None check returning a shared null
    context manager. This bench prices that claim on the two hot paths
    that carry the densest instrumentation:

    * ``streamed_fit`` — a small out-of-core ``fit_streaming`` run over
      an on-disk Avro shard (stream.upload spans + prefetch metrics on
      every chunk of every optimizer pass);
    * ``serving_closed_loop`` — sequential ``/score`` requests through
      ``ScoringService.handle_score`` under a per-request
      ``request_context`` (batch.execute / session.resolve /
      paged.fault_install / session.device_compute spans per batch).

    Per leg: warm once, time K tracing-OFF runs, then K tracing-ON runs
    (sample=1.0, big ring, no export thread) counting recorded events.
    Two overhead numbers come out:

    * ``off_overhead_pct`` — the DOCUMENTED gate (<= 2%, exit 9): the
      per-disabled-span cost (microbenchmarked, ~100ns) times the span
      emissions the leg actually makes (counted from the ON run),
      over the OFF wall-clock. This is a deterministic upper bound on
      what the instrumentation costs a production run with tracing off
      — an interleaved wall-diff at the 2% scale would be noise.
    * ``on_overhead_pct`` — (wall_on - wall_off)/wall_off, documented
      for operators deciding whether always-on sampling is affordable
      (noisy on a busy container; can read negative at small scale).

    Writes ``BENCH_trace.json`` (whose ``trace_off_overhead_pct_max``
    every other bench mode embeds via ``_environment``) and prints the
    same JSON. Sized by ``BENCH_TRACE_REPS`` / ``BENCH_TRACE_ROWS``."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import shutil
    import tempfile

    import jax

    from photon_ml_tpu.utils import apply_env_platforms

    apply_env_platforms()
    import jax.numpy as jnp  # noqa: F401  (platform init before obs use)

    from photon_ml_tpu.obs import trace

    assert trace.active_tracer() is None, "bench must start tracing-off"

    # -- the disabled-path unit cost: one module-global check + a shared
    # null context manager per span call
    n_calls = 200_000
    for _ in range(1000):  # warm the bytecode path
        with trace.span("bench.noop", cat="bench"):
            pass
    t0 = time.perf_counter()
    for _ in range(n_calls):
        with trace.span("bench.noop", cat="bench"):
            pass
    disabled_span_ns = (time.perf_counter() - t0) / n_calls * 1e9

    repeats = int(os.environ.get("BENCH_TRACE_REPEATS", 3))

    def measure(leg_fn):
        leg_fn()  # warm: compiles + caches out of both arms
        walls_off = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            leg_fn()
            walls_off.append(time.perf_counter() - t0)
        td = tempfile.mkdtemp(prefix="bench-trace-")
        walls_on, events = [], 0
        trace.start(td, sample=1.0, ring_size=1 << 20,
                    export_thread=False)
        try:
            for _ in range(repeats):
                t0 = time.perf_counter()
                leg_fn()
                walls_on.append(time.perf_counter() - t0)
            t = trace.active_tracer()
            events = len(t._events) + t._dropped
        finally:
            trace.stop()
            shutil.rmtree(td, ignore_errors=True)
        wall_off, wall_on = min(walls_off), min(walls_on)
        spans_per_run = events / repeats
        off_pct = (spans_per_run * disabled_span_ns * 1e-9
                   / wall_off * 100.0)
        on_pct = (wall_on - wall_off) / wall_off * 100.0
        return {
            "wall_off_s": round(wall_off, 4),
            "wall_on_s": round(wall_on, 4),
            "spans_per_run": round(spans_per_run, 1),
            "off_overhead_pct": round(off_pct, 4),
            "on_overhead_pct": round(on_pct, 2),
        }

    # -- leg 1: streamed fit ------------------------------------------------
    from photon_ml_tpu.io.data_reader import write_training_examples
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.stream_source import AvroChunkSource
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel.streaming import fit_streaming

    rng = np.random.default_rng(0)
    n = int(os.environ.get("BENCH_TRACE_ROWS", 6000))
    vocab, max_k, chunk_rows = 96, 12, 1024
    rows = []
    for _ in range(n):
        k = int(rng.integers(3, max_k + 1))
        cols = rng.choice(vocab, size=k, replace=False)
        rows.append([(f"feature_{c:04d}", "", float(rng.normal()))
                     for c in cols])
    labels = rng.integers(0, 2, n).astype(float)
    root = tempfile.mkdtemp(prefix="bench-trace-data-")
    try:
        path = os.path.join(root, "train.avro")
        write_training_examples(path, rows, labels, block_size=512)
        imap = IndexMap({f"feature_{c:04d}": c for c in range(vocab)},
                        add_intercept=True)
        src = AvroChunkSource(path, imap, chunk_rows=chunk_rows)
        obj = make_objective("logistic")
        cfg = OptimizerConfig(max_iters=4, tolerance=0.0)

        def stream_leg():
            res = fit_streaming(obj, src, src.dim, l2=0.5, config=cfg)
            float(res.value)  # scalar fetch: the fit actually completed

        stream_stats = measure(stream_leg)

        # -- leg 2: serving closed loop ------------------------------------
        from photon_ml_tpu.game.descent import (
            CoordinateConfig,
            CoordinateDescent,
            make_game_dataset,
        )
        from photon_ml_tpu.io.model_io import save_game_model
        from photon_ml_tpu.serve import (
            MicroBatcher,
            ScoringService,
            ScoringSession,
        )

        n_s, d_fix, d_re, n_entities = 600, 32, 8, 64
        Xg = rng.normal(size=(n_s, d_fix))
        Xu = rng.normal(size=(n_s, d_re))
        uid = rng.integers(0, n_entities, n_s)
        y = (rng.random(n_s) < 0.5).astype(float)
        ds = make_game_dataset({"g": Xg, "u": Xu}, y,
                               entity_ids={"userId": uid})
        cd = CoordinateDescent(
            [CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                              reg_weight=1.0),
             CoordinateConfig("per-user", coordinate_type="random",
                              feature_shard="u", entity_column="userId",
                              reg_type="l2", reg_weight=1.0)],
            task="logistic")
        model, _ = cd.run(ds)
        model_dir = os.path.join(root, "model")
        save_game_model(model, model_dir, {
            "g": IndexMap({f"g{j}": j for j in range(d_fix)}),
            "u": IndexMap({f"u{j}": j for j in range(d_re)}),
        })
        session = ScoringSession(model_dir, max_batch=64,
                                 coeff_cache_entries=n_entities,
                                 paged_table=True)
        svc = ScoringService(
            session,
            MicroBatcher(session.score_rows, max_batch=64,
                         max_delay_ms=0.5, metrics=session.metrics),
            request_timeout_s=30.0)
        score_rows = [{
            "features": (
                [{"name": f"g{j}", "value": float(Xg[i, j])}
                 for j in range(d_fix)]
                + [{"name": f"u{j}", "value": float(Xu[i, j])}
                   for j in range(d_re)]),
            "entityIds": {"userId": str(uid[i])},
        } for i in range(64)]
        reps = int(os.environ.get("BENCH_TRACE_REPS", 40))

        def serve_leg():
            for r in range(reps):
                with trace.request_context(request_id=f"bench-{r}"):
                    status, _ = svc.handle_score({"rows": score_rows},
                                                 request_id=f"bench-{r}")
                assert status == 200, f"bench request failed: {status}"

        serve_stats = measure(serve_leg)
        svc.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    worst_off = max(stream_stats["off_overhead_pct"],
                    serve_stats["off_overhead_pct"])
    record = {
        "environment": _environment(),
        "metric": "trace_off_overhead_pct_max",
        "value": round(worst_off, 4),
        "unit": ("% of leg wall-clock, worst leg; disabled-span upper "
                 f"bound = spans/run x {disabled_span_ns:.0f}ns over the "
                 "tracing-off wall (streamed-fit + serving closed-loop "
                 "legs in fields; on_overhead_pct is the interleaved "
                 "tracing-on wall diff, noisy at this scale)"),
        "trace_off_overhead_pct_max": round(worst_off, 4),
        "disabled_span_ns": round(disabled_span_ns, 1),
        "repeats": repeats,
        "legs": {"streamed_fit": stream_stats,
                 "serving_closed_loop": serve_stats},
    }
    ok = worst_off <= 2.0
    record["acceptance_ok"] = ok
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_trace.json"), "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    if not ok:
        print("trace bench acceptance FAILED (tracing-off overhead must "
              "stay <= 2% on both legs)", file=sys.stderr)
        sys.exit(9)


def _baseline() -> "tuple[float, str] | None":
    """The honest comparator for ``vs_baseline``.

    Preferred: the explicit record in ``BENCH_BASELINE.json`` — written
    because the mechanical "newest prior round > 0" rule resolves to
    BENCH_r02.json's 17.77M passes/s, which docs/PERF.md documents as a
    measurement artifact (per-call recompile + memoized warm-up==timed
    execution on the axon backend); dividing an honest number by an
    artifact would misbrand it a regression (VERDICT r3 weak #3).
    Fallback: the newest prior BENCH_r*.json with value > 0 that is not
    listed in the baseline file's ``artifact_rounds``."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    artifact_rounds: set = set()
    base_path = os.path.join(here, "BENCH_BASELINE.json")
    if os.path.exists(base_path):
        try:
            with open(base_path) as f:
                base = json.load(f)
            artifact_rounds = set(base.get("artifact_rounds", []))
            if float(base.get("value", 0.0)) > 0:
                return float(base["value"]), str(base.get("label", "pinned"))
        except Exception as e:
            # a malformed pin must NOT silently fall back to scanning with
            # an empty artifact list — that would resurrect the r02
            # artifact as comparator, the exact misbranding this file
            # exists to prevent
            print(f"BENCH_BASELINE.json unreadable ({e}); vs_baseline "
                  "reported as 1.0 (no comparator)", file=sys.stderr)
            return None
    best = None
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m or int(m.group(1)) in artifact_rounds:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
            prior = float(rec.get("parsed", rec).get("value", 0.0))
        except Exception:
            continue
        if prior > 0:
            best = (int(m.group(1)), prior)
    if best is None:
        return None
    return best[1], f"BENCH_r{best[0]:02d}"


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "degrade":
        degrade_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "serving":
        serving_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "affinity":
        affinity_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "swap":
        swap_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "stream":
        stream_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "cd":
        cd_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "path":
        path_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "shard":
        shard_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "recovery":
        recovery_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "trace":
        trace_main()
    else:
        main()
