"""Headline benchmark: Criteo-shaped sparse logistic regression throughput.

Mirrors the north star in BASELINE.json ("Criteo-1TB logistic-reg wall-clock
vs 256-exec Spark") at single-run scale: a Criteo-like batch (39 nonzeros per
row, hashed feature space) trained with the distributed jitted L-BFGS path —
the exact hot loop SURVEY.md §4.2 identifies (the reference pays one cluster
treeAggregate round-trip per optimizer iteration; here an iteration is an
on-device fused pass + psum).

Metric: example-passes/second = rows x optimizer-iterations / wall-clock of
the jitted fit (compile time excluded; one warm-up fit on identical shapes
precedes the timed run). ``vs_baseline`` is reported against the recorded
reference baseline; BASELINE.json has ``"published": {}`` (no repo-published
numbers — see BASELINE.md), so the ratio is against our own round-1 number
once recorded; until then 1.0.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _arm_watchdog() -> None:
    """The TPU tunnel in this environment can wedge indefinitely (even
    ``jax.devices()`` then blocks). Rather than hang the driver's bench run,
    emit an honest zero-valued record and exit when nothing completes within
    BENCH_TIMEOUT_S (default 20 min — far above a normal compile+run)."""
    import threading

    timeout = float(os.environ.get("BENCH_TIMEOUT_S", 1200))

    def fire():
        print(json.dumps({
            "metric": "criteo_shaped_logreg_lbfgs_example_passes_per_sec",
            "value": 0.0,
            "unit": f"TIMEOUT after {timeout:.0f}s (device unreachable or "
                    "run wedged) — no measurement",
            "vs_baseline": 0.0,
        }), flush=True)
        os._exit(2)

    t = threading.Timer(timeout, fire)
    t.daemon = True
    t.start()


def main() -> None:
    _arm_watchdog()
    import jax

    # The axon sitecustomize force-sets jax_platforms=axon,cpu at interpreter
    # startup, overriding the JAX_PLATFORMS env var; honor the env var again
    # so CPU runs don't try to initialize the TPU tunnel.
    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except RuntimeError:
            pass
    import jax.numpy as jnp

    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel.data_parallel import fit_distributed
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.types import LabeledBatch, SparseFeatures

    platform = jax.devices()[0].platform
    # Criteo shape: 39 features/row. Sized to finish the timed fit in
    # seconds; CPU fallback keeps CI/driver runs fast.
    if platform == "cpu":
        n_rows, dim, iters = 1 << 15, 1 << 14, 10
    else:
        n_rows, dim, iters = 1 << 21, 1 << 18, 20
    k = 39

    # Synthesize the dataset ON DEVICE: the axon tunnel to the TPU wedges on
    # bulk host->device transfers, and a transfer would time the pipe, not
    # the hot loop. jit'd jax.random keeps everything in HBM.
    @jax.jit
    def make_data(key):
        k_idx, k_w, k_lab = jax.random.split(key, 3)
        indices = jax.random.randint(k_idx, (n_rows, k), 0, dim, jnp.int32)
        values = jnp.ones((n_rows, k), jnp.float32)
        w_true = jax.random.normal(k_w, (dim,), jnp.float32) * 0.5
        logits = jnp.sum(w_true[indices], axis=1)
        labels = (jax.random.uniform(k_lab, (n_rows,))
                  < jax.nn.sigmoid(logits)).astype(jnp.float32)
        return indices, values, labels

    indices, values, labels = jax.block_until_ready(
        make_data(jax.random.key(0))
    )

    mesh = make_mesh()
    obj = make_objective("logistic")
    batch = LabeledBatch(
        SparseFeatures(indices, values, dim=dim),
        labels,
        jnp.zeros((n_rows,), jnp.float32),
        jnp.ones((n_rows,), jnp.float32),
    )
    w0 = jnp.zeros((dim,), jnp.float32)
    # tolerance=0 pins the iteration count so the metric is deterministic
    cfg = OptimizerConfig(max_iters=iters, tolerance=0.0)

    def run(sparse_grad, n_iters):
        res = fit_distributed(
            obj, batch, mesh, w0, l2=1.0, optimizer="lbfgs",
            config=OptimizerConfig(max_iters=n_iters, tolerance=0.0),
            sparse_grad=sparse_grad,
        )
        jax.block_until_ready(res.w)
        return res

    # Two sparse-gradient strategies exist (scatter-add vs scatter-free CSC
    # prefix sums — types.CSCTranspose); which wins is hardware-dependent, so
    # calibrate with short fits unless pinned via BENCH_SPARSE_GRAD.
    mode = os.environ.get("BENCH_SPARSE_GRAD", "auto")
    if mode == "auto":
        times = {}
        for m in ("scatter", "csc", "csc_pallas"):
            try:
                run(m, 3)  # compile + warm-up
                t0 = time.perf_counter()
                run(m, 3)
                times[m] = time.perf_counter() - t0
            except Exception as e:  # a mode that fails to lower is skipped
                print(f"calibration: {m} failed: {e}", file=sys.stderr)
        mode = min(times, key=times.get)
        print(f"calibration: {times} -> {mode}", file=sys.stderr)

    run(mode, iters)  # compile + warm-up
    t0 = time.perf_counter()
    res = run(mode, iters)
    elapsed = time.perf_counter() - t0

    done = int(res.iterations)
    value = n_rows * max(done, 1) / elapsed
    print(json.dumps({
        "metric": "criteo_shaped_logreg_lbfgs_example_passes_per_sec",
        "value": round(value, 1),
        "unit": f"example-passes/sec ({platform}, {len(jax.devices())} dev, "
                f"n={n_rows}, d={dim}, k={k}, iters={done}, "
                f"sparse_grad={mode})",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
