"""photon-ml-tpu: a TPU-native rebuild of Photon ML (GLM + GAME mixed-effect models).

A from-scratch JAX/XLA framework with the capabilities of the reference
``matthieubulte/photon-ml`` (a fork of LinkedIn Photon ML — see SURVEY.md;
the read-only reference mount was empty this round, so citations point at
SURVEY.md sections rather than file:line).

Design stance (TPU-first, not a port):

* Examples live in batched, device-resident arrays (``LabeledBatch``) instead
  of per-row JVM objects; sparse features use a padded ELL layout that XLA
  tiles well, with an optional scatter-free column-sorted gradient path
  (``CSCTranspose``) and a Pallas fused-scan kernel for it.
* The reference's Spark ``treeAggregate`` of gradient partials becomes an
  on-device sharded sum + ``psum`` over ICI (``photon_ml_tpu.parallel``);
  multi-host scaling is the JAX multi-controller runtime
  (``parallel.multihost``), and larger-than-HBM datasets stream host chunks
  through the device (``parallel.streaming``).
* The reference's per-entity random-effect solves (``mapValues`` of local
  Breeze optimizers) are a ``vmap`` of fixed-shape local solves over entity
  shards (``photon_ml_tpu.game``), with subspace or count-sketch projectors.
* Optimizers (L-BFGS / OWL-QN / TRON) are jitted ``lax.while_loop`` update
  steps with on-device convergence tracking (``photon_ml_tpu.optimize``).
* Avro-in/Avro-out is preserved (``photon_ml_tpu.io``): training examples,
  models, scores, and feature summaries use the reference's record shapes,
  with JSON / native-mmap / hashing feature index backends.
"""

__version__ = "0.1.0"

from photon_ml_tpu.estimators import GameEstimator, GameTransformer
from photon_ml_tpu.game.descent import (
    CoordinateConfig,
    CoordinateDescent,
    GameDataset,
    make_game_dataset,
)
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    GeneralizedLinearModel,
    RandomEffectModel,
)
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.normalization import NormalizationContext, NormalizationType
from photon_ml_tpu.ops.objective import GLMObjective, make_objective
from photon_ml_tpu.ops.regularization import RegularizationContext, RegularizationType
from photon_ml_tpu.optimize import OptimizerConfig, get_optimizer
from photon_ml_tpu.types import LabeledBatch, SparseFeatures, make_batch

__all__ = [
    "Coefficients",
    "CoordinateConfig",
    "CoordinateDescent",
    "FixedEffectModel",
    "GLMObjective",
    "GameDataset",
    "GameEstimator",
    "GameModel",
    "GameTransformer",
    "GeneralizedLinearModel",
    "LabeledBatch",
    "NormalizationContext",
    "NormalizationType",
    "OptimizerConfig",
    "RandomEffectModel",
    "RegularizationContext",
    "RegularizationType",
    "SparseFeatures",
    "get_loss",
    "get_optimizer",
    "make_batch",
    "make_game_dataset",
    "make_objective",
]
