"""photon-ml-tpu: a TPU-native rebuild of Photon ML (GLM + GAME mixed-effect models).

A from-scratch JAX/XLA framework with the capabilities of the reference
``matthieubulte/photon-ml`` (a fork of LinkedIn Photon ML — see SURVEY.md;
the read-only reference mount was empty this round, so citations point at
SURVEY.md sections rather than file:line).

Design stance (TPU-first, not a port):

* Examples live in batched, device-resident arrays (``LabeledBatch``) instead
  of per-row JVM objects; sparse features use a padded ELL layout that XLA
  tiles well.
* The reference's Spark ``treeAggregate`` of gradient partials becomes an
  on-device sharded sum + ``psum`` over ICI (``photon_ml_tpu.parallel``).
* The reference's per-entity random-effect solves (``mapValues`` of local
  Breeze optimizers) become a ``vmap`` of fixed-shape local solves over
  entity shards (``photon_ml_tpu.game`` — under construction; the GAME
  layer is the next milestone after the GLM core).
* Optimizers (L-BFGS / OWL-QN / TRON) are jitted ``lax.while_loop`` update
  steps with on-device convergence tracking (``photon_ml_tpu.optimize``).
"""

__version__ = "0.1.0"

from photon_ml_tpu.types import LabeledBatch, SparseFeatures
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.normalization import NormalizationContext, NormalizationType
from photon_ml_tpu.ops.regularization import RegularizationContext, RegularizationType
