"""GameEstimator / GameTransformer: the spark.ml-style entry points.

Equivalent of the reference's ``estimators.GameEstimator`` and
``transformers.GameTransformer`` (SURVEY.md §3.2 layer 5; reference mount
empty): ``fit`` trains one GAME model per optimization configuration in a
grid (coordinate datasets are built once and reused across configs, as in
the reference), evaluates each on validation, and returns all
(model, results, config) triples; ``select_best`` picks by the primary
evaluator. ``GameTransformer.transform`` scores a dataset with a model.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.evaluation import EvaluationResults, get_evaluator
from photon_ml_tpu.game.descent import (
    CoordinateConfig,
    CoordinateDescent,
    GameDataset,
)
from photon_ml_tpu.game.scoring import score_game_model
from photon_ml_tpu.models import GameModel


@dataclasses.dataclass(frozen=True)
class GameFitResult:
    model: GameModel
    evaluation: Optional[EvaluationResults]
    configs: Tuple[CoordinateConfig, ...]
    history: List[dict]


@dataclasses.dataclass(frozen=True)
class GlmPathFitResult:
    """One lambda of a pathwise fixed-effect fit: the full-width result
    (``w`` scattered back; ``solver_tolerance``/``screened_dim`` set), the
    screening record, and validation metrics (empty without evaluators)."""

    reg_weight: float
    result: object          # optimize.common.OptimizationResult
    stats: object           # optimize.path.PathLambdaStats
    metrics: dict


class GlmPathEstimator:
    """Pathwise fixed-effect GLM over a lambda grid — the estimator face
    of ``optimize.path.PathSolver`` (docs/path.md): screening + KKT
    certification per lambda, one shared solver so the whole grid (and
    any later ``fit`` call on the same estimator) reuses warm states and
    the compiled restricted-bucket ladder.

    Pass exactly one of ``batch`` (in-memory ``LabeledBatch``) or
    ``chunks``/``dim`` (streamed host chunks) to ``fit``. The grid is
    solved in the order given (decreasing lambda screens best)."""

    def __init__(
        self,
        task: str = "logistic",
        reg_type: str = "elastic_net",
        elastic_net_alpha: float = 0.5,
        optimizer: str = "auto",
        evaluators: Sequence[str] = (),
        intercept_index: int = -1,
        mesh=None,
        dtype=jnp.float32,
        config=None,
        path_config=None,
    ):
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.optimize import OptimizerConfig, PathConfig

        self.task = task
        self.reg = RegularizationContext(reg_type, alpha=elastic_net_alpha)
        self.optimizer = optimizer
        self.evaluator_names = list(evaluators)
        self.intercept_index = intercept_index
        self.mesh = mesh
        self.dtype = dtype
        self.config = config if config is not None else OptimizerConfig()
        self.path_config = (path_config if path_config is not None
                            else PathConfig())
        self._solver = None

    def solver(self, batch=None, chunks=None, dim=None):
        """The shared PathSolver, built on first use and pinned to the
        first dataset seen (warm states are only meaningful on one
        dataset; pass a fresh estimator for a new one)."""
        if self._solver is None:
            from photon_ml_tpu.ops.objective import make_objective
            from photon_ml_tpu.optimize import PathSolver
            from photon_ml_tpu.parallel.mesh import make_mesh

            objective = make_objective(
                self.task, intercept_index=self.intercept_index)
            mesh = self.mesh if self.mesh is not None else make_mesh()
            self._solver = PathSolver(
                objective, self.reg, batch=batch, chunks=chunks, dim=dim,
                mesh=mesh, optimizer=self.optimizer, config=self.config,
                path_config=self.path_config, dtype=self.dtype)
        return self._solver

    def fit(
        self,
        reg_weights: Sequence[float],
        batch=None,
        chunks=None,
        dim=None,
        validation_batch=None,
        tol_schedule=None,
    ) -> List[GlmPathFitResult]:
        solver = self.solver(batch=batch, chunks=chunks, dim=dim)
        out: List[GlmPathFitResult] = []
        for li, lam in enumerate(reg_weights):
            tol = None
            if tol_schedule is not None:
                tol = tol_schedule.at(li, self.config.tolerance)
            res, stats = solver.solve(lam, tolerance=tol)
            metrics = {}
            if validation_batch is not None and self.evaluator_names:
                scores = np.asarray(solver._objective.margins(
                    res.w, validation_batch))
                for name in self.evaluator_names:
                    metrics[name] = get_evaluator(name).evaluate(
                        scores, np.asarray(validation_batch.labels),
                        np.asarray(validation_batch.weights))
            out.append(GlmPathFitResult(float(lam), res, stats, metrics))
        return out

    def select_best(
        self, results: Sequence[GlmPathFitResult]
    ) -> GlmPathFitResult:
        if not results:
            raise ValueError("no fit results to select from")
        if not self.evaluator_names or not results[0].metrics:
            return results[0]
        primary = self.evaluator_names[0]
        ev = get_evaluator(primary)
        best = results[0]
        for r in results[1:]:
            if r.metrics and ev.better(r.metrics[primary],
                                       best.metrics[primary]):
                best = r
        return best


class GameEstimator:
    """Train GAME models over a grid of per-coordinate configurations."""

    def __init__(
        self,
        task: str = "logistic",
        n_iterations: int = 1,
        evaluators: Sequence[str] = (),
        mesh=None,
        dtype=jnp.float32,
        verbose: bool = False,
        cd_tolerance: float = 0.0,
        solver_tol_schedule=None,
        entity_shard=None,
        entity_table_budget_bytes=None,
        recovery=None,
    ):
        self.task = task
        self.n_iterations = n_iterations
        self.evaluator_names = list(evaluators)
        self.mesh = mesh
        self.dtype = dtype
        self.verbose = verbose
        # sweep-level early exit + inexact inner-solve schedule, passed
        # straight to CoordinateDescent (game/descent.py)
        self.cd_tolerance = cd_tolerance
        self.solver_tol_schedule = solver_tol_schedule
        # entity-sharded multi-controller training: this process's
        # EntityShardSpec plus the optional per-process entity-table
        # budget, passed straight to CoordinateDescent
        self.entity_shard = entity_shard
        self.entity_table_budget_bytes = entity_table_budget_bytes
        # parallel.recovery.RecoveryManager (or None): in-job rollback /
        # elastic-shrink recovery, shared across the whole grid (budgets
        # bound the job; each CoordinateDescent.run resets the pointers)
        self.recovery = recovery

    def fit(
        self,
        train: GameDataset,
        validation: Optional[GameDataset] = None,
        config_grid: Sequence[Sequence[CoordinateConfig]] = (),
        warm_start: Optional[GameModel] = None,
        locked: Sequence[str] = (),
        checkpoint_callback=None,
        fit_callback=None,
        dataset_cache: Optional[dict] = None,
    ) -> List[GameFitResult]:
        """Train one GAME model per grid point. ``checkpoint_callback(config_
        index, iteration, model)`` fires after each outer CD iteration;
        ``fit_callback(config_index, result)`` after each grid point.
        A dataset cache shared across grid points keeps the per-entity
        bucketing built once per (dataset, shard, entity, bucketing) combo;
        pass one explicitly to share it across ``fit`` calls too (the
        tuner does, so per-round refits don't rebuild it)."""
        if not config_grid:
            raise ValueError("config_grid must contain at least one configuration")
        results: List[GameFitResult] = []
        if dataset_cache is None:
            dataset_cache = {}
        for gi, configs in enumerate(config_grid):
            cd = CoordinateDescent(
                configs, task=self.task, n_iterations=self.n_iterations,
                mesh=self.mesh, evaluators=self.evaluator_names,
                dtype=self.dtype, verbose=self.verbose,
                dataset_cache=dataset_cache,
                cd_tolerance=self.cd_tolerance,
                solver_tol_schedule=self.solver_tol_schedule,
                entity_shard=self.entity_shard,
                entity_table_budget_bytes=self.entity_table_budget_bytes,
                recovery=self.recovery,
            )
            ckpt = None
            if checkpoint_callback is not None:
                ckpt = lambda it, model, gi=gi: checkpoint_callback(gi, it, model)
            model, history = cd.run(train, validation, warm_start=warm_start,
                                    locked=locked, checkpoint_callback=ckpt)
            evaluation = None
            if validation is not None and self.evaluator_names:
                # final metrics from the last history record
                metrics = {
                    name: history[-1][name]
                    for name in self.evaluator_names
                    if name in history[-1]
                }
                evaluation = EvaluationResults(metrics, self.evaluator_names[0])
            result = GameFitResult(model, evaluation, tuple(configs), history)
            results.append(result)
            if fit_callback is not None:
                fit_callback(gi, result)
        return results

    def select_best(self, results: Sequence[GameFitResult]) -> GameFitResult:
        """Pick the fit with the best primary validation metric (the model-
        selection step of GameTrainingDriver — SURVEY.md §4.1)."""
        if not results:
            raise ValueError("no fit results to select from")
        if results[0].evaluation is None:
            return results[0]
        ev = get_evaluator(results[0].evaluation.primary)
        best = results[0]
        for r in results[1:]:
            if r.evaluation is not None and ev.better(
                r.evaluation.primary_value, best.evaluation.primary_value
            ):
                best = r
        return best


class GameTransformer:
    """Score datasets with a trained GAME model."""

    def __init__(self, model: GameModel, dtype=jnp.float32):
        self.model = model
        self.dtype = dtype

    def transform(
        self,
        dataset: GameDataset,
        per_coordinate: bool = False,
    ):
        """Total scores (margins incl. offsets) for every row."""
        return score_game_model(
            self.model, dataset.features, dataset.entity_ids,
            offsets=dataset.offsets, dtype=self.dtype,
            per_coordinate=per_coordinate,
        )

    def predict_mean(self, dataset: GameDataset) -> np.ndarray:
        """Inverse-link applied to total scores (probabilities / rates)."""
        return np.asarray(self.model.loss.mean(self.transform(dataset)))

    def evaluate(self, dataset: GameDataset, evaluators: Sequence[str]):
        scores = np.asarray(self.transform(dataset))
        out = {}
        for name in evaluators:
            ev = get_evaluator(name)
            out[name] = ev.evaluate(scores, dataset.labels, dataset.weights,
                                    dataset.group_ids)
        return out
