"""Per-entity random-effect coefficient store with LRU eviction.

A GAME model's random effects can hold millions of per-entity coefficient
rows; a serving process must NOT require them all resident (that is the
batch loader's trade). This module keeps the HOT entities' coefficients in
memory behind an LRU and re-reads cold entities from the saved model
directory — the same ``coefficients.avro`` + index-map layout
``io/model_io`` writes, decoded through the same per-record helpers
(``entity_support_from_record`` / ``sketch_coefficients_from_record``), so
a cache entry can never diverge from what ``load_game_model`` would build.

An entity absent from the store is cached as ``None`` (negative entry):
the serving session then scores it with fixed effects only — byte-for-byte
the fallback ``game/scoring.py`` applies to unknown entities (their rows
are dropped from every random-effect score view, contributing score 0).
Negative entries occupy LRU slots like positive ones, so a scan of unknown
ids cannot pin the whole store in memory.

Cost model: a cold miss streams the coordinate's Avro file until the
entity's record (O(file) worst case); a first access builds a known-id set
in one streaming pass so ABSENT ids answer without touching the file
again. The LRU exists to make cold misses rare; size it to the working
set, not the model.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.parallel import fault_injection

__all__ = ["CoeffEntry", "EntityCoefficientLRU", "LayeredCoefficientStore",
           "ModelDirCoefficientStore"]


class CoeffEntry:
    """One entity's serving payload: ``local_map`` (global feature id ->
    local slot dict, or a shared SketchProjection) plus the matching local
    coefficient vector — exactly the pair ``_model_score_view`` derives
    from a loaded RandomEffectModel bucket row."""

    __slots__ = ("local_map", "coefficients")

    def __init__(self, local_map, coefficients: np.ndarray):
        self.local_map = local_map
        self.coefficients = np.asarray(coefficients, np.float64)

    @property
    def local_dim(self) -> int:
        return int(self.coefficients.shape[0])


class ModelDirCoefficientStore:
    """Cold-path loader over one random-effect coordinate of a saved model
    directory (the PalDB-backed-store role from the reference, built on
    this repo's persisted index maps + Avro records)."""

    def __init__(self, model_dir: str, name: str, imap,
                 projection_meta: Optional[dict] = None):
        self.model_dir = model_dir
        self.name = name
        self.imap = imap
        self.projection_meta = projection_meta
        self._sketch = None
        if projection_meta and projection_meta.get("type") == "random":
            from photon_ml_tpu.game.data import SketchProjection

            self._sketch = SketchProjection(
                int(projection_meta["dim"]),
                int(projection_meta.get("seed", 0)))
        self._known: Optional[frozenset] = None
        self._lock = threading.Lock()

    def _path(self) -> str:
        return os.path.join(self.model_dir, "random-effect", self.name,
                            "coefficients.avro")

    def known_ids(self) -> frozenset:
        """Every entity id present in the store (one streaming pass, ids
        only — payloads are not retained)."""
        with self._lock:
            if self._known is None:
                from photon_ml_tpu.io.avro import iter_avro_records

                self._known = frozenset(
                    str(rec["modelId"])
                    for rec in iter_avro_records([self._path()]))
            return self._known

    def _parse(self, rec) -> CoeffEntry:
        if self._sketch is not None:
            from photon_ml_tpu.io.model_io import (
                sketch_coefficients_from_record,
            )

            w = sketch_coefficients_from_record(rec, self._sketch.dim)
            return CoeffEntry(self._sketch, w)
        from photon_ml_tpu.io.model_io import entity_support_from_record

        ids, vals = entity_support_from_record(rec, self.imap)
        local_map = {int(g): s for s, g in enumerate(ids)}
        return CoeffEntry(local_map, vals)

    def load(self, entity_id: str) -> Optional[CoeffEntry]:
        """The entity's coefficients, or None when the store has no model
        for it (the caller caches that outcome as a negative entry)."""
        fault_injection.check("store.load")
        if str(entity_id) not in self.known_ids():
            return None
        from photon_ml_tpu.io.avro import iter_avro_records

        for rec in iter_avro_records([self._path()]):
            if str(rec["modelId"]) == str(entity_id):
                return self._parse(rec)
        return None  # pragma: no cover - known_ids guarantees a record

    def load_many(self, entity_ids: Sequence[str]
                  ) -> Dict[str, Optional[CoeffEntry]]:
        """Resolve a batch of ids in ONE streaming pass over the
        coordinate's file — a cold fault of m entities costs O(file), not
        O(m * file) as m single-entity :meth:`load` calls would (the
        paged table's install path and the LRU's batched misses come
        through here). Absent ids resolve to None without a file read."""
        fault_injection.check("store.load")
        known = self.known_ids()
        out: Dict[str, Optional[CoeffEntry]] = {}
        wanted = set()
        for eid in entity_ids:
            key = str(eid)
            if key in known:
                wanted.add(key)
            else:
                out[key] = None
        if wanted:
            from photon_ml_tpu.io.avro import iter_avro_records

            for rec in iter_avro_records([self._path()]):
                key = str(rec["modelId"])
                if key in wanted:
                    out[key] = self._parse(rec)
                    wanted.discard(key)
                    if not wanted:
                        break
        return out


class LayeredCoefficientStore:
    """Delta-chain resolution for per-entity coefficients: stores are
    ordered topmost (newest delta layer) first, and an entity resolves
    from the FIRST layer that knows it — a delta version's changed
    entities shadow the parent's records while untouched entities fall
    through to the parent chain (registry/delta.py). Same
    ``load``/``known_ids`` surface as :class:`ModelDirCoefficientStore`,
    so the LRU cannot tell a delta view from a full model."""

    def __init__(self, stores: Sequence):
        if not stores:
            raise ValueError("layered store needs at least one layer")
        self.stores = list(stores)

    def known_ids(self) -> frozenset:
        out: frozenset = frozenset()
        for s in self.stores:
            out = out | s.known_ids()
        return out

    def load(self, entity_id: str) -> Optional[CoeffEntry]:
        key = str(entity_id)
        for s in self.stores:
            if key in s.known_ids():
                return s.load(key)
        return None

    def load_many(self, entity_ids: Sequence[str]
                  ) -> Dict[str, Optional[CoeffEntry]]:
        """Batched delta-chain resolution: route each id to the FIRST
        layer that knows it, then one :meth:`load_many` pass per layer
        that owns any of the requested ids."""
        out: Dict[str, Optional[CoeffEntry]] = {}
        per_store: Dict[int, list] = {}
        routed = set()
        for eid in entity_ids:
            key = str(eid)
            if key in out or key in routed:
                continue
            for si, s in enumerate(self.stores):
                if key in s.known_ids():
                    per_store.setdefault(si, []).append(key)
                    routed.add(key)
                    break
            else:
                out[key] = None
        for si, keys in per_store.items():
            out.update(self.stores[si].load_many(keys))
        return out


class EntityCoefficientLRU:
    """Bounded LRU over :class:`CoeffEntry` payloads (negative entries
    included). ``loader`` is any ``entity_id -> CoeffEntry | None``
    callable — production passes :meth:`ModelDirCoefficientStore.load`;
    tests pass fakes to pin eviction/counter behaviour."""

    def __init__(self, loader: Callable[[str], Optional[CoeffEntry]],
                 capacity: int, metrics=None, batch_loader=None):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        self._loader = loader
        self._batch_loader = batch_loader  # ids -> {id: entry|None}
        self.capacity = int(capacity)
        self._data: "OrderedDict[str, Optional[CoeffEntry]]" = OrderedDict()
        self._lock = threading.Lock()
        self._metrics = metrics
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def cached_ids(self) -> Sequence[str]:
        """Current residents, least-recently-used first."""
        with self._lock:
            return list(self._data)

    def get(self, entity_id) -> Optional[CoeffEntry]:
        key = str(entity_id)
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                self.hits += 1
                if self._metrics is not None:
                    self._metrics.record_coeff(hits=1)
                return self._data[key]
            self.misses += 1
        # load OUTSIDE the lock: a cold miss may stream the model file
        entry = self._loader(key)
        evicted = 0
        with self._lock:
            self._data[key] = entry
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                evicted += 1
            self.evictions += evicted
            if self._metrics is not None:
                self._metrics.record_coeff(misses=1, evictions=evicted)
        return entry

    def prefetch(self, entity_ids) -> int:
        """Warm the cache with ``entity_ids`` WITHOUT touching the
        hit/miss counters — the hot-swap path seeds the new version's
        cache from the previous cache's resident set so the first
        post-swap requests do not pay a cold-read storm. Evictions are
        still counted (capacity is capacity). Returns the number of ids
        actually loaded."""
        loaded = 0
        for eid in entity_ids:
            key = str(eid)
            with self._lock:
                if key in self._data:
                    continue
            entry = self._loader(key)
            loaded += 1
            evicted = 0
            with self._lock:
                self._data[key] = entry
                self._data.move_to_end(key)
                while len(self._data) > self.capacity:
                    self._data.popitem(last=False)
                    evicted += 1
                self.evictions += evicted
                if self._metrics is not None and evicted:
                    self._metrics.record_coeff(evictions=evicted)
        return loaded

    def warm_entries(self, entity_ids) -> Dict[str, Optional[CoeffEntry]]:
        """Prefetch + return: load ``entity_ids`` WITHOUT touching the
        hit/miss counters (like :meth:`prefetch`) and hand the resolved
        entries back — the hot-swap path uses this to seed BOTH the new
        version's LRU and its paged device table from the previous hot
        set in one store pass (evictions still count)."""
        out: Dict[str, Optional[CoeffEntry]] = {}
        missing: list = []
        with self._lock:
            for eid in entity_ids:
                key = str(eid)
                if key in out or key in missing:
                    continue
                if key in self._data:
                    out[key] = self._data[key]
                else:
                    missing.append(key)
        if missing:
            if self._batch_loader is not None:
                loaded = self._batch_loader(missing)
            else:
                loaded = {key: self._loader(key) for key in missing}
            evicted = 0
            with self._lock:
                for key in missing:
                    entry = loaded.get(key)
                    out[key] = entry
                    self._data[key] = entry
                    self._data.move_to_end(key)
                while len(self._data) > self.capacity:
                    self._data.popitem(last=False)
                    evicted += 1
                self.evictions += evicted
            if self._metrics is not None and evicted:
                self._metrics.record_coeff(evictions=evicted)
        return out

    def resident_many(self, entity_ids) -> Dict[str, Optional[CoeffEntry]]:
        """Resolve ONLY the already-resident subset of ``entity_ids`` —
        the degraded-level-1 read: no loader call, no LRU reordering, no
        hit/miss accounting, so a brownout scoring pass cannot perturb
        the cache state the healthy path will resume with. Ids not in
        the cache are simply absent from the result."""
        out: Dict[str, Optional[CoeffEntry]] = {}
        with self._lock:
            for eid in entity_ids:
                key = str(eid)
                if key not in out and key in self._data:
                    out[key] = self._data[key]
        return out

    def get_many(self, entity_ids) -> Dict[str, Optional[CoeffEntry]]:
        """Resolve a batch of ids (deduplicated; order-preserving dict).
        With a ``batch_loader``, all of the batch's cold misses load in
        ONE store pass instead of one file scan per missing entity."""
        out: Dict[str, Optional[CoeffEntry]] = {}
        if self._batch_loader is None:
            for eid in entity_ids:
                key = str(eid)
                if key not in out:
                    out[key] = self.get(key)
            return out
        missing: list = []
        missing_set = set()
        hits = 0
        with self._lock:
            for eid in entity_ids:
                key = str(eid)
                if key in out or key in missing_set:
                    continue
                if key in self._data:
                    self._data.move_to_end(key)
                    hits += 1
                    out[key] = self._data[key]
                else:
                    missing.append(key)
                    missing_set.add(key)
            self.hits += hits
            self.misses += len(missing)
        if self._metrics is not None and hits:
            self._metrics.record_coeff(hits=hits)
        if not missing:
            return out
        # load OUTSIDE the lock: a cold batch may stream the model file
        loaded = self._batch_loader(missing)
        evicted = 0
        with self._lock:
            for key in missing:
                entry = loaded.get(key)
                out[key] = entry
                self._data[key] = entry
                self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                evicted += 1
            self.evictions += evicted
        if self._metrics is not None:
            self._metrics.record_coeff(misses=len(missing),
                                       evictions=evicted)
        return out

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0
