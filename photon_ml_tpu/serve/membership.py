"""Entity-affinity membership for the serving tier.

PR 7 partitioned *training* over entities with a stable-hash owner map
(``parallel/entity_shard.py``: splitmix64 for integer id dtypes, FNV-1a
64 otherwise) and PR 11 made that partition elastic — on a rank loss the
survivors recompute the map over the shrunken world and re-own the dead
rank's entities. This module is the serving twin: the SAME owner map,
computed over a replica set instead of a process grid, so that

* the front door routes a request's rows to the replica that OWNS their
  entities (each replica's paged table and LRU then hold only its slice
  — aggregate resident entities scale linearly with replicas instead of
  every replica paging the whole universe), and
* a membership change (replica join/leave/crash, breaker open) re-owns
  entities exactly the way training rank loss does: recompute the map
  over the survivors, hand the *moved* slice to its new owners, carry
  on.

Three pieces, one per side of the wire:

* :class:`MembershipEpoch` — the immutable versioned value both sides
  agree on: ``(epoch, replicas, id_kind)``. The replica tuple is sorted,
  and a replica's position IS its shard index, so
  ``EntityShardSpec(num_shards=len(replicas), shard_index=i)`` on the
  training side and ``epoch.owner_of`` here land every entity id on the
  same index (the train/serve parity test pins this for int and string
  id dtypes — the FNV-vs-splitmix edge lives in
  :func:`~photon_ml_tpu.parallel.entity_shard.serving_owner_of`).
* :class:`MembershipManager` — the front door's side: holds the current
  committed epoch, tracks the recently-routed hot entity ids (bounded),
  proposes a successor epoch when the live replica set changes, and
  computes which hot ids MOVE under the successor — the bounded handoff
  the rebalance prefetch walks into the new owners' paged tables before
  the epoch commits.
* :class:`MembershipView` — the replica's side: the latest epoch applied
  through ``POST /admin/membership`` (monotonic; stale epochs are
  refused), answering the one question the session asks per cold fault:
  "do I own this entity?".

The transport (epoch broadcast, prefetch push, failover routing) lives
in :class:`~photon_ml_tpu.serve.aserver.AsyncFrontDoor`; everything here
is pure state + arithmetic so it is testable without sockets and safe
under PT4xx's lock discipline (plain mutexes, no lock nesting, no
threads).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.parallel.entity_shard import serving_owner_of

__all__ = ["MembershipEpoch", "MembershipManager", "MembershipView"]

_ID_KINDS = ("auto", "int", "str")


@dataclasses.dataclass(frozen=True)
class MembershipEpoch:
    """One versioned (replica set + owner map) value.

    ``replicas`` is the sorted tuple of replica addresses; a replica's
    position in it is its shard index, so the owner map is fully
    determined by the tuple — no separate assignment table to drift out
    of sync. ``epoch`` is monotonically increasing across proposals;
    replicas refuse to apply a stale one.
    """

    epoch: int
    replicas: Tuple[str, ...]
    id_kind: str = "auto"

    def __post_init__(self):
        if self.epoch < 1:
            raise ValueError(f"epoch must be >= 1, got {self.epoch}")
        if not self.replicas:
            raise ValueError("an epoch needs at least one replica")
        if tuple(sorted(set(self.replicas))) != self.replicas:
            raise ValueError(
                f"replicas must be sorted and unique, got "
                f"{self.replicas!r}")
        if self.id_kind not in _ID_KINDS:
            raise ValueError(f"unknown id_kind {self.id_kind!r}")

    @property
    def num_shards(self) -> int:
        return len(self.replicas)

    def owner_of(self, entity_ids) -> np.ndarray:
        """int64 owning-replica index per entity id (the training-side
        ``EntityShardSpec.owner_of`` map over this replica set)."""
        return serving_owner_of(entity_ids, self.num_shards, self.id_kind)

    def owner_index(self, entity_id) -> int:
        return int(self.owner_of([entity_id])[0])

    def owner_address(self, entity_id) -> str:
        return self.replicas[self.owner_index(entity_id)]

    def payload(self, self_index: int,
                prefetch_entity_ids: Optional[Sequence[str]] = None
                ) -> dict:
        """The ``POST /admin/membership`` body for replica
        ``self_index``, optionally carrying the moved entity ids that
        replica must prefetch before the epoch commits."""
        body = {"epoch": self.epoch, "replicas": list(self.replicas),
                "selfIndex": int(self_index), "idKind": self.id_kind}
        if prefetch_entity_ids:
            body["prefetchEntityIds"] = list(prefetch_entity_ids)
        return body

    @classmethod
    def from_payload(cls, payload: dict) -> "MembershipEpoch":
        return cls(epoch=int(payload["epoch"]),
                   replicas=tuple(sorted(set(
                       str(r) for r in payload["replicas"]))),
                   id_kind=str(payload.get("idKind", "auto")))


class MembershipManager:
    """The front door's membership state: current committed epoch, the
    hot-id tracker, and the propose/moved/commit arithmetic. Transport-
    free by design (the front door owns the sockets)."""

    def __init__(self, replicas: Sequence[str], id_kind: str = "auto",
                 hot_track: int = 4096):
        if hot_track < 1:
            raise ValueError(f"hot_track must be >= 1, got {hot_track}")
        self._lock = threading.Lock()
        self._current = MembershipEpoch(
            1, tuple(sorted(set(str(r) for r in replicas))), id_kind)
        self._next_epoch = 2
        # recently-routed entity ids, insertion-ordered and bounded: the
        # candidate set for the rebalance prefetch. Bounded because the
        # handoff must be bounded — a join/leave moves at most this many
        # ids eagerly; colder entities fault through the LRU as always.
        self._hot: "OrderedDict[str, None]" = OrderedDict()
        self.hot_track = int(hot_track)

    @property
    def epoch(self) -> MembershipEpoch:
        with self._lock:
            return self._current

    def note_routed(self, entity_id: str) -> None:
        """Record a routed entity id into the bounded hot tracker."""
        key = str(entity_id)
        with self._lock:
            self._hot[key] = None
            self._hot.move_to_end(key)
            while len(self._hot) > self.hot_track:
                self._hot.popitem(last=False)

    def hot_ids(self) -> List[str]:
        with self._lock:
            return list(self._hot)

    def propose(self, replicas: Sequence[str]
                ) -> Optional[MembershipEpoch]:
        """The successor epoch over ``replicas``, or None when the set
        is unchanged from the committed epoch. Proposing does NOT
        commit — the caller pushes the epoch (and the moved-id
        prefetch) to every member first, then :meth:`commit`\\s."""
        members = tuple(sorted(set(str(r) for r in replicas)))
        with self._lock:
            if members == self._current.replicas:
                return None
            return MembershipEpoch(self._next_epoch, members,
                                   self._current.id_kind)

    def moved_ids(self, new: MembershipEpoch) -> Dict[int, List[str]]:
        """Hot entity ids whose owner CHANGES from the committed epoch
        to ``new``, grouped by their NEW owner's shard index — the
        bounded handoff set the rebalance prefetch walks. Ids whose
        owner is unchanged are never touched (their pages stay warm
        where they are)."""
        with self._lock:
            cur = self._current
            ids = list(self._hot)
        if not ids:
            return {}
        old_addr = [cur.replicas[i] for i in cur.owner_of(ids)]
        new_owner = new.owner_of(ids)
        moved: Dict[int, List[str]] = {}
        for eid, old_a, new_i in zip(ids, old_addr, new_owner):
            if new.replicas[int(new_i)] != old_a:
                moved.setdefault(int(new_i), []).append(eid)
        return moved

    def commit(self, new: MembershipEpoch) -> bool:
        """Install a proposed epoch (monotonic: a concurrent commit of
        a NEWER epoch wins and this one is dropped). Returns whether
        the epoch was installed."""
        with self._lock:
            if new.epoch <= self._current.epoch:
                return False
            self._current = new
            self._next_epoch = new.epoch + 1
            return True


class _Applied(object):
    """Immutable replica-side membership snapshot (swapped atomically)."""

    __slots__ = ("epoch", "num_shards", "shard_index", "id_kind")

    def __init__(self, epoch: int, num_shards: int, shard_index: int,
                 id_kind: str):
        self.epoch = int(epoch)
        self.num_shards = int(num_shards)
        self.shard_index = int(shard_index)
        self.id_kind = str(id_kind)


_NO_MEMBERSHIP = _Applied(0, 1, 0, "auto")


class MembershipView:
    """The membership a replica currently serves under. Starts inactive
    (epoch 0: the replica owns everything, pre-membership behavior is
    byte-identical to a non-affinity deployment); ``apply`` installs a
    newer epoch and refuses stale ones."""

    def __init__(self):
        self._lock = threading.Lock()
        self._applied = _NO_MEMBERSHIP

    def apply(self, epoch: int, num_shards: int, shard_index: int,
              id_kind: str = "auto") -> bool:
        """Install an epoch. Returns False (and changes nothing) when
        ``epoch`` is not newer than the applied one — the front door's
        broadcasts are monotonic, so a stale apply means a delayed or
        replayed message, never a legitimate rollback."""
        if num_shards < 1 or not 0 <= shard_index < num_shards:
            raise ValueError(
                f"shard_index must be in [0, {num_shards}), got "
                f"{shard_index}")
        if id_kind not in _ID_KINDS:
            raise ValueError(f"unknown id_kind {id_kind!r}")
        with self._lock:
            if int(epoch) <= self._applied.epoch:
                return False
            self._applied = _Applied(epoch, num_shards, shard_index,
                                     id_kind)
            return True

    @property
    def epoch(self) -> int:
        return self._applied.epoch

    @property
    def num_shards(self) -> int:
        return self._applied.num_shards

    @property
    def shard_index(self) -> int:
        return self._applied.shard_index

    @property
    def id_kind(self) -> str:
        return self._applied.id_kind

    @property
    def active(self) -> bool:
        """True when a real partition applies (an applied epoch with
        more than one shard) — with one shard (or pre-membership) the
        replica owns every entity and nothing is gated."""
        a = self._applied
        return a.epoch > 0 and a.num_shards > 1

    def owned_many(self, entity_ids) -> List[bool]:
        """Per-id ownership under the applied epoch (all-True when
        inactive)."""
        ids = list(entity_ids)
        a = self._applied
        if a.epoch <= 0 or a.num_shards <= 1 or not ids:
            return [True] * len(ids)
        owners = serving_owner_of(ids, a.num_shards, a.id_kind)
        return [int(o) == a.shard_index for o in owners]

    def owned(self, entity_id) -> bool:
        return self.owned_many([entity_id])[0]

    def describe(self) -> dict:
        a = self._applied
        return {"epoch": a.epoch, "numShards": a.num_shards,
                "shardIndex": a.shard_index, "idKind": a.id_kind}
