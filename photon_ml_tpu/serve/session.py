"""Resident scoring session: the device-side half of the serving stack.

A :class:`ScoringSession` loads a saved GAME model ONCE and answers
scoring batches for as long as the process lives:

* **Fixed effects resident on device.** Each fixed coordinate's
  coefficient vector is uploaded once per model version (through
  ``utils/transfer_budget`` — sanctioned, budget-accounted) and PASSED
  to the jit executables as an argument, so steady-state requests move
  only the batch's padded index/value arrays — and a hot swap to a new
  version reuses every compiled executable (see below).

* **Shape-bucketed compile cache.** XLA executables are specialized to
  input shapes, so naive serving would recompile on every new batch size
  — tens of ms to seconds of latency cliff, exactly the "keep the device
  fed with right-sized batches" failure mode the GPU-learning literature
  warns about (PAPERS.md). The session instead pads every batch up a
  bounded POWER-OF-TWO ladder of row counts (and one fixed nnz width per
  shard), pre-compiles the whole ladder at warmup, and counts
  hits/misses so a recompile in steady state is observable (the tier-1
  suite asserts the miss counter stays flat). Executables are keyed by
  ``(coefficient dim, rows, nnz)`` — NOT by model version — and take the
  coefficient vector as a runtime argument, which is what makes
  :meth:`swap` recompile-free: a new version with the same feature dims
  re-donates fresh device coefficients to the existing executables.

* **Random effects through the entity LRU.** Per-entity coefficients are
  fetched from :class:`~photon_ml_tpu.serve.coeff_cache
  .EntityCoefficientLRU`; a batch's score views are assembled with the
  SAME ``build_score_buckets`` / ``score_random_effect`` machinery the
  batch path uses, and the whole batch funnels through
  ``game.scoring.score_single_batch`` — one margin-math code path for
  offline and online scoring. Entities without a model contribute score
  0 (fixed-effect-only fallback), identical to ``score_game_model``.

* **Zero-downtime hot swap** (:meth:`swap`). All per-version state —
  loaded metadata, index maps, resident coefficient arrays, entity
  caches — lives in ONE immutable ``_ModelState``; a swap builds the
  next state off to the side (uploads, cache construction, optional
  warm-from-previous prefetch) and installs it with a single reference
  assignment, so an in-flight ``score_rows`` keeps its consistent
  snapshot and the next request sees the new version. The previous
  state is retained for :meth:`rollback` until the one after next.
  Sources: a model directory path, or a registry
  ``ResolvedVersion`` (a chain of model-dir layers, topmost first —
  delta versions resolve per-entity lookups down the chain through
  ``LayeredCoefficientStore``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import HostSparse
from photon_ml_tpu.game.scoring import score_single_batch
from photon_ml_tpu.io.model_io import (
    load_fixed_effect_coordinate,
    load_model_metadata,
)
from photon_ml_tpu.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.serve.coeff_cache import (
    EntityCoefficientLRU,
    LayeredCoefficientStore,
    ModelDirCoefficientStore,
)
from photon_ml_tpu.serve.metrics import ServingMetrics
from photon_ml_tpu.types import SparseFeatures, margins as _margins
from photon_ml_tpu.utils import resolve_dtype, transfer_budget

__all__ = ["ScoringSession", "bucket_ladder", "bucketize"]


def bucket_ladder(top: int, start: int = 1) -> List[int]:
    """Power-of-two ladder ``[start, 2*start, ...]`` whose last rung is
    the smallest power of two >= ``top``."""
    if top < 1:
        raise ValueError(f"ladder top must be >= 1, got {top}")
    out, b = [], max(1, start)
    while b < top:
        out.append(b)
        b *= 2
    out.append(b)
    return out


def bucketize(n: int, ladder: Sequence[int]) -> int:
    """Smallest ladder rung >= n; above the ladder, the next power of two
    (an off-ladder compile — counted as a cache miss, never silent)."""
    for b in ladder:
        if n <= b:
            return b
    b = ladder[-1]
    while b < n:
        b *= 2
    return b


class _ModelState:
    """Everything that changes when the served model changes — installed
    and read as one reference, never mutated after construction."""

    __slots__ = ("chain", "version", "task", "index_maps", "k_pad",
                 "model", "coeff_caches", "resident")

    def __init__(self, chain, version, task, index_maps, k_pad, model,
                 coeff_caches, resident):
        self.chain = chain
        self.version = version
        self.task = task
        self.index_maps = index_maps
        self.k_pad = k_pad
        self.model = model
        self.coeff_caches = coeff_caches
        self.resident = resident


def _layer_with(chain: Sequence[str], rel: str) -> Optional[str]:
    for d in chain:
        if os.path.exists(os.path.join(d, rel)):
            return d
    return None


class ScoringSession:
    """One resident GAME model + its pre-compiled scoring executables.

    Thread-safety: ``score_rows`` is safe to call from any thread (the
    compile cache takes a lock, per-version state is snapshotted once
    per call); the intended topology is a single
    :class:`~photon_ml_tpu.serve.batcher.MicroBatcher` worker calling
    it, with :meth:`swap` arriving from an admin endpoint or the
    registry watcher.

    Parameters:
      model_dir: saved model directory (``io/model_io`` layout) or a
        registry ``ResolvedVersion`` (duck-typed: ``.chain`` +
        ``.version``).
      dtype: scoring dtype ("float32"/"float64" or a jnp dtype); float64
        requires ``jax_enable_x64``.
      max_batch: top of the row-count bucket ladder; the micro-batcher's
        ``max_batch`` should equal it so no steady-state batch exceeds
        the pre-compiled shapes.
      pad_nnz: padded nonzero width per row (one per shard, clamped to
        the shard's feature-map size). A request row with more resolved
        features than this takes the uncompiled eager path (counted in
        ``fixed_eager_batches``) instead of minting a new executable.
      coeff_cache_entries: LRU capacity per random-effect coordinate.
      warmup: pre-compile the full ladder at construction (recommended;
        tests that exercise lazy compilation pass False).
    """

    def __init__(self, model_dir, *, dtype="float32",
                 max_batch: int = 64, pad_nnz: int = 64,
                 coeff_cache_entries: int = 4096,
                 metrics: Optional[ServingMetrics] = None,
                 warmup: bool = True):
        self.dtype = resolve_dtype(dtype) if isinstance(dtype, str) else dtype
        self.max_batch = int(max_batch)
        self.metrics = metrics or ServingMetrics()
        self.row_ladder = bucket_ladder(self.max_batch)
        self.fixed_eager_batches = 0
        self._pad_nnz = int(pad_nnz)
        self._coeff_cache_entries = int(coeff_cache_entries)

        # -- shape-bucketed compile cache: survives swaps by design ----
        self._compiled: Dict[tuple, object] = {}
        self._compile_lock = threading.Lock()
        self._swap_lock = threading.Lock()
        self._prev_state: Optional[_ModelState] = None
        self._state = self._build_state(model_dir)
        self.metrics.set_active_version(self._state.version)
        if warmup:
            self.warmup()

    # -- per-version state -------------------------------------------------
    def _build_state(self, source, version: Optional[str] = None
                     ) -> _ModelState:
        """Load one model version into an installable state: metadata,
        index maps, eager fixed-effect coordinates (uploaded to device
        through ``transfer_budget``), and entity-coefficient caches
        layered down a delta chain when the source is a resolved
        registry version."""
        chain = (list(source.chain) if hasattr(source, "chain")
                 else [str(source)])
        if version is None:
            version = getattr(source, "version", None) or chain[0]
        meta = load_model_metadata(chain[0])
        task = meta["task"]
        index_maps: Dict[str, object] = {}
        k_pad: Dict[str, int] = {}
        coords: Dict[str, object] = {}
        coeff_caches: Dict[str, EntityCoefficientLRU] = {}
        for c in meta["coordinates"]:
            shard = c["feature_shard"]
            if shard not in index_maps:
                from photon_ml_tpu.io.paldb import load_index_map

                layer = _layer_with(chain, f"index-map.{shard}.json")
                if layer is None:
                    raise FileNotFoundError(
                        f"index-map.{shard}.json missing from every "
                        f"layer of {chain}")
                imap = load_index_map(
                    os.path.join(layer, f"index-map.{shard}.json"))
                index_maps[shard] = imap
                k_pad[shard] = max(1, min(self._pad_nnz, imap.size))
            imap = index_maps[shard]
            if c["type"] == "fixed":
                rel = os.path.join("fixed-effect", c["name"],
                                   "coefficients.avro")
                layer = _layer_with(chain, rel)
                if layer is None:
                    raise FileNotFoundError(
                        f"{rel} missing from every layer of {chain}")
                coords[c["name"]] = load_fixed_effect_coordinate(
                    layer, c["name"], imap, task, shard)
            else:
                # bucketless stub: the coordinate participates in the
                # shared scoring loop, but its per-entity coefficients
                # come from the LRU, never from resident buckets
                coords[c["name"]] = RandomEffectModel(
                    c["name"], [], task, shard,
                    entity_column=c.get("entity_column", ""))
                rel = os.path.join("random-effect", c["name"],
                                   "coefficients.avro")
                stores = [
                    ModelDirCoefficientStore(d, c["name"], imap,
                                             c.get("projection"))
                    for d in chain
                    if os.path.exists(os.path.join(d, rel))
                ]
                store = (stores[0] if len(stores) == 1
                         else LayeredCoefficientStore(stores))
                coeff_caches[c["name"]] = EntityCoefficientLRU(
                    store.load, self._coeff_cache_entries,
                    metrics=self.metrics)
        model = GameModel(coords, task)

        # -- device residency: one budget-accounted upload per fixed
        # coordinate per VERSION (swaps re-upload; executables persist)
        resident: Dict[str, object] = {}
        for name, coord in model.coordinates.items():
            if isinstance(coord, FixedEffectModel):
                w = np.asarray(coord.model.coefficients.means,
                               np.dtype(self.dtype))
                resident[name] = transfer_budget.device_put(
                    w, what=f"serve.fixed[{name}]")
        return _ModelState(chain, str(version), task, index_maps, k_pad,
                           model, coeff_caches, resident)

    # -- compatibility views over the active state ------------------------
    @property
    def model_dir(self) -> str:
        return self._state.chain[0]

    @property
    def model(self) -> GameModel:
        return self._state.model

    @property
    def task(self) -> str:
        return self._state.task

    @property
    def active_version(self) -> str:
        return self._state.version

    @property
    def _index_maps(self):
        return self._state.index_maps

    @property
    def _k_pad(self):
        return self._state.k_pad

    @property
    def _coeff_caches(self):
        return self._state.coeff_caches

    # -- hot swap ----------------------------------------------------------
    def swap(self, source, *, version: Optional[str] = None,
             warm_from_previous: bool = True) -> str:
        """Atomically switch to another model version with zero downtime.

        Builds the whole next state off to the side — new fixed-effect
        coefficients uploaded through ``transfer_budget``, new entity
        caches over the new version's (possibly layered) store,
        optionally pre-warmed with the previous caches' resident hot set
        — then installs it with one reference assignment. The compiled
        executables are untouched: they are keyed by shape, not version,
        so a swap between same-dimensioned models never recompiles (the
        tier-1 suite pins the miss counter flat across a swap). The
        previous state is retained until the next swap so
        :meth:`rollback` is instant."""
        t0 = time.perf_counter()
        new = self._build_state(source, version)
        if warm_from_previous:
            for name, cache in new.coeff_caches.items():
                old = self._state.coeff_caches.get(name)
                if old is not None:
                    cache.prefetch(old.cached_ids())
        with self._swap_lock:
            self._prev_state, self._state = self._state, new
        self.metrics.record_swap(new.version,
                                 (time.perf_counter() - t0) * 1e3)
        return new.version

    def rollback(self) -> str:
        """Re-install the state the last swap replaced (its warmed
        entity caches and device arrays were retained for exactly
        this). Counts as a swap in the metrics."""
        t0 = time.perf_counter()
        with self._swap_lock:
            if self._prev_state is None:
                raise RuntimeError("no previous version to roll back to")
            self._prev_state, self._state = self._state, self._prev_state
            version = self._state.version
        self.metrics.record_swap(version, (time.perf_counter() - t0) * 1e3)
        return version

    # -- compile cache -----------------------------------------------------
    @property
    def compile_count(self) -> int:
        """Number of executables compiled so far (== compile-cache
        misses); the no-steady-state-recompile tests watch this."""
        return self.metrics.compile_cache_misses

    def _executable(self, dim: int, B: int, k: int):
        """The (coefficient dim, rows, nnz)-shaped executable, compiling
        on first use. The jitted callable takes the RESIDENT device
        coefficients as an argument — jax's own jit cache is keyed by
        the argument shapes, so our hit/miss counters stay faithful to
        real compiles AND a hot swap's new coefficient array (same
        shape) reuses the executable."""
        import jax

        key = (dim, B, k)
        with self._compile_lock:
            fn = self._compiled.get(key)
            if fn is not None:
                self.metrics.record_compile(hit=True)
                return fn
            self.metrics.record_compile(hit=False)

            @jax.jit
            def run(w, indices, values):
                feats = SparseFeatures(indices, values, dim=dim)
                return _margins(feats, w)

            dt = np.dtype(self.dtype)
            run(jnp.zeros((dim,), dt), jnp.zeros((B, k), jnp.int32),
                jnp.zeros((B, k), dt))
            self._compiled[key] = run
            return run

    def warmup(self) -> int:
        """Pre-compile every (fixed coordinate, row-bucket) executable so
        steady-state traffic inside the ladder never waits on XLA.
        Returns the number of executables compiled."""
        st = self._state
        before = self.metrics.compile_cache_misses
        for name, coord in st.model.coordinates.items():
            if not isinstance(coord, FixedEffectModel):
                continue
            k = st.k_pad[coord.feature_shard]
            dim = int(np.shape(st.resident[name])[0])
            for B in self.row_ladder:
                self._executable(dim, B, k)
        return self.metrics.compile_cache_misses - before

    # -- scoring -----------------------------------------------------------
    def _pad_shard(self, sp: HostSparse, B: int, k: int) -> HostSparse:
        n, kk = sp.indices.shape
        idx = np.zeros((B, k), np.int32)
        val = np.zeros((B, k), np.dtype(self.dtype))
        kc = min(kk, k)
        idx[:n, :kc] = sp.indices[:, :kc]
        if sp.values is not None:
            val[:n, :kc] = sp.values[:, :kc]
        else:
            val[:n, :kc] = 1.0
        return HostSparse(idx, val, sp.dim)

    def _fixed_scorer(self, n: int, st: _ModelState):
        """The ``fixed_scorer`` hook for ``score_single_batch``: route a
        fixed coordinate through the padded, device-resident executable
        (or the eager path for rows wider than the shard's pad width)."""

        def score(name, coord, sp: HostSparse):
            k = st.k_pad[coord.feature_shard]
            if sp.indices.shape[1] > k and _max_live_nnz(sp) > k:
                from photon_ml_tpu.game.scoring import fixed_effect_margins

                self.fixed_eager_batches += 1
                return fixed_effect_margins(sp, coord, self.dtype)
            B = bucketize(max(n, 1), self.row_ladder)
            w_dev = st.resident[name]
            padded = self._pad_shard(sp, B, k)
            run = self._executable(int(np.shape(w_dev)[0]), B, k)
            idx_dev = transfer_budget.device_put(
                padded.indices, what=f"serve.batch_idx[{name}]")
            val_dev = transfer_budget.device_put(
                padded.values, what=f"serve.batch_val[{name}]")
            return run(w_dev, idx_dev, val_dev)[:n]

        return score

    def _re_views(self, name: str, coord: RandomEffectModel,
                  entity_ids: np.ndarray, host: Dict[str, HostSparse],
                  st: _ModelState):
        """(views, coeffs) for one random coordinate of one batch, from
        cached entity coefficients — the same structures
        ``build_model_score_views`` derives from a fully-loaded model."""
        from photon_ml_tpu.game.data import (
            build_score_buckets,
            group_rows_by_slot,
        )

        cache = st.coeff_caches[name]
        resolved = cache.get_many(entity_ids)
        present = [eid for eid, entry in resolved.items()
                   if entry is not None]
        if not present:
            return [], []
        entity_to_slot = {eid: (0, j) for j, eid in enumerate(present)}
        per_bucket_rows = group_rows_by_slot(
            entity_ids, entity_to_slot, [len(present)])
        local_maps = [[resolved[eid].local_map for eid in present]]
        D = max(max(resolved[eid].local_dim for eid in present), 1)
        coeffs = np.zeros((len(present), D))
        for j, eid in enumerate(present):
            row = resolved[eid].coefficients
            coeffs[j, : row.shape[0]] = row
        views = build_score_buckets(
            host[coord.feature_shard], per_bucket_rows, local_maps)
        return views, [coeffs]

    def score_rows(self, rows: List[dict], per_coordinate: bool = False):
        """Score a batch of request rows.

        Each row is a dict: ``features`` — list of ``{"name", "term",
        "value"}`` feature dicts (or ``(name, term, value)`` tuples);
        ``entityIds`` — entity-column -> id for the random effects;
        ``offset`` — optional margin offset. Returns ``np.ndarray [n]``
        scores (plus a per-coordinate dict when requested), in row order.
        """
        st = self._state  # one consistent snapshot across the batch
        n = len(rows)
        if n == 0:
            return ((np.zeros(0), {}) if per_coordinate else np.zeros(0))
        if n > self.max_batch:
            raise ValueError(
                f"batch of {n} rows exceeds max_batch={self.max_batch}; "
                "split it (the micro-batcher never sends oversized "
                "batches)")
        host = {shard: self._resolve_features(rows, shard, st)
                for shard in st.index_maps}
        offsets = np.asarray(
            [float(r.get("offset") or 0.0) for r in rows],
            np.dtype(self.dtype))
        score_views = {}
        for name, coord in st.model.coordinates.items():
            if isinstance(coord, RandomEffectModel):
                ids = self._entity_column_values(rows, coord, name)
                score_views[name] = self._re_views(name, coord, ids, host,
                                                   st)
        result = score_single_batch(
            st.model, host, score_views, offsets=offsets,
            dtype=self.dtype, per_coordinate=per_coordinate,
            fixed_scorer=self._fixed_scorer(n, st))
        if per_coordinate:
            total, parts = result
            return (np.asarray(total),
                    {k: np.asarray(v) for k, v in parts.items()})
        return np.asarray(result)

    # -- request parsing ---------------------------------------------------
    def _resolve_features(self, rows: List[dict], shard: str,
                          st: _ModelState) -> HostSparse:
        """Resolve request feature names through the shard's persisted
        index map — the same resolution (+ implicit intercept) the Avro
        data reader applies, so served rows see the exact training-time
        feature space. Unknown features are dropped (per-shard feature
        selection, as in the batch path)."""
        imap = st.index_maps[shard]
        intercept = imap.intercept_index
        parsed: List[List[tuple]] = []
        for r in rows:
            out = []
            for feat in r.get("features") or ():
                if isinstance(feat, dict):
                    name, term, value = (feat["name"], feat.get("term", ""),
                                         feat.get("value", 1.0))
                else:
                    name, term, value = feat
                idx = imap.index_of(str(name), str(term))
                if idx is not None:
                    out.append((idx, float(value)))
            if intercept is not None and intercept >= 0:
                out.append((intercept, 1.0))
            parsed.append(out)
        k = max(max((len(p) for p in parsed), default=0), 1)
        indices = np.zeros((len(rows), k), np.int32)
        values = np.zeros((len(rows), k))
        for i, p in enumerate(parsed):
            for j, (idx, val) in enumerate(p):
                indices[i, j] = idx
                values[i, j] = val
        return HostSparse(indices, values, imap.size)

    @staticmethod
    def _entity_column_values(rows: List[dict], coord: RandomEffectModel,
                              name: str) -> np.ndarray:
        """Per-row entity ids for one random coordinate; a row without an
        id for this effect gets a sentinel no real id can equal, so it
        falls into the fixed-effect-only path."""
        keys = [k for k in (coord.entity_column, name, coord.effect_name)
                if k]
        out = []
        for r in rows:
            ids = r.get("entityIds") or {}
            val = None
            for key in keys:
                if key in ids:
                    val = ids[key]
                    break
            out.append("\x00<no-entity>" if val is None else str(val))
        return np.asarray(out)

    # -- introspection -----------------------------------------------------
    def coeff_cache_stats(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {"hits": c.hits, "misses": c.misses,
                   "evictions": c.evictions, "size": len(c),
                   "hit_rate": c.hit_rate}
            for name, c in self._state.coeff_caches.items()
        }


def _max_live_nnz(sp: HostSparse) -> int:
    """Widest row by LIVE (nonzero-value) entries — rows narrower than
    the storage width still fit the compiled pad width."""
    if sp.values is None:
        return sp.indices.shape[1]
    return int((np.asarray(sp.values) != 0).sum(axis=1).max(initial=0))
