"""Resident scoring session: the device-side half of the serving stack.

A :class:`ScoringSession` loads a saved GAME model ONCE and answers
scoring batches for as long as the process lives:

* **Fixed effects resident on device.** Each fixed coordinate's
  coefficient vector is uploaded once per model version (through
  ``utils/transfer_budget`` — sanctioned, budget-accounted) and PASSED
  to the jit executables as an argument, so steady-state requests move
  only the batch's padded index/value arrays — and a hot swap to a new
  version reuses every compiled executable (see below).

* **Shape-bucketed compile cache.** XLA executables are specialized to
  input shapes, so naive serving would recompile on every new batch size
  — tens of ms to seconds of latency cliff, exactly the "keep the device
  fed with right-sized batches" failure mode the GPU-learning literature
  warns about (PAPERS.md). The session instead pads every batch up a
  bounded POWER-OF-TWO ladder of row counts (and one fixed nnz width per
  shard), pre-compiles the whole ladder at warmup, and counts
  hits/misses so a recompile in steady state is observable (the tier-1
  suite asserts the miss counter stays flat). Executables are keyed by
  ``(coefficient dim, rows, nnz)`` — NOT by model version — and take the
  coefficient vector as a runtime argument, which is what makes
  :meth:`swap` recompile-free: a new version with the same feature dims
  re-donates fresh device coefficients to the existing executables.

* **Random effects through a device-resident paged table** (default) or
  the host entity LRU. The hot slice of per-entity coefficients lives in
  a :class:`~photon_ml_tpu.serve.paged_table.PagedCoefficientTable` on
  device, and a batch whose entities are warm scores in ONE fused
  executable call — fixed margins + a
  :func:`~photon_ml_tpu.ops.pallas_kernels.paged_gather_score` per
  random coordinate + offsets, no host gather, no per-batch coefficient
  upload. Cold entities fault through the
  :class:`~photon_ml_tpu.serve.coeff_cache.EntityCoefficientLRU` (one
  batched store pass) and are installed into pages before the batch's
  device call — the disk read dominates the fault, and one margin path
  keeps scores bitwise-stable across swaps; a background installer
  rebuilds pages asynchronously after a hot swap so the swap's critical
  path stays flat. Coordinates the table cannot hold (sketch
  projections, feature
  spaces wider than ``re_dense_dim_max``) keep the PR-2 LRU path:
  per-entity coefficients are
  fetched from :class:`~photon_ml_tpu.serve.coeff_cache
  .EntityCoefficientLRU`; a batch's score views are assembled with the
  SAME ``build_score_buckets`` / ``score_random_effect`` machinery the
  batch path uses, and the whole batch funnels through
  ``game.scoring.score_single_batch`` — one margin-math code path for
  offline and online scoring. Entities without a model contribute score
  0 (fixed-effect-only fallback), identical to ``score_game_model``.

* **Zero-downtime hot swap** (:meth:`swap`). All per-version state —
  loaded metadata, index maps, resident coefficient arrays, entity
  caches — lives in ONE immutable ``_ModelState``; a swap builds the
  next state off to the side (uploads, cache construction, optional
  warm-from-previous prefetch) and installs it with a single reference
  assignment, so an in-flight ``score_rows`` keeps its consistent
  snapshot and the next request sees the new version. The previous
  state is retained for :meth:`rollback` until the one after next.
  Sources: a model directory path, or a registry
  ``ResolvedVersion`` (a chain of model-dir layers, topmost first —
  delta versions resolve per-entity lookups down the chain through
  ``LayeredCoefficientStore``).
"""

from __future__ import annotations

import logging
import os
import queue as _queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import HostSparse
from photon_ml_tpu.game.scoring import score_single_batch
from photon_ml_tpu.io.model_io import (
    load_fixed_effect_coordinate,
    load_model_metadata,
)
from photon_ml_tpu.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.serve.coeff_cache import (
    EntityCoefficientLRU,
    LayeredCoefficientStore,
    ModelDirCoefficientStore,
)
from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.serve.membership import MembershipView
from photon_ml_tpu.serve.metrics import ServingMetrics
from photon_ml_tpu.serve.paged_table import PagedCoefficientTable
from photon_ml_tpu.types import SparseFeatures, margins as _margins
from photon_ml_tpu.utils import resolve_dtype, transfer_budget

_log = logging.getLogger(__name__)

__all__ = ["ScoringSession", "bucket_ladder", "bucketize"]

# per-row sentinel for "no entity id for this effect" — never a real id,
# never faulted against the store
_NO_ENTITY = "\x00<no-entity>"


def bucket_ladder(top: int, start: int = 1) -> List[int]:
    """Power-of-two ladder ``[start, 2*start, ...]`` whose last rung is
    the smallest power of two >= ``top``."""
    if top < 1:
        raise ValueError(f"ladder top must be >= 1, got {top}")
    out, b = [], max(1, start)
    while b < top:
        out.append(b)
        b *= 2
    out.append(b)
    return out


def bucketize(n: int, ladder: Sequence[int]) -> int:
    """Smallest ladder rung >= n; above the ladder, the next power of two
    (an off-ladder compile — counted as a cache miss, never silent)."""
    for b in ladder:
        if n <= b:
            return b
    b = ladder[-1]
    while b < n:
        b *= 2
    return b


class _ModelState:
    """Everything that changes when the served model changes — installed
    and read as one reference, never mutated after construction (the
    paged tables' interiors mutate behind their own locks; the REFERENCES
    here do not, so a swap rebuilds pages by building new tables)."""

    __slots__ = ("chain", "version", "task", "index_maps", "k_pad",
                 "model", "coeff_caches", "resident", "router",
                 "shard_order", "intercepts", "paged", "plan", "fused_sig")

    def __init__(self, chain, version, task, index_maps, k_pad, model,
                 coeff_caches, resident, router=None, shard_order=(),
                 intercepts=(), paged=None, plan=(), fused_sig=None):
        self.chain = chain
        self.version = version
        self.task = task
        self.index_maps = index_maps
        self.k_pad = k_pad
        self.model = model
        self.coeff_caches = coeff_caches
        self.resident = resident
        # -- fused-path plumbing (None fused_sig = fused path disabled)
        self.router = router          # feature key -> ((shard_pos, idx),..)
        self.shard_order = shard_order
        self.intercepts = intercepts  # per shard_order: intercept idx or -1
        self.paged = paged or {}      # RE name -> PagedCoefficientTable
        self.plan = plan              # ordered (kind, name, shard_pos)
        self.fused_sig = fused_sig    # executable key component


def _layer_with(chain: Sequence[str], rel: str) -> Optional[str]:
    for d in chain:
        if os.path.exists(os.path.join(d, rel)):
            return d
    return None


class ScoringSession:
    """One resident GAME model + its pre-compiled scoring executables.

    Thread-safety: ``score_rows`` is safe to call from any thread (the
    compile cache takes a lock, per-version state is snapshotted once
    per call); the intended topology is a single
    :class:`~photon_ml_tpu.serve.batcher.MicroBatcher` worker calling
    it, with :meth:`swap` arriving from an admin endpoint or the
    registry watcher.

    Parameters:
      model_dir: saved model directory (``io/model_io`` layout) or a
        registry ``ResolvedVersion`` (duck-typed: ``.chain`` +
        ``.version``).
      dtype: scoring dtype ("float32"/"float64" or a jnp dtype); float64
        requires ``jax_enable_x64``.
      max_batch: top of the row-count bucket ladder; the micro-batcher's
        ``max_batch`` should equal it so no steady-state batch exceeds
        the pre-compiled shapes.
      pad_nnz: padded nonzero width per row (one per shard, clamped to
        the shard's feature-map size). A request row with more resolved
        features than this takes the uncompiled eager path (counted in
        ``fixed_eager_batches``) instead of minting a new executable.
      coeff_cache_entries: LRU capacity per random-effect coordinate.
      paged_table: keep the hot entity coefficients device-resident in a
        paged table and score warm batches through the fused one-call
        executable (False restores the PR-2 host-LRU hot path; sketched
        or too-wide coordinates fall back per coordinate regardless).
      re_pages / re_page_rows: paged-table geometry per random
        coordinate — ``re_pages * re_page_rows`` resident entities, one
        page is the unit of install/evict transfer.
      re_dense_dim_max: widest random-effect feature space the paged
        table will densify; beyond it the coordinate stays on the LRU
        path (a dense row per entity would waste device memory).
      warmup: pre-compile the full ladder at construction (recommended;
        tests that exercise lazy compilation pass False).
    """

    def __init__(self, model_dir, *, dtype="float32",
                 max_batch: int = 64, pad_nnz: int = 64,
                 coeff_cache_entries: int = 4096,
                 paged_table: bool = True, re_pages: int = 4,
                 re_page_rows: int = 256, re_dense_dim_max: int = 4096,
                 metrics: Optional[ServingMetrics] = None,
                 warmup: bool = True):
        self.dtype = resolve_dtype(dtype) if isinstance(dtype, str) else dtype
        self.max_batch = int(max_batch)
        self.metrics = metrics or ServingMetrics()
        self.row_ladder = bucket_ladder(self.max_batch)
        self.fixed_eager_batches = 0
        self.fused_fallback_batches = 0
        self._pad_nnz = int(pad_nnz)
        self._coeff_cache_entries = int(coeff_cache_entries)
        self._paged_enabled = bool(paged_table)
        self._re_pages = int(re_pages)
        self._re_page_rows = int(re_page_rows)
        self._re_dense_dim_max = int(re_dense_dim_max)
        # EWMA of observed cold-fault service time (store read + page
        # install): the degradation ladder's budget check — a request
        # whose remaining deadline cannot cover one more fault is served
        # from resident coefficients instead of risking the store
        self._fault_ewma_s: Optional[float] = None

        # -- background page installer: cold faults resolve host-side in
        # the faulting batch, residency arrives asynchronously ----------
        self._install_q: "_queue.Queue" = _queue.Queue(maxsize=256)
        self._install_drops = 0
        self._install_stop = threading.Event()
        # installer joins that outlived close()'s grace (a wedged device
        # install); counted + logged, mirroring producer_join_timeouts
        self.join_timeouts = 0
        self._installer = threading.Thread(
            target=self._install_worker, daemon=True,
            name="photon-serve-page-install")
        self._installer.start()

        # -- entity-affinity membership: which slice of the entity
        # universe THIS replica owns (serve/membership.py). Session-
        # level, not per-version state — an epoch survives hot swaps.
        self._membership = MembershipView()

        # -- shape-bucketed compile cache: survives swaps by design ----
        self._compiled: Dict[tuple, object] = {}
        self._compile_lock = threading.Lock()
        self._swap_lock = threading.Lock()
        self._prev_state: Optional[_ModelState] = None
        self._state = self._build_state(model_dir)
        self.metrics.set_active_version(self._state.version)
        if warmup:
            self.warmup()

    # -- per-version state -------------------------------------------------
    def _build_state(self, source, version: Optional[str] = None
                     ) -> _ModelState:
        """Load one model version into an installable state: metadata,
        index maps, eager fixed-effect coordinates (uploaded to device
        through ``transfer_budget``), and entity-coefficient caches
        layered down a delta chain when the source is a resolved
        registry version."""
        chain = (list(source.chain) if hasattr(source, "chain")
                 else [str(source)])
        if version is None:
            version = getattr(source, "version", None) or chain[0]
        meta = load_model_metadata(chain[0])
        task = meta["task"]
        index_maps: Dict[str, object] = {}
        k_pad: Dict[str, int] = {}
        coords: Dict[str, object] = {}
        coeff_caches: Dict[str, EntityCoefficientLRU] = {}
        re_sketched: Dict[str, bool] = {}
        for c in meta["coordinates"]:
            shard = c["feature_shard"]
            if shard not in index_maps:
                from photon_ml_tpu.io.paldb import load_index_map

                layer = _layer_with(chain, f"index-map.{shard}.json")
                if layer is None:
                    raise FileNotFoundError(
                        f"index-map.{shard}.json missing from every "
                        f"layer of {chain}")
                imap = load_index_map(
                    os.path.join(layer, f"index-map.{shard}.json"))
                index_maps[shard] = imap
                k_pad[shard] = max(1, min(self._pad_nnz, imap.size))
            imap = index_maps[shard]
            if c["type"] == "fixed":
                rel = os.path.join("fixed-effect", c["name"],
                                   "coefficients.avro")
                layer = _layer_with(chain, rel)
                if layer is None:
                    raise FileNotFoundError(
                        f"{rel} missing from every layer of {chain}")
                coords[c["name"]] = load_fixed_effect_coordinate(
                    layer, c["name"], imap, task, shard)
            else:
                # bucketless stub: the coordinate participates in the
                # shared scoring loop, but its per-entity coefficients
                # come from the LRU, never from resident buckets
                coords[c["name"]] = RandomEffectModel(
                    c["name"], [], task, shard,
                    entity_column=c.get("entity_column", ""))
                rel = os.path.join("random-effect", c["name"],
                                   "coefficients.avro")
                stores = [
                    ModelDirCoefficientStore(d, c["name"], imap,
                                             c.get("projection"))
                    for d in chain
                    if os.path.exists(os.path.join(d, rel))
                ]
                store = (stores[0] if len(stores) == 1
                         else LayeredCoefficientStore(stores))
                coeff_caches[c["name"]] = EntityCoefficientLRU(
                    store.load, self._coeff_cache_entries,
                    metrics=self.metrics, batch_loader=store.load_many)
                proj = c.get("projection")
                re_sketched[c["name"]] = bool(
                    proj and proj.get("type") == "random")
        model = GameModel(coords, task)

        # -- device residency: one budget-accounted upload per fixed
        # coordinate per VERSION (swaps re-upload; executables persist)
        resident: Dict[str, object] = {}
        for name, coord in model.coordinates.items():
            if isinstance(coord, FixedEffectModel):
                w = np.asarray(coord.model.coefficients.means,
                               np.dtype(self.dtype))
                resident[name] = transfer_budget.device_put(
                    w, what=f"serve.fixed[{name}]")

        # -- one-pass feature router: feature key -> every (shard, index)
        # it resolves to, so a batch's features are resolved for ALL
        # shards in a single iteration instead of one pass per shard
        shard_order = tuple(index_maps)
        shard_pos = {s: i for i, s in enumerate(shard_order)}
        router: Dict[str, tuple] = {}
        for s, imap in index_maps.items():
            si = shard_pos[s]
            for key, idx in imap.forward.items():
                router[key] = router.get(key, ()) + ((si, idx),)
        intercepts = tuple(index_maps[s].intercept_index
                           for s in shard_order)

        # -- paged device residency + the fused one-call scoring plan:
        # eligible when EVERY random coordinate can live in a paged
        # table (dict local maps, bounded dense width)
        paged: Dict[str, PagedCoefficientTable] = {}
        plan: List[tuple] = []
        fused_ok = self._paged_enabled
        for name, coord in model.coordinates.items():
            si = shard_pos[coord.feature_shard]
            if isinstance(coord, FixedEffectModel):
                plan.append(("fixed", name, si))
                continue
            plan.append(("random", name, si))
            dim = index_maps[coord.feature_shard].size
            if (not self._paged_enabled or re_sketched.get(name)
                    or dim > self._re_dense_dim_max):
                fused_ok = False
                continue
            paged[name] = PagedCoefficientTable(
                dim, pages=self._re_pages, page_rows=self._re_page_rows,
                dtype=np.dtype(self.dtype), name=name,
                metrics=self.metrics)
        fused_sig = None
        if fused_ok:
            # same signature <=> same executables: a hot swap between
            # same-shaped models reuses the whole fused ladder
            fused_sig = (
                tuple(plan),
                tuple((s, index_maps[s].size, k_pad[s])
                      for s in shard_order),
                tuple((n, paged[n].capacity, paged[n].dim)
                      for _, n, _ in plan if n in paged),
            )
        return _ModelState(chain, str(version), task, index_maps, k_pad,
                           model, coeff_caches, resident, router=router,
                           shard_order=shard_order, intercepts=intercepts,
                           paged=paged, plan=tuple(plan),
                           fused_sig=fused_sig)

    # -- compatibility views over the active state ------------------------
    @property
    def model_dir(self) -> str:
        return self._state.chain[0]

    @property
    def model(self) -> GameModel:
        return self._state.model

    @property
    def task(self) -> str:
        return self._state.task

    @property
    def active_version(self) -> str:
        return self._state.version

    @property
    def _index_maps(self):
        return self._state.index_maps

    @property
    def _k_pad(self):
        return self._state.k_pad

    @property
    def _coeff_caches(self):
        return self._state.coeff_caches

    # -- hot swap ----------------------------------------------------------
    def swap(self, source, *, version: Optional[str] = None,
             warm_from_previous: bool = True) -> str:
        """Atomically switch to another model version with zero downtime.

        Builds the whole next state off to the side — new fixed-effect
        coefficients uploaded through ``transfer_budget``, new entity
        caches over the new version's (possibly layered) store,
        optionally pre-warmed with the previous caches' resident hot set
        — then installs it with one reference assignment. The compiled
        executables are untouched: they are keyed by shape, not version,
        so a swap between same-dimensioned models never recompiles (the
        tier-1 suite pins the miss counter flat across a swap). The
        previous state is retained until the next swap so
        :meth:`rollback` is instant."""
        t0 = time.perf_counter()
        new = self._build_state(source, version)
        if warm_from_previous:
            for name, cache in new.coeff_caches.items():
                old = self._state.coeff_caches.get(name)
                old_paged = self._state.paged.get(name)
                hot = list(old.cached_ids()) if old is not None else []
                if old_paged is not None:
                    seen = set(hot)
                    hot += [e for e in old_paged.resident_ids()
                            if e not in seen]
                if hot and self._membership.active:
                    # under a membership epoch, prewarm only the owned
                    # slice — the rest of the old hot set belongs to
                    # other replicas now and would waste the store pass
                    owned = self._membership.owned_many(hot)
                    hot = [e for e, o in zip(hot, owned) if o]
                if not hot:
                    continue
                table = new.paged.get(name)
                if table is None:
                    cache.prefetch(hot)
                else:
                    # rebuild pages off the swap's critical path: the
                    # LRU warms synchronously (one store pass), device
                    # page installs ride the background installer
                    self._install_async(table, cache.warm_entries(hot))
        with self._swap_lock:
            self._prev_state, self._state = self._state, new
        self.metrics.record_swap(new.version,
                                 (time.perf_counter() - t0) * 1e3)
        return new.version

    # -- entity-affinity membership ---------------------------------------
    @property
    def membership(self) -> MembershipView:
        return self._membership

    def set_membership(self, *, epoch: int, num_shards: int,
                       shard_index: int, id_kind: str = "auto") -> bool:
        """Apply a membership epoch (``POST /admin/membership``): this
        session is shard ``shard_index`` of ``num_shards`` replicas.
        Stale epochs are refused (returns False, nothing changes). On a
        real ownership change, every paged table drops + compacts the
        rows this replica no longer owns (``retain_only``) so the freed
        pages are immediately available to the owned slice; non-owned
        entities keep scoring correctly through the host LRU path."""
        if not self._membership.apply(epoch, num_shards, shard_index,
                                      id_kind):
            return False
        self.metrics.set_membership_epoch(self._membership.epoch)
        if self._membership.active:
            mv = self._membership
            for table in self._state.paged.values():
                table.retain_only(mv.owned)
        return True

    def prefetch_entities(self, entity_ids) -> tuple:
        """Warm the moved slice of a membership rebalance: load
        ``entity_ids`` through each coordinate's batched store pass
        (``warm_entries`` — one file scan per store, not one per id)
        and install them into the paged tables SYNCHRONOUSLY, so when
        the front door commits the epoch the new owner's pages already
        hold the handoff — a join/leave is a bounded transfer, not a
        cold-start fault storm. Ids this replica does not own under the
        applied epoch are skipped. Returns ``(entities, bytes)``
        actually landed."""
        ids = [str(e) for e in entity_ids]
        mv = self._membership
        if mv.active and ids:
            owned = mv.owned_many(ids)
            ids = [e for e, o in zip(ids, owned) if o]
        if not ids:
            return 0, 0
        st = self._state
        total = moved_bytes = 0
        with obs_trace.span("membership.prefetch", cat="serve",
                            entities=len(ids)):
            for name, cache in st.coeff_caches.items():
                entries = cache.warm_entries(ids)
                present = {k: v for k, v in entries.items()
                           if v is not None}
                if not present:
                    continue
                total += len(present)
                table = st.paged.get(name)
                if table is not None:
                    installed = table.install(present)
                    moved_bytes += (installed * table.dim
                                    * table.dtype.itemsize)
                else:
                    moved_bytes += sum(
                        v.coefficients.nbytes for v in present.values())
        if total:
            self.metrics.record_membership(prefetch_entities=total,
                                           prefetch_bytes=moved_bytes)
        return total, moved_bytes

    def rollback(self) -> str:
        """Re-install the state the last swap replaced (its warmed
        entity caches and device arrays were retained for exactly
        this). Counts as a swap in the metrics."""
        t0 = time.perf_counter()
        with self._swap_lock:
            if self._prev_state is None:
                raise RuntimeError("no previous version to roll back to")
            self._prev_state, self._state = self._state, self._prev_state
            version = self._state.version
        self.metrics.record_swap(version, (time.perf_counter() - t0) * 1e3)
        return version

    # -- background page installer -----------------------------------------
    # idle-poll interval (seconds) for the installer's queue wait; a
    # class attribute so tests can shrink it without monkeypatching
    _install_poll_s = 0.2

    def _install_worker(self) -> None:
        while True:
            try:
                # bounded idle poll: each expiry rechecks the stop
                # event, so a closed session never leaves the installer
                # parked in a blocking get forever
                item = self._install_q.get(timeout=self._install_poll_s)
            except _queue.Empty:
                if self._install_stop.is_set():
                    return
                continue
            if item is None:  # shutdown sentinel from close()
                self._install_q.task_done()
                return
            table, entries, tctx = item
            try:
                # the enqueuer's trace context crosses the thread handoff
                # so swap-prewarm installs land under the swap's trace
                with obs_trace.use_context(tctx), \
                        obs_trace.span("paged.install_async", cat="serve",
                                       entries=len(entries)):
                    table.install(entries)
            except Exception:  # a bad install must not kill the worker
                pass
            finally:
                self._install_q.task_done()

    def _install_async(self, table: PagedCoefficientTable,
                       entries: Dict[str, object]) -> None:
        """Queue a page install; under install-queue pressure the
        entries are DROPPED (the batch already scored correctly through
        the host fault path — residency is an optimization, and blocking
        the scoring thread on it would recreate the upload round-trip
        this table removes)."""
        if not entries:
            return
        try:
            self._install_q.put_nowait(
                (table, entries, obs_trace.current_context()))
        except _queue.Full:
            self._install_drops += 1

    def drain_installs(self, timeout_s: float = 10.0) -> bool:
        """Block until queued page installs have been applied (tests and
        the bench use this to make residency deterministic)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._install_q.unfinished_tasks == 0:
                return True
            time.sleep(0.002)
        return False

    @property
    def warming(self) -> bool:
        """True while background page installs are still pending — right
        after a swap the new version's pages are prewarming and a cold
        burst would fault heavily. ``/healthz`` reports ``warming`` so
        the front door's half-open breaker holds readmission until the
        installer drains."""
        return self._install_q.unfinished_tasks > 0

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the background page installer with a bounded join
        (idempotent). Pending installs are abandoned — residency is an
        optimization, and the session keeps scoring correctly through
        the host fault path regardless. An installer that outlives the
        grace (wedged device install) is counted and logged, never
        waited on forever."""
        if self._install_stop.is_set():
            return
        self._install_stop.set()
        try:
            self._install_q.put_nowait(None)  # wake the idle poll now
        except _queue.Full:
            pass  # the stop event wakes the bounded poll instead
        self._installer.join(timeout_s)
        if self._installer.is_alive():
            self.join_timeouts += 1
            _log.warning(
                "ScoringSession: installer thread %r still alive %.1fs "
                "after close() (wedged device install?); leaking it as "
                "a daemon (join timeouts so far: %d)",
                self._installer.name, timeout_s, self.join_timeouts)

    # -- compile cache -----------------------------------------------------
    @property
    def compile_count(self) -> int:
        """Number of executables compiled so far (== compile-cache
        misses); the no-steady-state-recompile tests watch this."""
        return self.metrics.compile_cache_misses

    def _executable(self, dim: int, B: int, k: int):
        """The (coefficient dim, rows, nnz)-shaped executable, compiling
        on first use. The jitted callable takes the RESIDENT device
        coefficients as an argument — jax's own jit cache is keyed by
        the argument shapes, so our hit/miss counters stay faithful to
        real compiles AND a hot swap's new coefficient array (same
        shape) reuses the executable."""
        import jax

        key = (dim, B, k)
        with self._compile_lock:
            fn = self._compiled.get(key)
            if fn is not None:
                self.metrics.record_compile(hit=True)
                return fn
            self.metrics.record_compile(hit=False)

            @jax.jit
            def run(w, indices, values):
                feats = SparseFeatures(indices, values, dim=dim)
                return _margins(feats, w)

            dt = np.dtype(self.dtype)
            run(jnp.zeros((dim,), dt), jnp.zeros((B, k), jnp.int32),
                jnp.zeros((B, k), dt))
            self._compiled[key] = run
            return run

    def _fused_executable(self, B: int, st: _ModelState):
        """The whole-batch one-call executable for row bucket ``B``:
        offsets + every fixed coordinate's margins + every random
        coordinate's paged gather, in one jit dispatch. Keyed by the
        state's ``fused_sig`` (coordinate plan + shard dims + table
        shapes) — NOT by version, so a hot swap between same-shaped
        models reuses the compiled ladder."""
        import jax

        from photon_ml_tpu.ops.pallas_kernels import paged_gather_score

        key = ("fused", B, st.fused_sig)
        with self._compile_lock:
            fn = self._compiled.get(key)
            if fn is not None:
                self.metrics.record_compile(hit=True)
                return fn
            self.metrics.record_compile(hit=False)
            plan = st.plan
            dims = tuple(st.index_maps[s].size for s in st.shard_order)

            @jax.jit
            def run(offsets, shard_idx, shard_val, fixed_w, re_buf,
                    re_slots):
                total = offsets
                parts = []
                fi = ri = 0
                for kind, _name, si in plan:
                    if kind == "fixed":
                        feats = SparseFeatures(shard_idx[si],
                                               shard_val[si], dim=dims[si])
                        m = _margins(feats, fixed_w[fi])
                        fi += 1
                    else:
                        m = paged_gather_score(re_buf[ri], re_slots[ri],
                                               shard_idx[si], shard_val[si])
                        ri += 1
                    parts.append(m)
                    total = total + m
                return total, tuple(parts)

            dt = np.dtype(self.dtype)
            z_idx = tuple(jnp.zeros((B, st.k_pad[s]), jnp.int32)
                          for s in st.shard_order)
            z_val = tuple(jnp.zeros((B, st.k_pad[s]), dt)
                          for s in st.shard_order)
            z_w = tuple(st.resident[name]
                        for kind, name, _ in plan if kind == "fixed")
            z_buf = tuple(st.paged[name].device_buffer
                          for kind, name, _ in plan if kind == "random")
            z_slots = tuple(jnp.full((B,), -1, jnp.int32) for _ in z_buf)
            run(jnp.zeros((B,), dt), z_idx, z_val, z_w, z_buf, z_slots)
            self._compiled[key] = run
            return run

    def warmup(self) -> int:
        """Pre-compile the executables the configured hot path uses for
        every row bucket so steady-state traffic inside the ladder never
        waits on XLA — the fused one-call ladder when the paged path is
        live, the per-fixed-coordinate ladder otherwise. Returns the
        number of executables compiled."""
        st = self._state
        before = self.metrics.compile_cache_misses
        if st.fused_sig is not None:
            for B in self.row_ladder:
                self._fused_executable(B, st)
            for table in st.paged.values():
                table.warm_device_path()  # page-refresh executable
        else:
            for name, coord in st.model.coordinates.items():
                if not isinstance(coord, FixedEffectModel):
                    continue
                k = st.k_pad[coord.feature_shard]
                dim = int(np.shape(st.resident[name])[0])
                for B in self.row_ladder:
                    self._executable(dim, B, k)
        return self.metrics.compile_cache_misses - before

    # -- scoring -----------------------------------------------------------
    def _pad_shard(self, sp: HostSparse, B: int, k: int) -> HostSparse:
        n, kk = sp.indices.shape
        idx = np.zeros((B, k), np.int32)
        val = np.zeros((B, k), np.dtype(self.dtype))
        kc = min(kk, k)
        idx[:n, :kc] = sp.indices[:, :kc]
        if sp.values is not None:
            val[:n, :kc] = sp.values[:, :kc]
        else:
            val[:n, :kc] = 1.0
        return HostSparse(idx, val, sp.dim)

    def _fixed_scorer(self, n: int, st: _ModelState):
        """The ``fixed_scorer`` hook for ``score_single_batch``: route a
        fixed coordinate through the padded, device-resident executable
        (or the eager path for rows wider than the shard's pad width)."""

        def score(name, coord, sp: HostSparse):
            k = st.k_pad[coord.feature_shard]
            if sp.indices.shape[1] > k and _max_live_nnz(sp) > k:
                from photon_ml_tpu.game.scoring import fixed_effect_margins

                self.fixed_eager_batches += 1
                return fixed_effect_margins(sp, coord, self.dtype)
            B = bucketize(max(n, 1), self.row_ladder)
            w_dev = st.resident[name]
            padded = self._pad_shard(sp, B, k)
            run = self._executable(int(np.shape(w_dev)[0]), B, k)
            idx_dev = transfer_budget.device_put(
                padded.indices, what=f"serve.batch_idx[{name}]")
            val_dev = transfer_budget.device_put(
                padded.values, what=f"serve.batch_val[{name}]")
            return run(w_dev, idx_dev, val_dev)[:n]

        return score

    # -- degradation ladder ------------------------------------------------
    @staticmethod
    def _ladder_level(ctx) -> int:
        """The effective degradation level for this point of the batch:
        the brownout floor raised by any budget/fault escalation earlier
        in the same batch (0 = full, 1 = resident-only, 2 = fixed-only)."""
        return 0 if ctx is None else max(ctx.level, ctx.degraded)

    @staticmethod
    def _note_degrade(ctx, level: int, reason: str) -> None:
        if ctx.degraded < level:
            ctx.degraded = level
        ctx.reasons.append(reason)

    def _note_fault_cost(self, elapsed_s: float) -> None:
        """Fold one observed cold-fault service time into the EWMA the
        budget check compares remaining deadline against. A slow store
        (delay faults, contended disk) raises it, so subsequent tight
        requests degrade instead of queueing behind the store."""
        prev = self._fault_ewma_s
        self._fault_ewma_s = (elapsed_s if prev is None
                              else prev + 0.3 * (elapsed_s - prev))

    def _budget_blocks_fault(self, ctx) -> bool:
        """True when the batch's remaining budget cannot cover one more
        cold-store fault (by the measured EWMA; with no measurement yet
        only an already-expired budget blocks)."""
        if ctx is None:
            return False
        rem = ctx.remaining_s()
        return rem is not None and rem <= (self._fault_ewma_s or 0.0)

    def _re_views(self, name: str, coord: RandomEffectModel,
                  entity_ids: np.ndarray, host: Dict[str, HostSparse],
                  st: _ModelState, ctx=None):
        """(views, coeffs) for one random coordinate of one batch, from
        cached entity coefficients — the same structures
        ``build_model_score_views`` derives from a fully-loaded model.
        Under a degraded ``ctx`` the store is never touched: level >= 2
        contributes nothing (fixed-effect-only margin), level 1 scores
        from the LRU's resident entries only, and level 0 escalates to 1
        when the remaining budget can't cover a cold fault or the store
        itself fails — entities left unresolved score 0, byte-for-byte
        the existing unknown-entity fallback."""
        from photon_ml_tpu.game.data import (
            build_score_buckets,
            group_rows_by_slot,
        )

        cache = st.coeff_caches[name]
        level = self._ladder_level(ctx)
        if level >= 2:
            return [], []
        if level >= 1:
            resolved = cache.resident_many(entity_ids)
        elif self._budget_blocks_fault(ctx):
            self._note_degrade(ctx, 1, "budget")
            resolved = cache.resident_many(entity_ids)
        else:
            try:
                misses0 = cache.misses
                t0 = time.monotonic()
                resolved = cache.get_many(entity_ids)
                if cache.misses > misses0:
                    self._note_fault_cost(time.monotonic() - t0)
            except Exception:
                if ctx is None:
                    raise
                self._note_fault_cost(time.monotonic() - t0)
                self._note_degrade(ctx, 1, "store_fault")
                resolved = cache.resident_many(entity_ids)
        present = [eid for eid, entry in resolved.items()
                   if entry is not None]
        if not present:
            return [], []
        entity_to_slot = {eid: (0, j) for j, eid in enumerate(present)}
        per_bucket_rows = group_rows_by_slot(
            entity_ids, entity_to_slot, [len(present)])
        local_maps = [[resolved[eid].local_map for eid in present]]
        D = max(max(resolved[eid].local_dim for eid in present), 1)
        coeffs = np.zeros((len(present), D))
        for j, eid in enumerate(present):
            row = resolved[eid].coefficients
            coeffs[j, : row.shape[0]] = row
        views = build_score_buckets(
            host[coord.feature_shard], per_bucket_rows, local_maps)
        return views, [coeffs]

    def score_rows(self, rows: List[dict], per_coordinate: bool = False,
                   ctx=None):
        """Score a batch of request rows.

        Each row is a dict: ``features`` — list of ``{"name", "term",
        "value"}`` feature dicts (or ``(name, term, value)`` tuples);
        ``entityIds`` — entity-column -> id for the random effects;
        ``offset`` — optional margin offset. Returns ``np.ndarray [n]``
        scores (plus a per-coordinate dict when requested), in row order.

        Warm batches take the fused paged path (one device call); a
        batch with rows wider than a shard's compiled pad width — or a
        model the paged table cannot hold — takes the PR-2 per-coordinate
        path. Both produce identical scores (the paged-parity tests pin
        <= 1e-9 in f64).

        ``ctx`` (a :class:`~photon_ml_tpu.serve.batcher.ScoreContext`)
        arms the degradation ladder: its remaining deadline budget gates
        cold-store faults, its brownout level floors the fidelity, and
        the level actually served lands back in ``ctx.degraded``. With
        ``ctx=None`` (or a level-0 ctx, no faults, ample budget) the
        code path — and therefore every score bit — is unchanged."""
        st = self._state  # one consistent snapshot across the batch
        n = len(rows)
        if n == 0:
            return ((np.zeros(0), {}) if per_coordinate else np.zeros(0))
        if n > self.max_batch:
            raise ValueError(
                f"batch of {n} rows exceeds max_batch={self.max_batch}; "
                "split it (the micro-batcher never sends oversized "
                "batches)")
        with obs_trace.span("session.resolve", cat="serve", rows=n):
            host = self._resolve_all(rows, st)
        offsets = np.asarray(
            [float(r.get("offset") or 0.0) for r in rows],
            np.dtype(self.dtype))
        if st.fused_sig is not None:
            if all(host[s].indices.shape[1] <= st.k_pad[s]
                   for s in st.shard_order):
                return self._score_fused(rows, host, offsets, n, st,
                                         per_coordinate, ctx)
            self.fused_fallback_batches += 1
        score_views = {}
        for name, coord in st.model.coordinates.items():
            if isinstance(coord, RandomEffectModel):
                ids = self._entity_column_values(rows, coord, name)
                score_views[name] = self._re_views(name, coord, ids, host,
                                                   st, ctx)
        result = score_single_batch(
            st.model, host, score_views, offsets=offsets,
            dtype=self.dtype, per_coordinate=per_coordinate,
            fixed_scorer=self._fixed_scorer(n, st))
        if per_coordinate:
            total, parts = result
            return (np.asarray(total),
                    {k: np.asarray(v) for k, v in parts.items()})
        return np.asarray(result)

    def _score_fused(self, rows, host, offsets, n, st: _ModelState,
                     per_coordinate: bool, ctx=None):
        """The paged hot path: pad the batch onto the row-bucket ladder,
        resolve entity ids to device slots, and score everything in one
        fused executable call. Cold entities (resident in neither pages
        nor the absent set) fault through the LRU and are installed into
        pages BEFORE the device call — the disk read dominates a cold
        fault anyway, and scoring the faulting batch host-side instead
        would fork the f64 summation order from the device gather (the
        swap suite pins scores bitwise-stable across identical swaps,
        which needs exactly one margin path). Only a batch with more
        distinct entities than the whole table falls back to host math
        for the overflow rows; the background installer is reserved for
        swap-prewarm page rebuilds off the request path."""
        dt = np.dtype(self.dtype)
        B = bucketize(max(n, 1), self.row_ladder)
        upload_bytes = 0
        shard_idx, shard_val = [], []
        for s in st.shard_order:
            sp = host[s]
            k = st.k_pad[s]
            idx = np.zeros((B, k), np.int32)
            val = np.zeros((B, k), dt)
            kk = sp.indices.shape[1]
            idx[:n, :kk] = sp.indices
            val[:n, :kk] = sp.values
            upload_bytes += idx.nbytes + val.nbytes
            shard_idx.append(idx)
            shard_val.append(val)
        fixed_w = tuple(st.resident[name]
                        for kind, name, _ in st.plan if kind == "fixed")
        re_bufs, re_slots = [], []
        extras: List[tuple] = []  # (plan position, host contribution)
        for pos, (kind, name, si) in enumerate(st.plan):
            if kind != "random":
                continue
            coord = st.model.coordinates[name]
            if self._ladder_level(ctx) >= 2:
                # fixed-effect-only margin: every slot is the -1
                # sentinel, so the gather contributes exactly 0 — the
                # same one-margin-path arithmetic as an unknown entity
                re_bufs.append(st.paged[name].device_buffer)
                slots_pad = np.full(B, -1, np.int32)
                re_slots.append(slots_pad)
                upload_bytes += slots_pad.nbytes
                continue
            ids = self._entity_column_values(rows, coord, name).tolist()
            table = st.paged[name]
            buf, slots, missing = table.lookup(ids)
            missing = [m for m in missing if m != _NO_ENTITY]
            if missing and self._ladder_level(ctx) >= 1:
                # resident-pages-only: the store is not consulted, the
                # missing entities keep slot -1 (fixed-only for them)
                missing = []
            elif missing and self._budget_blocks_fault(ctx):
                self._note_degrade(ctx, 1, "budget")
                missing = []
            if missing:
                self.metrics.record_paged(faults=len(missing))
                t0_fault = time.monotonic()
                try:
                    with obs_trace.span("paged.fault_install", cat="serve",
                                        coordinate=name,
                                        entities=len(missing)):
                        entries = st.coeff_caches[name].get_many(missing)
                        to_install = entries
                        if self._membership.active:
                            # non-owned entities never take device pages:
                            # they resolve through the LRU host-math path
                            # below (next batch hits the LRU, not the
                            # store), keeping this replica's pages for
                            # its owned slice
                            owned = self._membership.owned_many(
                                list(entries))
                            to_install = {
                                e: entries[e]
                                for e, o in zip(entries, owned) if o}
                            skipped = len(entries) - len(to_install)
                            if skipped:
                                self.metrics.record_membership(
                                    non_owned_skips=skipped)
                        table.install(to_install)
                        # re-read: fresh buffer + installed slots
                        buf, slots, still = table.lookup(ids)
                    self._note_fault_cost(time.monotonic() - t0_fault)
                except Exception:
                    if ctx is None:
                        raise
                    # store/install failure: serve this batch from
                    # whatever is resident (original buf/slots — the
                    # failed entities keep slot -1) instead of 5xx-ing
                    self._note_fault_cost(time.monotonic() - t0_fault)
                    self._note_degrade(ctx, 1, "store_fault")
                    still = set()
                else:
                    still = set(still) - {_NO_ENTITY}
                if still:
                    # batch entities exceed the table: host math for the
                    # overflow rows (size pages*page_rows >= max_batch
                    # to never take this)
                    sp = host[st.shard_order[si]]
                    extra = np.zeros(n, dt)
                    dense: Dict[str, np.ndarray] = {}
                    for i, eid in enumerate(ids):
                        if eid not in still:
                            continue
                        # an entity evicted by this very batch's installs
                        # resolves from the LRU, not the fault entries
                        entry = (entries.get(eid)
                                 or st.coeff_caches[name].get(eid))
                        if entry is None:
                            continue
                        drow = dense.get(eid)
                        if drow is None:
                            drow = dense[eid] = table.dense_row(entry)
                        extra[i] = np.dot(drow[sp.indices[i]],
                                          sp.values[i].astype(dt))
                    extras.append((pos, extra))
            slots_pad = np.full(B, -1, np.int32)
            slots_pad[:n] = slots
            re_bufs.append(buf)
            re_slots.append(slots_pad)
            upload_bytes += slots_pad.nbytes
        off = np.zeros(B, dt)
        off[:n] = offsets
        upload_bytes += off.nbytes
        # ONE budget charge for the batch's host->device bytes; the jit
        # dispatch commits the numpy arrays itself (a single C-level
        # shard_args pass beats one python device_put per array — at
        # production QPS those six dispatches were measurable)
        transfer_budget.charge(upload_bytes, "serve.fused_batch")
        run = self._fused_executable(B, st)
        with obs_trace.span("session.device_compute", cat="serve",
                            rows=n, bucket=B):
            total_d, parts_d = run(
                off, tuple(shard_idx), tuple(shard_val), fixed_w,
                tuple(re_bufs), tuple(re_slots))
            total = np.asarray(total_d)[:n]
        if extras:
            total = total.copy()
            for _pos, extra in extras:
                total += extra
        if not per_coordinate:
            return total
        parts = {}
        extra_by_pos = dict(extras)
        for pos, (kind, name, _si) in enumerate(st.plan):
            p = np.asarray(parts_d[pos])[:n]
            if pos in extra_by_pos:
                p = p + extra_by_pos[pos]
            parts[name] = p
        return total, parts

    # -- request parsing ---------------------------------------------------
    def _resolve_all(self, rows: List[dict],
                     st: _ModelState) -> Dict[str, HostSparse]:
        """Resolve every row's features for EVERY shard in one pass
        through the state's feature router — the same resolution (+
        implicit intercept) the Avro data reader applies, so served rows
        see the exact training-time feature space. Unknown features are
        dropped (per-shard feature selection, as in the batch path).
        One iteration instead of one per shard: at production QPS the
        per-feature dict lookups are the serving CPU floor."""
        S = len(st.shard_order)
        rget = st.router.get  # hoisted: this runs once per FEATURE
        per: List[List[list]] = [[] for _ in range(S)]
        for r in rows:
            rowbufs: List[Optional[list]] = [None] * S
            feats = r.get("features") or ()
            if feats and type(feats[0]) is dict:
                # hot shape (JSON rows): comprehension + C-level map keep
                # the per-feature python overhead at the bytecode floor
                keyed = [
                    (rget(f["name"] if type(f["name"]) is str
                          else str(f["name"]))
                     if not f.get("term") else
                     rget(f"{f['name']}\x01{f['term']}"),
                     f.get("value", 1.0))
                    for f in feats if "name" in f]
            else:
                keyed = []
                for name, term, value in feats:
                    if type(name) is not str:
                        name = str(name)
                    if term:
                        key = (f"{name}\x01{term}" if type(term) is str
                               else f"{name}\x01{term!s}")
                    else:
                        key = name
                    keyed.append((rget(key), value))
            for hits, value in keyed:
                if hits:
                    for si, idx in hits:
                        b = rowbufs[si]
                        if b is None:
                            b = rowbufs[si] = []
                        b.append((idx, value))
            for si in range(S):
                per[si].append(rowbufs[si] if rowbufs[si] is not None
                               else [])
        out: Dict[str, HostSparse] = {}
        for si, shard in enumerate(st.shard_order):
            parsed = per[si]
            intercept = st.intercepts[si]
            if intercept is not None and intercept >= 0:
                for p in parsed:
                    p.append((intercept, 1.0))
            k = max(max((len(p) for p in parsed), default=0), 1)
            indices = np.zeros((len(rows), k), np.int32)
            values = np.zeros((len(rows), k))
            for i, p in enumerate(parsed):
                for j, (idx, val) in enumerate(p):
                    indices[i, j] = idx
                    values[i, j] = val
            out[shard] = HostSparse(indices, values,
                                    st.index_maps[shard].size)
        return out

    @staticmethod
    def _entity_column_values(rows: List[dict], coord: RandomEffectModel,
                              name: str) -> np.ndarray:
        """Per-row entity ids for one random coordinate; a row without an
        id for this effect gets a sentinel no real id can equal, so it
        falls into the fixed-effect-only path."""
        keys = [k for k in (coord.entity_column, name, coord.effect_name)
                if k]
        out = []
        for r in rows:
            ids = r.get("entityIds") or {}
            val = None
            for key in keys:
                if key in ids:
                    val = ids[key]
                    break
            out.append(_NO_ENTITY if val is None else str(val))
        return np.asarray(out)

    # -- introspection -----------------------------------------------------
    def coeff_cache_stats(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {"hits": c.hits, "misses": c.misses,
                   "evictions": c.evictions, "size": len(c),
                   "hit_rate": c.hit_rate}
            for name, c in self._state.coeff_caches.items()
        }

    def paged_table_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-coordinate device-residency stats (empty when the paged
        path is off or no coordinate is eligible)."""
        return {name: t.stats() for name, t in self._state.paged.items()}

    @property
    def paged_active(self) -> bool:
        """True when the fused paged hot path serves this model."""
        return self._state.fused_sig is not None


def _max_live_nnz(sp: HostSparse) -> int:
    """Widest row by LIVE (nonzero-value) entries — rows narrower than
    the storage width still fit the compiled pad width."""
    if sp.values is None:
        return sp.indices.shape[1]
    return int((np.asarray(sp.values) != 0).sum(axis=1).max(initial=0))
