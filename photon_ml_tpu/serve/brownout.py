"""Brownout controller: move the DEFAULT degradation level under load.

The degradation ladder (session.py) answers a per-request question —
"can this request's remaining budget cover a cold-coefficient fault?".
This module answers the fleet-health one: when the admission queue's
wait EWMA shows SUSTAINED overload, every request should start at a
cheaper ladder level (resident-only, then fixed-effect-only) so the
replica sheds work before it sheds requests — 429 becomes the last
resort, not the first. Snap ML's hierarchical-composition argument
(arXiv:1803.06333) applied to operations: each model level must stay
useful when the level below it is unavailable, and an overloaded store
IS an unavailable level.

Mechanics: the batcher feeds every request's observed queue wait into
:meth:`note_queue_wait`; the controller keeps an EWMA and compares it
against per-level enter thresholds (level 2's above level 1's) with
hysteresis on the way down (``exit_ratio`` of the enter threshold) and
a minimum dwell so the level cannot flap batch-to-batch. The current
level becomes the FLOOR of every new request's :class:`ScoreContext`;
a request may still degrade further on its own budget. Level changes
are exported through ``photon_serve_brownout_level`` — the metrics call
happens AFTER the controller's lock is released (snapshot-then-fire,
the PT405 discipline: never call foreign code under your own lock).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

__all__ = ["BrownoutController"]


class BrownoutController:
    """Queue-wait-EWMA keyed ladder-level controller.

    ``enter_ms`` maps level -> the EWMA (ms) at which that level engages
    (defaults: level 1 at 50ms, level 2 at 200ms). The level drops back
    only when the EWMA falls below ``exit_ratio`` of the CURRENT level's
    enter threshold AND the level has been held for ``min_dwell_s`` —
    both guards exist because an engaged brownout itself shortens queue
    waits, which without hysteresis immediately argues for disengaging.

    ``time_fn`` is injectable so tests drive the dwell clock without
    sleeping. Thread-safe; every method is safe from the batcher's
    worker thread and from request threads concurrently.
    """

    def __init__(self, enter_ms: Optional[dict] = None,
                 exit_ratio: float = 0.5, alpha: float = 0.1,
                 min_dwell_s: float = 2.0, max_level: int = 2,
                 metrics=None,
                 time_fn: Callable[[], float] = time.monotonic):
        self.enter_ms = dict(enter_ms) if enter_ms else {1: 50.0, 2: 200.0}
        if not (0.0 < exit_ratio < 1.0):
            raise ValueError(f"exit_ratio must be in (0,1), {exit_ratio}")
        self.exit_ratio = float(exit_ratio)
        self.alpha = float(alpha)
        self.min_dwell_s = float(min_dwell_s)
        self.max_level = int(max_level)
        self._metrics = metrics
        self._time = time_fn
        self._lock = threading.Lock()
        self._ewma_ms: Optional[float] = None
        self._level = 0
        self._level_since = self._time()
        self.transitions = 0

    @property
    def level(self) -> int:
        """The current default ladder level (the floor for new requests)."""
        return self._level

    @property
    def queue_wait_ewma_ms(self) -> float:
        with self._lock:
            return self._ewma_ms or 0.0

    def note_queue_wait(self, wait_ms: float) -> int:
        """Fold one request's observed queue wait into the EWMA and
        re-evaluate the level. Returns the (possibly new) level."""
        changed_to: Optional[int] = None
        with self._lock:
            if self._ewma_ms is None:
                self._ewma_ms = float(wait_ms)
            else:
                self._ewma_ms += self.alpha * (wait_ms - self._ewma_ms)
            target = self._target_level_locked()
            if target != self._level:
                now = self._time()
                # escalation is immediate (overload is now); de-escalation
                # waits out the dwell so recovery cannot flap
                if (target > self._level
                        or now - self._level_since >= self.min_dwell_s):
                    self._level = target
                    self._level_since = now
                    self.transitions += 1
                    changed_to = target
            level = self._level
        if changed_to is not None and self._metrics is not None:
            self._metrics.set_brownout_level(changed_to)
        return level

    def _target_level_locked(self) -> int:
        """The level the current EWMA argues for, with hysteresis: to
        ENTER level L the EWMA must exceed enter_ms[L]; to LEAVE the
        current level it must fall below exit_ratio * enter_ms[level]."""
        ewma = self._ewma_ms or 0.0
        target = 0
        for lvl in sorted(self.enter_ms):
            if lvl <= self.max_level and ewma >= self.enter_ms[lvl]:
                target = lvl
        if target < self._level:
            # de-escalate only once clearly below the held level's band
            floor = self.exit_ratio * self.enter_ms.get(
                self._level, float("inf"))
            if ewma >= floor:
                return self._level
        return target
