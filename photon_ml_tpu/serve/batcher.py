"""Deadline-based micro-batcher with a bounded admission queue.

The device scores padded batches; requests arrive one at a time. The
micro-batcher bridges the two: an admitted request waits at most
``max_delay_ms`` for companions, and a batch dispatches as soon as it
reaches ``max_batch`` rows — the classic throughput/latency knob
("right-sized batches keep the device fed", PAPERS.md GPU-learning
entry; Snap ML's pipelined host tier).

**Bounded, not elastic.** The admission queue holds at most ``max_queue``
requests. When it is full, :meth:`MicroBatcher.submit` raises
:class:`QueueFullError` IMMEDIATELY — explicit load shedding the caller
can convert into HTTP 429/503 — instead of queuing unboundedly and
converting overload into unbounded latency for everyone. (A server that
melts down by latency is much harder to operate than one that says no.)

**Stuck-batch watchdog.** A scoring execution that wedges (a device gone
bad, a compile that never returns — see docs/PERF.md for this
environment's tunnel history) would otherwise hang the worker and every
queued request behind it. Each execution runs under the PR-1 watchdog
discipline from ``parallel/resilience.py``: the batch is scored on a
helper thread joined with a timeout, and on expiry every request of that
batch fails with :class:`BatchWatchdogTimeout` (a
``resilience.WatchdogTimeout`` subclass) while the worker moves on —
same abandon-the-thread semantics as the health barrier's allgather
watchdog, for the same reason.
"""

from __future__ import annotations

import inspect
import logging
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.obs.logging import SlowRequestLog
from photon_ml_tpu.parallel.resilience import WatchdogTimeout

_log = logging.getLogger(__name__)

__all__ = ["QueueFullError", "BatchWatchdogTimeout", "MicroBatcher",
           "PendingRequest", "ScoreContext"]


class ScoreContext:
    """Per-batch scoring budget + degradation state, threaded from the
    batcher into ``ScoringSession.score_rows``. ``deadline_at`` is an
    absolute ``time.monotonic()`` instant (None = no deadline);
    ``level`` is the ladder FLOOR the brownout controller set for this
    batch (0 full fidelity, 1 resident-coefficients-only, 2
    fixed-effect-only); the session raises ``degraded`` to the level it
    actually served at and appends a reason per escalation (``budget``,
    ``store_fault``, ``brownout``)."""

    __slots__ = ("deadline_at", "level", "degraded", "reasons")

    def __init__(self, deadline_at: Optional[float] = None,
                 level: int = 0):
        self.deadline_at = deadline_at
        self.level = int(level)
        self.degraded = int(level)
        self.reasons: List[str] = (["brownout"] if level > 0 else [])

    def remaining_s(self) -> Optional[float]:
        """Seconds of budget left (None = unlimited; may be <= 0)."""
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.monotonic()


class QueueFullError(RuntimeError):
    """The request was SHED, not queued — either the admission queue was
    at capacity (``cause="queue_full"``) or the request's deadline
    expired while it waited for a batch slot (``cause="deadline"``).
    Callers should surface this as retryable backpressure (HTTP 429);
    ``retry_after_s`` is the server's backoff hint — the backlog ahead
    of a retry divided by the MEASURED drain rate (EWMA of batch
    service time), i.e. roughly how long a retry would wait."""

    def __init__(self, depth: int, capacity: int,
                 retry_after_s: float = 0.0, cause: str = "queue_full"):
        what = ("admission queue full" if cause == "queue_full"
                else "deadline expired while queued")
        super().__init__(
            f"{what} ({depth}/{capacity}); request shed — "
            "retry with backoff or scale out")
        self.depth = depth
        self.capacity = capacity
        self.retry_after_s = float(retry_after_s)
        self.cause = cause


class BatchWatchdogTimeout(WatchdogTimeout):
    """One scoring execution exceeded the batch watchdog; the batch's
    requests fail, the worker abandons the execution thread and
    continues (fail-stop discipline from ``parallel/resilience.py``)."""


class PendingRequest:
    """One admitted request: rows in, (scores, parts) or an exception
    out. ``result()`` blocks the submitting thread until the batcher's
    worker resolves it; ``add_done_callback`` is the non-blocking
    alternative the asyncio front end uses (the callback fires on the
    batcher's worker thread — bridge back to the event loop with
    ``loop.call_soon_threadsafe``)."""

    __slots__ = ("rows", "per_coordinate", "_event", "_result", "_error",
                 "admitted_at", "_callbacks", "_cb_lock", "request_id",
                 "trace_ctx", "deadline_at", "degraded")

    def __init__(self, rows: Sequence[dict], per_coordinate: bool,
                 request_id: Optional[str] = None,
                 deadline_at: Optional[float] = None):
        self.rows = list(rows)
        self.per_coordinate = per_coordinate
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._callbacks: List[Callable] = []
        self._cb_lock = threading.Lock()
        self.admitted_at = time.monotonic()
        # absolute budget expiry (monotonic) — every later stage checks
        # remaining = deadline_at - now before spending work on this
        # request; the ladder level the session actually served at lands
        # in `degraded` for the response body
        self.deadline_at = deadline_at
        self.degraded = 0
        # identity captured at admission: the submitting thread's trace
        # context rides the request across the worker-thread handoff, so
        # batcher/session/install spans land under the request's trace
        self.request_id = request_id
        self.trace_ctx = obs_trace.current_context()

    def set_result(self, value) -> None:
        self._result = value
        self._event.set()
        self._fire_callbacks()

    def set_error(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()
        self._fire_callbacks()

    def _fire_callbacks(self) -> None:
        with self._cb_lock:
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb(self)

    def add_done_callback(self, cb: Callable) -> None:
        """Invoke ``cb(self)`` when the request resolves (immediately if
        it already has). Runs on whichever thread resolves the request —
        the submitter may race the worker, so registration is locked
        against the resolution's callback drain."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("scoring request not resolved in time")
        if self._error is not None:
            raise self._error
        return self._result


class MicroBatcher:
    """Coalesce scoring requests into bounded, deadline-dispatched batches.

    ``score_fn(rows, per_coordinate)`` is the execution target — in the
    serving stack, ``ScoringSession.score_rows``. Requests carrying
    multiple rows are admitted atomically and their scores sliced back
    out of the batch result in order. ``max_batch`` bounds the rows per
    execution; a single request larger than ``max_batch`` is rejected at
    submit (ValueError) — the transport layer splits if it wants to.

    ``watchdog_s=None`` disables the stuck-batch watchdog (execution runs
    inline on the worker); the default keeps it armed.

    ``request_deadline_s`` arms queued-request expiry: a request that is
    still waiting when its admission time + deadline passes is shed by
    the worker (:class:`QueueFullError` with ``cause="deadline"``)
    instead of being scored — under sustained overload the queue would
    otherwise serve only requests whose clients already gave up. A
    per-request ``deadline_s`` at :meth:`submit` (the propagated
    ``X-Deadline-Ms`` budget) overrides it; either way the expiry is
    checked at every stage BEFORE work is spent (admission, queue,
    pre-compute), with the drop stage recorded in
    ``photon_serve_deadline_drop_total{stage}``.

    ``brownout`` is an optional
    :class:`~photon_ml_tpu.serve.brownout.BrownoutController`: the
    batcher feeds it every request's queue wait and stamps its current
    level into each batch's :class:`ScoreContext` as the degradation
    floor (the session may degrade further on budget/faults).
    """

    def __init__(self, score_fn: Callable, *, max_batch: int = 64,
                 max_delay_ms: float = 5.0, max_queue: int = 256,
                 watchdog_s: Optional[float] = 60.0,
                 request_deadline_s: Optional[float] = None, metrics=None,
                 brownout=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._score_fn = score_fn
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.watchdog_s = watchdog_s
        self.request_deadline_s = (None if request_deadline_s is None
                                   else float(request_deadline_s))
        self.brownout = brownout
        # does score_fn accept the ScoreContext? Checked ONCE here so
        # plain fakes (tests pass lambdas) keep working ctx-less
        try:
            sig = inspect.signature(score_fn)
            self._ctx_ok = ("ctx" in sig.parameters or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in sig.parameters.values()))
        except (TypeError, ValueError):
            self._ctx_ok = False
        # measured drain rate for retry_after_s: EWMA of batch service
        # time + EWMA of requests per batch (worker writes, admission
        # reads — both under _ewma_lock)
        self._ewma_lock = threading.Lock()
        self._svc_ewma_s: Optional[float] = None
        self._rpb_ewma: Optional[float] = None
        self._queue: "queue.Queue[Optional[PendingRequest]]" = queue.Queue(
            maxsize=int(max_queue))
        self._metrics = metrics
        self._closed = False
        self._stop = threading.Event()
        # worker joins that outlived the drain grace (a wedged scoring
        # execution); counted + logged, mirroring producer_join_timeouts
        self.join_timeouts = 0
        # top-N slow-request exemplars (request id + queue/compute split)
        self.slow_log = SlowRequestLog(top_n=10)
        self._carry: Optional[PendingRequest] = None  # worker-only state
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="photon-serve-batcher")
        self._worker.start()

    # -- submission --------------------------------------------------------
    def submit(self, rows: Sequence[dict],
               per_coordinate: bool = False,
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None) -> PendingRequest:
        """Admit a request (non-blocking). Raises :class:`QueueFullError`
        when the queue is at capacity and ValueError for oversized or
        empty requests; never blocks the caller on a full queue.
        ``deadline_s`` is this request's remaining budget (overrides the
        batcher-wide ``request_deadline_s``); a request arriving with no
        budget left is dropped HERE — the cheapest possible point."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        rows = list(rows)
        if not rows:
            raise ValueError("empty request (no rows)")
        if len(rows) > self.max_batch:
            raise ValueError(
                f"request of {len(rows)} rows exceeds max_batch="
                f"{self.max_batch}; split it client-side")
        budget = (float(deadline_s) if deadline_s is not None
                  else self.request_deadline_s)
        if budget is not None and budget <= 0.0:
            if self._metrics is not None:
                self._metrics.record_shed(cause="deadline")
                self._metrics.record_deadline_drop("admission")
            raise QueueFullError(self._queue.qsize(), self._queue.maxsize,
                                 retry_after_s=self.retry_after_s,
                                 cause="deadline")
        deadline_at = (None if budget is None
                       else time.monotonic() + budget)
        req = PendingRequest(rows, per_coordinate, request_id=request_id,
                             deadline_at=deadline_at)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            if self._metrics is not None:
                self._metrics.record_shed(cause="queue_full")
            raise QueueFullError(self._queue.qsize(), self._queue.maxsize,
                                 retry_after_s=self.retry_after_s,
                                 cause="queue_full") from None
        if self._metrics is not None:
            self._metrics.set_queue_depth(self._queue.qsize())
        return req

    @property
    def retry_after_s(self) -> float:
        """Backoff hint for shed requests: the backlog ahead of a retry
        divided by the MEASURED drain rate — queue depth over the EWMA
        of requests-per-batch, times the EWMA of batch service time.
        The previous static queue-depth x batching-deadline estimate
        ignored how long batches actually take, so it under-advised
        whenever scoring dominated the delay and over-advised under
        sparse traffic with mixed batch sizes. Before the first batch
        completes (no measurement yet) the static estimate remains the
        fallback. Floored at one batching deadline either way."""
        qsize = self._queue.qsize()
        with self._ewma_lock:
            svc, rpb = self._svc_ewma_s, self._rpb_ewma
        if svc is not None and rpb:
            return max(self.max_delay_s, (qsize / max(rpb, 1.0)) * svc)
        batches_queued = qsize / max(self.max_batch, 1)
        return max(self.max_delay_s, batches_queued * self.max_delay_s)

    def score(self, rows: Sequence[dict], per_coordinate: bool = False,
              timeout: Optional[float] = None,
              request_id: Optional[str] = None):
        """Blocking convenience: submit + wait for the result."""
        return self.submit(rows, per_coordinate,
                           request_id=request_id).result(timeout)

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    def close(self, drain_timeout_s: float = 5.0) -> None:
        """Stop admitting, let the worker drain queued requests, join it
        with a bounded timeout; a worker that outlives the grace (wedged
        execution) is counted and logged, never waited on forever."""
        if self._closed:
            return
        self._closed = True
        try:
            self._queue.put_nowait(None)  # wake the worker for shutdown
        except queue.Full:
            pass  # the stop event below wakes the idle poll instead
        self._stop.set()
        self._worker.join(drain_timeout_s)
        if self._worker.is_alive():
            self.join_timeouts += 1
            _log.warning(
                "MicroBatcher: worker thread %r still alive %.1fs after "
                "close() (wedged scoring execution?); leaking it as a "
                "daemon (join timeouts so far: %d)",
                self._worker.name, drain_timeout_s, self.join_timeouts)

    # -- worker ------------------------------------------------------------
    # idle-poll interval (seconds) for the worker's first-request wait; a
    # class attribute so tests can shrink it without monkeypatching
    _idle_poll_s = 0.2

    def _expired(self, req: PendingRequest, stage: str = "queue") -> bool:
        """Shed a request whose deadline passed (worker-side; returns
        True when the request was shed and must be skipped). ``stage``
        labels WHERE the budget ran out in the drop counter — the
        acceptance gate for "dropped before device compute"."""
        if req.deadline_at is None or time.monotonic() < req.deadline_at:
            return False
        if self._metrics is not None:
            self._metrics.record_shed(cause="deadline")
            self._metrics.record_deadline_drop(stage)
        req.set_error(QueueFullError(
            self._queue.qsize(), self._queue.maxsize,
            retry_after_s=self.retry_after_s, cause="deadline"))
        return True

    def _collect_batch(self) -> Optional[List[PendingRequest]]:
        """Block for the first request, then coalesce companions until
        the deadline (first request's arrival + max_delay) or max_batch
        rows. Requests are admitted whole: one whose rows would overflow
        the batch stays queued for the next one. Requests whose own
        deadline expired while queued are shed, not scored."""
        first = None
        while first is None:
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                try:
                    # bounded idle poll: each expiry rechecks the stop
                    # event, so a closed batcher can never leave the
                    # worker parked in a blocking get forever
                    first = self._queue.get(timeout=self._idle_poll_s)
                except queue.Empty:
                    if self._stop.is_set():
                        return None
                    continue
                if first is None:
                    return None
            if self._expired(first):
                first = None
        batch = [first]
        rows = len(first.rows)
        deadline = time.monotonic() + self.max_delay_s
        while rows < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is None:
                self._queue.put(None)  # re-post the shutdown token
                break
            if self._expired(nxt):
                continue
            if rows + len(nxt.rows) > self.max_batch:
                # no peeking API on queue.Queue: hold the overflow
                # request back; it seeds the next batch
                self._carry = nxt
                break
            batch.append(nxt)
            rows += len(nxt.rows)
        return batch

    def _run(self) -> None:
        while True:
            batch = self._collect_batch()
            if batch is None:
                return
            self._execute(batch)
            if self._metrics is not None:
                self._metrics.set_queue_depth(self._queue.qsize())
            if (self._closed and self._carry is None
                    and self._queue.empty()):
                return

    def _score_with_watchdog(self, rows: List[dict], per_coordinate: bool,
                             ctx: Optional[ScoreContext] = None):
        kwargs = {"ctx": ctx} if ctx is not None else {}
        if self.watchdog_s is None:
            return self._score_fn(rows, per_coordinate, **kwargs)
        box: dict = {}
        tctx = obs_trace.current_context()  # ride into the helper thread

        def run():
            try:
                with obs_trace.use_context(tctx):
                    box["result"] = self._score_fn(rows, per_coordinate,
                                                   **kwargs)
            except BaseException as e:  # surfaced to the batch below
                box["error"] = e

        t = threading.Thread(target=run, daemon=True,
                             name="photon-serve-score")
        t.start()
        t.join(self.watchdog_s)
        if t.is_alive():
            raise BatchWatchdogTimeout(
                f"scoring execution exceeded the {self.watchdog_s:.1f}s "
                "batch watchdog (stuck device or compile); abandoning it "
                "and failing this batch's requests")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _execute(self, batch: List[PendingRequest]) -> None:
        # last budget check BEFORE device compute: a request that expired
        # between queue pickup and execution is dropped here, stage
        # "pre_compute" — never after scoring has been paid for
        batch = [req for req in batch
                 if not self._expired(req, stage="pre_compute")]
        if not batch:
            return
        rows: List[dict] = []
        for req in batch:
            rows.extend(req.rows)
        t0 = time.monotonic()
        queue_waits = [(t0 - req.admitted_at) * 1e3 for req in batch]
        per_coord = any(r.per_coordinate for r in batch)
        # the batch's scoring budget is its TIGHTEST member's deadline;
        # the brownout level is the ladder floor for the whole batch
        ctx: Optional[ScoreContext] = None
        if self._ctx_ok:
            deadlines = [r.deadline_at for r in batch
                         if r.deadline_at is not None]
            level = self.brownout.level if self.brownout is not None else 0
            ctx = ScoreContext(
                deadline_at=min(deadlines) if deadlines else None,
                level=level)
        # adopt the first traced request's context so the batch's session
        # and device-compute spans carry its trace/request id (a batch is
        # one execution; per-request attribution is the args list below)
        tctx = next((r.trace_ctx for r in batch
                     if r.trace_ctx is not None), None)
        try:
            with obs_trace.use_context(tctx), \
                    obs_trace.span(
                        "batch.execute", cat="serve", rows=len(rows),
                        requests=len(batch),
                        request_ids=[r.request_id for r in batch
                                     if r.request_id]):
                result = self._score_with_watchdog(rows, per_coord,
                                                   ctx=ctx)
        except BaseException as e:
            for req in batch:
                req.set_error(e)
            if self._metrics is not None:
                self._metrics.record_error()
            return
        if per_coord:
            scores, parts = result
        else:
            scores, parts = result, {}
        elapsed_ms = (time.monotonic() - t0) * 1e3
        if self._metrics is not None:
            self._metrics.record_batch(len(rows), self.max_batch,
                                       elapsed_ms)
        # fold this batch into the drain-rate EWMAs retry_after_s reads
        alpha = 0.2
        elapsed_s = elapsed_ms / 1e3
        with self._ewma_lock:
            self._svc_ewma_s = (
                elapsed_s if self._svc_ewma_s is None else
                self._svc_ewma_s + alpha * (elapsed_s - self._svc_ewma_s))
            self._rpb_ewma = (
                float(len(batch)) if self._rpb_ewma is None else
                self._rpb_ewma + alpha * (len(batch) - self._rpb_ewma))
        degraded = ctx.degraded if ctx is not None else 0
        now = time.monotonic()
        start = 0
        for req, waited_ms in zip(batch, queue_waits):
            end = start + len(req.rows)
            sl = {k: v[start:end] for k, v in parts.items()}
            req.degraded = degraded
            req.set_result((scores[start:end], sl)
                           if req.per_coordinate else scores[start:end])
            if self._metrics is not None:
                # queue_wait: admission -> execution start; compute: the
                # batch's scoring wall attributed to each of its requests
                self._metrics.record_request(
                    len(req.rows), (now - req.admitted_at) * 1e3,
                    queue_wait_ms=waited_ms, compute_ms=elapsed_ms)
                if degraded:
                    self._metrics.record_degraded(degraded)
            if self.brownout is not None:
                self.brownout.note_queue_wait(waited_ms)
            self.slow_log.note(
                req.request_id, (now - req.admitted_at) * 1e3,
                queue_wait_ms=round(waited_ms, 3),
                compute_ms=round(elapsed_ms, 3), rows=len(req.rows))
            start = end
