"""Device-resident paged table of per-entity random-effect coefficients.

The host-side :class:`~photon_ml_tpu.serve.coeff_cache
.EntityCoefficientLRU` keeps the hot working set in HOST memory, which
forces every batch through a host gather (rebuild score buckets, pack a
coefficient matrix, upload it) — the per-batch round-trip that capped
BENCH_serving.json at ~6k rows/s. This module is the device-side tier of
that hierarchy (Snap ML's "keep the working set resident next to the
compute", arXiv:1803.06333): the hot entities' coefficients live in a
padded ``(pages, page_rows, k_pad)`` buffer ON DEVICE, densified into the
shard's global feature space, and a warm batch's random-effect margins
are one :func:`~photon_ml_tpu.ops.pallas_kernels.paged_gather_score`
call inside the session's fused executable — no host gather, no upload.

Design points:

* **Pages are the unit of transfer and eviction.** Installs write a host
  mirror then refresh only the touched pages through a jitted
  ``dynamic_update_slice`` whose page index is a TRACED argument — one
  executable per table shape, shared process-wide, never a recompile as
  pages churn. Eviction drops the least-recently-SCORED full page (all
  of its entities leave the slot map at once); per-entity LRU bookkeeping
  on the device tier would cost more host work than it saves.
* **Functional updates keep in-flight batches consistent.** A scoring
  call snapshots ``device_buffer`` + its slots under the table lock; an
  install builds a NEW device array (jax functional update), so the
  snapshot stays valid however the install/evict races the batch.
* **Negative entries are host-side only.** Entities the store does not
  know get a ``slot -1`` sentinel (scores 0 in the gather, matching the
  fixed-effect-only fallback) and are remembered in an absent set so a
  scan of unknown ids cannot trigger repeated store faults — they never
  occupy device rows.
* **Dense rows bound the shard size.** A row is the entity's coefficient
  vector scattered into ``k_pad`` dense global dims; coordinates whose
  feature space exceeds ``dense_dim_max`` (or that use a sketch
  projection, whose "local map" is a hash, not a dict) stay on the
  host-LRU path — the session gates eligibility per coordinate.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.serve.coeff_cache import CoeffEntry
from photon_ml_tpu.utils import transfer_budget

__all__ = ["PagedCoefficientTable", "entry_supported"]


def entry_supported(entry: Optional[CoeffEntry]) -> bool:
    """Only plain global-id->slot dict local maps densify into a page
    row; sketch-projected entries (shared hash map) do not."""
    return entry is None or isinstance(entry.local_map, dict)


@functools.lru_cache(maxsize=None)
def _page_setter(page_rows: int, dim: int, dtype_name: str):
    """The (page_rows, dim, dtype)-shaped page refresh executable. The
    page index is a traced scalar, so every page of every same-shaped
    table shares ONE compile (cached per shape process-wide)."""
    import jax

    @jax.jit
    def set_page(buf, page, rows):
        start = page * page_rows
        return jax.lax.dynamic_update_slice(buf, rows, (start, 0))

    return set_page


class PagedCoefficientTable:
    """Paged device residency for one random-effect coordinate.

    ``dim`` — dense width of a row (the shard's index-map size);
    ``pages`` x ``page_rows`` bound the device working set. ``loader``
    is unused here by design: the table only stores what the session
    installs (the session faults cold entities through the LRU so cache
    hit/miss accounting stays in one place).
    """

    def __init__(self, dim: int, *, pages: int = 4, page_rows: int = 256,
                 dtype=np.float32, name: str = "", metrics=None):
        if dim < 1:
            raise ValueError(f"dense dim must be >= 1, got {dim}")
        if pages < 1 or page_rows < 1:
            raise ValueError(
                f"need pages >= 1 and page_rows >= 1, got "
                f"{pages}x{page_rows}")
        self.dim = int(dim)
        self.pages = int(pages)
        self.page_rows = int(page_rows)
        self.name = name
        self.dtype = np.dtype(dtype)
        self._metrics = metrics
        self._lock = threading.Lock()
        self._host = np.zeros((self.pages * self.page_rows, self.dim),
                              self.dtype)
        self._device = transfer_budget.device_put(
            self._host, what=f"serve.paged_table[{name}]")
        self._slots: Dict[str, int] = {}
        self._absent: set = set()
        self._page_ids: List[List[str]] = [[] for _ in range(self.pages)]
        self._fill = [0] * self.pages
        self._clock = 0
        self._page_last = [0] * self.pages
        self._setter = _page_setter(self.page_rows, self.dim,
                                    self.dtype.name)
        # counters (exposed through session stats + /metrics)
        self.installs = 0
        self.page_evictions = 0
        self.absent_marks = 0
        self.membership_drops = 0

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    @property
    def capacity(self) -> int:
        return self.pages * self.page_rows

    @property
    def device_buffer(self):
        return self._device

    def resident_ids(self) -> List[str]:
        with self._lock:
            return list(self._slots)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "resident": len(self._slots),
                "capacity": self.capacity,
                "installs": self.installs,
                "page_evictions": self.page_evictions,
                "absent": len(self._absent),
                "membership_drops": self.membership_drops,
            }

    # -- lookup ------------------------------------------------------------
    def lookup(self, entity_ids: Sequence[str]
               ) -> Tuple[object, np.ndarray, List[str]]:
        """One consistent read for a batch: ``(device_buffer, slots,
        missing)``. ``slots`` is int32 per id (-1 for absent/unknown);
        ``missing`` lists the deduplicated ids that are neither resident
        nor known-absent — the caller faults those through the LRU and
        (asynchronously) installs them. Touches the hit pages' LRU
        clocks."""
        slots = np.empty(len(entity_ids), np.int32)
        missing: List[str] = []
        seen_missing: set = set()
        with self._lock:
            self._clock += 1
            clock = self._clock
            get = self._slots.get
            for i, eid in enumerate(entity_ids):
                s = get(eid)
                if s is None:
                    slots[i] = -1
                    if eid not in self._absent and eid not in seen_missing:
                        missing.append(eid)
                        seen_missing.add(eid)
                else:
                    slots[i] = s
                    self._page_last[s // self.page_rows] = clock
            return self._device, slots, missing

    def warm_device_path(self) -> None:
        """Trigger the page-refresh executable's compile during warmup
        (the refreshed buffer is identical — page 0 rewritten with its
        own contents — so this is shape-warming, not a data change)."""
        import jax.numpy as jnp

        with self._lock:
            self._device = self._setter(
                self._device, 0,
                jnp.asarray(self._host[:self.page_rows]))

    def retain_only(self, keep) -> int:
        """Drop every resident entity for which ``keep(entity_id)`` is
        falsy and compact the survivors into the low pages — the
        membership re-own path: when a replica's owned slice shrinks
        (or rotates) under a new epoch, the pages its no-longer-owned
        entities held must be free for the owned slice IMMEDIATELY, not
        after page-LRU churn evicts them one cold fault at a time.
        The absent set is kept (store absence is a property of the
        model version, not of ownership). Returns the number of rows
        dropped. Like :meth:`install`, the refresh is functional —
        in-flight batches keep scoring their snapshot."""
        with self._lock:
            survivors = [(eid, self._host[slot].copy())
                         for eid, slot in sorted(self._slots.items(),
                                                 key=lambda kv: kv[1])
                         if keep(eid)]
            dropped = len(self._slots) - len(survivors)
            if dropped == 0:
                return 0
            pages_before = sum(1 for f in self._fill if f)
            self._host[:] = 0
            self._slots.clear()
            self._page_ids = [[] for _ in range(self.pages)]
            self._fill = [0] * self.pages
            for slot, (eid, row) in enumerate(survivors):
                page = slot // self.page_rows
                self._host[slot] = row
                self._slots[eid] = slot
                self._page_ids[page].append(eid)
                self._fill[page] = slot % self.page_rows + 1
            self.membership_drops += dropped
            pages_after = sum(1 for f in self._fill if f)
            touched = range(max(pages_before, pages_after))
            with obs_trace.span("paged.retain_only", cat="serve",
                                table=self.name, dropped=dropped,
                                pages=len(touched)):
                buf = self._device
                for page in touched:
                    rows = transfer_budget.device_put(
                        self._host[page * self.page_rows:
                                   (page + 1) * self.page_rows],
                        what=f"serve.paged_retain[{self.name}]")
                    buf = self._setter(buf, page, rows)
                self._device = buf
        if self._metrics is not None:
            self._metrics.record_membership(evictions=dropped)
        return dropped

    # -- install / evict ---------------------------------------------------
    def dense_row(self, entry: CoeffEntry) -> np.ndarray:
        row = np.zeros(self.dim, self.dtype)
        coeffs = entry.coefficients
        for g, s in entry.local_map.items():
            if 0 <= g < self.dim and s < coeffs.shape[0]:
                row[g] = coeffs[s]
        return row

    def _allocate(self) -> int:
        """A free flat slot, evicting the least-recently-scored full
        page when the table is at capacity (caller holds the lock)."""
        for p in range(self.pages):
            if self._fill[p] < self.page_rows:
                return p * self.page_rows + self._fill[p]
        victim = min(range(self.pages), key=self._page_last.__getitem__)
        for eid in self._page_ids[victim]:
            self._slots.pop(eid, None)
        self._page_ids[victim] = []
        self._fill[victim] = 0
        self._host[victim * self.page_rows:
                   (victim + 1) * self.page_rows] = 0
        self.page_evictions += 1
        if self._metrics is not None:
            self._metrics.record_paged(page_evictions=1)
        return victim * self.page_rows

    def install(self, entries: Dict[str, Optional[CoeffEntry]]) -> int:
        """Install a fault's resolutions: positive entries get page rows
        (allocating/evicting as needed) and the touched pages are
        refreshed on device; ``None`` resolutions join the absent set.
        Returns the number of rows written. Safe to call from the
        session's background installer while batches score."""
        fault_injection.check("paged.install")
        touched: set = set()
        installed = 0
        with self._lock:
            for eid, entry in entries.items():
                if entry is None:
                    if eid not in self._absent:
                        self._absent.add(eid)
                        self.absent_marks += 1
                    continue
                if not entry_supported(entry):
                    raise ValueError(
                        f"paged table {self.name!r} cannot hold sketch-"
                        "projected entries; gate the coordinate off the "
                        "paged path")
                slot = self._slots.get(eid)
                if slot is None:
                    slot = self._allocate()
                    page = slot // self.page_rows
                    self._slots[eid] = slot
                    self._page_ids[page].append(eid)
                    self._fill[page] = max(self._fill[page],
                                           slot % self.page_rows + 1)
                self._host[slot] = self.dense_row(entry)
                touched.add(slot // self.page_rows)
                installed += 1
            if installed:
                self.installs += installed
                # page-wise functional refresh: new buffer per install
                # burst, old snapshots stay valid for in-flight batches
                with obs_trace.span("paged.page_refresh", cat="serve",
                                    table=self.name,
                                    pages=len(touched), rows=installed):
                    buf = self._device
                    for page in sorted(touched):
                        rows = transfer_budget.device_put(
                            self._host[page * self.page_rows:
                                       (page + 1) * self.page_rows],
                            what=f"serve.paged_install[{self.name}]")
                        buf = self._setter(buf, page, rows)
                    self._device = buf
        if installed and self._metrics is not None:
            self._metrics.record_paged(installs=installed)
        return installed
