"""Registry-polling watcher: follow ``LATEST`` and hot-swap the session.

The push path is ``POST /admin/reload``; this is the pull path — a
daemon thread that polls the registry's ``LATEST`` pointer and swaps the
resident :class:`~photon_ml_tpu.serve.session.ScoringSession` when it
moves, so a gate promotion on another machine reaches every serving
process without an orchestrator fanning out reload calls.

Concurrent-publish tolerance (the failure mode this must survive): the
registry's atomic-rename discipline means a COMPLETE version appears in
one step, but the watcher can still observe (a) no ``LATEST`` yet —
``read_latest`` already retries ENOENT briefly and then reports None,
(b) a ``.tmp-`` staging dir next to real versions — never listed as a
version, (c) a crashed publisher that landed a version without moving
``LATEST`` — the pointer still names the old live version, so nothing
swaps. Any error opening or swapping to the new version is logged,
counted, and RETRIED on the next tick — the previous model keeps
serving; the watcher never tears down live state on a bad poll.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from photon_ml_tpu.parallel import fault_injection

__all__ = ["RegistryWatcher"]

_log = logging.getLogger(__name__)


class RegistryWatcher:
    """Poll ``registry.read_latest()`` every ``interval_s`` and swap the
    session when it names a version other than the active one.
    ``on_swap(version)`` / ``on_error(exc)`` are optional observation
    hooks (the serving driver logs through them).

    ``jitter_s`` adds a uniform random extra sleep per tick: in
    multi-replica mode every replica watches the SAME registry, and
    identical intervals would have N processes stat the same files (and
    then all swap) on the same tick — jitter de-synchronizes the
    stampede while keeping every replica within one interval+jitter of a
    promotion (the consistency the front door relies on).

    Consecutive FAILED polls back off exponentially (jittered, capped at
    ``error_backoff_max_s``) instead of hammering a down registry at the
    fixed interval — N replicas polling a struggling shared filesystem
    every tick is exactly the thundering herd that keeps it struggling.
    The first successful poll resets the schedule."""

    def __init__(self, registry, session, interval_s: float = 10.0,
                 on_swap: Optional[Callable[[str], None]] = None,
                 on_error: Optional[Callable[[Exception], None]] = None,
                 jitter_s: float = 0.0,
                 error_backoff_max_s: float = 300.0):
        self.registry = registry
        self.session = session
        self.interval_s = float(interval_s)
        self.jitter_s = max(0.0, float(jitter_s))
        self.on_swap = on_swap
        self.on_error = on_error
        self.errors = 0
        self.checks = 0
        # jittered exponential backoff applied ONLY after failed polls;
        # healthy ticks use interval_s + uniform jitter as before
        from photon_ml_tpu.parallel.resilience import Backoff

        self._error_backoff = Backoff(
            base_s=self.interval_s, factor=2.0,
            max_s=max(float(error_backoff_max_s), self.interval_s),
            jitter=0.1)
        # stop() joins that expired (a poll wedged inside a swap);
        # counted + logged, mirroring producer_join_timeouts
        self.join_timeouts = 0
        # stale-model serving: a failing registry (corrupt LATEST,
        # gate-refused version, unreadable manifest) must pin the live
        # model, not wedge reload — staleness_s is how long the process
        # has been serving without a confirmed-fresh poll, exported as
        # photon_serve_model_staleness_seconds so on-call sees a stuck
        # publish pipeline instead of a silent old model (poll thread
        # writes, metrics/healthz readers — both under _age_lock)
        self._age_lock = threading.Lock()
        self.last_success_at = time.monotonic()
        self._metrics = getattr(session, "metrics", None)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def staleness_s(self) -> float:
        """Seconds since the last SUCCESSFUL poll (0 right after one —
        an up-to-date pointer counts as success even with no swap)."""
        with self._age_lock:
            last = self.last_success_at
        return max(0.0, time.monotonic() - last)

    def check_once(self) -> Optional[str]:
        """One poll: returns the version swapped to, or None (no change,
        no live version yet, or a tolerated transient error)."""
        self.checks += 1
        try:
            fault_injection.check("registry.read")
            latest = self.registry.read_latest()
            if latest is None or latest == self.session.active_version:
                self._note_success()
                return None
            resolved = self.registry.open_version(latest)
            self.session.swap(resolved, version=latest)
        except Exception as e:
            # mid-publish registry states and swap failures are
            # transient by construction: keep serving, retry next tick —
            # the live _ModelState stays pinned and staleness age rises
            self.errors += 1
            if self._metrics is not None:
                self._metrics.set_model_staleness(self.staleness_s)
            if self.on_error is not None:
                self.on_error(e)
            return None
        self._note_success()
        membership = getattr(self.session, "membership", None)
        if membership is not None and membership.epoch > 0:
            # under an entity-affinity epoch the swap prewarmed only
            # this replica's owned slice — worth a line when reading a
            # replica's log against the front door's rebalance spans
            _log.info(
                "RegistryWatcher: swapped to %s under membership epoch "
                "%d (shard %d of %d); prewarmed owned slice only",
                latest, membership.epoch, membership.shard_index,
                membership.num_shards)
        if self.on_swap is not None:
            self.on_swap(latest)
        return latest

    def _note_success(self) -> None:
        with self._age_lock:
            self.last_success_at = time.monotonic()
        if self._metrics is not None:
            self._metrics.set_model_staleness(0.0)

    def _next_delay(self, rng) -> float:
        """Sleep before the next poll: the plain jittered interval while
        healthy, the escalating error backoff while the registry is
        failing (split out so tests can drive the schedule without
        sleeping)."""
        if self._error_backoff.attempts:
            return self._error_backoff.next_delay()
        return self.interval_s + rng.uniform(0.0, self.jitter_s)

    def _observe(self, before_errors: int) -> None:
        if self.errors > before_errors:
            if not self._error_backoff.attempts:
                # enter backoff: the next delay is the SECOND rung (the
                # first failed tick already waited one interval)
                self._error_backoff.next_delay()
        else:
            self._error_backoff.reset()

    def _run(self) -> None:
        import random

        rng = random.Random(os.getpid())
        delay = self.interval_s + rng.uniform(0.0, self.jitter_s)
        while not self._stop.wait(delay):
            before = self.errors
            self.check_once()
            self._observe(before)
            delay = self._next_delay(rng)

    def start(self) -> "RegistryWatcher":
        if self._thread is not None:
            raise RuntimeError("watcher already started")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="photon-serve-watcher")
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        """Signal the poll loop and join it with a bounded timeout; a
        watcher wedged inside a swap (stuck registry IO, hung compile)
        is counted and logged, never waited on forever."""
        self._stop.set()
        if self._thread is None:
            return
        self._thread.join(timeout_s)
        if self._thread.is_alive():
            self.join_timeouts += 1
            _log.warning(
                "RegistryWatcher: poll thread %r still alive %.1fs "
                "after stop() (wedged swap?); leaking it as a daemon "
                "(join timeouts so far: %d)",
                self._thread.name, timeout_s, self.join_timeouts)
