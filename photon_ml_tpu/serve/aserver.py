"""Asyncio serving front end + multi-replica front door (stdlib-only).

The PR-2 transport was ``http.server.ThreadingHTTPServer``: one OS
thread per connection, JSON parsed on the request thread, and every
blocked reader holding a thread while it waits on the batcher. At
production QPS the thread churn and per-connection stacks dominate the
host budget before the scoring stack is even warm. This module replaces
that edge with an event loop:

* :class:`AsyncScoringServer` — protocol-level HTTP/1.1 over
  ``asyncio.start_server`` (uvloop is used when importable; the stdlib
  loop is the floor). Requests are parsed ON the loop, handed to the
  existing :class:`~photon_ml_tpu.serve.batcher.MicroBatcher` through
  its non-blocking ``submit`` (a bounded ``put_nowait`` — the loop never
  blocks on admission), and resolved back onto the loop via
  ``PendingRequest.add_done_callback`` + ``call_soon_threadsafe``. The
  200/400/404/429/503/504 status contract, ``Retry-After`` hints,
  graceful SIGTERM drain, and Prometheus ``/metrics`` all carry over
  (the response shaping is shared with the threaded server through
  :class:`~photon_ml_tpu.serve.server.ScoringService`).

* :class:`AsyncFrontDoor` — the multi-replica edge: a tiny asyncio
  reverse proxy that spreads ``/score`` traffic across N replica
  servers, least-loaded first (ties round-robin), with per-backend
  connection pooling, failure cool-down, and one retry on another
  backend. Replicas stay consistent under hot swap by all watching the
  same registry (``serve/watcher.py``); the front door is deliberately
  model-oblivious.

Entity-affinity routing (``affinity=True``): the front door additionally
runs a :class:`~photon_ml_tpu.serve.membership.MembershipManager` — the
training tier's stable-hash owner map over the live replica set — and
routes each ``/score`` row to the replica that OWNS its entity (mixed
batches are scattered by owner and the per-row scores merged at the
door). Replicas learn their slice through ``POST /admin/membership``
broadcasts; on churn (join/leave/breaker-open) the door proposes a new
epoch, pushes the moved hot ids into their new owners' paged tables,
and commits the epoch only AFTER every member acknowledged — a
rebalance is a bounded warm handoff, not a cold-fault storm. When an
owner is unroutable the request fails over to any live replica (which
serves the foreign entities through its store/LRU path) and the
response carries ``"routing": "fallback"`` — degraded residency, never
a 5xx. See docs/serving.md "Entity-affinity routing & membership".

Admin/scoring split: ``/admin/reload`` and ``/admin/membership`` run in
a worker thread (``run_in_executor``) because a swap or a prefetch
legitimately takes milliseconds to seconds — the loop keeps serving
scores while they build off to the side.
"""

from __future__ import annotations

import asyncio
import json
import signal
import time
from typing import Dict, List, Optional, Sequence, Tuple

from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.obs.metrics import Histogram, escape_label_value
from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.serve.membership import MembershipEpoch, MembershipManager
from photon_ml_tpu.serve.server import ScoringService

__all__ = ["AsyncScoringServer", "AsyncFrontDoor", "install_uvloop"]

_MAX_HEAD = 64 * 1024
_MAX_BODY = 64 * 1024 * 1024


def install_uvloop() -> bool:
    """Install uvloop's event-loop policy when the wheel is present.
    Optional by design: the container may not ship uvloop, and the
    stdlib loop must remain a correct (slower) floor."""
    try:
        import uvloop  # type: ignore
    except ImportError:
        return False
    uvloop.install()
    return True


def _http_date() -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())


def _encode_response(status: int, body, content_type="application/json",
                     keep_alive=True, extra_headers: Sequence[Tuple[str,
                                                                    str]] = ()
                     ) -> bytes:
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              409: "Conflict", 429: "Too Many Requests",
              500: "Internal Server Error", 503: "Service Unavailable",
              504: "Gateway Timeout"}.get(status, "Status")
    data = body if isinstance(body, (bytes, str)) else json.dumps(body)
    if isinstance(data, str):
        data = data.encode("utf-8")
    head = [f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(data)}",
            f"Connection: {'keep-alive' if keep_alive else 'close'}"]
    for k, v in extra_headers:
        head.append(f"{k}: {v}")
    return ("\r\n".join(head) + "\r\n\r\n").encode("ascii") + data


async def _read_request(reader: asyncio.StreamReader):
    """One HTTP/1.1 request: ``(method, path, headers, body)`` or None
    on clean EOF. Raises ValueError on malformed input (caller answers
    400 and closes)."""
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None  # clean close between requests
        raise ValueError("truncated request head") from None
    except asyncio.LimitOverrunError:
        raise ValueError(f"request head over {_MAX_HEAD} bytes") from None
    lines = head.decode("latin-1").split("\r\n")
    try:
        method, path, _version = lines[0].split(" ", 2)
    except ValueError:
        raise ValueError(f"bad request line {lines[0]!r}") from None
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    if "chunked" in headers.get("transfer-encoding", "").lower():
        raise ValueError("chunked request bodies are not supported")
    length = int(headers.get("content-length", "0") or 0)
    if length < 0 or length > _MAX_BODY:
        raise ValueError(f"bad content-length {length}")
    body = await reader.readexactly(length) if length else b""
    return method, path, headers, body


def _request_id_from(headers: Dict[str, str]) -> str:
    """Honor a client-supplied X-Request-Id (trimmed, bounded); assign
    one otherwise — the same contract as the threaded handler."""
    rid = (headers.get("x-request-id") or "").strip()
    return rid[:128] if rid else obs_trace.new_request_id()


class AsyncScoringServer:
    """Event-loop HTTP endpoint over a :class:`ScoringService`.

    Same endpoints and status contract as the threaded
    :class:`~photon_ml_tpu.serve.server.ScoringServer`; the difference
    is the execution model — parsing on the loop, scoring resolved
    through batcher callbacks, no thread per connection. ``start()`` /
    ``aclose()`` are the async API (tests, in-process bench);
    :meth:`run_forever` is the driver entry (installs SIGTERM/SIGINT
    drain handlers on the loop)."""

    def __init__(self, service: ScoringService, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        self._host_arg, self._port_arg = host, port
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.host: str = host
        self.port: int = 0
        self._conns: set = set()
        self._draining = False

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "AsyncScoringServer":
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._serve_connection, self._host_arg, self._port_arg,
            limit=_MAX_HEAD)
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        return self

    async def aclose(self, drain_timeout_s: float = 5.0) -> None:
        """Graceful drain: stop accepting, let in-flight requests finish
        (bounded), flush the batcher, then drop stragglers."""
        self._draining = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        deadline = time.monotonic() + drain_timeout_s
        while self._conns and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        # batcher drain blocks: keep the loop alive in an executor
        await asyncio.get_running_loop().run_in_executor(
            None, self.service.close, drain_timeout_s)
        for task in list(self._conns):
            task.cancel()

    def run_forever(self, drain_timeout_s: float = 30.0,
                    ready_callback=None) -> int:
        """Foreground serve (the CLI driver's main loop): SIGTERM/SIGINT
        stop the listener, the batcher drains, then return 0 — the same
        rolling-restart contract as the threaded server."""
        install_uvloop()

        async def main():
            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    loop.add_signal_handler(sig, stop.set)
                except (NotImplementedError, RuntimeError):
                    pass  # non-main thread / platforms without support
            await self.start()
            if ready_callback is not None:
                # ready callbacks are opaque and the driver's write
                # JSONL logs — file IO stays off the loop (PB303)
                await loop.run_in_executor(None, ready_callback, self)
            await stop.wait()
            await self.aclose(drain_timeout_s)

        asyncio.run(main())
        return 0

    # -- connection handling ----------------------------------------------
    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            while not self._draining:
                try:
                    req = await _read_request(reader)
                except ValueError as e:
                    writer.write(_encode_response(
                        400, {"error": str(e)}, keep_alive=False))
                    await writer.drain()
                    return
                except (ConnectionError, asyncio.IncompleteReadError):
                    return
                if req is None:
                    return
                method, path, headers, body = req
                keep = headers.get("connection", "").lower() != "close"
                data = await self._dispatch(method, path, body, headers)
                writer.write(data if keep else
                             data.replace(b"Connection: keep-alive",
                                          b"Connection: close", 1))
                await writer.drain()
                if not keep:
                    return
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._conns.discard(task)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, method: str, path: str, body: bytes,
                        headers: Optional[Dict[str, str]] = None) -> bytes:
        svc = self.service
        rid = _request_id_from(headers or {})
        rid_hdr = (("X-Request-Id", rid),)
        if method == "GET":
            if path == "/healthz":
                status, payload = svc.handle_healthz()
                payload["server"] = "asyncio"
                return _encode_response(status, payload,
                                        extra_headers=rid_hdr)
            if path == "/metrics":
                status, text = svc.handle_metrics()
                return _encode_response(
                    status, text, content_type="text/plain; version=0.0.4",
                    extra_headers=rid_hdr)
            return _encode_response(404, {"error": f"unknown path {path}"},
                                    extra_headers=rid_hdr)
        if method != "POST" or path not in ("/score", "/admin/reload",
                                            "/admin/membership"):
            return _encode_response(404, {"error": f"unknown path {path}"},
                                    extra_headers=rid_hdr)
        try:
            payload = json.loads(body or b"null")
        except (ValueError, json.JSONDecodeError) as e:
            return _encode_response(
                400, {"error": f"bad JSON: {e}", "requestId": rid},
                extra_headers=rid_hdr)
        if path == "/admin/reload":
            # swaps take ms-seconds: off the loop, scores keep flowing
            status, resp = await asyncio.get_running_loop().run_in_executor(
                None, svc.handle_reload, payload)
            return _encode_response(status, resp, extra_headers=rid_hdr)
        if path == "/admin/membership":
            # the prefetch half does store IO — off the loop (PB303),
            # like reload; the reply still means "pages are warm"
            status, resp = await asyncio.get_running_loop().run_in_executor(
                None, svc.handle_membership, payload)
            return _encode_response(status, resp, extra_headers=rid_hdr)
        try:
            deadline_ms = svc.parse_deadline_ms(
                (headers or {}).get("x-deadline-ms"))
        except ValueError as e:
            return _encode_response(
                400, {"error": str(e), "requestId": rid},
                extra_headers=rid_hdr)
        # contextvars-ambient context: safe across the await (each
        # asyncio task carries its own copy, no cross-request bleed)
        with obs_trace.request_context(request_id=rid):
            status, resp = await self.score_async(payload, request_id=rid,
                                                  deadline_ms=deadline_ms)
        extra = rid_hdr
        if status == 429 and isinstance(resp, dict):
            after = max(1, int(-(-float(resp.get("retryAfterS", 1.0)) // 1)))
            extra = rid_hdr + (("Retry-After", str(after)),)
        return _encode_response(status, resp, extra_headers=extra)

    async def score_async(self, payload,
                          request_id: Optional[str] = None,
                          deadline_ms: Optional[float] = None
                          ) -> Tuple[int, dict]:
        """``/score`` without blocking the loop: validate inline, admit
        through the batcher's non-blocking submit, await the worker's
        resolution via done-callback. ``deadline_ms`` is the propagated
        ``X-Deadline-Ms`` budget."""
        svc = self.service
        valid, err = svc.validate_score_payload(payload)
        if valid is None:
            if request_id:
                err = dict(err, requestId=request_id)
            return 400, err
        rows, per_coord = valid
        loop = asyncio.get_running_loop()
        fut: "asyncio.Future" = loop.create_future()

        def _resolve(req):
            if not fut.cancelled():
                loop.call_soon_threadsafe(_complete, req)

        def _complete(req):
            if fut.cancelled():
                return
            if req.error is not None:
                fut.set_exception(req.error)
            else:
                # the ladder level rides along with the scores so the
                # response body can report "degraded"
                fut.set_result((req.result(0), req.degraded))

        try:
            with obs_trace.span("http.score", cat="serve", rows=len(rows)):
                pending = svc.batcher.submit(
                    rows, per_coord, request_id=request_id,
                    deadline_s=svc.deadline_s(deadline_ms))
            pending.add_done_callback(_resolve)
            result, degraded = await asyncio.wait_for(
                fut, svc.request_timeout_s)
        except Exception as e:
            return svc.score_error_response(e, request_id=request_id)
        return 200, svc.score_body(rows, per_coord, result,
                                   degraded=degraded)


_BACKEND_STATE_NUM = {"closed": 0, "half_open": 1, "open": 2}

# Hedge-policy latency resolution: ~1.25x geometric steps. The default
# exposition buckets step 2-2.5x, and a p99 read at bucket granularity
# can overstate the true tail by that whole ratio — a hedge that fires
# 2.5x late cannot bound the tail it exists to cut. This histogram is
# policy-internal (never rendered), so density costs nothing on the wire.
_HEDGE_LAT_BUCKETS_MS = (
    0.5, 1.0, 1.5, 2.0, 2.5, 3.2, 4.0, 5.0, 6.5, 8.0, 10.0, 13.0, 16.0,
    20.0, 25.0, 32.0, 40.0, 50.0, 65.0, 80.0, 100.0, 130.0, 160.0, 200.0,
    250.0, 320.0, 400.0, 500.0, 650.0, 800.0, 1000.0, 1300.0, 1600.0,
    2000.0, 2500.0, 5000.0,
)


class _Backend:
    """One replica behind the front door: address, pooled connections,
    in-flight count, and a per-backend circuit breaker.

    Breaker states: ``closed`` (serving), ``open`` (ejected after
    ``threshold`` CONSECUTIVE failures; nothing is routed here until a
    timed health probe readmits it), ``half_open`` (a ``/healthz`` probe
    is in flight; success closes the breaker, failure reopens it with an
    escalated jittered cool-down). A single failure no longer ejects a
    replica — one slow GC pause used to eject-and-readmit on a fixed
    timer with no health evidence at all."""

    __slots__ = ("host", "port", "inflight", "pool", "picked", "cooldowns",
                 "state", "fails", "opened", "next_probe_at",
                 "probe_inflight", "backoff", "lat_ms")

    def __init__(self, host: str, port: int, cooldown_s: float = 1.0):
        from photon_ml_tpu.parallel.resilience import Backoff

        self.host = host
        self.port = int(port)
        self.inflight = 0
        self.pool: List[tuple] = []  # (reader, writer) keep-alive pairs
        self.picked = 0     # times selected to carry a proxied request
        self.cooldowns = 0  # failure events observed (counter continuity)
        self.state = "closed"
        self.fails = 0      # CONSECUTIVE failures; any success resets
        self.opened = 0     # times the breaker tripped open
        self.next_probe_at = 0.0
        self.probe_inflight = False
        # open-state cool-down: exponential with jitter so N front doors
        # probing one recovering replica don't re-slam it in lockstep
        self.backoff = Backoff(base_s=cooldown_s, factor=2.0,
                               max_s=max(30.0, cooldown_s), jitter=0.1)
        # observed exchange latency — the hedging policy's p99 source
        self.lat_ms = Histogram(_HEDGE_LAT_BUCKETS_MS)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def note_latency(self, ms: float) -> None:
        self.lat_ms.observe(ms)

    def record_failure(self, threshold: int, now: float) -> None:
        self.fails += 1
        self.cooldowns += 1
        if self.state == "half_open" or self.fails >= threshold:
            if self.state != "open":
                self.opened += 1
            self.state = "open"
            self.next_probe_at = now + self.backoff.next_delay()

    def record_success(self) -> None:
        self.fails = 0
        self.state = "closed"
        self.backoff.reset()


class AsyncFrontDoor:
    """Least-loaded/round-robin HTTP front door for N scoring replicas.

    Policy: among backends whose circuit breaker is CLOSED, pick the
    lowest in-flight count (ties resolved round-robin). A backend that
    fails to connect or mid-exchange gets the request retried ONCE on
    another backend; ``breaker_threshold`` consecutive failures open its
    breaker — nothing is routed there until a timed ``/healthz`` probe
    (half-open state, jittered exponential cool-down starting at
    ``retry_backend_s``) readmits it. With every backend open the client
    sees 503 (the front door never queues — queueing and shedding live
    in the replicas' batchers, one admission-control point per
    process).

    The probe readmits only on a ``/healthz`` body whose ``status`` is
    ``ok``: a replica still prewarming pages after a swap reports
    ``warming`` (HTTP 200 — the process is alive) and is HELD half-open
    with a quick re-probe instead of being readmitted into a cold-fault
    storm or backed off as if it had failed.

    Hedging (``hedge_enabled``): when a picked backend's exchange runs
    past its own observed p99 (from at least ``hedge_min_samples``
    samples, floored at ``hedge_min_s``), the front door fires a
    DUPLICATE of the request at a second backend; the first success
    wins and the loser is cancelled — a cancelled loser is never
    counted as a backend failure, so hedging cannot trip breakers. Use
    only for idempotent traffic (scoring is).

    Deadline guard: a ``/score`` carrying ``X-Deadline-Ms <= 0`` is
    shed HERE (429, ``photon_fd_deadline_rejects_total``) — the
    cheapest drop point of all — and a positive budget is forwarded to
    the replica, whose batcher/session spend it stage by stage.

    Entity affinity (``affinity=True``): ``/score`` rows are routed to
    the replica owning their entity under the committed
    :class:`~photon_ml_tpu.serve.membership.MembershipEpoch` (a batch
    spanning owners is scattered and its per-row scores merged back in
    request order). The failover ladder per owner group: owner closed →
    route; owner open/unknown → any live replica + ``"routing":
    "fallback"`` label (``photon_fd_owner_miss_total{reason}``: a
    breaker-open owner is ``breaker``, an owner outside the backend
    list is ``epoch_skew``, a hedge duplicate winning on a non-owner is
    ``hedge``); nothing live → the plain 503. Membership changes flow
    through :meth:`_rebalance` — propose over the live set, broadcast
    ``/admin/membership`` (with the moved hot ids to prefetch) to every
    member, commit only after all acknowledged. Routing is by the
    row's first ``entityIds`` column (sorted by name): co-residency is
    an optimization, so additional entity columns simply resolve
    through their replica's LRU path at full fidelity."""

    def __init__(self, backends: Sequence[str], host: str = "127.0.0.1",
                 port: int = 0, policy: str = "least_loaded",
                 retry_backend_s: float = 1.0, breaker_threshold: int = 3,
                 hedge_enabled: bool = False, hedge_min_s: float = 0.05,
                 hedge_min_samples: int = 20, affinity: bool = False,
                 affinity_id_kind: str = "auto", hot_track: int = 4096):
        if not backends:
            raise ValueError("front door needs at least one backend")
        if policy not in ("least_loaded", "round_robin"):
            raise ValueError(f"unknown policy {policy!r}")
        if breaker_threshold < 1:
            raise ValueError(f"breaker_threshold must be >= 1, got "
                             f"{breaker_threshold}")
        self._backends = []
        for b in backends:
            h, _, p = str(b).rpartition(":")
            self._backends.append(_Backend(h or "127.0.0.1", int(p),
                                           cooldown_s=float(retry_backend_s)))
        self.policy = policy
        self.retry_backend_s = float(retry_backend_s)
        self.breaker_threshold = int(breaker_threshold)
        self._rr = 0
        self._host_arg, self._port_arg = host, port
        self._server: Optional[asyncio.AbstractServer] = None
        self.host: str = host
        self.port: int = 0
        self.hedge_enabled = bool(hedge_enabled)
        self.hedge_min_s = float(hedge_min_s)
        self.hedge_min_samples = int(hedge_min_samples)
        self.proxied = 0
        self.retried = 0
        self.unavailable = 0
        self.readmitted = 0  # breakers closed again by a healthz probe
        self.hedged = 0           # duplicate requests fired
        self.hedge_wins = 0       # duplicates that answered first
        self.deadline_rejects = 0  # X-Deadline-Ms <= 0 shed at the door
        self.warming_holds = 0    # probes held half-open on "warming"
        # -- entity-affinity membership state ------------------------------
        self._membership: Optional[MembershipManager] = (
            MembershipManager([b.address for b in self._backends],
                              id_kind=affinity_id_kind,
                              hot_track=hot_track)
            if affinity else None)
        self._announced = False        # epoch pushed to every member yet?
        self._rebalance_lock = asyncio.Lock()
        self._bg_tasks: set = set()    # live fire-and-forget rebalances
        self.owner_routed = 0     # groups answered by their owner
        self.scattered = 0        # batches split across owners
        self.fallback_served = 0  # responses served off the fallback path
        self.owner_miss: Dict[str, int] = {"breaker": 0, "epoch_skew": 0,
                                           "hedge": 0}
        self.epoch_commits = 0
        self.membership_faults = 0  # rebalance failures (fd.membership)
        self.route_faults = 0       # routing failures (fd.route)
        self.prefetch_entities_sent = 0  # replica-reported prefetch sums
        self.prefetch_bytes_sent = 0

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "AsyncFrontDoor":
        self._server = await asyncio.start_server(
            self._serve_connection, self._host_arg, self._port_arg,
            limit=_MAX_HEAD)
        addr = self._server.sockets[0].getsockname()
        self.host, self.port = addr[0], addr[1]
        return self

    async def aclose(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for b in self._backends:
            for _r, w in b.pool:
                try:
                    w.close()
                except Exception:
                    pass
            b.pool.clear()

    def run_forever(self, ready_callback=None) -> int:
        install_uvloop()

        async def main():
            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    loop.add_signal_handler(sig, stop.set)
                except (NotImplementedError, RuntimeError):
                    pass
            await self.start()
            if self._membership is not None:
                # announce the initial epoch so every replica pages its
                # owned slice from the first request (a failed announce
                # is retried lazily from the request path)
                await self._rebalance()
            if ready_callback is not None:
                # same contract as AsyncScoringServer.run_forever: the
                # driver's ready callback logs to disk — executor it
                await loop.run_in_executor(None, ready_callback, self)
            await stop.wait()
            await self.aclose()

        asyncio.run(main())
        return 0

    # -- circuit breaker ---------------------------------------------------
    def _maybe_probe(self, backend: _Backend, now: float) -> None:
        """Lazy open→half_open transition: when an open backend's
        cool-down has elapsed, fire ONE async ``/healthz`` probe (guarded
        so concurrent picks don't stack probes). Runs from the request
        path — no timer thread; an idle front door simply probes on its
        next request or metrics scrape. A HALF-OPEN backend re-probes
        too: a warming replica parks there until its installer drains."""
        if (backend.state not in ("open", "half_open")
                or now < backend.next_probe_at or backend.probe_inflight):
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (sync caller): stay open until a real request
        backend.state = "half_open"
        backend.probe_inflight = True
        loop.create_task(self._probe(backend))

    async def _probe(self, backend: _Backend) -> None:
        probe = (b"GET /healthz HTTP/1.1\r\nHost: backend\r\n"
                 b"Content-Length: 0\r\nConnection: keep-alive\r\n\r\n")
        warming = False
        try:
            data = await self._backend_exchange(backend, probe)
            is_200 = b" 200 " in data.split(b"\r\n", 1)[0]
            # a 200 readmits UNLESS the body explicitly says the replica
            # is still prewarming pages after a swap ({"status":
            # "warming"}) — alive, but it must stay out of rotation
            # until its installer drains; health endpoints without the
            # status body keep their plain 200-is-healthy contract
            warming = is_200 and b'"status": "warming"' in data
            ok = is_200 and not warming
        except Exception:
            ok = False
        finally:
            backend.probe_inflight = False
        if ok:
            backend.record_success()
            self.readmitted += 1
        elif warming:
            # alive but cold: hold half-open with a quick re-probe and
            # WITHOUT escalating the failure backoff
            self.warming_holds += 1
            backend.next_probe_at = time.monotonic() + self.retry_backend_s
        else:
            backend.record_failure(self.breaker_threshold, time.monotonic())

    # -- backend selection -------------------------------------------------
    def _pick(self, exclude: set) -> Optional[_Backend]:
        now = time.monotonic()
        live = []
        for b in self._backends:
            self._maybe_probe(b, now)
            if b.address not in exclude and b.state == "closed":
                live.append(b)
        if not live:
            return None
        if self.policy == "round_robin":
            self._rr += 1
            chosen = live[self._rr % len(live)]
        else:
            best = min(b.inflight for b in live)
            tied = [b for b in live if b.inflight == best]
            self._rr += 1
            chosen = tied[self._rr % len(tied)]
        chosen.picked += 1
        return chosen

    async def _backend_exchange(self, backend: _Backend,
                                request: bytes) -> bytes:
        """Send one request on a pooled (or fresh) connection; return
        the full response bytes (head + body, content-length framed)."""
        if backend.pool:
            reader, writer = backend.pool.pop()
        else:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(backend.host, backend.port,
                                        limit=_MAX_HEAD), timeout=5.0)
        try:
            writer.write(request)
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            length = 0
            for line in head.split(b"\r\n")[1:]:
                if line.lower().startswith(b"content-length:"):
                    length = int(line.split(b":", 1)[1])
                    break
            body = await reader.readexactly(length) if length else b""
            backend.pool.append((reader, writer))
            return head + body
        except BaseException:
            try:
                writer.close()
            except Exception:
                pass
            raise

    # -- proxy loop --------------------------------------------------------
    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    req = await _read_request(reader)
                except ValueError as e:
                    writer.write(_encode_response(
                        400, {"error": str(e)}, keep_alive=False))
                    await writer.drain()
                    return
                except (ConnectionError, asyncio.IncompleteReadError):
                    return
                if req is None:
                    return
                method, path, headers, body = req
                rid = _request_id_from(headers)
                rid_hdr = (("X-Request-Id", rid),)
                if method == "GET" and path == "/fd/healthz":
                    writer.write(_encode_response(200, self.stats(),
                                                  extra_headers=rid_hdr))
                    await writer.drain()
                    continue
                if method == "GET" and path == "/fd/metrics":
                    text = await self._fd_metrics()
                    writer.write(_encode_response(
                        200, text, content_type="text/plain; version=0.0.4",
                        extra_headers=rid_hdr))
                    await writer.drain()
                    continue
                if (method == "POST"
                        and path in ("/fd/admin/join", "/fd/admin/leave")):
                    writer.write(await self._handle_admin(path, body, rid))
                    await writer.drain()
                    continue
                deadline_ms = None
                if method == "POST":
                    try:
                        deadline_ms = ScoringService.parse_deadline_ms(
                            headers.get("x-deadline-ms"))
                    except ValueError as e:
                        writer.write(_encode_response(
                            400, {"error": str(e), "requestId": rid},
                            extra_headers=rid_hdr))
                        await writer.drain()
                        continue
                    if deadline_ms is not None and deadline_ms <= 0:
                        # the budget is already spent: drop at the door,
                        # before any backend connection is even touched
                        self.deadline_rejects += 1
                        writer.write(_encode_response(
                            429, {"error": "deadline budget exhausted "
                                           "before proxy", "shed": True,
                                  "cause": "deadline", "requestId": rid},
                            extra_headers=rid_hdr))
                        await writer.drain()
                        continue
                if (self._membership is not None and method == "POST"
                        and path == "/score"):
                    data = await self._score_affinity(body, rid,
                                                      deadline_ms)
                else:
                    data = await self._proxy(method, path, body,
                                             request_id=rid,
                                             deadline_ms=deadline_ms)
                writer.write(data)
                await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    return
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _hedge_delay(self, backend: _Backend) -> Optional[float]:
        """How long to wait on ``backend`` before firing a duplicate at a
        second replica — its own observed p99 (floored at ``hedge_min_s``)
        — or None when hedging is off, there is no second replica to
        hedge to, or the backend has too few samples to call a tail."""
        if (not self.hedge_enabled or len(self._backends) < 2
                or backend.lat_ms.total < self.hedge_min_samples):
            return None
        return max(self.hedge_min_s, backend.lat_ms.quantile(0.99) / 1e3)

    async def _timed_exchange(self, backend: _Backend,
                              request: bytes, path: str) -> bytes:
        """One breaker-aware exchange: inflight bookkeeping, fault hook,
        latency sample + breaker close on success, breaker failure on
        error. A ``CancelledError`` (hedge loser being reaped) is NOT a
        backend failure — cancelling the slow-but-healthy replica must
        never trip its breaker."""
        backend.inflight += 1
        try:
            with obs_trace.span("fd.proxy", cat="serve", path=path,
                                backend=backend.address):
                t0 = time.monotonic()
                await fault_injection.async_check("fd.proxy")
                data = await self._backend_exchange(backend, request)
            backend.record_success()
            backend.note_latency((time.monotonic() - t0) * 1e3)
            return data
        except asyncio.CancelledError:
            raise
        except BaseException:
            backend.record_failure(self.breaker_threshold, time.monotonic())
            raise
        finally:
            backend.inflight -= 1

    async def _hedged_exchange(self, primary: _Backend, request: bytes,
                               path: str, tried: set
                               ) -> Tuple[Optional[bytes], bool]:
        """Race ``primary`` against (at most one) hedge duplicate: wait
        ``_hedge_delay`` on the primary; if it hasn't answered, fire the
        same request at a second backend and take whichever answers
        first, cancelling the loser. Returns ``(response, hedge_won)``;
        the response is None when every attempted backend failed
        (addresses added to ``tried``). ``hedge_won`` lets the affinity
        router know the answer came from a NON-owner (the duplicate) so
        it can label the response as fallback-served."""
        task_backend: Dict["asyncio.Task", _Backend] = {}

        def _spawn(b: _Backend) -> "asyncio.Task":
            t = asyncio.ensure_future(
                self._timed_exchange(b, request, path))
            task_backend[t] = b
            return t

        pending = {_spawn(primary)}
        delay = self._hedge_delay(primary)
        winner: Optional[bytes] = None
        winner_was_hedge = False
        hedge_task: Optional["asyncio.Task"] = None
        while pending:
            done, pending = await asyncio.wait(
                pending, timeout=delay,
                return_when=asyncio.FIRST_COMPLETED)
            if not done:
                # primary ran past its own p99: duplicate onto a second
                # replica (once), then wait for whichever answers first
                delay = None
                alt = self._pick(tried | {primary.address})
                if alt is not None:
                    self.hedged += 1
                    hedge_task = _spawn(alt)
                    pending.add(hedge_task)
                continue
            delay = None
            for task in done:
                backend = task_backend[task]
                if task.cancelled() or task.exception() is not None:
                    tried.add(backend.address)
                    continue
                if winner is None:
                    winner = task.result()
                    if task is hedge_task:
                        self.hedge_wins += 1
                        winner_was_hedge = True
            if winner is not None:
                for task in pending:
                    task.cancel()
                if pending:
                    await asyncio.gather(*pending, return_exceptions=True)
                return winner, winner_was_hedge
        return None, False

    @staticmethod
    def _build_request(method: str, path: str, body: bytes, rid: str,
                       deadline_ms: Optional[float] = None) -> bytes:
        deadline_hdr = ("" if deadline_ms is None
                        else f"X-Deadline-Ms: {deadline_ms:g}\r\n")
        return (
            f"{method} {path} HTTP/1.1\r\n"
            f"Host: backend\r\nContent-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"X-Request-Id: {rid}\r\n{deadline_hdr}"
            f"Connection: keep-alive\r\n\r\n").encode("ascii") + body

    async def _proxy(self, method: str, path: str, body: bytes,
                     request_id: Optional[str] = None,
                     deadline_ms: Optional[float] = None,
                     exclude: Optional[set] = None) -> bytes:
        rid = request_id or obs_trace.new_request_id()
        request = self._build_request(method, path, body, rid, deadline_ms)
        tried: set = set(exclude or ())
        with obs_trace.request_context(request_id=rid):
            for _attempt in range(2):
                backend = self._pick(tried)
                if backend is None:
                    break
                data, _hedge_won = await self._hedged_exchange(
                    backend, request, path, tried)
                if data is not None:
                    self.proxied += 1
                    return data
                self.retried += 1
        self.unavailable += 1
        return _encode_response(
            503, {"error": "no live backend replica", "requestId": rid},
            extra_headers=(("X-Request-Id", rid),))

    # -- entity-affinity membership ----------------------------------------
    def _backend_by_address(self, address: str) -> Optional[_Backend]:
        for b in self._backends:
            if b.address == address:
                return b
        return None

    @property
    def membership_epoch(self) -> Optional[MembershipEpoch]:
        """The committed epoch (None when affinity is disabled)."""
        return None if self._membership is None else self._membership.epoch

    def _live_addresses(self) -> List[str]:
        return sorted(b.address for b in self._backends
                      if b.state == "closed")

    def _membership_stale(self) -> bool:
        """Does the committed epoch disagree with the live replica set
        (or has the initial epoch never been announced)? Cheap enough to
        ask per request — the rebalance itself is lazy."""
        if self._membership is None:
            return False
        if not self._announced:
            return True
        live = tuple(self._live_addresses())
        return bool(live) and live != self._membership.epoch.replicas

    def _maybe_rebalance(self) -> None:
        """Kick a background rebalance when the live set drifted from
        the committed epoch. Fire-and-forget from the request path: the
        current request routes on the committed epoch (the failover
        ladder covers its dead owner), the NEXT requests get the new
        one. The task set keeps strong references (a GC'd task would
        silently drop the rebalance)."""
        if not self._membership_stale() or self._rebalance_lock.locked():
            return
        task = asyncio.get_running_loop().create_task(self._rebalance())
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    async def sync_membership(self) -> dict:
        """Run one rebalance to completion — propose over the live set,
        broadcast + prefetch, commit — and report it. The await-able
        form of :meth:`_maybe_rebalance` for drivers, benches, and
        tests that need 'the epoch is committed' as a postcondition."""
        if self._membership is None:
            return {"committed": False, "reason": "affinity disabled"}
        return await self._rebalance()

    async def _rebalance(self) -> dict:
        """One membership transition, serialized by the rebalance lock:
        propose a successor epoch over the live replicas, push it (plus
        each new owner's moved hot ids to prefetch) to EVERY member,
        and only then commit — so by the time requests route on the new
        map, the handed-over pages are already warm. Failures are
        counted (``membership_faults``), never raised: the committed
        epoch keeps routing and a later request retries the
        transition."""
        if self._membership is None:
            return {"committed": False, "reason": "affinity disabled"}
        async with self._rebalance_lock:
            try:
                await fault_injection.async_check("fd.membership")
                live = self._live_addresses()
                if not live:
                    return {"committed": False,
                            "reason": "no live replicas"}
                new = self._membership.propose(live)
                if new is None and self._announced:
                    return {"committed": False, "reason": "unchanged",
                            "epoch": self._membership.epoch.epoch}
                # first rebalance: the constructor epoch exists but the
                # replicas have never heard it — announce before routing
                target = new if new is not None else self._membership.epoch
                moved = (self._membership.moved_ids(target)
                         if new is not None else {})
                with obs_trace.span("fd.rebalance", cat="serve",
                                    epoch=target.epoch,
                                    replicas=target.num_shards,
                                    moved=sum(len(v)
                                              for v in moved.values())):
                    ok = await self._broadcast_epoch(target, moved)
                if new is None:
                    self._announced = ok
                    return {"committed": ok, "epoch": target.epoch,
                            "replicas": list(target.replicas)}
                if not ok:
                    self.membership_faults += 1
                    return {"committed": False,
                            "reason": "broadcast failed",
                            "epoch": target.epoch}
                if self._membership.commit(new):
                    self.epoch_commits += 1
                self._announced = True
                return {"committed": True, "epoch": new.epoch,
                        "replicas": list(new.replicas)}
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.membership_faults += 1
                return {"committed": False, "error": str(e)}

    async def _broadcast_epoch(self, epoch: MembershipEpoch,
                               moved: Dict[int, List[str]]) -> bool:
        """Push ``epoch`` (and each member's moved-id prefetch list) to
        every replica in it. True only when EVERY member replied 200 —
        the commit gate."""
        ok = True
        for i, addr in enumerate(epoch.replicas):
            backend = self._backend_by_address(addr)
            if backend is None:
                ok = False
                continue
            body = json.dumps(epoch.payload(i, moved.get(i))
                              ).encode("utf-8")
            request = self._build_request(
                "POST", "/admin/membership", body,
                obs_trace.new_request_id())
            try:
                data = await self._timed_exchange(backend, request,
                                                  "/admin/membership")
            except asyncio.CancelledError:
                raise
            except Exception:
                ok = False
                continue
            status, reply = self._parse_response(data)
            if status != 200:
                ok = False
                continue
            if isinstance(reply, dict):
                self.prefetch_entities_sent += int(
                    reply.get("prefetched", 0))
                self.prefetch_bytes_sent += int(
                    reply.get("prefetchBytes", 0))
        return ok

    async def add_backend(self, address: str) -> dict:
        """Join a replica (``POST /fd/admin/join``): register it and
        rebalance so it owns (and has prefetched) its slice before the
        epoch routes to it."""
        address = str(address)
        if self._backend_by_address(address) is None:
            h, _, p = address.rpartition(":")
            self._backends.append(
                _Backend(h or "127.0.0.1", int(p),
                         cooldown_s=self.retry_backend_s))
        if self._membership is None:
            return {"committed": False, "reason": "affinity disabled"}
        return await self._rebalance()

    async def remove_backend(self, address: str) -> dict:
        """Drain a replica out (``POST /fd/admin/leave``): deregister,
        close its pooled connections, re-own its slice across the
        survivors. The last backend cannot leave."""
        address = str(address)
        b = self._backend_by_address(address)
        if b is not None:
            if len(self._backends) <= 1:
                return {"committed": False,
                        "reason": "cannot remove the last backend"}
            self._backends.remove(b)
            for _r, w in b.pool:
                try:
                    w.close()
                except Exception:
                    pass
            b.pool.clear()
        if self._membership is None:
            return {"committed": False, "reason": "affinity disabled"}
        return await self._rebalance()

    async def _handle_admin(self, path: str, body: bytes,
                            rid: str) -> bytes:
        """``POST /fd/admin/join`` / ``/fd/admin/leave`` with
        ``{"address": "host:port"}``: mutate the replica set and run
        the rebalance to completion before replying — a 200 here means
        the new epoch is committed (or reports why it is not)."""
        rid_hdr = (("X-Request-Id", rid),)
        try:
            payload = json.loads(body or b"null")
            address = str(payload["address"])
            if ":" not in address:
                raise ValueError(f"address must be host:port, "
                                 f"got {address!r}")
            int(address.rpartition(":")[2])
        except (KeyError, TypeError, ValueError,
                json.JSONDecodeError) as e:
            return _encode_response(
                400, {"error": f"bad admin payload: {e}",
                      "requestId": rid}, extra_headers=rid_hdr)
        if path.endswith("/join"):
            result = await self.add_backend(address)
        else:
            result = await self.remove_backend(address)
            if result.get("reason") == "cannot remove the last backend":
                return _encode_response(
                    409, {"error": result["reason"], "requestId": rid},
                    extra_headers=rid_hdr)
        return _encode_response(
            200, {"backends": [b.address for b in self._backends],
                  "rebalance": result, "requestId": rid},
            extra_headers=rid_hdr)

    # -- affinity routing --------------------------------------------------
    @staticmethod
    def _row_entity(row) -> Optional[str]:
        """The routing entity id of a score row: the value of its
        first ``entityIds`` column (sorted by column name, so routing
        is deterministic for multi-coordinate models); None routes the
        row with whatever owner group goes first."""
        ids = row.get("entityIds") if isinstance(row, dict) else None
        if not isinstance(ids, dict) or not ids:
            return None
        value = (next(iter(ids.values())) if len(ids) == 1
                 else ids[min(ids)])
        return None if value is None else str(value)

    def _owner_groups(self, payload: dict, epoch: MembershipEpoch
                      ) -> Optional[List[Tuple[str, List[int]]]]:
        """Group a batch's row indices by owning replica address under
        ``epoch``; None when no row carries an entity id (plain proxy
        is the right path). Rows without an entity ride with the
        lowest-indexed owner group — they score identically anywhere."""
        rows = payload.get("rows")
        if not isinstance(rows, list) or not rows:
            return None
        eids = [self._row_entity(r) for r in rows]
        with_id = [(i, e) for i, e in enumerate(eids) if e is not None]
        if not with_id:
            return None
        ids = [e for _i, e in with_id]
        owners = epoch.owner_of(ids)
        for e in ids:
            self._membership.note_routed(e)
        groups: Dict[int, List[int]] = {}
        for (i, _e), o in zip(with_id, owners):
            groups.setdefault(int(o), []).append(i)
        free = [i for i, e in enumerate(eids) if e is None]
        if free:
            first = min(groups)
            groups[first] = sorted(groups[first] + free)
        return [(epoch.replicas[o], idxs)
                for o, idxs in sorted(groups.items())]

    def _note_owner_miss(self, reason: str) -> None:
        self.owner_miss[reason] = self.owner_miss.get(reason, 0) + 1

    @staticmethod
    def _parse_response(data: bytes) -> Tuple[int, Optional[dict]]:
        head, _, payload = data.partition(b"\r\n\r\n")
        try:
            status = int(head.split(b" ", 2)[1])
        except (IndexError, ValueError):
            return 500, None
        try:
            body = json.loads(payload) if payload else None
        except (ValueError, json.JSONDecodeError):
            body = None
        return status, body if isinstance(body, dict) else None

    def _label_fallback(self, data: bytes) -> bytes:
        """Stamp ``"routing": "fallback"`` into a 200 JSON response
        served off the non-owner path — the contract's degraded-
        residency marker (clients alert on fidelity, not availability).
        Forwarded headers the status contract pins (X-Request-Id,
        Retry-After) survive the rewrite; non-200s and non-JSON bodies
        pass through untouched."""
        head, _, payload = data.partition(b"\r\n\r\n")
        if b" 200 " not in head.split(b"\r\n", 1)[0]:
            return data
        try:
            body = json.loads(payload)
        except (ValueError, json.JSONDecodeError):
            return data
        if not isinstance(body, dict):
            return data
        body["routing"] = "fallback"
        extra = []
        for line in head.split(b"\r\n")[1:]:
            k, _, v = line.partition(b":")
            if k.strip().lower() in (b"x-request-id", b"retry-after"):
                extra.append((k.decode("latin-1").strip(),
                              v.decode("latin-1").strip()))
        self.fallback_served += 1
        return _encode_response(200, body, extra_headers=tuple(extra))

    async def _owner_send(self, owner_addr: str, body: bytes, rid: str,
                          deadline_ms: Optional[float]
                          ) -> Tuple[bytes, bool]:
        """Send one owner group's rows down the failover ladder:
        owner's breaker closed → route to it (hedging may still
        duplicate onto a non-owner; if the duplicate wins the response
        is fallback-labeled and counted ``owner_miss{reason=hedge}``);
        owner open (``breaker``) / not a registered backend
        (``epoch_skew``) / failed mid-exchange → any live replica,
        fallback-labeled. Returns ``(response_bytes, fell_back)``."""
        backend = self._backend_by_address(owner_addr)
        reason: Optional[str] = None
        if backend is None:
            reason = "epoch_skew"
        elif backend.state != "closed":
            self._maybe_probe(backend, time.monotonic())
            reason = "breaker"
        else:
            request = self._build_request("POST", "/score", body, rid,
                                          deadline_ms)
            tried: set = set()
            data, hedge_won = await self._hedged_exchange(
                backend, request, "/score", tried)
            if data is not None:
                self.proxied += 1
                self.owner_routed += 1
                if hedge_won:
                    # the duplicate landed on a NON-owner: it served the
                    # foreign entities off its store/LRU path — correct
                    # scores, degraded residency, so label it
                    self._note_owner_miss("hedge")
                    return self._label_fallback(data), True
                return data, False
            reason = "breaker"
        self._note_owner_miss(reason)
        data = await self._proxy("POST", "/score", body, request_id=rid,
                                 deadline_ms=deadline_ms,
                                 exclude={owner_addr})
        return self._label_fallback(data), True

    async def _score_affinity(self, body: bytes, rid: str,
                              deadline_ms: Optional[float]) -> bytes:
        """The affinity ``/score`` path: group rows by owner under the
        committed epoch, route each group down the owner ladder,
        scatter/merge when the batch spans owners. Any routing failure
        (``fd.route``, malformed rows) degrades to the plain
        least-loaded proxy — a non-owner serves every entity correctly
        through its LRU path, so routing is never allowed to fail a
        request that a dumb proxy would have served."""
        self._maybe_rebalance()
        epoch = self._membership.epoch
        groups = None
        try:
            await fault_injection.async_check("fd.route")
            payload = json.loads(body or b"null")
            if isinstance(payload, dict):
                groups = self._owner_groups(payload, epoch)
        except asyncio.CancelledError:
            raise
        except Exception:
            self.route_faults += 1
            groups = None
        if not groups:
            return await self._proxy("POST", "/score", body,
                                     request_id=rid,
                                     deadline_ms=deadline_ms)
        if len(groups) == 1:
            # single-owner batch: forward the ORIGINAL bytes untouched
            data, _fell_back = await self._owner_send(
                groups[0][0], body, rid, deadline_ms)
            return data
        self.scattered += 1
        return await self._scatter_merge(groups, payload, rid,
                                         deadline_ms)

    async def _scatter_merge(self, groups: List[Tuple[str, List[int]]],
                             payload: dict, rid: str,
                             deadline_ms: Optional[float]) -> bytes:
        """Fan a mixed-owner batch out by owner group (concurrently)
        and reassemble the per-row results in request order: the row
        partition is disjoint and exhaustive, so scores/uids/
        scoreComponents merge by position; ``degraded`` is the worst
        level any group was served at; ``routing`` is ``fallback`` if
        ANY group missed its owner, else ``scatter``. A group answering
        non-200 fails the whole batch with THAT response — merging
        partial scores would silently misreport rows."""
        rows = payload["rows"]

        async def one(addr: str, idxs: List[int]) -> Tuple[bytes, bool]:
            sub = {k: v for k, v in payload.items() if k != "rows"}
            sub["rows"] = [rows[i] for i in idxs]
            return await self._owner_send(
                addr, json.dumps(sub).encode("utf-8"), rid, deadline_ms)

        results = await asyncio.gather(
            *(one(addr, idxs) for addr, idxs in groups))
        n = len(rows)
        scores = [0.0] * n
        uids: List[object] = [None] * n
        comps: Dict[str, List[float]] = {}
        degraded = 0
        have_uids = False
        any_fallback = any(fb for _d, fb in results)
        for (addr, idxs), (data, _fb) in zip(groups, results):
            status, resp = self._parse_response(data)
            if status != 200 or resp is None:
                return data
            if resp.get("routing") == "fallback":
                any_fallback = True
            degraded = max(degraded, int(resp.get("degraded", 0)))
            for pos, s in zip(idxs, resp.get("scores", ())):
                scores[pos] = float(s)
            got_uids = resp.get("uids")
            if got_uids is not None:
                have_uids = True
                for pos, u in zip(idxs, got_uids):
                    uids[pos] = u
            for cname, vals in (resp.get("scoreComponents") or {}).items():
                dst = comps.setdefault(cname, [0.0] * n)
                for pos, v in zip(idxs, vals):
                    dst[pos] = float(v)
        merged = {"scores": scores, "degraded": degraded,
                  "routing": "fallback" if any_fallback else "scatter"}
        if have_uids:
            merged["uids"] = uids
        if comps:
            merged["scoreComponents"] = comps
        return _encode_response(200, merged,
                                extra_headers=(("X-Request-Id", rid),))

    async def _fd_metrics(self) -> str:
        """Aggregate ``/metrics`` across replicas: each backend's samples
        re-emitted with an injected ``replica="host:port"`` label
        (``# TYPE`` lines deduplicated across replicas), followed by the
        front door's own ``photon_fd_*`` counters. A backend that fails
        the scrape is cooled down exactly like a failed proxy exchange
        and simply omitted from this scrape."""
        scrape = (b"GET /metrics HTTP/1.1\r\nHost: backend\r\n"
                  b"Content-Length: 0\r\nConnection: keep-alive\r\n\r\n")
        out: List[str] = []
        seen_meta: set = set()
        now = time.monotonic()
        for b in self._backends:
            self._maybe_probe(b, now)
            if b.state != "closed":
                continue
            try:
                data = await self._backend_exchange(b, scrape)
            except Exception:
                b.record_failure(self.breaker_threshold, time.monotonic())
                continue
            head, _, payload = data.partition(b"\r\n\r\n")
            if b" 200 " not in head.split(b"\r\n", 1)[0]:
                continue
            replica = escape_label_value(b.address)
            for line in payload.decode("utf-8", "replace").splitlines():
                if not line:
                    continue
                if line.startswith("#"):
                    if line not in seen_meta:
                        seen_meta.add(line)
                        out.append(line)
                    continue
                series, _, value = line.rpartition(" ")
                if "{" in series:
                    name, _, rest = series.partition("{")
                    series = f'{name}{{replica="{replica}",{rest}'
                else:
                    series = f'{series}{{replica="{replica}"}}'
                out.append(f"{series} {value}")
        out.append("# TYPE photon_fd_proxied_total counter")
        out.append(f"photon_fd_proxied_total {self.proxied}")
        out.append("# TYPE photon_fd_retried_total counter")
        out.append(f"photon_fd_retried_total {self.retried}")
        out.append("# TYPE photon_fd_unavailable_total counter")
        out.append(f"photon_fd_unavailable_total {self.unavailable}")
        out.append("# TYPE photon_fd_backend_picked_total counter")
        for b in self._backends:
            out.append(f'photon_fd_backend_picked_total'
                       f'{{backend="{escape_label_value(b.address)}"}} '
                       f'{b.picked}')
        out.append("# TYPE photon_fd_backend_cooldowns_total counter")
        for b in self._backends:
            out.append(f'photon_fd_backend_cooldowns_total'
                       f'{{backend="{escape_label_value(b.address)}"}} '
                       f'{b.cooldowns}')
        out.append("# TYPE photon_fd_backend_state gauge")
        for b in self._backends:
            # 0 = closed (serving), 1 = half_open (probing), 2 = open
            out.append(f'photon_fd_backend_state'
                       f'{{backend="{escape_label_value(b.address)}"}} '
                       f'{_BACKEND_STATE_NUM[b.state]}')
        out.append("# TYPE photon_fd_readmitted_total counter")
        out.append(f"photon_fd_readmitted_total {self.readmitted}")
        out.append("# TYPE photon_fd_hedged_total counter")
        out.append(f"photon_fd_hedged_total {self.hedged}")
        out.append("# TYPE photon_fd_hedge_wins_total counter")
        out.append(f"photon_fd_hedge_wins_total {self.hedge_wins}")
        out.append("# TYPE photon_fd_deadline_rejects_total counter")
        out.append(f"photon_fd_deadline_rejects_total {self.deadline_rejects}")
        out.append("# TYPE photon_fd_warming_holds_total counter")
        out.append(f"photon_fd_warming_holds_total {self.warming_holds}")
        if self._membership is not None:
            epoch = self._membership.epoch
            out.append("# TYPE photon_fd_membership_epoch gauge")
            out.append(f"photon_fd_membership_epoch {epoch.epoch}")
            out.append("# TYPE photon_fd_membership_replicas gauge")
            out.append(f"photon_fd_membership_replicas {epoch.num_shards}")
            out.append("# TYPE photon_fd_owner_routed_total counter")
            out.append(f"photon_fd_owner_routed_total {self.owner_routed}")
            out.append("# TYPE photon_fd_scattered_total counter")
            out.append(f"photon_fd_scattered_total {self.scattered}")
            out.append("# TYPE photon_fd_fallback_served_total counter")
            out.append(f"photon_fd_fallback_served_total "
                       f"{self.fallback_served}")
            out.append("# TYPE photon_fd_owner_miss_total counter")
            for reason in sorted(self.owner_miss):
                out.append(
                    f'photon_fd_owner_miss_total'
                    f'{{reason="{escape_label_value(reason)}"}} '
                    f'{self.owner_miss[reason]}')
            out.append("# TYPE photon_fd_epoch_commits_total counter")
            out.append(f"photon_fd_epoch_commits_total "
                       f"{self.epoch_commits}")
            out.append("# TYPE photon_fd_membership_faults_total counter")
            out.append(f"photon_fd_membership_faults_total "
                       f"{self.membership_faults}")
            out.append("# TYPE photon_fd_route_faults_total counter")
            out.append(f"photon_fd_route_faults_total {self.route_faults}")
            out.append("# TYPE photon_fd_prefetch_entities_total counter")
            out.append(f"photon_fd_prefetch_entities_total "
                       f"{self.prefetch_entities_sent}")
            out.append("# TYPE photon_fd_prefetch_bytes_total counter")
            out.append(f"photon_fd_prefetch_bytes_total "
                       f"{self.prefetch_bytes_sent}")
        return "\n".join(out) + "\n"

    def stats(self) -> Dict[str, object]:
        out = {
            "policy": self.policy,
            "backends": [
                {"address": b.address, "inflight": b.inflight,
                 "state": b.state, "down": b.state != "closed",
                 "picked": b.picked, "cooldowns": b.cooldowns,
                 "opened": b.opened}
                for b in self._backends
            ],
            "proxied": self.proxied,
            "retried": self.retried,
            "unavailable": self.unavailable,
            "readmitted": self.readmitted,
            "hedged": self.hedged,
            "hedgeWins": self.hedge_wins,
            "deadlineRejects": self.deadline_rejects,
            "warmingHolds": self.warming_holds,
        }
        if self._membership is not None:
            epoch = self._membership.epoch
            out["affinity"] = {
                "epoch": epoch.epoch,
                "replicas": list(epoch.replicas),
                "idKind": epoch.id_kind,
                "announced": self._announced,
                "ownerRouted": self.owner_routed,
                "scattered": self.scattered,
                "fallbackServed": self.fallback_served,
                "ownerMiss": dict(self.owner_miss),
                "epochCommits": self.epoch_commits,
                "membershipFaults": self.membership_faults,
                "routeFaults": self.route_faults,
                "prefetchedEntities": self.prefetch_entities_sent,
                "prefetchedBytes": self.prefetch_bytes_sent,
            }
        return out
