"""Stdlib-only JSON scoring endpoint over the resident session.

Two layers, deliberately separated:

* :class:`ScoringService` — transport-agnostic request handling: parse /
  validate a payload dict, run it through the micro-batcher, shape the
  response and status code. The tier-1 tests exercise THIS layer
  in-process (no sockets, no ports, no flakes).
* :class:`ScoringServer` — a ``http.server.ThreadingHTTPServer`` wrapper
  exposing ``POST /score``, ``POST /admin/reload``,
  ``POST /admin/membership``, ``GET /healthz``, and ``GET /metrics``
  (Prometheus text). One real-HTTP smoke test covers the wire.

Status-code contract (the load-shedding contract callers program
against; see docs/serving.md):

  200 scored; 400 malformed request; 404 unknown path;
  429 shed — admission queue full OR deadline budget expired, retry
      with backoff (explicit backpressure instead of unbounded
      queueing latency);
  503 scoring failed; 504 batch watchdog expired (stuck execution).

Deadline propagation: an ``X-Deadline-Ms`` request header (or the
service's ``default_deadline_ms``) becomes the request's remaining
budget — checked at admission, in-queue, and pre-compute by the batcher
(``photon_serve_deadline_drop_total{stage}``) and spent deliberately by
the session's degradation ladder. Every ``/score`` response carries
``"degraded"``: 0 full fidelity, 1 resident-coefficients-only, 2
fixed-effect-only margin.

``/admin/reload`` drives the zero-downtime hot swap (docs/lifecycle.md):
an empty body follows the registry's ``LATEST``; ``{"version": "vNNNNNN"}``
pins a version (rollback = reload an older one); ``{"modelDir": path}``
swaps to a bare model directory when no registry is configured. Replies
200 with the active version (``"swapped": false`` when already there),
404 for an unknown version, 409 when the registry has no live version,
and 503 when the swap itself failed (the previous model keeps serving —
a failed swap never tears down the live state).

``/admin/membership`` applies an entity-affinity epoch (docs/serving.md
"Entity-affinity routing & membership"): the front door tells this
replica which slice of the entity universe it owns — ``{"epoch": N,
"replicas": [...], "selfIndex": i, "idKind": "auto",
"prefetchEntityIds"?: [...]}``. The session drops non-owned paged rows,
prefetches the handed-over ids SYNCHRONOUSLY (so the 200 reply means
"the pages are warm" — the front door commits the epoch only after
every member replied), and reports ``applied: false`` for stale epochs
(a replayed broadcast, never an error)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.serve.batcher import (
    BatchWatchdogTimeout,
    MicroBatcher,
    QueueFullError,
)
from photon_ml_tpu.serve.metrics import ServingMetrics
from photon_ml_tpu.serve.session import ScoringSession

__all__ = ["ScoringService", "ScoringServer"]


class ScoringService:
    """Session + batcher + metrics behind a payload-in/payload-out API."""

    def __init__(self, session: ScoringSession,
                 batcher: Optional[MicroBatcher] = None,
                 request_timeout_s: float = 30.0,
                 registry=None,
                 default_deadline_ms: Optional[float] = None,
                 brownout=None):
        self.session = session
        self.metrics: ServingMetrics = session.metrics
        self.batcher = batcher or MicroBatcher(
            session.score_rows, max_batch=session.max_batch,
            metrics=self.metrics, brownout=brownout)
        self.request_timeout_s = float(request_timeout_s)
        # budget applied to requests that carry no X-Deadline-Ms header
        self.default_deadline_ms = (
            None if default_deadline_ms is None
            else float(default_deadline_ms))
        self.brownout = brownout if brownout is not None else getattr(
            self.batcher, "brownout", None)
        self.registry = registry  # optional registry.ModelRegistry
        self._reload_lock = threading.Lock()

    # -- endpoints ---------------------------------------------------------
    @staticmethod
    def validate_score_payload(payload):
        """``(rows, per_coordinate) | None, error_body | None`` — the
        parse/validate half of ``/score``, shared by the sync handler
        and the asyncio front end (which must not block the event loop
        on the scoring half)."""
        if not isinstance(payload, dict) or not isinstance(
                payload.get("rows"), list):
            return None, {"error": "payload must be "
                                   '{"rows": [...], "perCoordinate"?: '
                                   'bool}'}
        rows = payload["rows"]
        if not rows:
            return None, {"error": "empty rows"}
        if not all(isinstance(r, dict) for r in rows):
            return None, {"error": "every row must be an object"}
        return (rows, bool(payload.get("perCoordinate"))), None

    @staticmethod
    def score_error_response(e: BaseException,
                             request_id: Optional[str] = None
                             ) -> Tuple[int, dict]:
        """Map a scoring-path exception onto the status contract — ONE
        definition for the threaded and asyncio transports. Shed/error
        bodies carry the request id so a client's 429/503 is greppable
        against the server's slow-request and error logs."""
        if isinstance(e, QueueFullError):
            body = {"error": str(e), "shed": True, "cause": e.cause,
                    "retryAfterS": round(e.retry_after_s, 3)}
            status = 429
        elif isinstance(e, ValueError):
            status, body = 400, {"error": str(e)}
        elif isinstance(e, (BatchWatchdogTimeout, TimeoutError)):
            status, body = 504, {"error": str(e)}
        else:
            status, body = 503, {"error": f"scoring failed: {e}"}
        if request_id:
            body["requestId"] = request_id
        return status, body

    @staticmethod
    def score_body(rows, per_coord: bool, result, degraded: int = 0
                   ) -> dict:
        """Shape a resolved batcher result into the response body.
        ``degraded`` is the ladder level the batch was actually served
        at — always present so clients can alert on fidelity, not just
        availability."""
        if per_coord:
            scores, parts = result
        else:
            scores, parts = result, {}
        body = {"scores": [float(s) for s in scores],
                "degraded": int(degraded)}
        uids = [r.get("uid") for r in rows]
        if any(u is not None for u in uids):
            body["uids"] = uids
        if per_coord:
            body["scoreComponents"] = {
                k: [float(x) for x in v] for k, v in parts.items()}
        return body

    @staticmethod
    def parse_deadline_ms(raw) -> Optional[float]:
        """Parse an ``X-Deadline-Ms`` header value. None/blank means no
        per-request deadline; a malformed value raises ValueError (the
        transports turn that into a 400 — a client that SENT a budget
        but garbled it must not silently run unbounded)."""
        if raw is None:
            return None
        raw = str(raw).strip()
        if not raw:
            return None
        try:
            return float(raw)
        except ValueError:
            raise ValueError(
                f"bad X-Deadline-Ms value {raw!r}: must be a number "
                "of milliseconds") from None

    def deadline_s(self, deadline_ms: Optional[float]) -> Optional[float]:
        """The effective budget in seconds: the request's own header
        wins; otherwise the service default; otherwise None."""
        ms = (deadline_ms if deadline_ms is not None
              else self.default_deadline_ms)
        return None if ms is None else ms / 1e3

    def handle_score(self, payload, request_id: Optional[str] = None,
                     deadline_ms: Optional[float] = None
                     ) -> Tuple[int, dict]:
        """``{"rows": [...], "perCoordinate": bool}`` -> scores. Each row
        as ``ScoringSession.score_rows`` documents (features /
        entityIds / offset, plus an optional echoed ``uid``).
        ``request_id`` rides the pending request through the batcher and
        appears in shed/error bodies; ``deadline_ms`` is the propagated
        remaining budget (``X-Deadline-Ms``)."""
        valid, err = self.validate_score_payload(payload)
        if valid is None:
            if request_id:
                err = dict(err, requestId=request_id)
            return 400, err
        rows, per_coord = valid
        try:
            pending = self.batcher.submit(
                rows, per_coord, request_id=request_id,
                deadline_s=self.deadline_s(deadline_ms))
            result = pending.result(self.request_timeout_s)
        except Exception as e:
            return self.score_error_response(e, request_id=request_id)
        return 200, self.score_body(rows, per_coord, result,
                                    degraded=pending.degraded)

    def handle_healthz(self) -> Tuple[int, dict]:
        """Liveness + readiness in one: HTTP 200 whenever the process
        can serve, but ``status`` distinguishes ``ok`` from ``warming``
        (background page installs still draining after a swap) — the
        front door's half-open probe readmits only on ``ok``."""
        warming = bool(getattr(self.session, "warming", False))
        body = {
            "status": "warming" if warming else "ok",
            "model_dir": self.session.model_dir,
            "active_version": self.session.active_version,
            "task": self.session.task,
            "queue_depth": self.batcher.queue_depth,
            "max_batch": self.batcher.max_batch,
        }
        if self.brownout is not None:
            body["brownout_level"] = self.brownout.level
        # duck-typed test sessions may not carry a membership view
        membership = getattr(self.session, "membership", None)
        if membership is not None and membership.epoch > 0:
            body["membership"] = membership.describe()
        return 200, body

    def handle_reload(self, payload) -> Tuple[int, dict]:
        """Hot-swap the session (``POST /admin/reload``). Serialized by
        a lock — two concurrent reloads would race the session's
        prev-state rollback slot; requests keep flowing either way."""
        payload = payload if isinstance(payload, dict) else {}
        model_dir = payload.get("modelDir")
        version = payload.get("version")
        with self._reload_lock:
            if model_dir:
                source, version = model_dir, str(model_dir)
            elif self.registry is not None:
                try:
                    version = version or self.registry.read_latest()
                except Exception as e:
                    return 503, {"error": f"registry unreadable: {e}"}
                if version is None:
                    return 409, {"error": "registry has no live version "
                                          "(nothing promoted yet)"}
                try:
                    source = self.registry.open_version(version)
                except Exception as e:
                    return 404, {"error": f"unknown version "
                                          f"{version!r}: {e}"}
            else:
                return 400, {"error": "no registry configured; pass "
                                      '{"modelDir": ...}'}
            if (version == self.session.active_version
                    and not payload.get("force")):
                return 200, {"activeVersion": self.session.active_version,
                             "swapped": False}
            try:
                active = self.session.swap(source, version=version)
            except Exception as e:
                # the old state keeps serving; surface the failure
                return 503, {"error": f"swap failed: {e}",
                             "activeVersion": self.session.active_version}
        return 200, {"activeVersion": active, "swapped": True}

    def handle_membership(self, payload) -> Tuple[int, dict]:
        """Apply a membership epoch (``POST /admin/membership``). The
        reply is sent only after the owned-slice eviction AND the moved-
        id prefetch completed — the front door's prefetch-before-commit
        contract hangs on this reply meaning "done", not "queued"."""
        if not isinstance(payload, dict):
            return 400, {"error": "membership payload must be an object"}
        try:
            epoch = int(payload["epoch"])
            if "replicas" in payload:
                replicas = [str(r) for r in payload["replicas"]]
                num_shards = len(replicas)
                shard_index = int(payload["selfIndex"])
            else:
                num_shards = int(payload["numShards"])
                shard_index = int(payload["shardIndex"])
            id_kind = str(payload.get("idKind", "auto"))
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": f"bad membership payload: {e}"}
        try:
            applied = self.session.set_membership(
                epoch=epoch, num_shards=num_shards,
                shard_index=shard_index, id_kind=id_kind)
        except ValueError as e:
            return 400, {"error": str(e)}
        body = {"applied": bool(applied),
                "membership": self.session.membership.describe()}
        if applied and payload.get("prefetchEntityIds"):
            n, nbytes = self.session.prefetch_entities(
                payload["prefetchEntityIds"])
            body["prefetched"] = n
            body["prefetchBytes"] = nbytes
        return 200, body

    def handle_metrics(self) -> Tuple[int, str]:
        return 200, self.metrics.render()

    def close(self, drain_timeout_s: float = 5.0) -> None:
        self.batcher.close(drain_timeout_s)
        # duck-typed test sessions may not carry the installer thread
        close = getattr(self.session, "close", None)
        if close is not None:
            close()


class _Handler(BaseHTTPRequestHandler):
    service: ScoringService  # injected by ScoringServer via subclassing
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet; metrics carry the signal
        pass

    def _reply(self, status: int, body, content_type="application/json",
               request_id=None):
        retry_after = (body.get("retryAfterS")
                       if status == 429 and isinstance(body, dict) else None)
        data = (body if isinstance(body, (bytes, str))
                else json.dumps(body))
        if isinstance(data, str):
            data = data.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        if request_id is not None:
            self.send_header("X-Request-Id", request_id)
        if retry_after is not None:
            # ceil to whole seconds: Retry-After is integral per RFC 9110
            self.send_header("Retry-After",
                             str(max(1, int(-(-float(retry_after) // 1)))))
        self.end_headers()
        self.wfile.write(data)

    def _request_id(self) -> str:
        """Honor a client-supplied X-Request-Id (trimmed, bounded);
        assign one otherwise — every response echoes it."""
        rid = (self.headers.get("X-Request-Id") or "").strip()
        return rid[:128] if rid else obs_trace.new_request_id()

    def do_GET(self):
        rid = self._request_id()
        if self.path == "/healthz":
            status, body = self.service.handle_healthz()
            self._reply(status, body, request_id=rid)
        elif self.path == "/metrics":
            status, text = self.service.handle_metrics()
            self._reply(status, text,
                        content_type="text/plain; version=0.0.4",
                        request_id=rid)
        else:
            self._reply(404, {"error": f"unknown path {self.path}"},
                        request_id=rid)

    def do_POST(self):
        rid = self._request_id()
        if self.path not in ("/score", "/admin/reload",
                             "/admin/membership"):
            self._reply(404, {"error": f"unknown path {self.path}"},
                        request_id=rid)
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"null")
        except (ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad JSON: {e}",
                              "requestId": rid}, request_id=rid)
            return
        try:
            deadline_ms = self.service.parse_deadline_ms(
                self.headers.get("X-Deadline-Ms"))
        except ValueError as e:
            self._reply(400, {"error": str(e), "requestId": rid},
                        request_id=rid)
            return
        with obs_trace.request_context(request_id=rid):
            if self.path == "/admin/reload":
                status, body = self.service.handle_reload(payload)
            elif self.path == "/admin/membership":
                status, body = self.service.handle_membership(payload)
            else:
                status, body = self.service.handle_score(
                    payload, request_id=rid, deadline_ms=deadline_ms)
        self._reply(status, body, request_id=rid)


class ScoringServer:
    """Threaded HTTP server over a :class:`ScoringService`. ``port=0``
    binds an ephemeral port (tests); ``start()`` serves on a daemon
    thread, ``close()`` shuts the listener and drains the batcher."""

    def __init__(self, service: ScoringService, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        handler = type("BoundHandler", (_Handler,), {"service": service})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        self._serving = False

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "ScoringServer":
        self._serving = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="photon-serve-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Foreground serve (the CLI driver's main loop)."""
        self._serving = True
        self._httpd.serve_forever()

    def close(self, drain_timeout_s: float = 5.0) -> None:
        # shutdown() handshakes with a RUNNING serve_forever loop and
        # blocks forever without one — only call it when a loop started
        if self._serving:
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
        self.service.close(drain_timeout_s)
