"""Serving metrics — compatibility re-export.

The metrics core (histograms, counters, text exposition) moved to
:mod:`photon_ml_tpu.obs.metrics` so training and the front door share
the same primitives; this module keeps the historical import path and
the exact classes the serving stack and its tests bind to. The
``/metrics`` render is byte-identical to the pre-move output for every
pre-existing series (``tests/test_obs_metrics.py`` pins it against a
golden exposition captured before the move).
"""

from __future__ import annotations

from photon_ml_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS_MS,
    Histogram,
    ServingMetrics,
    _fmt,
)

__all__ = ["Histogram", "ServingMetrics"]
