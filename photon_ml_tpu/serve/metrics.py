"""Serving metrics: counters, gauges, latency histograms, text exposition.

Stdlib-only (the serving stack adds no dependencies). The exposition
format is the Prometheus text format's subset that covers counters,
gauges, and cumulative histograms, so the ``/metrics`` endpoint scrapes
directly; everything is also readable as a plain dict (``snapshot``) for
the in-process tests and the bench harness.

Thread-safety: one lock per :class:`ServingMetrics` instance — every
recording site is a handful of float ops, and the handler threads +
batcher worker all write here.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["Histogram", "ServingMetrics"]

# Default latency buckets (milliseconds): log-ish spacing from sub-ms to
# the watchdog regime. Cumulative counts, prometheus ``le`` semantics.
DEFAULT_LATENCY_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0,
)


class Histogram:
    """Fixed-bucket cumulative histogram (prometheus semantics): bucket
    ``le=b`` counts observations ``<= b``, plus ``+Inf``/count/sum."""

    def __init__(self, bounds: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf overflow
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        i = len(self.bounds)
        for j, b in enumerate(self.bounds):
            if value <= b:
                i = j
                break
        self.counts[i] += 1
        self.total += 1
        self.sum += value

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket boundaries (upper bound of the
        bucket the rank lands in; +Inf bucket reports the last bound)."""
        if self.total == 0:
            return 0.0
        rank = q * self.total
        seen = 0
        for j, b in enumerate(self.bounds):
            seen += self.counts[j]
            if seen >= rank:
                return b
        return self.bounds[-1] if self.bounds else float("inf")

    def render(self, name: str, out: List[str]) -> None:
        out.append(f"# TYPE {name} histogram")
        cum = 0
        for j, b in enumerate(self.bounds):
            cum += self.counts[j]
            out.append(f'{name}_bucket{{le="{_fmt(b)}"}} {cum}')
        out.append(f'{name}_bucket{{le="+Inf"}} {self.total}')
        out.append(f"{name}_sum {_fmt(self.sum)}")
        out.append(f"{name}_count {self.total}")


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


class ServingMetrics:
    """All serving-side instrumentation in one place.

    Exported series (``photon_serve_`` prefix):
      requests_total / rows_total / shed_total / errors_total — counters;
      shed_queue_full_total / shed_deadline_total — the load-shedding
        split by cause: admission-queue-at-capacity rejections vs
        requests whose deadline expired while still queued (shed_total
        stays the sum, for dashboards that predate the split);
      request_latency_ms / batch_latency_ms — histograms (request latency
        is admission -> response; batch latency is one scoring execution);
      queue_wait_ms / compute_ms — the request-latency split: time a
        request sat in the admission queue waiting for a batch slot vs
        the scoring execution's wall time attributed to the request, so
        the bench's stall accounting and /metrics agree on where time
        goes (queue_wait + compute ~= request_latency per request);
      queue_depth — gauge, current admission-queue occupancy;
      batch_fill_ratio — gauge, rolling mean of rows/max_batch per batch;
      compile_cache_{hits,misses}_total, coeff_cache_{hits,misses,
        evictions}_total — cache counters (hit rates derive from these);
      swaps_total / swap_latency_ms / active_version_info — the model-
        lifecycle series: hot-swap count, build-to-install latency, and
        a version-labeled info gauge (value constant 1; the label
        carries the active version, the standard prometheus idiom for
        string-valued state);
      gate_{pass,fail}_total — promotion-gate verdicts observed by this
        process (the gate tool and the reload path record here).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.rows_total = 0
        self.shed_total = 0
        self.shed_queue_full_total = 0
        self.shed_deadline_total = 0
        self.errors_total = 0
        self.batches_total = 0
        self.batch_rows_sum = 0
        self.batch_fill_sum = 0.0
        self.queue_depth = 0
        self.request_latency_ms = Histogram()
        self.batch_latency_ms = Histogram()
        self.queue_wait_ms = Histogram()
        self.compute_ms = Histogram()
        # cache counters are owned here but incremented through the cache
        # objects' stat hooks so the caches stay usable standalone
        self.compile_cache_hits = 0
        self.compile_cache_misses = 0
        self.coeff_cache_hits = 0
        self.coeff_cache_misses = 0
        self.coeff_cache_evictions = 0
        # device-resident paged coefficient table (serve/paged_table.py)
        self.paged_installs = 0
        self.paged_page_evictions = 0
        self.paged_faults = 0
        # model lifecycle (registry/ + ScoringSession.swap)
        self.swaps_total = 0
        self.swap_latency_ms = Histogram()
        self.active_version = ""
        self.gate_pass_total = 0
        self.gate_fail_total = 0

    # -- recording sites ---------------------------------------------------
    def record_request(self, rows: int, latency_ms: float,
                       queue_wait_ms: Optional[float] = None,
                       compute_ms: Optional[float] = None) -> None:
        with self._lock:
            self.requests_total += 1
            self.rows_total += rows
            self.request_latency_ms.observe(latency_ms)
            if queue_wait_ms is not None:
                self.queue_wait_ms.observe(queue_wait_ms)
            if compute_ms is not None:
                self.compute_ms.observe(compute_ms)

    def record_shed(self, cause: str = "queue_full") -> None:
        with self._lock:
            self.shed_total += 1
            if cause == "deadline":
                self.shed_deadline_total += 1
            else:
                self.shed_queue_full_total += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def record_batch(self, rows: int, max_batch: int,
                     latency_ms: float) -> None:
        with self._lock:
            self.batches_total += 1
            self.batch_rows_sum += rows
            self.batch_fill_sum += rows / max(max_batch, 1)
            self.batch_latency_ms.observe(latency_ms)

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth

    def record_compile(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.compile_cache_hits += 1
            else:
                self.compile_cache_misses += 1

    def record_coeff(self, hits: int = 0, misses: int = 0,
                     evictions: int = 0) -> None:
        with self._lock:
            self.coeff_cache_hits += hits
            self.coeff_cache_misses += misses
            self.coeff_cache_evictions += evictions

    def record_paged(self, installs: int = 0, page_evictions: int = 0,
                     faults: int = 0) -> None:
        with self._lock:
            self.paged_installs += installs
            self.paged_page_evictions += page_evictions
            self.paged_faults += faults

    def set_active_version(self, version: str) -> None:
        with self._lock:
            self.active_version = str(version)

    def record_swap(self, version: str, latency_ms: float) -> None:
        with self._lock:
            self.swaps_total += 1
            self.active_version = str(version)
            self.swap_latency_ms.observe(latency_ms)

    def record_gate(self, passed: bool) -> None:
        with self._lock:
            if passed:
                self.gate_pass_total += 1
            else:
                self.gate_fail_total += 1

    # -- views -------------------------------------------------------------
    @staticmethod
    def _rate(hits: int, misses: int) -> float:
        total = hits + misses
        return hits / total if total else 0.0

    def snapshot(self) -> Dict[str, float]:
        """Flat dict view (tests, bench, logs)."""
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "rows_total": self.rows_total,
                "shed_total": self.shed_total,
                "shed_queue_full_total": self.shed_queue_full_total,
                "shed_deadline_total": self.shed_deadline_total,
                "errors_total": self.errors_total,
                "batches_total": self.batches_total,
                "queue_depth": self.queue_depth,
                "batch_fill_ratio": (self.batch_fill_sum
                                     / max(self.batches_total, 1)),
                "request_latency_p50_ms":
                    self.request_latency_ms.quantile(0.5),
                "request_latency_p99_ms":
                    self.request_latency_ms.quantile(0.99),
                "queue_wait_p50_ms": self.queue_wait_ms.quantile(0.5),
                "queue_wait_p99_ms": self.queue_wait_ms.quantile(0.99),
                "compute_p50_ms": self.compute_ms.quantile(0.5),
                "compute_p99_ms": self.compute_ms.quantile(0.99),
                "compile_cache_hits": self.compile_cache_hits,
                "compile_cache_misses": self.compile_cache_misses,
                "compile_cache_hit_rate": self._rate(
                    self.compile_cache_hits, self.compile_cache_misses),
                "coeff_cache_hits": self.coeff_cache_hits,
                "coeff_cache_misses": self.coeff_cache_misses,
                "coeff_cache_evictions": self.coeff_cache_evictions,
                "paged_installs": self.paged_installs,
                "paged_page_evictions": self.paged_page_evictions,
                "paged_faults": self.paged_faults,
                "coeff_cache_hit_rate": self._rate(
                    self.coeff_cache_hits, self.coeff_cache_misses),
                "swaps_total": self.swaps_total,
                "swap_latency_p50_ms": self.swap_latency_ms.quantile(0.5),
                "active_version": self.active_version,
                "gate_pass_total": self.gate_pass_total,
                "gate_fail_total": self.gate_fail_total,
            }

    def render(self) -> str:
        """Prometheus text exposition of every series."""
        with self._lock:
            out: List[str] = []

            def counter(name, v):
                out.append(f"# TYPE {name} counter")
                out.append(f"{name} {_fmt(v)}")

            def gauge(name, v):
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name} {_fmt(v)}")

            counter("photon_serve_requests_total", self.requests_total)
            counter("photon_serve_rows_total", self.rows_total)
            counter("photon_serve_shed_total", self.shed_total)
            counter("photon_serve_shed_queue_full_total",
                    self.shed_queue_full_total)
            counter("photon_serve_shed_deadline_total",
                    self.shed_deadline_total)
            counter("photon_serve_errors_total", self.errors_total)
            counter("photon_serve_batches_total", self.batches_total)
            gauge("photon_serve_queue_depth", self.queue_depth)
            gauge("photon_serve_batch_fill_ratio",
                  self.batch_fill_sum / max(self.batches_total, 1))
            self.request_latency_ms.render(
                "photon_serve_request_latency_ms", out)
            self.batch_latency_ms.render(
                "photon_serve_batch_latency_ms", out)
            self.queue_wait_ms.render("photon_serve_queue_wait_ms", out)
            self.compute_ms.render("photon_serve_compute_ms", out)
            counter("photon_serve_compile_cache_hits_total",
                    self.compile_cache_hits)
            counter("photon_serve_compile_cache_misses_total",
                    self.compile_cache_misses)
            gauge("photon_serve_compile_cache_hit_rate", self._rate(
                self.compile_cache_hits, self.compile_cache_misses))
            counter("photon_serve_coeff_cache_hits_total",
                    self.coeff_cache_hits)
            counter("photon_serve_coeff_cache_misses_total",
                    self.coeff_cache_misses)
            counter("photon_serve_coeff_cache_evictions_total",
                    self.coeff_cache_evictions)
            counter("photon_serve_paged_installs_total",
                    self.paged_installs)
            counter("photon_serve_paged_page_evictions_total",
                    self.paged_page_evictions)
            counter("photon_serve_paged_faults_total", self.paged_faults)
            gauge("photon_serve_coeff_cache_hit_rate", self._rate(
                self.coeff_cache_hits, self.coeff_cache_misses))
            counter("photon_serve_swaps_total", self.swaps_total)
            self.swap_latency_ms.render("photon_serve_swap_latency_ms", out)
            out.append("# TYPE photon_serve_active_version_info gauge")
            label = (self.active_version.replace("\\", "\\\\")
                     .replace('"', '\\"'))
            out.append(
                f'photon_serve_active_version_info{{version="{label}"}} 1')
            counter("photon_serve_gate_pass_total", self.gate_pass_total)
            counter("photon_serve_gate_fail_total", self.gate_fail_total)
            return "\n".join(out) + "\n"
