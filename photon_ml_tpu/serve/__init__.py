"""Online GAME scoring service.

The batch path (``cli/game_scoring_driver.py``) loads a model, scores a
dataset, and exits; this package keeps a model RESIDENT and answers
scoring requests while it stays loaded — the Snap ML-style hierarchy
(PAPERS.md, arXiv:1803.06333) of pinning hot state next to the compute
and pipelining host work around it, applied to a GAME model:

* :class:`~photon_ml_tpu.serve.session.ScoringSession` — fixed-effect
  coefficients live on device; jit executables are pre-compiled for a
  bounded ladder of padded batch shapes so steady-state traffic never
  recompiles; per-entity random-effect coefficients come from an LRU.
* :class:`~photon_ml_tpu.serve.batcher.MicroBatcher` — deadline-based
  micro-batching (``max_batch`` / ``max_delay_ms``) with a bounded
  admission queue and explicit load shedding.
* :class:`~photon_ml_tpu.serve.coeff_cache.EntityCoefficientLRU` — hot
  entity coefficients resident, cold entities re-read from the saved
  model directory; unknown entities fall back to fixed-effect-only
  scores exactly as ``game/scoring.py`` does.
* :class:`~photon_ml_tpu.serve.server.ScoringServer` — stdlib-only JSON
  endpoint with ``/healthz`` and a text ``/metrics`` exporter.

See ``docs/serving.md`` for the architecture and operational contract.
"""

from photon_ml_tpu.serve.batcher import (
    BatchWatchdogTimeout,
    MicroBatcher,
    QueueFullError,
    ScoreContext,
)
from photon_ml_tpu.serve.brownout import BrownoutController
from photon_ml_tpu.serve.coeff_cache import (
    EntityCoefficientLRU,
    LayeredCoefficientStore,
    ModelDirCoefficientStore,
)
from photon_ml_tpu.serve.membership import (
    MembershipEpoch,
    MembershipManager,
    MembershipView,
)
from photon_ml_tpu.serve.metrics import Histogram, ServingMetrics
from photon_ml_tpu.serve.paged_table import PagedCoefficientTable
from photon_ml_tpu.serve.session import ScoringSession
from photon_ml_tpu.serve.server import ScoringService, ScoringServer
from photon_ml_tpu.serve.aserver import AsyncFrontDoor, AsyncScoringServer
from photon_ml_tpu.serve.watcher import RegistryWatcher

__all__ = [
    "ScoringSession", "MicroBatcher", "QueueFullError", "ScoreContext",
    "BrownoutController", "BatchWatchdogTimeout", "EntityCoefficientLRU",
    "LayeredCoefficientStore", "ModelDirCoefficientStore", "Histogram",
    "ServingMetrics", "PagedCoefficientTable", "ScoringService",
    "ScoringServer", "AsyncScoringServer", "AsyncFrontDoor",
    "RegistryWatcher", "MembershipEpoch", "MembershipManager",
    "MembershipView",
]
