"""GAME coordinate descent: the training algorithm.

Equivalent of the reference's ``algorithm.{CoordinateDescent, Coordinate,
FixedEffectCoordinate, RandomEffectCoordinate, CoordinateFactory}``
(SURVEY.md §3.2/§4.1; reference mount empty). Same structure as the
reference: an outer loop over iterations x coordinates (sequential by
design — SURVEY.md §3.8 block-coordinate row); per coordinate, the offsets
fed to training are ``base + total_scores - this coordinate's scores`` (the
residual trick), the coordinate retrains warm-started from its previous
model, then its scores are recomputed and validation metrics tracked.

TPU mapping: the outer loop is host-side Python (coarse-grained, a handful
of steps); each coordinate's training is one jitted device computation built
ONCE per coordinate (shapes are stable across CD steps, so XLA compiles
once) — data-parallel ``shard_map`` over the mesh ``data`` axis for the
fixed effect, ``vmap``-of-solvers (optionally over the ``entity`` axis) for
random effects.

Coefficient spaces: optimizer-space coefficients (normalization folded into
the objective) stay internal; scoring and saved models use model-space
coefficients via ``NormalizationContext.to_model_space`` so scores computed
on raw features match the normalized-training margins exactly.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.analysis.sanitizers import (
    deterministic_replay,
    nan_guard_check,
)
from photon_ml_tpu.evaluation import get_evaluator
from photon_ml_tpu.game.data import (
    HostSparse,
    RandomEffectTrainData,
    SketchProjection,
    build_random_effect_data,
    build_score_view,
    host_sparse_from_features,
)
from photon_ml_tpu.game.random_effect import (
    score_random_effect,
    train_random_effect,
)
from photon_ml_tpu.game.sampling import down_sample
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    GeneralizedLinearModel,
    RandomEffectBucket,
    RandomEffectModel,
)
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.obs import metrics as obs_metrics
from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.regularization import RegularizationContext, RegularizationType
from photon_ml_tpu.optimize import OptimizerConfig, get_optimizer
from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.parallel.data_parallel import (
    distributed_hvp,
    distributed_value_and_grad,
)
from photon_ml_tpu.parallel.entity_shard import (
    EntityShardSpec,
    ShardCommStats,
    allgather_objects,
    check_table_budget,
    exchange_score_updates,
)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.parallel.resilience import (
    CollectiveGuard,
    PeerFailure,
    health_barrier,
)
from photon_ml_tpu.types import LabeledBatch, SparseFeatures, margins as _margins


@dataclasses.dataclass(frozen=True)
class CoordinateConfig:
    """Per-coordinate optimization configuration — the reference's
    ``FixedEffectOptimizationConfiguration`` / ``RandomEffectOptimization-
    Configuration`` parameter surface (SURVEY.md §3.2/§5.6)."""

    name: str
    coordinate_type: str = "fixed"  # "fixed" | "random"
    feature_shard: str = "global"
    entity_column: Optional[str] = None  # required for random
    # "auto" (default): fixed effects use the margin L-BFGS (the measured
    # best across platforms); random coordinates resolve to the measured
    # per-platform batched solver (random_effect.resolve_re_optimizer —
    # dense-Newton on TPU, 3.4x the vmapped L-BFGS on the v5e). Explicit:
    # "lbfgs" | "tron" | "owlqn", plus "newton" (random only).
    optimizer: str = "auto"
    max_iters: int = 100
    tolerance: float = 1e-8
    reg_type: str | RegularizationType = RegularizationType.NONE
    reg_weight: float = 0.0
    elastic_net_alpha: float = 0.5
    down_sampling_rate: float = 1.0  # fixed-effect only
    # fixed-effect sparse gradient strategy: "auto" (measured per-platform
    # default — parallel.data_parallel.resolve_sparse_grad), "scatter"
    # (XLA scatter-add), "csc" or "csc_pallas" (scatter-free column-sorted
    # — types.CSCTranspose)
    sparse_grad: str = "auto"
    # fixed-effect larger-than-HBM mode: features stay in host RAM, every
    # optimizer pass streams fixed-shape chunks through the device
    # (parallel/streaming.py); sparse_grad is ignored (per-chunk autodiff)
    streaming: bool = False
    chunk_rows: int = 1 << 16
    # streamed transfer-ring depth (parallel/streaming.iter_device_chunks):
    # None = the module default / PHOTON_PREFETCH_DEPTH
    prefetch_depth: Optional[int] = None
    active_cap: Optional[int] = None  # random-effect only
    num_buckets: int = 4  # random-effect entity size buckets
    # Active-set coordinate descent (random-effect only): entities whose
    # solver converged are FROZEN; a later sweep re-solves an entity only
    # if its residual offsets drifted by more than active_tol (max-abs over
    # its rows, relative to max(1, |offsets|)) since its last solve — an
    # unchanged-offset re-solve of a converged entity is a no-op by
    # construction (the bucket solvers return the pre-step point on the
    # converging iteration), so the skip is exact to within the drift
    # tolerance, and the per-sweep work tracks the unconverged frontier.
    # Every refresh_every-th sweep is a full refresh that re-solves every
    # entity regardless (belt-and-braces re-activation). active_tol=None
    # defaults to a few ulps of the working dtype — near-exact skipping;
    # set it looser (e.g. 1e-6) to trade a bounded approximation for
    # bigger savings on slowly-converging runs.
    active_set: bool = True
    refresh_every: int = 4
    active_tol: Optional[float] = None
    # random-effect projector: "subspace" (exact per-entity maps) or
    # "random" (shared count-sketch of width projection_dim)
    projection: str = "subspace"
    projection_dim: Optional[int] = None
    projection_seed: int = 0
    # False | True/"diagonal" (1/diag(H), the reference's SIMPLE type) |
    # "full" (diag(H^-1), small dims only — the reference's FULL type)
    compute_variance: bool | str = False
    normalization: Optional[NormalizationContext] = None
    intercept_index: int = -1

    def reg_context(self) -> RegularizationContext:
        return RegularizationContext(RegularizationType(self.reg_type),
                                     self.elastic_net_alpha)

    def opt_config(self) -> OptimizerConfig:
        return OptimizerConfig(max_iters=self.max_iters, tolerance=self.tolerance)

    def __post_init__(self):
        if self.coordinate_type not in ("fixed", "random"):
            raise ValueError(f"coordinate_type must be fixed|random, got "
                             f"{self.coordinate_type}")
        if self.coordinate_type == "random" and self.entity_column is None:
            raise ValueError(f"random coordinate '{self.name}' needs entity_column")
        if self.streaming and self.coordinate_type != "fixed":
            raise ValueError(
                f"coordinate '{self.name}': streaming applies to fixed "
                "effects (random-effect data is per-entity bucketed)")
        if self.optimizer == "newton" and self.coordinate_type != "random":
            raise ValueError(
                f"coordinate '{self.name}': optimizer='{self.optimizer}' "
                "selects a batched per-entity solver — random coordinates "
                "only (fixed effects use lbfgs/owlqn/tron)")
        if (self.coordinate_type == "random" and self.normalization is not None
                and self.projection == "random"):
            raise ValueError(
                f"random coordinate '{self.name}': normalization is not "
                "supported with projection='random' (count-sketch slots mix "
                "features); use projection='subspace'"
            )
        if self.compute_variance not in (False, True, "diagonal", "full"):
            raise ValueError(
                f"compute_variance={self.compute_variance!r}; expected "
                "False, True, 'diagonal' or 'full'")
        # fail at config time, not after an hours-long streamed fit
        if self.compute_variance == "full" and self.streaming:
            raise ValueError(
                "compute_variance='full' needs the d x d Hessian in device "
                "memory; not available with streaming=True (use 'diagonal')")
        if self.prefetch_depth is not None and self.prefetch_depth < 0:
            raise ValueError(
                f"coordinate '{self.name}': prefetch_depth must be >= 0, "
                f"got {self.prefetch_depth}")
        if self.refresh_every < 1:
            raise ValueError(
                f"coordinate '{self.name}': refresh_every must be >= 1, "
                f"got {self.refresh_every}")
        if self.active_tol is not None and not (
                np.isfinite(self.active_tol) and self.active_tol >= 0):
            raise ValueError(
                f"coordinate '{self.name}': active_tol must be finite and "
                f">= 0, got {self.active_tol}")


@dataclasses.dataclass
class GameDataset:
    """Host-resident GAME dataset: shared labels/weights/offsets plus one
    feature matrix per shard and one id column per entity type
    (the reference's GameDatum/DataFrame — SURVEY.md §3.2)."""

    features: Dict[str, HostSparse]
    labels: np.ndarray
    weights: np.ndarray
    offsets: np.ndarray
    entity_ids: Dict[str, np.ndarray]
    group_ids: Optional[np.ndarray] = None  # for per_group_* evaluators
    # larger-than-host-RAM shards: a disk-backed chunk source (e.g.
    # io.stream_source.AvroChunkSource over the same rows, in order) per
    # shard that should NOT be materialized in `features`. A streaming
    # fixed-effect coordinate on such a shard re-decodes its features from
    # disk every optimizer pass (O(12B/row) host state for the scalars)
    feature_sources: Optional[Dict[str, object]] = None

    def __post_init__(self):
        self.labels = np.asarray(self.labels, np.float64)
        n = len(self.labels)
        self.weights = (
            np.ones(n) if self.weights is None else np.asarray(self.weights, np.float64)
        )
        self.offsets = (
            np.zeros(n) if self.offsets is None else np.asarray(self.offsets, np.float64)
        )
        self.features = {k: host_sparse_from_features(v) for k, v in self.features.items()}

    @property
    def num_samples(self) -> int:
        return len(self.labels)


def make_game_dataset(features, labels, weights=None, offsets=None,
                      entity_ids=None, group_ids=None) -> GameDataset:
    if not isinstance(features, dict):
        features = {"global": features}
    return GameDataset(features, labels, weights, offsets, entity_ids or {}, group_ids)


def _device_features(sp: HostSparse, dtype) -> SparseFeatures:
    return SparseFeatures(
        jnp.asarray(sp.indices),
        None if sp.values is None else jnp.asarray(sp.values, dtype),
        dim=sp.dim
    )


# one shared jitted margin kernel (streamed scoring reuses the compilation
# across chunks and CD iterations)
_margins_jit = jax.jit(_margins)

_log = logging.getLogger(__name__)


def _changed_rows(new_np: np.ndarray, old_np: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """This shard's bitwise-changed rows and their new values — the
    published delta. Pure in its inputs (the replay-hook contract)."""
    rows = np.flatnonzero(new_np != old_np).astype(np.int32)
    return rows, new_np[rows]


def _scatter_rows(prev_np: np.ndarray, row_parts: Sequence[np.ndarray],
                  val_parts: Sequence[np.ndarray]) -> np.ndarray:
    """Scatter every shard's published rows (disjoint by entity
    ownership) into a copy of the previous global vector. Pure in its
    inputs; rank order of the parts is pinned by the gather."""
    out = np.array(prev_np, copy=True)
    rows = np.concatenate(list(row_parts))
    if len(rows):
        out[rows] = np.concatenate(list(val_parts))
    return out


class _ResidualTotal:
    """Running residual total ``base + sum(coordinate scores)``.

    The CD loop previously recomputed ``base + sum(scores.values())`` inside
    the per-coordinate loop — O(C) device adds per coordinate step, O(C^2)
    per sweep. This keeps one running vector updated with a subtract/add on
    the changed coordinate; ``resync`` (called once per sweep) re-derives it
    from scratch so low-precision drift from the running updates cannot
    accumulate across sweeps."""

    def __init__(self, base):
        self.base = base
        self.total = base

    def resync(self, scores: Dict[str, jax.Array]) -> None:
        # the per-sweep resync is pure in (base, scores) — dict order is
        # insertion order, pinned by the config list — and parity-bearing,
        # so it carries a replay hook (no-op outside the sim harness)
        self.total = deterministic_replay(
            "cd.residual_resync", self._recompute, scores)

    def _recompute(self, scores: Dict[str, jax.Array]):
        return self.base + sum(scores.values())

    def excluding(self, name: str, scores: Dict[str, jax.Array]):
        """Residual offsets for one coordinate: everything but its own
        scores."""
        return self.total - scores[name]

    def replace(self, old_scores, new_scores) -> None:
        self.total = self.total - old_scores + new_scores


def _drift_active_masks(buckets, frozen, offs_np: np.ndarray,
                        snap: np.ndarray, tol: float) -> List[np.ndarray]:
    """Per-bucket ACTIVE masks for a non-refresh sweep: an entity must be
    re-solved when it never converged (``~frozen``) or when its residual
    offsets drifted — max-abs change over its rows since its last solve
    exceeds ``tol * max(1, |snapshot|_inf over its rows)``. Host numpy over
    the already-materialized bucket index arrays: O(rows) per sweep, no
    device work."""
    d_all = np.abs(offs_np - snap)
    masks: List[np.ndarray] = []
    for b, bucket in enumerate(buckets):
        E = bucket.num_entities
        if E == 0:
            masks.append(np.zeros(0, bool))
            continue
        sidx = bucket.sample_idx
        valid = sidx >= 0
        safe = np.maximum(sidx, 0)
        drift = np.max(d_all[safe] * valid, axis=1)
        scale = np.maximum(1.0, np.max(np.abs(snap)[safe] * valid, axis=1))
        masks.append(~frozen[b] | (drift > tol * scale))
    return masks


class _FixedState:
    """Per-coordinate fixed-effect state with a jit-compiled fit function
    built once (the reference's FixedEffectCoordinate role)."""

    def __init__(self, cfg: CoordinateConfig, data: GameDataset, dtype,
                 task: str, mesh: Optional[Mesh]):
        source = (data.feature_sources or {}).get(cfg.feature_shard)
        sp = None if source is not None else data.features[cfg.feature_shard]
        self.cfg = cfg
        self.dtype = dtype
        self.dim = source.dim if source is not None else sp.dim
        self.n_all = data.num_samples
        if source is not None:
            self._init_out_of_core(cfg, data, source, task, mesh)
            return
        if cfg.down_sampling_rate < 1.0:
            rows, w = down_sample(data.labels, data.weights,
                                  cfg.down_sampling_rate, task=task, seed=0)
        else:
            rows, w = np.arange(data.num_samples), data.weights
        self.train_rows = jnp.asarray(rows)
        self.w: Optional[jax.Array] = None  # optimizer (training) space
        self.variances = None

        reg = cfg.reg_context()
        self.l2 = reg.l2_weight(cfg.reg_weight)
        self.l1 = reg.l1_weight(cfg.reg_weight)
        optimizer = "lbfgs" if cfg.optimizer == "auto" else cfg.optimizer
        if self.l1 > 0 and optimizer != "owlqn":
            optimizer = "owlqn"  # the reference routes L1 to OWLQN
        self.obj = make_objective(task, normalization=cfg.normalization,
                                  intercept_index=cfg.intercept_index)
        opt = get_optimizer(optimizer)
        cfg_opt = cfg.opt_config()
        d = sp.dim

        use_mesh = mesh is not None and "data" in mesh.shape
        n_rows = len(rows)
        pad = (-n_rows) % mesh.shape["data"] if use_mesh else 0
        self._offset_pad = pad
        self.streaming = cfg.streaming

        if self.streaming:
            # larger-than-HBM: features stay host-resident as fixed-shape
            # chunks; every optimizer pass streams them through the device
            # (VERDICT r1 #3 — no device-resident copy of the shard at all).
            # Multi-process: each process holds only its process_span of the
            # rows; streamed partials reduce across processes inside
            # parallel/streaming.py, and chunk sharding stays on a
            # process-LOCAL mesh so per-process partials are local sums.
            import dataclasses as _dc

            from photon_ml_tpu.parallel.multihost import process_span
            from photon_ml_tpu.parallel.streaming import (
                fit_streaming,
                make_host_chunks,
            )

            pc = jax.process_count()
            n_local = len(jax.local_devices())
            chunk_rows = cfg.chunk_rows
            if use_mesh:
                chunk_rows = -(-chunk_rows // n_local) * n_local
            self._chunk_rows = chunk_rows
            if use_mesh:
                self._stream_mesh = (
                    mesh if pc == 1
                    else make_mesh({"data": n_local},
                                   devices=jax.local_devices()))
            else:
                self._stream_mesh = None
            self._offset_pad = 0
            self._offset_sharding = None
            t0, t1 = process_span(len(rows)) if pc > 1 else (0, len(rows))
            self._train_span = (t0, t1)
            rows_local = rows[t0:t1]
            train_sp = HostSparse(
                np.asarray(sp.indices)[rows_local],
                (None if sp.values is None
                 else np.asarray(sp.values)[rows_local]), sp.dim)
            self._chunks, _ = make_host_chunks(
                train_sp, data.labels[rows_local], None, w[t0:t1],
                chunk_rows=chunk_rows)
            s0, s1 = process_span(self.n_all) if pc > 1 else (0, self.n_all)
            self._score_span = (s0, s1)
            if cfg.down_sampling_rate >= 1.0 and (t0, t1) == (s0, s1):
                self._score_chunks = self._chunks  # same rows, same order
            else:
                score_sp = HostSparse(
                    np.asarray(sp.indices)[s0:s1],
                    (None if sp.values is None
                     else np.asarray(sp.values)[s0:s1]), sp.dim)
                self._score_chunks, _ = make_host_chunks(
                    score_sp, data.labels[s0:s1], chunk_rows=chunk_rows)
            self._last_chunks = self._chunks

            def _with_offsets(offs_np):
                offs_np = offs_np[t0:t1]  # this process's train span
                out = []
                for i, c in enumerate(self._chunks):
                    seg = offs_np[i * chunk_rows:(i + 1) * chunk_rows]
                    if len(seg) < chunk_rows:
                        seg = np.pad(seg, (0, chunk_rows - len(seg)))
                    out.append(_dc.replace(c, offsets=seg))
                return out

            def _make_fit(run_cfg):
                def _fit(w0, offs, l2, l1):
                    chunks = _with_offsets(np.asarray(offs))
                    self._last_chunks = chunks
                    return fit_streaming(
                        self.obj, chunks, self.dim, w0=w0, l2=float(l2),
                        l1=float(l1), optimizer=optimizer, config=run_cfg,
                        dtype=dtype, mesh=self._stream_mesh,
                        prefetch_depth=cfg.prefetch_depth,
                    )
                return _fit

            self._batch_parts = None
            self._install_fit(_make_fit, cfg_opt, needs_jit=False)
            return

        feats = SparseFeatures(
            jnp.asarray(np.concatenate([sp.indices[rows],
                                        np.zeros((pad,) + sp.indices.shape[1:], np.int32)])),
            # implicit-ones HostSparse stays value-free; padding rows are
            # weight-0 so their implicit 1.0 slots contribute nothing
            (None if sp.values is None else
             jnp.asarray(np.concatenate([sp.values[rows],
                                         np.zeros((pad,) + sp.values.shape[1:])]), dtype)),
            dim=sp.dim,
        )
        labels = jnp.asarray(np.concatenate([data.labels[rows], np.ones(pad)]), dtype)
        weights = jnp.asarray(np.concatenate([w, np.zeros(pad)]), dtype)

        l1_mask = None
        if cfg.intercept_index >= 0:
            l1_mask = jnp.ones((d,), dtype).at[cfg.intercept_index].set(0.0)

        from photon_ml_tpu.parallel.data_parallel import resolve_sparse_grad

        sparse_grad = resolve_sparse_grad(cfg.sparse_grad, feats)
        use_csc = sparse_grad in ("csc", "csc_pallas")
        if use_csc and not isinstance(feats, SparseFeatures):
            raise ValueError(f"sparse_grad='{sparse_grad}' needs sparse "
                             "features")
        if use_mesh or use_csc:
            work_mesh = mesh if use_mesh else make_mesh({"data": 1})
            if use_mesh:
                sharding = NamedSharding(mesh, P("data"))
                feats = jax.tree.map(lambda a: jax.device_put(a, sharding), feats)
                labels = jax.device_put(labels, sharding)
                weights = jax.device_put(weights, sharding)
                self._offset_sharding = sharding
            else:
                self._offset_sharding = None
            if use_csc:
                from photon_ml_tpu.parallel.data_parallel import make_csc_path

                build, fg_csc, hvp_csc = make_csc_path(
                    self.obj, work_mesh,
                    use_pallas=(sparse_grad == "csc_pallas"),
                )
                # sorted once here; offsets change per CD iteration, the
                # sparsity pattern never does
                csc = jax.jit(build)(
                    LabeledBatch(feats, labels, jnp.zeros_like(labels), weights)
                )

                def _make_fit(run_cfg):
                    def _fit(w0, offs, l2, l1):
                        batch = LabeledBatch(feats, labels, offs, weights)
                        fg = lambda w: fg_csc(w, batch, csc, l2)
                        if optimizer == "owlqn":
                            return opt(fg, w0, l1, run_cfg, l1_mask=l1_mask)
                        if optimizer == "tron":
                            return opt(fg, w0, run_cfg,
                                       hvp=lambda w, v: hvp_csc(w, v, batch, csc, l2))
                        return opt(fg, w0, run_cfg)
                    return _fit
            else:
                fg_dist = distributed_value_and_grad(self.obj, mesh)
                hvp_dist = distributed_hvp(self.obj, mesh) if optimizer == "tron" else None

                def _make_fit(run_cfg):
                    def _fit(w0, offs, l2, l1):
                        batch = LabeledBatch(feats, labels, offs, weights)
                        fg = lambda w: fg_dist(w, batch, l2)
                        if optimizer == "owlqn":
                            return opt(fg, w0, l1, run_cfg, l1_mask=l1_mask)
                        if optimizer == "tron":
                            return opt(fg, w0, run_cfg,
                                       hvp=lambda w, v: hvp_dist(w, v, batch, l2))
                        return opt(fg, w0, run_cfg)
                    return _fit
        else:
            self._offset_sharding = None

            def _make_fit(run_cfg):
                def _fit(w0, offs, l2, l1):
                    batch = LabeledBatch(feats, labels, offs, weights)
                    fg = lambda w: self.obj.value_and_grad(w, batch, l2)
                    if optimizer == "owlqn":
                        return opt(fg, w0, l1, run_cfg, l1_mask=l1_mask)
                    return opt(fg, w0, run_cfg)
                return _fit

        # scoring features: when training uses every row un-padded, the
        # training copy IS the scoring copy — aliasing avoids the 2x
        # feature memory the round-1 design paid (VERDICT r1 weak #7)
        if cfg.down_sampling_rate >= 1.0 and pad == 0:
            self.full_features = feats
        else:
            self.full_features = _device_features(sp, dtype)
        self._batch_parts = (feats, labels, weights)
        self._install_fit(_make_fit, cfg_opt, needs_jit=True)

    def _init_out_of_core(self, cfg: CoordinateConfig, data: GameDataset,
                          source, task: str, mesh: Optional[Mesh]) -> None:
        """Fixed effect over a shard that never materializes in host RAM:
        every optimizer pass re-decodes the source's chunks from disk
        (io/stream_source.py), with the CD residual offsets — which change
        every step and live as an O(12B/row) host array — overlaid onto
        the streamed scalars (ScalarOverlaySource). Streaming semantics
        otherwise match the in-RAM streaming branch."""
        from photon_ml_tpu.io.stream_source import ScalarOverlaySource
        from photon_ml_tpu.parallel.streaming import fit_streaming

        if not cfg.streaming:
            raise ValueError(
                f"coordinate '{cfg.name}': shard '{cfg.feature_shard}' is "
                "disk-backed (feature_sources) — set streaming=True")
        if cfg.down_sampling_rate < 1.0:
            raise ValueError(
                f"coordinate '{cfg.name}': down-sampling needs row "
                "indexing; not supported out of core")
        pc = jax.process_count()
        total_rows = getattr(source, "total_rows", source.rows)
        if pc > 1:
            # multi-controller: every process holds its OWN contiguous
            # block share of the same file set
            # (AvroChunkSource(process_part=(i, pc))); per-pass partials
            # reduce across processes inside parallel/streaming.py, and
            # scoring reassembles via the parts' recorded row spans
            spans = getattr(source, "part_spans", None)
            if not spans or len(spans) != pc:
                raise ValueError(
                    f"coordinate '{cfg.name}': multi-process out-of-core "
                    "training needs a per-process "
                    f"AvroChunkSource(process_part=(i, {pc})) — this "
                    "source has no matching part_spans")
            if (spans[0][0] != 0 or spans[-1][1] != total_rows or any(
                    spans[i][1] != spans[i + 1][0] for i in range(pc - 1))):
                raise ValueError(
                    f"coordinate '{cfg.name}': part spans {spans} do not "
                    "tile the dataset (need >= one container block per "
                    "process — rewrite the data with a smaller "
                    "block_size)")
        if total_rows != data.num_samples:
            raise ValueError(
                f"coordinate '{cfg.name}': source has {total_rows} rows, "
                f"dataset has {data.num_samples} — they must be the same "
                "data in the same order")
        lo, hi = getattr(source, "row_span", (0, source.rows))
        self.streaming = True
        self.train_rows = jnp.arange(data.num_samples)
        self.w = None
        self.variances = None
        reg = cfg.reg_context()
        self.l2 = reg.l2_weight(cfg.reg_weight)
        self.l1 = reg.l1_weight(cfg.reg_weight)
        optimizer = cfg.optimizer
        if self.l1 > 0 and optimizer != "owlqn":
            optimizer = "owlqn"
        self.obj = make_objective(task, normalization=cfg.normalization,
                                  intercept_index=cfg.intercept_index)
        cfg_opt = cfg.opt_config()
        use_mesh = mesh is not None and "data" in mesh.shape
        if use_mesh and pc > 1:
            # chunk sharding stays on a process-LOCAL mesh so per-process
            # partials are local sums (same policy as the in-RAM branch)
            self._stream_mesh = make_mesh({"data": len(jax.local_devices())},
                                          devices=jax.local_devices())
        else:
            self._stream_mesh = mesh if use_mesh else None
        if (self._stream_mesh is not None
                and source.chunk_rows % len(jax.local_devices())):
            raise ValueError(
                f"coordinate '{cfg.name}': source chunk_rows="
                f"{source.chunk_rows} must divide the "
                f"{len(jax.local_devices())}-device data mesh")
        self._offset_pad = 0
        self._offset_sharding = None
        self._ooc_source = source
        self._score_chunks = source  # features-only streamed scoring
        self._score_span = (lo, hi)
        self._ooc_part_spans = getattr(source, "part_spans", None)
        self._batch_parts = None
        # this process's slice of the dataset-level scalars (full slice
        # in single-process mode)
        labels = data.labels[lo:hi]
        weights = data.weights[lo:hi]
        dim = self.dim

        def _make_fit(run_cfg):
            def _fit(w0, offs, l2, l1):
                overlay = ScalarOverlaySource(
                    source, labels=labels, weights=weights,
                    offsets=np.asarray(offs)[lo:hi])
                self._last_chunks = overlay
                return fit_streaming(
                    self.obj, overlay, dim, w0=w0, l2=float(l2),
                    l1=float(l1), optimizer=optimizer, config=run_cfg,
                    dtype=self.dtype, mesh=self._stream_mesh,
                    prefetch_depth=cfg.prefetch_depth,
                )
            return _fit

        self._last_chunks = ScalarOverlaySource(source, labels=labels,
                                                weights=weights)
        self._install_fit(_make_fit, cfg_opt, needs_jit=False)

    def _install_fit(self, make_fit, base_config, needs_jit: bool) -> None:
        """Register the per-OptimizerConfig fit builder. The built (and,
        for in-memory paths, jitted) fit functions are memoized per config
        so an inexact-CD tolerance schedule pays one compile per distinct
        tolerance — a bounded set, since the schedule clamps at the final
        tolerance (optimize.ToleranceSchedule)."""
        self._make_fit = make_fit
        self._base_opt_config = base_config
        self._fit_needs_jit = needs_jit
        self._fit_cache: dict = {}

    def _fit_for(self, opt_config):
        run_cfg = (self._base_opt_config if opt_config is None
                   else opt_config)
        fn = self._fit_cache.get(run_cfg)
        if fn is None:
            fn = self._make_fit(run_cfg)
            if self._fit_needs_jit:
                fn = jax.jit(fn)
            self._fit_cache[run_cfg] = fn
        return fn

    def fit(self, offsets_full: jax.Array, opt_config=None):
        offs = jnp.take(offsets_full, self.train_rows, axis=0).astype(self.dtype)
        if self._offset_pad:
            offs = jnp.concatenate(
                [offs, jnp.zeros((self._offset_pad,), self.dtype)]
            )
        if self._offset_sharding is not None:
            offs = jax.device_put(offs, self._offset_sharding)
        w0 = self.w if self.w is not None else jnp.zeros(
            (self.dim,), self.dtype
        )
        res = self._fit_for(opt_config)(
            w0, offs, jnp.asarray(self.l2, self.dtype),
            jnp.asarray(self.l1, self.dtype))
        self.w = res.w
        # opt-in NaN trap (no-op unless a NaNGuard context is armed):
        # the jitted solver is one fused while_loop and cannot host-check
        # mid-iteration, so divergence is caught where the result lands
        nan_guard_check(f"fe_solver:{self.cfg.name}", res.w)
        if self.cfg.compute_variance:
            if self.streaming:
                if self.cfg.compute_variance == "full":
                    raise ValueError(
                        "compute_variance='full' needs the d x d Hessian in "
                        "device memory; not available in streaming mode "
                        "(use 'diagonal')")
                from photon_ml_tpu.parallel.streaming import (
                    streaming_coefficient_variances,
                )

                self.variances = np.asarray(streaming_coefficient_variances(
                    self.obj, self._last_chunks, self.dim, res.w, self.l2,
                    dtype=self.dtype, mesh=self._stream_mesh,
                    prefetch_depth=self.cfg.prefetch_depth,
                ))
            else:
                feats, labels, weights = self._batch_parts
                batch = LabeledBatch(feats, labels, offs, weights)
                mode = ("full" if self.cfg.compute_variance == "full"
                        else "diagonal")
                self.variances = np.asarray(
                    self.obj.coefficient_variances(res.w, batch, self.l2,
                                                   mode=mode)
                )
        return res

    def train_scores(self, w_model: jax.Array) -> jax.Array:
        """This coordinate's margins over every training row (the
        CoordinateDataScores role). Streaming mode computes them in one
        streamed pass — the transfer ring stages the next chunks' feature
        uploads (budget-accounted) while the current chunk's margins
        compute, and the device->host fetch of chunk i-1 overlaps chunk
        i's dispatch — so no device-resident feature copy ever exists."""
        if not self.streaming:
            return _margins(self.full_features, w_model)
        from photon_ml_tpu.parallel.multihost import (
            allgather_spans,
            allgather_varspans,
        )
        from photon_ml_tpu.parallel.streaming import iter_device_chunks
        from photon_ml_tpu.utils import transfer_budget

        w_model = jnp.asarray(w_model, self.dtype)

        def to_feats(c):
            # features only: scoring never needs the 24B/row scalars
            return SparseFeatures(
                transfer_budget.device_put(np.asarray(c.indices, np.int32),
                                           what="score chunk"),
                (None if c.values is None
                 else transfer_budget.device_put(
                     np.asarray(c.values, self.dtype), what="score chunk")),
                dim=self.dim)

        outs = []
        pending = None
        for _c, feats in iter_device_chunks(self._score_chunks, to_feats,
                                            self.cfg.prefetch_depth):
            res = _margins_jit(feats, w_model)
            if pending is not None:
                outs.append(np.asarray(pending))
            pending = res
        if pending is not None:
            outs.append(np.asarray(pending))
        s0, s1 = self._score_span
        local = np.concatenate(outs)[: s1 - s0]
        # The reassembly allgather is a collective boundary and must
        # follow the PR-1 contract: pre-gather health barrier so a peer
        # whose streamed pass failed aborts every process here instead
        # of wedging the gather. train_scores is also reachable OUTSIDE
        # the sweep guard (warm start / initial scoring in run()), so
        # the barrier lives at the gather, not only in the caller.
        fault_injection.check("cd.score_gather")
        health_barrier("cd.score_gather")
        # out-of-core block parts are contiguous but not span_of-aligned:
        # reassemble via the parts' recorded row spans
        if getattr(self, "_ooc_part_spans", None) is not None:
            return jnp.asarray(allgather_varspans(local,
                                                  self._ooc_part_spans))
        return jnp.asarray(allgather_spans(local, self.n_all))

    def model_space_w(self) -> jax.Array:
        """Raw-feature-space coefficients for scoring/saving."""
        if self.cfg.normalization is not None:
            return self.cfg.normalization.to_model_space(self.w)
        return self.w


class _RandomState:
    def __init__(self, cfg: CoordinateConfig, data: GameDataset, dtype,
                 cache: Optional[dict] = None,
                 entity_shard: Optional[EntityShardSpec] = None,
                 table_budget_bytes: Optional[int] = None):
        sp = data.features[cfg.feature_shard]
        ids = data.entity_ids[cfg.entity_column]
        shard_key = (None if entity_shard is None
                     else (entity_shard.num_shards, entity_shard.shard_index))
        key = ("re_data", id(data), cfg.name, cfg.feature_shard,
               cfg.entity_column, cfg.num_buckets, cfg.active_cap,
               cfg.projection, cfg.projection_dim, cfg.projection_seed,
               shard_key)
        if cache is not None and key in cache:
            # entry[0] pins the keyed dataset alive so its id() can't be
            # recycled by a different GameDataset while the cache lives
            _, self.train_data, self.train_view = cache[key]
        else:
            self.train_data: RandomEffectTrainData = build_random_effect_data(
                sp, data.labels, data.weights, ids,
                effect_name=cfg.name, num_buckets=cfg.num_buckets,
                active_cap=cfg.active_cap,
                projection=cfg.projection,
                projection_dim=cfg.projection_dim,
                projection_seed=cfg.projection_seed,
                entity_shard=entity_shard,
            )
            self.train_view = build_score_view(self.train_data, sp, ids)
            if cache is not None:
                cache[key] = (data, self.train_data, self.train_view)
        # fail BEFORE the first sweep when the local entity table is over
        # the per-process budget (points at --entity-shards)
        check_table_budget(
            self.train_data.table_bytes(), table_budget_bytes,
            coordinate=cfg.name,
            num_shards=1 if entity_shard is None else entity_shard.num_shards)
        self.coeffs: Optional[List[np.ndarray]] = None
        self.variances = None
        # active-set tracking across sweeps: per-bucket boolean masks of
        # FROZEN entities (solver reported converged at their last solve);
        # None until the first full solve
        self.frozen: Optional[List[np.ndarray]] = None
        # residual offsets as of each row's owning entity's last solve —
        # the drift reference for re-activation (length-n host vector)
        self.offs_snap: Optional[np.ndarray] = None
        # entity-sharded mode: this shard's OWN score vectors (zeros on
        # unowned rows). The published delta each sweep is the rows where
        # these bitwise changed; the loop-facing `scores[name]` stays the
        # assembled GLOBAL vector on every process.
        self.local_scores: Optional[jax.Array] = None
        self.local_val_scores: Optional[jax.Array] = None


class CoordinateDescent:
    """Run the GAME block-coordinate loop over a list of coordinates."""

    def __init__(
        self,
        configs: Sequence[CoordinateConfig],
        task: str = "logistic",
        n_iterations: int = 1,
        mesh: Optional[Mesh] = None,
        evaluators: Sequence[str] = (),
        dtype=jnp.float32,
        verbose: bool = False,
        dataset_cache: Optional[dict] = None,
        cd_tolerance: float = 0.0,
        solver_tol_schedule=None,
        entity_shard: Optional[EntityShardSpec] = None,
        entity_table_budget_bytes: Optional[int] = None,
        recovery=None,
    ):
        names = [c.name for c in configs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate coordinate names: {names}")
        if not np.isfinite(cd_tolerance) or cd_tolerance < 0:
            raise ValueError(f"cd_tolerance must be finite and >= 0, got "
                             f"{cd_tolerance}")
        self.configs = list(configs)
        self.task = task
        self.n_iterations = n_iterations
        self.mesh = mesh
        self.evaluator_names = list(evaluators)
        self.dtype = dtype
        self.verbose = verbose
        # Sweep-level early exit: stop when EVERY coordinate's score vector
        # moved by at most cd_tolerance (max-abs) over a whole sweep. 0
        # disables the test — exactly n_iterations sweeps run, as before.
        self.cd_tolerance = float(cd_tolerance)
        # optimize.ToleranceSchedule (or None): inexact inner solves —
        # loose solver tolerance on early sweeps, tightening geometrically
        # to each coordinate's configured tolerance
        self.solver_tol_schedule = solver_tol_schedule
        # Shared across CoordinateDescent instances by GameEstimator so the
        # expensive per-entity bucketing is built once per dataset, not once
        # per grid point (the reference builds coordinate datasets once and
        # reuses them across configs — SURVEY.md §4.1).
        self.dataset_cache = dataset_cache
        # Entity-sharded multi-controller training: each process builds and
        # solves only the random-effect entities its shard owns; sweeps
        # exchange only changed rows' scores (parallel/entity_shard.py).
        # ``entity_table_budget_bytes`` fails fast when any coordinate's
        # LOCAL table exceeds the per-process budget.
        self.entity_shard = entity_shard
        self.entity_table_budget_bytes = entity_table_budget_bytes
        self._sharded = entity_shard is not None and entity_shard.active
        self._comm = ShardCommStats()
        # parallel.recovery.RecoveryManager (or None): per-sweep shard
        # snapshots + in-job rollback/shrink recovery from PeerFailure.
        # One manager serves every grid point of an estimator fit
        # (run() calls reset_for_run(); budgets are job-cumulative).
        self.recovery = recovery

    # -- main loop -------------------------------------------------------
    def run(
        self,
        train: GameDataset,
        validation: Optional[GameDataset] = None,
        warm_start: Optional[GameModel] = None,
        locked: Sequence[str] = (),
        checkpoint_callback=None,
    ) -> Tuple[GameModel, List[dict]]:
        dtype = self.dtype
        n = train.num_samples
        locked = set(locked)
        unknown_locked = locked - {c.name for c in self.configs}
        if unknown_locked:
            raise ValueError(f"locked coordinates not in configs: {unknown_locked}")
        if locked:
            covered = set() if warm_start is None else set(warm_start.coordinates)
            uncovered = locked - covered
            if uncovered:
                raise ValueError(
                    f"locked coordinates {sorted(uncovered)} need a warm_start "
                    "model providing their coefficients"
                )

        states: Dict[str, object] = {}
        val_states: Dict[str, object] = {}
        val_feats: Dict[str, SparseFeatures] = {}
        for cfg in self.configs:
            if cfg.coordinate_type == "fixed":
                states[cfg.name] = _FixedState(cfg, train, dtype, self.task, self.mesh)
                if validation is not None:
                    val_feats[cfg.name] = _device_features(
                        validation.features[cfg.feature_shard], dtype
                    )
        # random-effect states (and their validation score views) build
        # through a helper so elastic recovery can REBUILD them against a
        # shrunk owner map after a rank loss (_recovery_restore)
        self._build_random_states(train, validation, states, val_states)

        # initialize scores (zeros, or from warm-start model)
        scores = {c.name: jnp.zeros((n,), dtype) for c in self.configs}
        val_n = validation.num_samples if validation is not None else 0
        val_scores = {c.name: jnp.zeros((val_n,), dtype) for c in self.configs}
        if self._sharded:
            for cfg in self.configs:
                if cfg.coordinate_type == "random":
                    st = states[cfg.name]
                    st.local_scores = jnp.zeros((n,), dtype)
                    st.local_val_scores = jnp.zeros((val_n,), dtype)
        if warm_start is not None:
            self._load_warm_start(warm_start, states, scores, val_scores,
                                  train, validation, val_states, val_feats)
            if self._sharded:
                # _load_warm_start fills each sharded random coordinate's
                # scores with the LOCAL (owned-rows-only) vector; publish
                # every shard's rows once so the loop starts from the same
                # global vector on every process
                for cfg in self.configs:
                    if (cfg.coordinate_type != "random"
                            or warm_start.coordinates.get(cfg.name) is None):
                        continue
                    st = states[cfg.name]
                    has_val = validation is not None and cfg.name in val_states
                    scores[cfg.name], val_scores[cfg.name], _, _ = (
                        self._exchange_scores(
                            f"warm:{cfg.name}", st, scores[cfg.name],
                            jnp.zeros((n,), dtype),
                            val_scores[cfg.name] if has_val else None,
                            jnp.zeros((val_n,), dtype) if has_val
                            else val_scores[cfg.name]))

        base = jnp.asarray(train.offsets, dtype)
        history: List[dict] = []
        evaluators = [get_evaluator(e) for e in self.evaluator_names]
        entity_mesh = (self.mesh if self.mesh is not None
                       and "entity" in self.mesh.shape else None)

        # Per-iteration validation metrics run on device where a device form
        # exists (VERDICT r2 #9: no full score-vector round-trip to host
        # numpy per iteration); the definitive host-f64 numbers are
        # recomputed once for the final history record below.
        device_evals: dict = {}
        if validation is not None and evaluators:
            from photon_ml_tpu.evaluation.device import (
                make_device_evaluator,
                make_grouped_device_evaluator,
            )

            data_mesh = (self.mesh if self.mesh is not None
                         and "data" in self.mesh.shape
                         and self.mesh.shape["data"] > 1 else None)
            for ev in evaluators:
                if ev.grouped:
                    # grouped metrics run as device segment ops over the
                    # once-factorized group ids — no full score-vector
                    # host round trip per CD iteration (VERDICT r4 #8)
                    device_evals[ev.name] = (
                        None if validation.group_ids is None
                        else make_grouped_device_evaluator(
                            ev.name, validation.group_ids))
                else:
                    device_evals[ev.name] = make_device_evaluator(
                        ev.name, data_mesh)
            val_labels_dev = jnp.asarray(validation.labels, dtype)
            val_weights_dev = jnp.asarray(validation.weights, dtype)
            val_offsets_dev = jnp.asarray(validation.offsets, dtype)

        # Running residual totals (train + validation): maintained by
        # subtract/add on the changed coordinate and resynced once per
        # sweep — the per-coordinate `base + sum(scores.values())` re-sum
        # made every sweep O(C^2) in the coordinate count.
        rt = _ResidualTotal(base)
        vt = (_ResidualTotal(val_offsets_dev)
              if validation is not None and evaluators else None)
        _eps = float(jnp.finfo(dtype).eps)
        stop_reason = "max_iterations"

        def _one_sweep(it: int) -> bool:
            # One full CD sweep; True means the cd_tolerance early exit
            # fired. A closure (not a plain loop body) so the recovery
            # wrapper below can re-run a sweep from a restored snapshot.
            nonlocal stop_reason
            rt.resync(scores)
            if vt is not None:
                vt.resync(val_scores)
            sweep_deltas: Dict[str, float] = {}
            for cfg in self.configs:
                st = states[cfg.name]
                t0 = time.time()
                offs = rt.excluding(cfg.name, scores)
                record = {"iteration": it, "coordinate": cfg.name}
                run_cfg = None
                if self.solver_tol_schedule is not None:
                    run_cfg = dataclasses.replace(
                        cfg.opt_config(),
                        tolerance=self.solver_tol_schedule.at(
                            it, cfg.tolerance))
                    record["solver_tolerance"] = run_cfg.tolerance
                score_delta = 0.0
                # A CD sweep boundary is a collective phase boundary in
                # multi-controller runs (streamed-pass reductions, score
                # allgathers, device-eval psums): the guard converts any
                # process's local failure inside this step into PeerFailure
                # on every process at the step boundary, instead of letting
                # the survivors deadlock in the next coordinate's
                # collectives (parallel/resilience.py).
                with obs_trace.span("cd.coordinate", cat="train",
                                    coordinate=cfg.name, iteration=it), \
                        CollectiveGuard(f"cd:{it}:{cfg.name}"):
                    fault_injection.check("cd.step")
                    if cfg.name not in locked:
                        if cfg.coordinate_type == "fixed":
                            res = st.fit(offs, opt_config=run_cfg)
                            record.update(
                                loss=float(res.value), converged=bool(res.converged),
                                optimizer_iterations=int(res.iterations),
                            )
                            if res.stream_stats is not None:
                                # streamed fixed effects: per-fit pipeline
                                # stall breakdown (decode-wait / transfer /
                                # compute-stall seconds) rides the history
                                record["stream"] = res.stream_stats
                                record["comm_seconds"] = (
                                    res.stream_stats.get("comm_s", 0.0))
                            w_model = st.model_space_w()
                            new_scores = st.train_scores(w_model)
                            score_delta = float(jnp.max(jnp.abs(
                                new_scores - scores[cfg.name]))) if n else 0.0
                            rt.replace(scores[cfg.name], new_scores)
                            scores[cfg.name] = new_scores
                            if validation is not None:
                                new_v = _margins(val_feats[cfg.name], w_model)
                                if vt is not None:
                                    vt.replace(val_scores[cfg.name], new_v)
                                val_scores[cfg.name] = new_v
                        else:
                            score_delta = self._random_step(
                                cfg, st, it, offs, run_cfg, scores,
                                val_scores, val_states, rt, vt, n, val_n,
                                validation, entity_mesh, _eps, record)
                    # comm_seconds rides every record (next to the solve/
                    # eval split): cross-shard score-exchange seconds for
                    # sharded random coordinates, the streamed pass's
                    # cross-process reduction for fixed ones, 0 otherwise
                    record.setdefault("comm_seconds", 0.0)
                    record["solve_seconds"] = time.time() - t0
                    t_eval = time.time()
                    if vt is not None:
                        v_total_host = None
                        for ev in evaluators:
                            fn = device_evals.get(ev.name)
                            if fn is not None:
                                record[ev.name] = float(
                                    fn(vt.total, val_labels_dev,
                                       val_weights_dev))
                            else:  # grouped / precision@k: host path
                                if v_total_host is None:
                                    v_total_host = np.asarray(vt.total)
                                record[ev.name] = ev.evaluate(
                                    v_total_host, validation.labels,
                                    validation.weights, validation.group_ids,
                                )
                    record["eval_seconds"] = time.time() - t_eval
                    record["seconds"] = time.time() - t0
                    record["score_delta"] = score_delta
                    sweep_deltas[cfg.name] = score_delta
                obs_metrics.training_metrics().record_step(
                    cfg.name, record["solve_seconds"],
                    record["eval_seconds"], record["comm_seconds"])
                # coordinate identity rides the record dict + the
                # obs.logging rank/trace stamps, not a hand-rolled prefix
                _log.log(logging.INFO if self.verbose else logging.DEBUG,
                         "cd.step %s", record)
                history.append(record)
            if checkpoint_callback is not None:
                # coarse-grained per-outer-iteration checkpoint (the
                # reference's per-stage HDFS writes — SURVEY.md §5.4)
                checkpoint_callback(it, self._build_model(states))
            if (self.cd_tolerance > 0 and sweep_deltas and
                    all(d <= self.cd_tolerance for d in
                        sweep_deltas.values())):
                # every coordinate's score vector is stationary to within
                # cd_tolerance: the remaining sweeps would re-derive the
                # same model (frozen coordinates skip their streamed /
                # solver passes entirely from here on)
                stop_reason = "cd_tolerance"
                _log.log(logging.INFO if self.verbose else logging.DEBUG,
                         "cd.early_exit after sweep %d: max score delta "
                         "%.3g <= cd_tolerance %.3g", it,
                         max(sweep_deltas.values()), self.cd_tolerance)
                return True
            return False

        recovery = self.recovery
        if recovery is not None:
            recovery.reset_for_run()
        it = 0
        while it < self.n_iterations:
            try:
                if recovery is not None:
                    # sweep-start commit: the rollback target for any
                    # failure inside this sweep (all-or-nothing barrier →
                    # every survivor agrees on the committed sweep)
                    recovery.commit(it, lambda: self._recovery_payload(
                        states, scores, val_scores, validation))
                stop = _one_sweep(it)
                it += 1
                if stop:
                    break
            except PeerFailure as exc:
                if recovery is None:
                    raise
                # re-raises when the failure is fatal / budgets exhausted /
                # nothing committed; a failure DURING recovery propagates
                # out of on_failure or _recovery_restore as a coordinated
                # abort (bounded by the barrier watchdog — no hangs)
                plan = recovery.on_failure(exc)
                it = self._recovery_restore(
                    plan, train, validation, states, val_states,
                    scores, val_scores, history, recovery)
        if history:
            history[-1]["stop_reason"] = stop_reason

        # Definitive final metrics: exact host f64 evaluators (per-iteration
        # device values above are monitoring; model selection reads
        # history[-1], which must be the reference numbers).
        if history and validation is not None and evaluators:
            v_total = np.asarray(val_offsets_dev + sum(val_scores.values()))
            for ev in evaluators:
                history[-1][ev.name] = ev.evaluate(
                    v_total, validation.labels, validation.weights,
                    validation.group_ids,
                )

        model = self._build_model(states)
        return model, history

    # -- helpers ---------------------------------------------------------
    def _build_random_states(self, train, validation, states, val_states):
        """(Re)build every random coordinate's ``_RandomState`` and
        validation score view against the CURRENT ``self.entity_shard``.
        Used at run() entry and again by recovery after a shrink (the
        dataset cache keys include the shard spec, so a remapped owner
        map rebuilds rather than aliasing the stale layout)."""
        for cfg in self.configs:
            if cfg.coordinate_type != "random":
                continue
            states[cfg.name] = _RandomState(
                cfg, train, self.dtype, cache=self.dataset_cache,
                entity_shard=self.entity_shard,
                table_budget_bytes=self.entity_table_budget_bytes)
            if validation is not None:
                st: _RandomState = states[cfg.name]
                key = ("val_view", id(validation), id(st.train_data))
                cache = self.dataset_cache
                if cache is not None and key in cache:
                    val_states[cfg.name] = cache[key][2]
                else:
                    sp = validation.features[cfg.feature_shard]
                    ids = validation.entity_ids[cfg.entity_column]
                    val_states[cfg.name] = build_score_view(st.train_data, sp, ids)
                    if cache is not None:
                        # pin both keyed objects against id() recycling
                        cache[key] = (validation, st.train_data,
                                      val_states[cfg.name])

    def _random_step(self, cfg, st, it, offs, run_cfg, scores, val_scores,
                     val_states, rt, vt, n, val_n, validation, entity_mesh,
                     eps, record) -> float:
        """One random-effect coordinate step with active-set freezing and
        incremental rescoring. Returns the coordinate's score delta.

        Entity-sharded mode: the solve/rescore below run over this
        shard's OWNED entities only; the step then publishes the rows
        whose local score bitwise changed and scatter-applies every
        shard's published rows into the global score vector — the
        delta-only exchange (parallel/entity_shard.py). The exchange
        runs EVERY sweep (possibly with an empty payload) so the
        collective stays SPMD-aligned whatever each shard's local
        frontier looks like."""
        sharded = self._sharded
        refresh = (st.coeffs is None or st.frozen is None
                   or st.offs_snap is None or not cfg.active_set
                   or it % cfg.refresh_every == 0)
        active = None
        offs_np = None
        solve = True
        if not refresh:
            offs_np = np.asarray(offs)
            tol = (cfg.active_tol if cfg.active_tol is not None else 0.0)
            # floor at a few ulps of the working dtype: comparing offsets
            # for bit-stability at a tolerance below the arithmetic noise
            # would never skip anything
            tol = max(float(tol), 8.0 * eps)
            active = _drift_active_masks(st.train_data.buckets, st.frozen,
                                         offs_np, st.offs_snap, tol)
            if sum(int(a.sum()) for a in active) == 0:
                # every local entity frozen with stationary offsets: the
                # solve and rescore are skipped outright — no device work
                record.update(converged_fraction=1.0,
                              mean_optimizer_iterations=0.0,
                              entities_solved=0, refresh=False)
                if not sharded:
                    return 0.0
                solve = False  # still participates in the exchange below
        prev_local = st.local_scores if sharded else scores[cfg.name]
        prev_val_local = (st.local_val_scores if sharded
                          else val_scores.get(cfg.name))
        new_local = prev_local
        new_val_local = None
        if solve:
            reg = cfg.reg_context()
            fit = train_random_effect(
                st.train_data, offs, task=self.task,
                l2=reg.l2_weight(cfg.reg_weight),
                l1=reg.l1_weight(cfg.reg_weight),
                optimizer=cfg.optimizer,
                config=run_cfg if run_cfg is not None else cfg.opt_config(),
                w0=st.coeffs, mesh=entity_mesh,
                compute_variance=cfg.compute_variance, dtype=self.dtype,
                normalization=cfg.normalization,
                active=active, prev_variances=st.variances,
            )
            if cfg.active_set:
                st.frozen = [np.asarray(c) for c in fit.converged]
                if offs_np is None:
                    offs_np = np.asarray(offs)
                if active is None or st.offs_snap is None:
                    st.offs_snap = np.array(offs_np, copy=True)
                else:
                    # re-solved entities get a fresh drift reference; frozen
                    # ones keep the offsets they last solved against
                    for b, bucket in enumerate(st.train_data.buckets):
                        if bucket.num_entities == 0 or not active[b].any():
                            continue
                        rows = bucket.sample_idx[active[b]]
                        rows = rows[rows >= 0]
                        st.offs_snap[rows] = offs_np[rows]
            st.coeffs = fit.coefficients
            st.variances = fit.variances
            record.update(
                converged_fraction=fit.converged_fraction,
                mean_optimizer_iterations=fit.mean_iterations,
                entities_solved=fit.entities_solved,
                refresh=bool(refresh),
            )
            # incremental rescoring after a partial solve: only rows owned
            # by re-solved entities are recomputed and scatter-overwritten
            # into the previous score vector (the LOCAL vector when
            # sharded — unowned rows stay zero there)
            new_local = score_random_effect(
                st.train_view, st.coeffs, n, self.dtype,
                prev=None if active is None else prev_local,
                changed=active)
            if validation is not None and cfg.name in val_states:
                new_val_local = score_random_effect(
                    val_states[cfg.name], st.coeffs, val_n, self.dtype,
                    prev=None if active is None else prev_val_local,
                    changed=active)

        if not sharded:
            delta = (float(jnp.max(jnp.abs(new_local - scores[cfg.name])))
                     if n else 0.0)
            rt.replace(scores[cfg.name], new_local)
            scores[cfg.name] = new_local
            if new_val_local is not None:
                if vt is not None:
                    vt.replace(val_scores[cfg.name], new_val_local)
                val_scores[cfg.name] = new_val_local
            return delta

        # -- entity-sharded: delta-only cross-shard exchange ---------------
        has_val = validation is not None and cfg.name in val_states
        if has_val and new_val_local is None:
            new_val_local = st.local_val_scores  # skipped solve: unchanged
        new_global, new_val_global, comm_bytes, comm_s = (
            self._exchange_scores(
                f"cd:{it}:{cfg.name}", st, new_local, scores[cfg.name],
                new_val_local if has_val else None,
                val_scores[cfg.name]))
        record["comm_seconds"] = comm_s
        record["comm_bytes"] = comm_bytes
        delta = (float(jnp.max(jnp.abs(new_global - scores[cfg.name])))
                 if n else 0.0)
        rt.replace(scores[cfg.name], new_global)
        scores[cfg.name] = new_global
        if has_val:
            if vt is not None:
                vt.replace(val_scores[cfg.name], new_val_global)
            val_scores[cfg.name] = new_val_global
        return delta

    def _exchange_scores(self, tag, st, new_local, prev_global,
                         new_val_local, prev_val_global):
        """Publish this shard's bitwise-changed rows (train + validation)
        and scatter the union of every shard's published rows into the
        global vectors. Each row's entity has exactly one owner, so the
        row sets are disjoint and the scatter lands on the bit-identical
        vector the single-host loop computes; rows whose recomputed score
        equals the previous value are not shipped at all — that is what
        keeps per-sweep bytes proportional to the moving frontier, not
        the table."""
        new_np = np.asarray(new_local)
        old_np = np.asarray(st.local_scores)
        rows, vals = deterministic_replay(
            f"cd.delta:{tag}", _changed_rows, new_np, old_np)
        if new_val_local is not None:
            vnew = np.asarray(new_val_local)
            vold = np.asarray(st.local_val_scores)
            vrows, vvals = deterministic_replay(
                f"cd.delta-val:{tag}", _changed_rows, vnew, vold)
        else:
            vrows = np.zeros(0, np.int32)
            vvals = np.zeros(0, new_np.dtype)
        b0, t0 = self._comm.bytes_gathered, self._comm.seconds
        gathered = exchange_score_updates([rows, vals, vrows, vvals],
                                          tag=tag, stats=self._comm)
        comm_bytes = self._comm.bytes_gathered - b0
        comm_s = self._comm.seconds - t0
        g_np = deterministic_replay(
            f"cd.scatter:{tag}", _scatter_rows, np.asarray(prev_global),
            [g[0] for g in gathered], [g[1] for g in gathered])
        new_global = jnp.asarray(g_np)
        new_val_global = prev_val_global
        if new_val_local is not None:
            v_np = deterministic_replay(
                f"cd.scatter-val:{tag}", _scatter_rows,
                np.asarray(prev_val_global),
                [g[2] for g in gathered], [g[3] for g in gathered])
            new_val_global = jnp.asarray(v_np)
            st.local_val_scores = new_val_local
        st.local_scores = new_local
        return new_global, new_val_global, comm_bytes, comm_s

    def _build_model(self, states) -> GameModel:
        coords = {}
        for cfg in self.configs:
            st = states[cfg.name]
            if cfg.coordinate_type == "fixed":
                coef = Coefficients(
                    jnp.asarray(st.model_space_w()),
                    None if st.variances is None else jnp.asarray(st.variances),
                )
                coords[cfg.name] = FixedEffectModel(
                    GeneralizedLinearModel(coef, self.task), cfg.feature_shard
                )
            else:
                buckets = []
                for b, bucket in enumerate(st.train_data.buckets):
                    lm0 = bucket.local_maps[0] if bucket.local_maps else None
                    buckets.append(
                        RandomEffectBucket(
                            entity_ids=bucket.entity_ids,
                            coefficients=st.coeffs[b],
                            projection=bucket.projection,
                            variances=None if st.variances is None else st.variances[b],
                            sketch=lm0 if isinstance(lm0, SketchProjection) else None,
                        )
                    )
                if self._sharded:
                    # the ONE place the full entity table crosses the wire:
                    # save points (checkpoints + the final model), never
                    # per sweep. Every process merges the same rank-ordered
                    # buckets, so checkpoints and the saved model keep the
                    # single-file io/model_io layout (serving/registry
                    # unchanged) and every process returns the same model.
                    # Collective: in a sharded run EVERY process must reach
                    # _build_model at the same points (run() does; sharded
                    # drivers give non-lead processes a no-op checkpoint
                    # callback so the gather stays aligned).
                    gathered = allgather_objects(
                        buckets, tag=f"model:{cfg.name}", stats=self._comm)
                    buckets = [b for shard in gathered for b in shard]
                coords[cfg.name] = RandomEffectModel(
                    cfg.name, buckets, self.task, cfg.feature_shard,
                    entity_column=cfg.entity_column,
                )
        return GameModel(coords, self.task)

    # -- in-job recovery -------------------------------------------------
    def _recovery_payload(self, states, scores, val_scores, validation):
        """This rank's sweep-start shard snapshot: everything a survivor
        set needs to resume the sweep bit-exactly. Replicated state (fixed
        coefficients, global score vectors) plus this shard's random-effect
        tables; sharded runs additionally record the bucket-level entity
        table (ids + projections + coefficients) so a SHRUNK survivor set
        can redistribute a dead rank's entities through the warm-start
        remap. All values are host numpy copies (the npz ResumeManager
        pickles them; device arrays must not leak into the marker)."""
        fixed = {}
        random = {}
        for cfg in self.configs:
            st = states[cfg.name]
            if cfg.coordinate_type == "fixed":
                fixed[cfg.name] = {
                    "w": None if st.w is None else np.asarray(st.w),
                    "variances": (None if st.variances is None
                                  else np.asarray(st.variances)),
                }
                continue
            buckets = None
            if self._sharded and st.coeffs is not None:
                buckets = []
                for b, bucket in enumerate(st.train_data.buckets):
                    lm0 = bucket.local_maps[0] if bucket.local_maps else None
                    buckets.append({
                        "entity_ids": np.asarray(bucket.entity_ids),
                        "projection": (None if bucket.projection is None
                                       else np.asarray(bucket.projection)),
                        "coefficients": np.asarray(st.coeffs[b]),
                        "frozen": (None if st.frozen is None
                                   else np.asarray(st.frozen[b])),
                        "sketch": (lm0 if isinstance(lm0, SketchProjection)
                                   else None),
                    })
            random[cfg.name] = {
                "coeffs": (None if st.coeffs is None
                           else [np.asarray(c) for c in st.coeffs]),
                "frozen": (None if st.frozen is None
                           else [np.asarray(f) for f in st.frozen]),
                "offs_snap": (None if st.offs_snap is None
                              else np.array(st.offs_snap, copy=True)),
                "local_scores": (
                    None if getattr(st, "local_scores", None) is None
                    else np.asarray(st.local_scores)),
                "local_val_scores": (
                    None if getattr(st, "local_val_scores", None) is None
                    else np.asarray(st.local_val_scores)),
                "buckets": buckets,
            }
        return {
            "fixed": fixed,
            "random": random,
            "scores": {k: np.asarray(v) for k, v in scores.items()},
            "val_scores": (None if validation is None else
                           {k: np.asarray(v) for k, v in val_scores.items()}),
        }

    def _recovery_restore(self, plan, train, validation, states, val_states,
                          scores, val_scores, history, recovery) -> int:
        """Roll the run back to the plan's agreed committed sweep. Pure
        rollback (same membership) restores every table from this rank's
        own snapshot in place. A shrink additionally recomputes the
        entity owner map over the survivors, rebuilds the random states
        against it, and redistributes the dead rank's entities from the
        old members' committed bucket tables via the warm-start remap
        (bitwise-exact per the PR-7 roundtrip guarantee); local score
        vectors are re-derived by scoring the redistributed coefficients,
        which at a committed point bitwise-matches an uninterrupted run's
        vectors on the new layout. Random-effect posterior variances are
        NOT snapshotted (they are O(entities * dim^2)); a recovered run
        recomputes them at its next solve (docs/resilience.md). Returns
        the sweep index to resume from."""
        dtype = self.dtype
        n = train.num_samples
        val_n = validation.num_samples if validation is not None else 0
        own = plan.snapshots[plan.own_rank]
        remap = plan.remapped and self._sharded
        old_spec = self.entity_shard
        if remap:
            self.entity_shard = EntityShardSpec(plan.new_num_shards,
                                                plan.new_shard_index)
            self._sharded = self.entity_shard.active
            self._build_random_states(train, validation, states, val_states)
        for cfg in self.configs:
            if cfg.coordinate_type != "fixed":
                continue
            snap = own["fixed"][cfg.name]
            st = states[cfg.name]
            st.w = None if snap["w"] is None else jnp.asarray(snap["w"])
            st.variances = (None if snap["variances"] is None
                            else jnp.asarray(snap["variances"]))
        for name, arr in own["scores"].items():
            scores[name] = jnp.asarray(arr)
        if validation is not None and own.get("val_scores") is not None:
            for name, arr in own["val_scores"].items():
                val_scores[name] = jnp.asarray(arr)
        for cfg in self.configs:
            if cfg.coordinate_type != "random":
                continue
            st = states[cfg.name]
            snap = own["random"][cfg.name]
            st.variances = None
            if not remap:
                st.coeffs = (None if snap["coeffs"] is None
                             else [np.asarray(c) for c in snap["coeffs"]])
                st.frozen = (None if snap["frozen"] is None
                             else [np.asarray(f) for f in snap["frozen"]])
                st.offs_snap = (None if snap["offs_snap"] is None
                                else np.array(snap["offs_snap"], copy=True))
                if self._sharded:
                    st.local_scores = (
                        jnp.zeros((n,), dtype)
                        if snap["local_scores"] is None
                        else jnp.asarray(snap["local_scores"]))
                    st.local_val_scores = (
                        jnp.zeros((val_n,), dtype)
                        if snap["local_val_scores"] is None
                        else jnp.asarray(snap["local_val_scores"]))
                continue
            merged = []
            for r in plan.old_members:
                b = plan.snapshots[r]["random"][cfg.name]["buckets"]
                if b:
                    merged.extend(b)
            if not merged:
                # crashed before this coordinate's first solve: cold state
                st.coeffs = None
                st.frozen = None
                st.offs_snap = None
                st.local_scores = jnp.zeros((n,), dtype)
                st.local_val_scores = jnp.zeros((val_n,), dtype)
                continue
            prev = RandomEffectModel(
                cfg.name,
                [RandomEffectBucket(
                    entity_ids=b["entity_ids"],
                    coefficients=b["coefficients"],
                    projection=b["projection"],
                    variances=None,
                    sketch=b["sketch"]) for b in merged],
                self.task, cfg.feature_shard,
                entity_column=cfg.entity_column)
            st.coeffs = _coeffs_from_prev(prev, st.train_data)
            # active-set freeze flags travel per ENTITY (layout-free)
            fmap = {}
            for b in merged:
                if b["frozen"] is None:
                    continue
                for eid, fz in zip(b["entity_ids"], b["frozen"]):
                    fmap[str(eid)] = bool(fz)
            st.frozen = (None if not fmap else [
                np.asarray([fmap.get(str(e), False)
                            for e in bucket.entity_ids], bool)
                for bucket in st.train_data.buckets])
            # each row's residual reference belongs to the entity's OLD
            # owner: that shard solved the entity last, so its snapshot
            # holds the row's value as of that solve (layout-independent)
            snaps_offs = [plan.snapshots[r]["random"][cfg.name]["offs_snap"]
                          for r in plan.old_members]
            if any(o is None for o in snaps_offs):
                st.offs_snap = None
            else:
                old_owner = old_spec.owner_of(
                    train.entity_ids[cfg.entity_column])
                merged_offs = np.array(np.asarray(snaps_offs[0]), copy=True)
                for si in range(1, len(plan.old_members)):
                    rows = old_owner == si
                    merged_offs[rows] = np.asarray(snaps_offs[si])[rows]
                st.offs_snap = merged_offs
            if self._sharded:
                st.local_scores = score_random_effect(
                    st.train_view, st.coeffs, n, dtype)
                st.local_val_scores = (
                    score_random_effect(val_states[cfg.name], st.coeffs,
                                        val_n, dtype)
                    if validation is not None and cfg.name in val_states
                    else jnp.zeros((val_n,), dtype))
        history[:] = [r for r in history
                      if r.get("iteration", -1) < plan.sweep]
        # re-commit the restored state at the agreed sweep under the NEW
        # membership: survivors re-enter the loop from an aligned,
        # rollback-able point (this also closes the recovery timer)
        recovery.commit(plan.sweep, lambda: self._recovery_payload(
            states, scores, val_scores, validation), force=True)
        _log.warning(
            "recovery: restored to committed sweep %d on %d shard(s) after "
            "%s; resuming", plan.sweep, len(plan.members),
            plan.failure_class)
        return plan.sweep

    def _load_warm_start(self, model, states, scores, val_scores,
                         train, validation, val_states, val_feats):
        """Initialize coordinate states and scores from a previous GameModel
        (the reference's warm-start / partial-retrain path, SURVEY.md §5.4).
        Saved coefficients are model-space; internal state is optimizer
        space, so convert through the normalization context."""
        for cfg in self.configs:
            prev = model.coordinates.get(cfg.name)
            if prev is None:
                continue
            st = states[cfg.name]
            if cfg.coordinate_type == "fixed":
                w_model = jnp.asarray(prev.model.coefficients.means, self.dtype)
                if cfg.normalization is not None:
                    st.w = cfg.normalization.to_training_space(w_model)
                else:
                    st.w = w_model
                scores[cfg.name] = st.train_scores(w_model)
                if validation is not None:
                    val_scores[cfg.name] = _margins(val_feats[cfg.name], w_model)
            else:
                coeffs = _coeffs_from_prev(prev, st.train_data)
                st.coeffs = coeffs
                scores[cfg.name] = score_random_effect(
                    st.train_view, coeffs, train.num_samples, self.dtype
                )
                if validation is not None and cfg.name in val_states:
                    val_scores[cfg.name] = score_random_effect(
                        val_states[cfg.name], coeffs, validation.num_samples, self.dtype
                    )


def _coeffs_from_prev(prev, train_data) -> List[np.ndarray]:
    """Fill a training-layout coefficient table from a previous model's
    entity table. Warm start and recovery redistribution share this: both
    are "re-address each entity's coefficients from an old bucket layout
    into the current one" joins.

    One dict probe per entity; ALL slot remapping below is numpy group
    ops (VERDICT r4 #7: the per-entity x per-slot Python loops were
    O(minutes) at the survey's thousands-to-millions-of-entities scale)."""
    prev_index = prev.entity_index()
    coeffs = []
    for bucket in train_data.buckets:
        W = np.zeros((bucket.num_entities, bucket.local_dim))
        rows, pbs, prs = [], [], []
        for r, eid in enumerate(bucket.entity_ids):
            slot = prev_index.get(eid)
            if slot is None:  # loaded models key entities as str
                slot = prev_index.get(str(eid))
            if slot is not None:
                rows.append(r)
                pbs.append(slot[0])
                prs.append(slot[1])
        if rows:
            rows_a = np.asarray(rows)
            pbs_a = np.asarray(pbs)
            prs_a = np.asarray(prs)
            for pb in np.unique(pbs_a):
                sel = pbs_a == pb
                _warm_fill_bucket(W, bucket, rows_a[sel],
                                  prev.buckets[int(pb)], prs_a[sel])
        coeffs.append(W)
    return coeffs


def _warm_fill_bucket(W, bucket, rows, prev_bucket, prs) -> None:
    """Vectorized warm-start slot remap for one (current-bucket,
    previous-bucket) entity group: ``W[rows]`` receives the previous
    coefficients of rows ``prs`` of ``prev_bucket``, re-addressed from the
    previous per-entity subspaces to the current ones.

    The remap is a composite-key join (entity-local row id * 2^32 +
    global feature id; projection slots hold int32 ids so keys cannot
    collide) between the previous and current projection arrays — no
    per-entity or per-slot Python. Sketched cases: identical sketches
    copy rows wholesale; a previous EXACT subspace warm-starts a sketched
    current coordinate by pushing each (gid, coef) through the sketch
    (the projector's own embedding — collisions sum, like any count
    sketch); a previous sketch cannot be inverted into an exact subspace,
    so those entities start cold."""
    cur_lm0 = bucket.local_maps[0] if bucket.num_entities else None
    cur_sketched = isinstance(cur_lm0, SketchProjection)
    C = np.asarray(prev_bucket.coefficients)[prs]        # [M, Dp]
    if prev_bucket.sketch is not None:
        if cur_sketched and cur_lm0 == prev_bucket.sketch:
            W[rows, : C.shape[1]] = C
        return
    P = np.asarray(prev_bucket.projection)[prs]          # [M, Dp] gids, -1 pad
    valid_p = (P >= 0) & (C != 0)
    if cur_sketched:
        slots, signs = cur_lm0.slots_signs(np.maximum(P, 0).ravel())
        flat = valid_p.ravel()
        np.add.at(
            W,
            (np.repeat(rows, P.shape[1])[flat], slots[flat]),
            (C.ravel() * signs)[flat],
        )
        return
    curP = np.asarray(bucket.projection)[rows]           # [M, Dc] gids, -1 pad
    M, Dp = P.shape
    Dc = curP.shape[1]
    BIG = np.int64(1) << 32
    m_ids = np.arange(M, dtype=np.int64)
    kp = (m_ids[:, None] * BIG + P).ravel()[valid_p.ravel()]
    cvals = C.ravel()[valid_p.ravel()]
    if not len(kp):
        return
    order = np.argsort(kp)
    kp, cvals = kp[order], cvals[order]
    valid_c = (curP >= 0).ravel()
    kc = (m_ids[:, None] * BIG + curP).ravel()[valid_c]
    pos = np.minimum(np.searchsorted(kp, kc), len(kp) - 1)
    hit = kp[pos] == kc
    rows_flat = np.repeat(rows, Dc)[valid_c]
    slots_flat = np.tile(np.arange(Dc), M)[valid_c]
    W[rows_flat[hit], slots_flat[hit]] = cvals[pos[hit]]
