"""GAME random-effect data layer: entity grouping, active/passive split,
per-entity feature-subspace projection, and size-bucketing into padded
arrays.

Equivalent of the reference's ``data.{RandomEffectDataset, LocalDataset,
RandomEffectDatasetPartitioner}`` + ``projector.LinearSubspaceProjector``
(SURVEY.md §3.2; reference mount empty). The reference groups samples by
entity id into an RDD of per-entity local datasets; each entity's features
are projected onto the subspace it has actually seen. TPU-native rebuild:

* entities are *bucketed by size* and padded to per-bucket shapes
  ``[E, N, k]`` so the per-entity solves run as one ``vmap`` per bucket with
  static shapes (SURVEY.md §7 "ragged entity data" hard part);
* **active** data (up to ``active_cap`` rows per entity, seeded random
  subset) trains the entity model; **passive** rows only receive scores —
  via a *score view* built over any dataset with the training-time
  projections (``build_score_view``);
* projections are built from active data, so features first seen in passive
  or validation rows contribute zero score, matching the projector
  semantics.

This is host-side preprocessing (the reference does it as a Spark shuffle
stage); it runs in vectorized numpy. The per-entity feature remapping is the
candidate for a native C++ kernel if it shows up in profiles at scale.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.types import SparseFeatures


@dataclasses.dataclass(frozen=True)
class HostSparse:
    """Host-side padded sparse matrix (numpy twin of SparseFeatures)."""

    indices: np.ndarray  # [n, k] int32
    values: Optional[np.ndarray]  # [n, k]; None = implicit-ones layout
    dim: int

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]


def host_sparse_from_dense(X: np.ndarray) -> HostSparse:
    n, d = X.shape
    k = max(int((X != 0).sum(axis=1).max()) if n else 0, 1)
    indices = np.zeros((n, k), np.int32)
    values = np.zeros((n, k))
    for i in range(n):
        nz = np.nonzero(X[i])[0]
        indices[i, : len(nz)] = nz
        values[i, : len(nz)] = X[i, nz]
    return HostSparse(indices, values, d)


def materialize_ones(sp: HostSparse) -> HostSparse:
    """Give an implicit-ones HostSparse explicit 1.0 values. Per-entity
    subspace remapping carries explicit values through the local views, so
    the random-effect data layer materializes here (same footprint the
    caller would have paid with an explicit-values layout); fixed-effect
    paths stay value-free end to end."""
    if sp.values is None:
        return HostSparse(sp.indices, np.ones(sp.indices.shape), sp.dim)
    return sp


def host_sparse_from_features(features) -> HostSparse:
    """Accept SparseFeatures / HostSparse / dense numpy or jax array."""
    if isinstance(features, HostSparse):
        return features
    if isinstance(features, SparseFeatures):
        return HostSparse(
            np.asarray(features.indices),
            None if features.values is None else np.asarray(features.values),
            features.dim,
        )
    return host_sparse_from_dense(np.asarray(features))


@dataclasses.dataclass(frozen=True)
class REBucket:
    """One size bucket of entities, padded to common shapes.

    Training arrays (active data):
      indices/values: [E, N, k] local-subspace sparse rows (pad value 0).
      labels/weights: [E, N] (pad weight 0).
      sample_idx: int32 [E, N] row index into the source dataset, -1 pad.
    Projection:
      projection: int32 [E, D] global feature id per local slot, -1 pad.
      local_maps: per-entity dict global id -> local slot (host side, reused
        to build score views for other datasets).
    """

    entity_ids: Sequence
    indices: np.ndarray
    values: np.ndarray
    labels: np.ndarray
    weights: np.ndarray
    sample_idx: np.ndarray
    projection: np.ndarray
    local_maps: List[Dict[int, int]]

    @property
    def num_entities(self) -> int:
        return len(self.entity_ids)

    @property
    def local_dim(self) -> int:
        return self.projection.shape[1]


_U64 = (1 << 64) - 1


@dataclasses.dataclass(frozen=True)
class SketchProjection:
    """Count-sketch random projection: global feature id → (slot, ±1).

    The reference's older random-projection projector (SURVEY.md §3.2
    ``projector`` row, marked ``(?)``) for random effects whose entity
    feature spaces are too wide for exact subspace maps: every entity of the
    effect shares one signed hash into a fixed ``dim``-wide space, so entity
    problems have constant shape regardless of support size. Mixing is a
    splitmix64-style finalizer — stable across processes (the same reason
    ``io.hashing`` avoids Python's ``hash``)."""

    dim: int
    seed: int = 0

    def slots_signs(self, gids: np.ndarray):
        x = np.asarray(gids, np.uint64) + np.uint64(
            (self.seed * 0x9E3779B97F4A7C15 + 0x632BE59BD9B4E019) & _U64
        )
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9) & _U64
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB) & _U64
        x = x ^ (x >> np.uint64(31))
        slots = (x % np.uint64(self.dim)).astype(np.int64)
        signs = 1.0 - 2.0 * ((x >> np.uint64(32)) & np.uint64(1)).astype(np.float64)
        return slots, signs


def _local_map_arrays(lm: Dict[int, int]):
    """Sorted global ids + their local slots, for vectorized remapping."""
    if not lm:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    gids = np.fromiter(lm.keys(), np.int64, len(lm))
    slots = np.fromiter(lm.values(), np.int64, len(lm))
    order = np.argsort(gids)
    return gids[order], slots[order]


def _remap_to_local(row_idx: np.ndarray, row_val: np.ndarray, lm):
    """Map global feature ids to entity-local slots in one vectorized pass
    (np.searchsorted); entries outside the local map are zeroed (projector
    semantics: their coefficient is structurally 0). ``lm`` is either a
    global-id→slot dict (subspace projector) or a SketchProjection."""
    if isinstance(lm, SketchProjection):
        slots, signs = lm.slots_signs(row_idx)
        present = row_val != 0
        loc = np.where(present, slots, 0).astype(row_idx.dtype)
        val = np.where(present, row_val * signs, 0.0)
        return loc, val
    gids, slots = _local_map_arrays(lm)
    if len(gids) == 0:
        return np.zeros_like(row_idx), np.zeros_like(row_val)
    pos = np.searchsorted(gids, row_idx)
    pos = np.minimum(pos, len(gids) - 1)
    known = (gids[pos] == row_idx) & (row_val != 0)
    loc = np.where(known, slots[pos], 0).astype(row_idx.dtype)
    val = np.where(known, row_val, 0.0)
    return loc, val


@dataclasses.dataclass(frozen=True)
class REScoreBucket:
    """Score view of one bucket over some dataset: every row of every entity
    (active + passive), features projected to the entity's local subspace."""

    indices: np.ndarray  # [E, M, k] local
    values: np.ndarray  # [E, M, k]
    sample_idx: np.ndarray  # [E, M], -1 pad


@dataclasses.dataclass(frozen=True)
class RandomEffectTrainData:
    effect_name: str
    buckets: List[REBucket]
    num_samples: int  # rows in the source dataset
    # entity id -> (bucket, row) for score-view building
    entity_to_slot: Dict

    @property
    def num_entities(self) -> int:
        return sum(b.num_entities for b in self.buckets)

    def table_bytes(self) -> int:
        """Host bytes of the padded per-entity training arrays — the
        memory the entity sharding bounds per process (score views and
        coefficients scale with the same entity slice)."""
        total = 0
        for b in self.buckets:
            for a in (b.indices, b.values, b.labels, b.weights,
                      b.sample_idx, b.projection):
                total += np.asarray(a).nbytes
        return total


def build_random_effect_data(
    features,
    labels: np.ndarray,
    weights: np.ndarray,
    entity_ids: Sequence,
    effect_name: str = "random",
    num_buckets: int = 4,
    active_cap: Optional[int] = None,
    seed: int = 0,
    projection: str = "subspace",
    projection_dim: Optional[int] = None,
    projection_seed: int = 0,
    entity_shard=None,
) -> RandomEffectTrainData:
    """Group rows by entity, split active/passive, project, bucket, pad.

    ``projection``: "subspace" builds exact per-entity feature maps (the
    LinearSubspaceProjector role); "random" uses a shared count-sketch of
    width ``projection_dim`` (the RandomProjection role — constant-shape
    entity problems, non-invertible).

    ``entity_shard`` (a ``parallel.entity_shard.EntityShardSpec``):
    entity-sharded multi-controller training — this process grooms and
    buckets ONLY the entities its shard owns (stable-hash owner map);
    rows of unowned entities never enter a bucket or score view, so the
    per-process entity-table footprint is the owned slice. Note that
    ``active_cap`` sampling draws from one sequential rng stream, so a
    sharded run's sampled subsets differ from the single-host run's
    (full-data training — no cap — is bit-compatible across shard
    counts)."""
    sp = materialize_ones(host_sparse_from_features(features))
    labels = np.asarray(labels, np.float64)
    weights = np.asarray(weights, np.float64)
    n = sp.num_rows
    ent = np.asarray(entity_ids)
    uniq, codes = np.unique(ent, return_inverse=True)
    rng = np.random.default_rng(seed)

    # rows per entity (stable order)
    order = np.argsort(codes, kind="mergesort")
    sorted_codes = codes[order]
    boundaries = np.searchsorted(sorted_codes, np.arange(len(uniq) + 1))

    if entity_shard is not None and entity_shard.num_shards > 1:
        keep = np.flatnonzero(entity_shard.owned_mask(uniq))
    else:
        keep = np.arange(len(uniq))

    active_rows: List[np.ndarray] = []
    for e in keep:
        rows = order[boundaries[e] : boundaries[e + 1]]
        if active_cap is not None and len(rows) > active_cap:
            rows = rng.choice(rows, size=active_cap, replace=False)
            rows.sort()
        active_rows.append(rows)
    uniq = uniq[keep]

    # per-entity local feature maps from active data
    if projection == "random":
        if not projection_dim or projection_dim <= 0:
            raise ValueError("projection='random' needs a positive "
                             "projection_dim")
        sketch = SketchProjection(projection_dim, projection_seed)
        local_maps = [sketch] * len(uniq)
    elif projection == "subspace":
        local_maps = []
        for e in range(len(uniq)):
            rows = active_rows[e]
            feats = sp.indices[rows][sp.values[rows] != 0]
            ids = np.unique(feats)
            local_maps.append({int(g): i for i, g in enumerate(ids)})
    else:
        raise ValueError(f"unknown projection '{projection}' "
                         "(subspace|random)")

    # bucket entities by active-row count
    counts = np.array([len(r) for r in active_rows])
    ent_order = np.argsort(counts, kind="mergesort")
    num_buckets = max(1, min(num_buckets, len(uniq)))
    splits = np.array_split(ent_order, num_buckets)
    splits = [s for s in splits if len(s)]

    buckets: List[REBucket] = []
    entity_to_slot: Dict = {}
    for b, members in enumerate(splits):
        E = len(members)
        N = max(int(counts[members].max()), 1)
        if projection == "random":
            D = projection_dim
        else:
            D = max(max(len(local_maps[e]) for e in members), 1)
        k = sp.indices.shape[1]
        indices = np.zeros((E, N, k), np.int32)
        values = np.zeros((E, N, k))
        lab = np.zeros((E, N))
        wts = np.zeros((E, N))
        sidx = np.full((E, N), -1, np.int32)
        proj = np.full((E, D), -1, np.int32)
        eids = []
        for r, e in enumerate(members):
            rows = active_rows[e]
            m = len(rows)
            lm = local_maps[e]
            loc, row_val = _remap_to_local(sp.indices[rows], sp.values[rows], lm)
            indices[r, :m] = loc
            values[r, :m] = row_val
            lab[r, :m] = labels[rows]
            wts[r, :m] = weights[rows]
            sidx[r, :m] = rows
            if not isinstance(lm, SketchProjection):
                for gid, slot in lm.items():
                    proj[r, slot] = gid
            eids.append(uniq[e])
            entity_to_slot[uniq[e]] = (b, r)
        buckets.append(
            REBucket(eids, indices, values, lab, wts, sidx, proj,
                     [local_maps[e] for e in members])
        )
    return RandomEffectTrainData(effect_name, buckets, n, entity_to_slot)


def group_rows_by_slot(entity_ids, entity_to_slot, num_entities_per_bucket):
    """Group dataset row indices by (bucket, entity-row). Rows of unknown
    entities are dropped (they get no random-effect score)."""
    per_bucket_rows: List[List[List[int]]] = [
        [[] for _ in range(e)] for e in num_entities_per_bucket
    ]
    for i, eid in enumerate(np.asarray(entity_ids)):
        slot = entity_to_slot.get(eid)
        if slot is None:
            slot = entity_to_slot.get(str(eid))
        if slot is None:
            continue
        b, r = slot
        per_bucket_rows[b][r].append(i)
    return per_bucket_rows


def build_score_buckets(
    sp: HostSparse,
    per_bucket_rows: List[List[List[int]]],
    local_maps_per_bucket: List[List[Dict[int, int]]],
) -> List[REScoreBucket]:
    """Shared score-view construction: project rows onto each entity's local
    subspace (single code path for train-data views and model-based views)."""
    sp = materialize_ones(sp)
    out: List[REScoreBucket] = []
    for rows_per_entity, local_maps in zip(per_bucket_rows, local_maps_per_bucket):
        E = len(rows_per_entity)
        M = max(max((len(r) for r in rows_per_entity), default=0), 1)
        k = sp.indices.shape[1]
        indices = np.zeros((E, M, k), np.int32)
        values = np.zeros((E, M, k))
        sidx = np.full((E, M), -1, np.int32)
        for r in range(E):
            rows = rows_per_entity[r]
            if not rows:
                continue
            loc, rval = _remap_to_local(sp.indices[rows], sp.values[rows],
                                        local_maps[r])
            indices[r, : len(rows)] = loc
            values[r, : len(rows)] = rval
            sidx[r, : len(rows)] = rows
        out.append(REScoreBucket(indices, values, sidx))
    return out


def build_score_view(
    train_data: RandomEffectTrainData, features, entity_ids: Sequence
) -> List[REScoreBucket]:
    """Project any dataset onto the training-time entity subspaces for
    device-side scoring. Rows of entities unseen in training contribute no
    score; features outside an entity's subspace are dropped (their
    coefficient is structurally zero — projector semantics)."""
    sp = host_sparse_from_features(features)
    per_bucket_rows = group_rows_by_slot(
        entity_ids, train_data.entity_to_slot,
        [b.num_entities for b in train_data.buckets],
    )
    return build_score_buckets(
        sp, per_bucket_rows, [b.local_maps for b in train_data.buckets]
    )
