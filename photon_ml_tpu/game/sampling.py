"""Down-samplers for coordinate training data.

Equivalent of the reference's ``sampling.{DownSampler,
BinaryClassificationDownSampler, DefaultDownSampler}`` (SURVEY.md §3.2;
reference mount empty): binary tasks keep all positives and sample negatives
at ``rate`` with weights rescaled by 1/rate (so gradient expectations are
unchanged); other tasks sample uniformly with the same weight compensation.
Host-side: sampling decides *which rows* enter a coordinate's training set.
"""

from __future__ import annotations

import numpy as np


def down_sample(
    labels: np.ndarray,
    weights: np.ndarray,
    rate: float,
    task: str = "logistic",
    seed: int = 0,
):
    """Returns (row_indices, adjusted_weights). rate >= 1 is a no-op."""
    n = len(labels)
    if rate >= 1.0:
        return np.arange(n), np.asarray(weights, np.float64)
    if not (0.0 < rate):
        raise ValueError(f"down-sampling rate must be > 0, got {rate}")
    rng = np.random.default_rng(seed)
    keep = rng.random(n) < rate
    if task in ("logistic", "smoothed_hinge"):
        # binary: all positives survive; kept negatives get 1/rate weight
        pos = np.asarray(labels) > 0.5
        sel = pos | keep
        idx = np.nonzero(sel)[0]
        w = np.asarray(weights, np.float64)[idx].copy()
        w[np.asarray(labels)[idx] <= 0.5] /= rate
        return idx, w
    idx = np.nonzero(keep)[0]
    return idx, np.asarray(weights, np.float64)[idx] / rate
