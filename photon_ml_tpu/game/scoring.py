"""Score arbitrary datasets with a trained GameModel.

Equivalent of the reference's ``GameTransformer.transform`` scoring path
(SURVEY.md §4.4; reference mount empty): fixed effects broadcast their
coefficient vector and add ``x . w`` per row; random effects join rows to
their entity's model — here a host-side projection onto each entity's local
subspace followed by the same bucketed gather/dot/scatter used in training
(no shuffle; the entity index is a dict lookup at view-build time).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import (
    build_score_buckets,
    group_rows_by_slot,
    host_sparse_from_features,
)
from photon_ml_tpu.game.random_effect import score_random_effect
from photon_ml_tpu.models import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.types import SparseFeatures, margins as _margins


def _model_score_view(re_model: RandomEffectModel, sp, entity_ids):
    """Build score-view buckets directly from a RandomEffectModel's
    projections (used when scoring without the original train data); shares
    the projection kernel with the train-data path (data.build_score_buckets)."""
    per_bucket_rows = group_rows_by_slot(
        entity_ids, re_model.entity_index(),
        [len(b.entity_ids) for b in re_model.buckets],
    )
    local_maps_per_bucket = []
    coeffs = []
    for bucket in re_model.buckets:
        if bucket.sketch is not None:
            local_maps_per_bucket.append(
                [bucket.sketch] * len(bucket.entity_ids)
            )
        else:
            proj = np.asarray(bucket.projection)
            local_maps_per_bucket.append(
                [{int(g): s for s, g in enumerate(proj[r]) if g >= 0}
                 for r in range(len(bucket.entity_ids))]
            )
        coeffs.append(np.asarray(bucket.coefficients))
    views = build_score_buckets(sp, per_bucket_rows, local_maps_per_bucket)
    return views, coeffs


def score_game_model(
    model: GameModel,
    features: Dict[str, object],
    entity_ids: Optional[Dict[str, np.ndarray]] = None,
    offsets: Optional[np.ndarray] = None,
    dtype=jnp.float32,
    per_coordinate: bool = False,
):
    """Total score (sum of coordinate scores + offsets) for each row.

    ``features``: dict shard -> features (any representation);
    ``entity_ids``: dict entity-column -> per-row ids; random-effect
    coordinates look up ids under their effect name's entity column — by
    convention the RandomEffectModel's ``effect_name``."""
    entity_ids = entity_ids or {}
    host = {k: host_sparse_from_features(v) for k, v in features.items()}
    n = next(iter(host.values())).num_rows
    total = jnp.zeros((n,), dtype) if offsets is None else jnp.asarray(offsets, dtype)
    parts = {}
    for name, coord in model.coordinates.items():
        sp = host[coord.feature_shard]
        if isinstance(coord, FixedEffectModel):
            feats = SparseFeatures(
                jnp.asarray(sp.indices),
                None if sp.values is None else jnp.asarray(sp.values, dtype),
                dim=sp.dim,
            )
            s = _margins(feats, jnp.asarray(coord.model.coefficients.means, dtype))
        else:
            ids = _entity_ids_for(entity_ids, coord, name)
            views, coeffs = _model_score_view(coord, sp, ids)
            s = score_random_effect(views, coeffs, n, dtype)
        parts[name] = s
        total = total + s
    if per_coordinate:
        return total, parts
    return total


def _entity_ids_for(entity_ids: Dict, coord: RandomEffectModel, name: str):
    for key in (coord.entity_column, name, coord.effect_name):
        if key and key in entity_ids:
            return entity_ids[key]
    raise ValueError(
        f"scoring random effect '{name}' needs entity ids under key "
        f"'{coord.entity_column or name}' (have: {sorted(entity_ids)})"
    )
