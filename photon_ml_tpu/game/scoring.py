"""Score arbitrary datasets with a trained GameModel.

Equivalent of the reference's ``GameTransformer.transform`` scoring path
(SURVEY.md §4.4; reference mount empty): fixed effects broadcast their
coefficient vector and add ``x . w`` per row; random effects join rows to
their entity's model — here a host-side projection onto each entity's local
subspace followed by the same bucketed gather/dot/scatter used in training
(no shuffle; the entity index is a dict lookup at view-build time).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import (
    build_score_buckets,
    group_rows_by_slot,
    host_sparse_from_features,
)
from photon_ml_tpu.game.random_effect import score_random_effect
from photon_ml_tpu.models import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.types import SparseFeatures, margins as _margins


def fixed_effect_margins(sp, coord: FixedEffectModel, dtype) -> jax.Array:
    """Per-row margins of one fixed-effect coordinate over a HostSparse
    batch — the single definition of the fixed-effect margin math, shared
    by the batch path below and the serving session's parity reference."""
    feats = SparseFeatures(
        jnp.asarray(sp.indices),
        None if sp.values is None else jnp.asarray(sp.values, dtype),
        dim=sp.dim,
    )
    return _margins(feats, jnp.asarray(coord.model.coefficients.means, dtype))


def build_model_score_views(
    model: GameModel,
    host: Dict[str, object],
    entity_ids: Dict[str, np.ndarray],
) -> Dict[str, tuple]:
    """Pre-built random-effect score views for every random coordinate:
    coordinate name -> (views, coeffs) as :func:`score_single_batch`
    consumes them. Split out so callers that assemble their own views
    (the serving session's coefficient cache) share the scoring entry."""
    out = {}
    for name, coord in model.coordinates.items():
        if isinstance(coord, RandomEffectModel):
            ids = _entity_ids_for(entity_ids, coord, name)
            out[name] = _model_score_view(coord, host[coord.feature_shard],
                                          ids)
    return out


def score_single_batch(
    model: GameModel,
    features: Dict[str, object],
    score_views: Dict[str, tuple],
    offsets: Optional[np.ndarray] = None,
    dtype=jnp.float32,
    per_coordinate: bool = False,
    fixed_scorer=None,
):
    """Score ONE batch through pre-built random-effect score views.

    The serving session (``serve/session.py``) and the batch scoring path
    (:func:`score_game_model`) both land here, so there is exactly one
    definition of the per-coordinate margin math. ``score_views`` maps
    each random coordinate name to ``(views, coeffs)`` — a sequence of
    :class:`~photon_ml_tpu.game.data.REScoreBucket` plus the matching
    per-bucket ``[E, D]`` coefficient arrays (``build_model_score_views``
    builds them from a full model; the serving session builds them from
    its entity-coefficient cache).

    ``fixed_scorer`` optionally overrides HOW a fixed-effect coordinate's
    margins are computed — ``(name, coord, host_sparse) -> [n] margins`` —
    without forking the coordinate loop: the serving session routes fixed
    effects through its device-resident pre-compiled executables here,
    while the default stays the eager :func:`fixed_effect_margins`."""
    host = {k: host_sparse_from_features(v) for k, v in features.items()}
    n = next(iter(host.values())).num_rows
    total = (jnp.zeros((n,), dtype) if offsets is None
             else jnp.asarray(offsets, dtype))
    parts = {}
    for name, coord in model.coordinates.items():
        if isinstance(coord, FixedEffectModel):
            sp = host[coord.feature_shard]
            s = (fixed_scorer(name, coord, sp) if fixed_scorer is not None
                 else fixed_effect_margins(sp, coord, dtype))
        else:
            views, coeffs = score_views[name]
            s = score_random_effect(views, coeffs, n, dtype)
        parts[name] = s
        total = total + s
    if per_coordinate:
        return total, parts
    return total


def _model_score_view(re_model: RandomEffectModel, sp, entity_ids):
    """Build score-view buckets directly from a RandomEffectModel's
    projections (used when scoring without the original train data); shares
    the projection kernel with the train-data path (data.build_score_buckets)."""
    per_bucket_rows = group_rows_by_slot(
        entity_ids, re_model.entity_index(),
        [len(b.entity_ids) for b in re_model.buckets],
    )
    local_maps_per_bucket = []
    coeffs = []
    for bucket in re_model.buckets:
        if bucket.sketch is not None:
            local_maps_per_bucket.append(
                [bucket.sketch] * len(bucket.entity_ids)
            )
        else:
            proj = np.asarray(bucket.projection)
            local_maps_per_bucket.append(
                [{int(g): s for s, g in enumerate(proj[r]) if g >= 0}
                 for r in range(len(bucket.entity_ids))]
            )
        coeffs.append(np.asarray(bucket.coefficients))
    views = build_score_buckets(sp, per_bucket_rows, local_maps_per_bucket)
    return views, coeffs


def score_game_model(
    model: GameModel,
    features: Dict[str, object],
    entity_ids: Optional[Dict[str, np.ndarray]] = None,
    offsets: Optional[np.ndarray] = None,
    dtype=jnp.float32,
    per_coordinate: bool = False,
):
    """Total score (sum of coordinate scores + offsets) for each row.

    ``features``: dict shard -> features (any representation);
    ``entity_ids``: dict entity-column -> per-row ids; random-effect
    coordinates look up ids under their effect name's entity column — by
    convention the RandomEffectModel's ``effect_name``."""
    entity_ids = entity_ids or {}
    host = {k: host_sparse_from_features(v) for k, v in features.items()}
    views = build_model_score_views(model, host, entity_ids)
    return score_single_batch(model, host, views, offsets=offsets,
                              dtype=dtype, per_coordinate=per_coordinate)


def _entity_ids_for(entity_ids: Dict, coord: RandomEffectModel, name: str):
    for key in (coord.entity_column, name, coord.effect_name):
        if key and key in entity_ids:
            return entity_ids[key]
    raise ValueError(
        f"scoring random effect '{name}' needs entity ids under key "
        f"'{coord.entity_column or name}' (have: {sorted(entity_ids)})"
    )
