"""Per-entity random-effect training and scoring.

Equivalent of the reference's ``RandomEffectCoordinate.trainModel`` /
``RandomEffectOptimizationProblem`` (SURVEY.md §4.3; reference mount empty):
the reference runs ``mapValues`` of local Breeze solves over an entity-keyed
RDD — thousands of small independent optimizations, executor-local. Here
each size bucket solves ALL its entities at once with ``vmap`` of the jitted
optimizer (one XLA program per bucket shape), optionally sharded over a mesh
``entity`` axis with ``shard_map`` — embarrassingly parallel, no collectives,
exactly like the reference's no-comm local solves.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.game.data import RandomEffectTrainData, REScoreBucket
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optimize import OptimizerConfig, get_optimizer
from photon_ml_tpu.types import LabeledBatch, SparseFeatures


@dataclasses.dataclass(frozen=True)
class RandomEffectFitResult:
    coefficients: List[np.ndarray]  # per bucket [E, D]
    variances: Optional[List[np.ndarray]]
    converged_fraction: float
    mean_iterations: float


def _solver_for_bucket(local_dim: int, task: str, optimizer: str,
                       config: OptimizerConfig, compute_variance: bool | str,
                       norm_mode: int = 0):
    """Build the vmapped per-bucket solve function.

    ``norm_mode``: 0 = no normalization; 1 = per-entity scale factors;
    2 = factors + shifts. Each entity carries its own local factor/shift
    vectors (the global context gathered through its subspace projection,
    with the intercept slot pre-pinned to 1/0, so ``intercept_index=-1``)."""
    opt = get_optimizer(optimizer)

    def solve_one(indices, values, labels, weights, offs, w0, f_loc, s_loc,
                  l2, l1):
        ctx = None
        if norm_mode == 1:
            ctx = NormalizationContext(f_loc, None, -1)
        elif norm_mode == 2:
            ctx = NormalizationContext(f_loc, s_loc, -1)
        obj = make_objective(task, normalization=ctx)
        batch = LabeledBatch(
            SparseFeatures(indices, values, dim=local_dim), labels, offs, weights
        )
        fg = lambda w: obj.value_and_grad(w, batch, l2)
        if optimizer == "owlqn":
            res = opt(fg, w0, l1, config)
        else:
            res = opt(fg, w0, config)
        # compute_variance: False | True/"diagonal" | "full" — the FULL
        # (d x d inverse) mode is feasible per entity because local dims
        # are small; vmap batches the tiny solves.
        if compute_variance:
            mode = "full" if compute_variance == "full" else "diagonal"
            var = obj.coefficient_variances(res.w, batch, l2, mode=mode)
        else:
            var = jnp.zeros((0,), res.w.dtype)
        return res.w, var, res.converged, res.iterations

    return jax.vmap(solve_one, in_axes=(0,) * 8 + (None, None))


@functools.lru_cache(maxsize=256)
def _jitted_solver(local_dim, task, optimizer, config, compute_variance,
                   norm_mode=0):
    """Cache the jitted per-bucket solver so repeated coordinate-descent
    steps with identical shapes reuse one XLA compilation."""
    return jax.jit(_solver_for_bucket(local_dim, task, optimizer, config,
                                      compute_variance, norm_mode))


@functools.lru_cache(maxsize=256)
def _jitted_sharded_solver(local_dim, task, optimizer, config, compute_variance,
                           mesh, axis, norm_mode=0):
    solver = _solver_for_bucket(local_dim, task, optimizer, config,
                                compute_variance, norm_mode)
    spec = (P(axis),) * 8 + (P(), P())
    sharded = jax.shard_map(
        solver, mesh=mesh, in_specs=spec,
        out_specs=(P(axis), P(axis), P(axis), P(axis)),
    )
    return jax.jit(sharded)


def _local_normalization(buckets, norm: NormalizationContext):
    """Gather the global normalization context into per-entity local
    vectors: for each bucket, (f_loc [E,D], s_loc [E,D] | None,
    intercept_pos [E] | None). Padding slots (projection -1) get f=1, s=0;
    the global intercept slot is pinned (f=1, s=0) so the local context
    runs with ``intercept_index=-1`` and the fold-back is explicit."""
    f_g = None if norm.factors is None else np.asarray(norm.factors).copy()
    s_g = None if norm.shifts is None else np.asarray(norm.shifts).copy()
    ii = norm.intercept_index
    if f_g is not None and ii >= 0:
        f_g[ii] = 1.0
    if s_g is not None and ii >= 0:
        s_g[ii] = 0.0
    out = []
    for bucket in buckets:
        from photon_ml_tpu.game.data import SketchProjection

        if any(isinstance(lm, SketchProjection) for lm in bucket.local_maps):
            raise ValueError(
                "normalization is not supported with projection='random' "
                "(count-sketch slots mix features); use projection='subspace'")
        proj = np.asarray(bucket.projection)
        safe = np.maximum(proj, 0)
        f_loc = (np.where(proj >= 0, f_g[safe], 1.0) if f_g is not None
                 else np.ones_like(proj, np.float64))
        s_loc = None
        pos = None
        if s_g is not None:
            s_loc = np.where(proj >= 0, s_g[safe], 0.0)
            has = proj == ii
            if ii < 0 or not has.any(axis=1).all():
                raise ValueError(
                    "shift normalization requires the intercept feature in "
                    "every entity's feature subspace")
            pos = has.argmax(axis=1)
        out.append((f_loc, s_loc, pos))
    return out


def _re_to_training_space(W_raw: np.ndarray, f_loc, s_loc, pos) -> np.ndarray:
    """Per-entity inverse of the model-space fold (warm starts)."""
    W = np.array(W_raw, np.float64, copy=True)
    E = W.shape[0]
    if s_loc is not None:
        w_noint = W.copy()
        w_noint[np.arange(E), pos] = 0.0
        W[np.arange(E), pos] += np.sum(s_loc * w_noint, axis=1)
    return W / f_loc


def _re_to_model_space(W_opt: np.ndarray, f_loc, s_loc, pos) -> np.ndarray:
    """Optimizer-space bucket coefficients -> raw-feature space."""
    W = np.asarray(W_opt, np.float64) * f_loc
    if s_loc is not None:
        E = W.shape[0]
        adjust = -np.sum(s_loc * W, axis=1)  # s_loc is 0 at the intercept
        W[np.arange(E), pos] += adjust
    return W


def train_random_effect(
    data: RandomEffectTrainData,
    offsets: jax.Array,
    task: str = "logistic",
    l2=0.0,
    l1=0.0,
    optimizer: str = "lbfgs",
    config: OptimizerConfig = OptimizerConfig(max_iters=50, history=5),
    w0: Optional[List[np.ndarray]] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "entity",
    compute_variance: bool | str = False,  # False | "diagonal" | "full"
    dtype=jnp.float32,
    normalization: Optional[NormalizationContext] = None,
) -> RandomEffectFitResult:
    """Solve every entity's local GLM. ``offsets`` is the full-dataset
    residual-offset vector [n] from the coordinate-descent loop. L1 weight
    requires (and auto-routes to) the OWL-QN optimizer.

    ``normalization`` (the shard's global context) is applied inside each
    per-entity objective via gathered local factor/shift vectors; incoming
    ``w0`` and returned coefficients stay in raw feature space (conversion
    happens here), so scoring/saving/warm-start paths are unchanged."""
    if np.asarray(l1).item() > 0 and optimizer != "owlqn":
        optimizer = "owlqn"
    offsets = jnp.asarray(offsets, dtype)
    local_norm = (None if normalization is None
                  else _local_normalization(data.buckets, normalization))
    norm_mode = 0
    if normalization is not None:
        norm_mode = 2 if normalization.shifts is not None else 1
    coeffs, variances = [], []
    conv_sum, iter_sum, total = 0.0, 0.0, 0
    for b, bucket in enumerate(data.buckets):
        E, D = bucket.num_entities, bucket.local_dim
        sidx = jnp.asarray(bucket.sample_idx)
        # padding rows (sidx == -1) carry weight 0, offset value irrelevant
        off = jnp.take(offsets, jnp.maximum(sidx, 0), axis=0) * (sidx >= 0)
        if w0 is not None:
            w_init = np.asarray(w0[b])
            if local_norm is not None:
                w_init = _re_to_training_space(w_init, *local_norm[b])
            w_init = jnp.asarray(w_init, dtype)
        else:
            w_init = jnp.zeros((E, D), dtype)
        if local_norm is not None:
            f_loc = jnp.asarray(local_norm[b][0], dtype)
            s_loc = (jnp.zeros((E, 1), dtype) if local_norm[b][1] is None
                     else jnp.asarray(local_norm[b][1], dtype))
        else:  # unused dummies (dead-code-eliminated under jit)
            f_loc = jnp.zeros((E, 1), dtype)
            s_loc = jnp.zeros((E, 1), dtype)
        args = (
            jnp.asarray(bucket.indices),
            jnp.asarray(bucket.values, dtype),
            jnp.asarray(bucket.labels, dtype),
            jnp.asarray(bucket.weights, dtype),
            off.astype(dtype),
            w_init,
            f_loc,
            s_loc,
            jnp.asarray(l2, dtype),
            jnp.asarray(l1, dtype),
        )
        if mesh is not None:
            n_dev = mesh.shape[axis]
            pad = (-E) % n_dev
            if pad:
                args = tuple(
                    jnp.concatenate([a, jnp.zeros((pad,) + a.shape[1:], a.dtype)])
                    if i < 8
                    else a
                    for i, a in enumerate(args)
                )
            run = _jitted_sharded_solver(D, task, optimizer, config,
                                         compute_variance, mesh, axis,
                                         norm_mode)
            W, V, conv, iters = run(*args)
            W, V, conv, iters = W[:E], V[:E], conv[:E], iters[:E]
        else:
            run = _jitted_solver(D, task, optimizer, config, compute_variance,
                                 norm_mode)
            W, V, conv, iters = run(*args)
        W = np.asarray(W)
        if local_norm is not None:
            W = _re_to_model_space(W, *local_norm[b])
        coeffs.append(W)
        variances.append(np.asarray(V) if compute_variance else None)
        conv_sum += float(jnp.sum(conv))
        iter_sum += float(jnp.sum(iters))
        total += E
    return RandomEffectFitResult(
        coefficients=coeffs,
        variances=variances if compute_variance else None,
        converged_fraction=conv_sum / max(total, 1),
        mean_iterations=iter_sum / max(total, 1),
    )


def score_random_effect(
    score_view: Sequence[REScoreBucket],
    coefficients: Sequence[np.ndarray],
    num_samples: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Margins of every sample under its entity's model, scattered into a
    full-dataset score vector (the reference's CoordinateDataScores role,
    SURVEY.md §3.2). Samples with no entity model score 0."""
    scores = jnp.zeros((num_samples + 1,), dtype)  # slot n swallows padding
    for view, W in zip(score_view, coefficients):
        Wd = jnp.asarray(W, dtype)
        idx = jnp.asarray(view.indices)
        val = jnp.asarray(view.values, dtype)
        sidx = jnp.asarray(view.sample_idx)

        def margins_one(w_e, idx_e, val_e):
            return jnp.sum(val_e * w_e[idx_e], axis=-1)  # [M]

        m = jax.vmap(margins_one)(Wd, idx, val)  # [E, M]
        target = jnp.where(sidx >= 0, sidx, num_samples)
        scores = scores.at[target.reshape(-1)].add(
            jnp.where(sidx >= 0, m, 0.0).reshape(-1)
        )
    return scores[:num_samples]
