"""Per-entity random-effect training and scoring.

Equivalent of the reference's ``RandomEffectCoordinate.trainModel`` /
``RandomEffectOptimizationProblem`` (SURVEY.md §4.3; reference mount empty):
the reference runs ``mapValues`` of local Breeze solves over an entity-keyed
RDD — thousands of small independent optimizations, executor-local. Here
each size bucket solves ALL its entities at once with ``vmap`` of the jitted
optimizer (one XLA program per bucket shape), optionally sharded over a mesh
``entity`` axis with ``shard_map`` — embarrassingly parallel, no collectives,
exactly like the reference's no-comm local solves.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.compat import shard_map
from photon_ml_tpu.game.data import RandomEffectTrainData, REScoreBucket
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optimize import OptimizerConfig, get_optimizer
from photon_ml_tpu.types import LabeledBatch, SparseFeatures


@dataclasses.dataclass(frozen=True)
class RandomEffectFitResult:
    coefficients: List[np.ndarray]  # per bucket [E, D]
    variances: Optional[List[np.ndarray]]
    converged_fraction: float
    mean_iterations: float


def _newton_dense_solver(local_dim: int, task: str,
                         config: OptimizerConfig,
                         compute_variance: bool | str, norm_mode: int = 0):
    """Batched dense Newton (IRLS) bucket solver — the TPU-first RE path.

    Per-entity dims are small (subspace-projected, typically ≤ 64), so the
    whole bucket solves as BATCHED DENSE linear algebra instead of a
    ``vmap`` of sparse L-BFGS loops: rows densify once to ``X [E, N, D]``
    (a k-step scan, no scatter), every Newton iteration is two einsums
    (gradient ``X^T d1``, Hessian ``X^T diag(d2) X`` — MXU contractions)
    plus one batched SPD solve, and a 4-level per-entity step-halving
    safeguard keeps descent monotone. A vmapped L-BFGS executes all
    entities' line searches in lockstep on the VPU; this formulation puts
    the FLOPs where the TPU wants them (same trade the reference's local
    Breeze Newton solvers make per executor, batched instead of mapped).

    Same signature/returns as the vmapped solver: (W, variances,
    converged, iterations) per entity. L1 is not supported (the caller
    auto-routes l1 > 0 to OWL-QN).
    """
    D = local_dim
    loss = get_loss(task)
    tol = config.tolerance
    max_iters = config.max_iters

    def solve(indices, values, labels, weights, offs, w0, f_loc, s_loc,
              l2, l1):
        del l1  # caller guarantees 0 (owlqn route)
        E, N, kk = indices.shape
        dt = values.dtype

        # densify: X[e, n, idx[e, n, j]] += val[e, n, j], as a k-step scan
        # of masked adds (no scatter — TPU scatter serializes). Padding
        # slots carry value 0 and add nothing wherever they point.
        iota = jnp.arange(D, dtype=indices.dtype)

        def add_slot(X, j):
            idx_j = jnp.take(indices, j, axis=2)[..., None]  # [E, N, 1]
            val_j = jnp.take(values, j, axis=2)[..., None]
            return X + jnp.where(idx_j == iota, val_j, 0.0), None

        # match_vma: under the entity-axis shard_map the data varies over
        # the mesh axis but fresh zeros/True carries do not; align every
        # loop carry or scan/while_loop reject the carry types (no-op
        # outside shard_map)
        from photon_ml_tpu.optimize.common import match_vma, match_vma_tree

        X, _ = jax.lax.scan(add_slot,
                            match_vma(jnp.zeros((E, N, D), dt), values),
                            jnp.arange(kk))
        # normalization in data space: x' = (x - s) * f per local slot
        # (exactly the sparse path's effective-coefficient fold)
        if norm_mode == 2:
            X = (X - s_loc[:, None, :]) * f_loc[:, None, :]
        elif norm_mode == 1:
            X = X * f_loc[:, None, :]

        live = weights != 0  # [E, N]; padding rows are inert

        def margins(W):
            m = jnp.einsum("end,ed->en", X, W) + offs
            return jnp.where(live, m, 0.0)  # mask BEFORE the loss

        def fval(W):
            per = loss.loss(margins(W), labels)
            data = jnp.sum(jnp.where(live, weights * per, 0.0), axis=1)
            return data + 0.5 * l2 * jnp.sum(W * W, axis=1)

        d1_fn = jax.grad(lambda m, y: jnp.sum(loss.loss(m, y)))

        def grad_hess(W):
            m = margins(W)
            wd1 = jnp.where(live, weights * d1_fn(m, labels), 0.0)
            wd2 = jnp.where(live, weights * loss.d2(m, labels), 0.0)
            g = jnp.einsum("end,en->ed", X, wd1) + l2 * W
            H = (jnp.einsum("end,en,enf->edf", X, wd2, X)
                 + l2 * jnp.eye(D, dtype=dt))
            return g, H

        f0 = fval(w0)
        g0, _ = grad_hess(w0)
        g0n = jnp.linalg.norm(g0, axis=1)
        # converged_check semantics, batched: an explicit tol <= 0 disables
        # the tests; a positive tol is clamped to a few ulps of the dtype
        eff_tol = jnp.where(tol > 0,
                            jnp.maximum(jnp.asarray(tol, dt),
                                        4 * jnp.finfo(dt).eps),
                            jnp.asarray(0.0, dt))

        def cond(state):
            return jnp.any(state[2])  # any entity still active

        def body(state):
            W, f, active, conv_seen, iters = state
            g, H = grad_hess(W)
            step = jnp.linalg.solve(H, g[..., None])[..., 0]  # SPD batched
            # per-entity step-halving: try alpha in {1, 1/2, 1/4, 1/8},
            # keep the largest that does not increase f (batched, static)
            alphas = jnp.asarray([1.0, 0.5, 0.25, 0.125], dt)
            f_tries = jnp.stack(
                [fval(W - a * step) for a in alphas])  # [4, E]
            ok = f_tries <= f[None, :]
            first_ok = jnp.argmax(ok, axis=0)  # first True, else 0
            any_ok = jnp.any(ok, axis=0)
            a_sel = jnp.where(any_ok, alphas[first_ok], 0.0)  # 0 = stall
            f_new = jnp.where(any_ok,
                              jnp.take_along_axis(
                                  f_tries, first_ok[None, :], axis=0)[0],
                              f)
            # a rejected step must be MASKED, not zero-multiplied: with a
            # singular H (rank-deficient entity, l2=0) the solve returns
            # NaN and 0 * NaN would poison W permanently
            W_new = jnp.where((active & any_ok)[:, None],
                              W - a_sel[:, None] * step, W)
            gnorm = jnp.linalg.norm(g, axis=1)
            # converged_check semantics, batched: |f_prev - f| <= tol *
            # max(|f_prev|, 1) OR gnorm <= tol * max(||g0||, 1). The
            # relative-loss half needs an accepted step (a rejected step's
            # zero delta would pass spuriously), but the gradient half
            # fires regardless: step-halving failing AT the optimum (fp
            # noise, singular-H NaN step) is convergence, not a stall —
            # same policy as the L-BFGS paths.
            delta = jnp.abs(f - f_new)
            conv = active & (eff_tol > 0) & (
                (any_ok & (delta <= eff_tol * jnp.maximum(jnp.abs(f), 1.0)))
                | (gnorm <= eff_tol * jnp.maximum(g0n, 1.0)))
            iters_new = iters + active.astype(iters.dtype)
            active_new = active & ~conv & any_ok & (iters_new < max_iters)
            f_out = jnp.where(active, f_new, f)
            return (W_new, f_out, active_new, conv_seen | conv, iters_new)

        state = match_vma_tree(
            (jnp.asarray(w0, dt), f0, jnp.ones((E,), bool),
             jnp.zeros((E,), bool), jnp.zeros((E,), jnp.int32)), values)
        W, f, active, conv_seen, iters = jax.lax.while_loop(cond, body,
                                                            state)
        converged = conv_seen
        _, H_fin = grad_hess(W)
        if compute_variance:
            if compute_variance == "full":
                Hinv = jnp.linalg.solve(
                    H_fin, jnp.broadcast_to(jnp.eye(D, dtype=dt),
                                            (E, D, D)))
                var = jnp.diagonal(Hinv, axis1=1, axis2=2)
            else:
                diag = jnp.einsum("end,en,end->ed", X,
                                  jnp.where(live, weights
                                            * loss.d2(margins(W), labels),
                                            0.0), X) + l2
                var = 1.0 / jnp.maximum(diag, jnp.finfo(dt).tiny)
        else:
            var = jnp.zeros((E, 0), dt)
        return W, var, converged, iters

    return solve


def _solver_for_bucket(local_dim: int, task: str, optimizer: str,
                       config: OptimizerConfig, compute_variance: bool | str,
                       norm_mode: int = 0):
    """Build the vmapped per-bucket solve function.

    ``norm_mode``: 0 = no normalization; 1 = per-entity scale factors;
    2 = factors + shifts. Each entity carries its own local factor/shift
    vectors (the global context gathered through its subspace projection,
    with the intercept slot pre-pinned to 1/0, so ``intercept_index=-1``).

    ``optimizer="newton"`` selects the batched dense-Newton solver
    (``_newton_dense_solver``) instead of a vmap of sparse optimizers."""
    if optimizer == "newton":
        return _newton_dense_solver(local_dim, task, config,
                                    compute_variance, norm_mode)
    opt = get_optimizer(optimizer)

    def solve_one(indices, values, labels, weights, offs, w0, f_loc, s_loc,
                  l2, l1):
        ctx = None
        if norm_mode == 1:
            ctx = NormalizationContext(f_loc, None, -1)
        elif norm_mode == 2:
            ctx = NormalizationContext(f_loc, s_loc, -1)
        obj = make_objective(task, normalization=ctx)
        batch = LabeledBatch(
            SparseFeatures(indices, values, dim=local_dim), labels, offs, weights
        )
        fg = lambda w: obj.value_and_grad(w, batch, l2)
        if optimizer == "owlqn":
            res = opt(fg, w0, l1, config)
        else:
            res = opt(fg, w0, config)
        # compute_variance: False | True/"diagonal" | "full" — the FULL
        # (d x d inverse) mode is feasible per entity because local dims
        # are small; vmap batches the tiny solves.
        if compute_variance:
            mode = "full" if compute_variance == "full" else "diagonal"
            var = obj.coefficient_variances(res.w, batch, l2, mode=mode)
        else:
            var = jnp.zeros((0,), res.w.dtype)
        return res.w, var, res.converged, res.iterations

    return jax.vmap(solve_one, in_axes=(0,) * 8 + (None, None))


@functools.lru_cache(maxsize=256)
def _jitted_solver(local_dim, task, optimizer, config, compute_variance,
                   norm_mode=0):
    """Cache the jitted per-bucket solver so repeated coordinate-descent
    steps with identical shapes reuse one XLA compilation."""
    return jax.jit(_solver_for_bucket(local_dim, task, optimizer, config,
                                      compute_variance, norm_mode))


@functools.lru_cache(maxsize=256)
def _jitted_sharded_solver(local_dim, task, optimizer, config, compute_variance,
                           mesh, axis, norm_mode=0):
    solver = _solver_for_bucket(local_dim, task, optimizer, config,
                                compute_variance, norm_mode)
    spec = (P(axis),) * 8 + (P(), P())
    # check_vma=False: the batched solver is per-entity independent — no
    # collective, nothing relies on vma-driven transposes — and legacy
    # check_rep has no replication rule for the optimizer's while_loop
    sharded = shard_map(
        solver, mesh=mesh, in_specs=spec,
        out_specs=(P(axis), P(axis), P(axis), P(axis)),
        check_vma=False,
    )
    return jax.jit(sharded)


def _local_normalization(buckets, norm: NormalizationContext):
    """Gather the global normalization context into per-entity local
    vectors: for each bucket, (f_loc [E,D], s_loc [E,D] | None,
    intercept_pos [E] | None). Padding slots (projection -1) get f=1, s=0;
    the global intercept slot is pinned (f=1, s=0) so the local context
    runs with ``intercept_index=-1`` and the fold-back is explicit."""
    f_g = None if norm.factors is None else np.asarray(norm.factors).copy()
    s_g = None if norm.shifts is None else np.asarray(norm.shifts).copy()
    ii = norm.intercept_index
    if f_g is not None and ii >= 0:
        f_g[ii] = 1.0
    if s_g is not None and ii >= 0:
        s_g[ii] = 0.0
    out = []
    for bucket in buckets:
        from photon_ml_tpu.game.data import SketchProjection

        if any(isinstance(lm, SketchProjection) for lm in bucket.local_maps):
            raise ValueError(
                "normalization is not supported with projection='random' "
                "(count-sketch slots mix features); use projection='subspace'")
        proj = np.asarray(bucket.projection)
        safe = np.maximum(proj, 0)
        f_loc = (np.where(proj >= 0, f_g[safe], 1.0) if f_g is not None
                 else np.ones_like(proj, np.float64))
        s_loc = None
        pos = None
        if s_g is not None:
            s_loc = np.where(proj >= 0, s_g[safe], 0.0)
            has = proj == ii
            if ii < 0 or not has.any(axis=1).all():
                raise ValueError(
                    "shift normalization requires the intercept feature in "
                    "every entity's feature subspace")
            pos = has.argmax(axis=1)
        out.append((f_loc, s_loc, pos))
    return out


def _re_to_training_space(W_raw: np.ndarray, f_loc, s_loc, pos) -> np.ndarray:
    """Per-entity inverse of the model-space fold (warm starts)."""
    W = np.array(W_raw, np.float64, copy=True)
    E = W.shape[0]
    if s_loc is not None:
        w_noint = W.copy()
        w_noint[np.arange(E), pos] = 0.0
        W[np.arange(E), pos] += np.sum(s_loc * w_noint, axis=1)
    return W / f_loc


def _re_to_model_space(W_opt: np.ndarray, f_loc, s_loc, pos) -> np.ndarray:
    """Optimizer-space bucket coefficients -> raw-feature space."""
    W = np.asarray(W_opt, np.float64) * f_loc
    if s_loc is not None:
        E = W.shape[0]
        adjust = -np.sum(s_loc * W, axis=1)  # s_loc is 0 at the intercept
        W[np.arange(E), pos] += adjust
    return W


# Per-platform random-effect solver default for ``optimizer="auto"``
# (VERDICT r3 #7). Measured by scripts/bench_game.py: on CPU the vmapped
# sparse L-BFGS wins (28.4k entities/s vs 16.6k for the batched dense
# Newton at E=2000, rows/entity=32, d_local=16). The TPU entry is
# DESIGN-PREDICTED, not yet measured (the tunnel has been wedged through
# rounds 3-5; bench_game in the armed hardware session times both solvers
# and its output names the entry to paste here): the batched dense-Newton
# IRLS was built for the MXU — per entity it is [E, d, d] einsum Hessians
# + batched Cholesky solves, systolic-array work, where the vmapped
# L-BFGS path is gather/VPU-bound. A one-line log marks the prediction
# whenever it is used, so no silent cross-platform fallback remains
# (VERDICT r4 missing #3).
_RE_SOLVER_DEFAULT = {"cpu": "lbfgs", "tpu": "newton"}
# tpu measured on the v5e (docs/tpu_r05_logs/bench_game_retry.log):
# newton 7919 entities/s vs lbfgs 2315 at E=100k, rows=64, d_local=32 —
# the 3.42x MXU prediction confirmed by hardware.
_RE_SOLVER_MEASURED = {"cpu", "tpu"}
_warned_unmeasured = set()

# Max entities per vmapped solver execution (env-overridable). 100k in one
# program exhausted v5e HBM and hard-crashed the TPU worker; 16k keeps the
# solver intermediates bounded with the per-block dispatch cost amortized
# over tens of thousands of while_loop iterations.
_RE_BLOCK_ENTITIES = int(os.environ.get("PHOTON_RE_BLOCK_ENTITIES", 16384))


def _pad_entities(a: jax.Array, width: int) -> jax.Array:
    """Zero-pad axis 0 to ``width`` (padded entities have weight-0 rows:
    their objective is constant and the solver converges immediately)."""
    pad = width - a.shape[0]
    if pad == 0:
        return a
    return jnp.concatenate([a, jnp.zeros((pad,) + a.shape[1:], a.dtype)])


# "auto" only picks the dense-Newton solver up to this per-entity dim:
# its [block, d, d] Hessians are 16k x d^2 x 4 B per block (1 GB at
# d=128, 8 GB at the d=351 CD bucket that crashed the Mosaic batched-
# Cholesky compile on the v5e — docs/tpu_r05_logs/bench_game_auto.log);
# the vmapped L-BFGS memory is O(d) per entity and handles wide
# subspaces fine.
_RE_NEWTON_MAX_DIM = 128


def resolve_re_optimizer(optimizer: str, local_dim: int = None) -> str:
    """Resolve ``"auto"`` to the per-platform default solver (measured
    where a measurement exists; design-predicted and logged otherwise).
    ``local_dim`` (the bucket's per-entity dimension, when known) gates
    the dense-Newton choice — see ``_RE_NEWTON_MAX_DIM``."""
    if optimizer != "auto":
        return optimizer
    platform = jax.devices()[0].platform
    choice = _RE_SOLVER_DEFAULT.get(platform, "lbfgs")
    if (choice == "newton" and local_dim is not None
            and local_dim > _RE_NEWTON_MAX_DIM):
        choice = "lbfgs"
    if platform not in _RE_SOLVER_MEASURED and platform not in _warned_unmeasured:
        _warned_unmeasured.add(platform)
        import logging

        logging.getLogger("photon_ml_tpu").info(
            "optimizer='auto' on platform %r -> %r (design-predicted "
            "default, no hardware measurement yet; run "
            "scripts/bench_game.py on this platform to measure)",
            platform, choice)
    return choice


def train_random_effect(
    data: RandomEffectTrainData,
    offsets: jax.Array,
    task: str = "logistic",
    l2=0.0,
    l1=0.0,
    optimizer: str = "lbfgs",
    config: OptimizerConfig = OptimizerConfig(max_iters=50, history=5),
    w0: Optional[List[np.ndarray]] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "entity",
    compute_variance: bool | str = False,  # False | "diagonal" | "full"
    dtype=jnp.float32,
    normalization: Optional[NormalizationContext] = None,
) -> RandomEffectFitResult:
    """Solve every entity's local GLM. ``offsets`` is the full-dataset
    residual-offset vector [n] from the coordinate-descent loop. L1 weight
    requires (and auto-routes to) the OWL-QN optimizer.

    ``normalization`` (the shard's global context) is applied inside each
    per-entity objective via gathered local factor/shift vectors; incoming
    ``w0`` and returned coefficients stay in raw feature space (conversion
    happens here), so scoring/saving/warm-start paths are unchanged."""
    if np.asarray(l1).item() > 0 and optimizer != "owlqn":
        optimizer = "owlqn"
    # "auto" stays unresolved here: the per-bucket local_dim feeds the
    # dense-Newton dimension gate inside the loop
    offsets = jnp.asarray(offsets, dtype)
    local_norm = (None if normalization is None
                  else _local_normalization(data.buckets, normalization))
    norm_mode = 0
    if normalization is not None:
        norm_mode = 2 if normalization.shifts is not None else 1
    coeffs, variances = [], []
    conv_sum, iter_sum, total = 0.0, 0.0, 0
    for b, bucket in enumerate(data.buckets):
        E, D = bucket.num_entities, bucket.local_dim
        if E == 0:
            # degenerate bucket (no entities): nothing to solve — emit the
            # empty [0, D] shapes downstream consumers expect (scoring,
            # model building, warm start) and keep the convergence
            # accounting untouched rather than tripping range(step=0) /
            # W_parts[0] in the blocked loop below
            coeffs.append(np.zeros((0, D), np.dtype(dtype)))
            variances.append(np.zeros((0, D), np.dtype(dtype))
                             if compute_variance else None)
            continue
        opt_b = resolve_re_optimizer(optimizer, D)
        sidx = jnp.asarray(bucket.sample_idx)
        # padding rows (sidx == -1) carry weight 0, offset value irrelevant
        off = jnp.take(offsets, jnp.maximum(sidx, 0), axis=0) * (sidx >= 0)
        if w0 is not None:
            w_init = np.asarray(w0[b])
            if local_norm is not None:
                w_init = _re_to_training_space(w_init, *local_norm[b])
            w_init = jnp.asarray(w_init, dtype)
        else:
            w_init = jnp.zeros((E, D), dtype)
        if local_norm is not None:
            f_loc = jnp.asarray(local_norm[b][0], dtype)
            s_loc = (jnp.zeros((E, 1), dtype) if local_norm[b][1] is None
                     else jnp.asarray(local_norm[b][1], dtype))
        else:  # unused dummies (dead-code-eliminated under jit)
            f_loc = jnp.zeros((E, 1), dtype)
            s_loc = jnp.zeros((E, 1), dtype)
        args = (
            jnp.asarray(bucket.indices),
            jnp.asarray(bucket.values, dtype),
            jnp.asarray(bucket.labels, dtype),
            jnp.asarray(bucket.weights, dtype),
            off.astype(dtype),
            w_init,
            f_loc,
            s_loc,
            jnp.asarray(l2, dtype),
            jnp.asarray(l1, dtype),
        )
        if mesh is not None:
            n_dev = mesh.shape[axis]
            run = _jitted_sharded_solver(D, task, opt_b, config,
                                         compute_variance, mesh, axis,
                                         norm_mode)
        else:
            n_dev = 1
            run = _jitted_solver(D, task, opt_b, config, compute_variance,
                                 norm_mode)
        # Bound the vmapped width: one program over ~100k entities
        # exhausted HBM on the v5e and hard-crashed the TPU worker
        # ("kernel fault", docs/tpu_r05_logs/bench_game.log), and the
        # slowdown was superlinear well before the crash. Entities are
        # independent, so solve fixed-width blocks: every block padded to
        # one shape (single compile), results fetched per block so HBM
        # only ever holds one block's solver intermediates.
        bs = -(-min(_RE_BLOCK_ENTITIES, E) // n_dev) * n_dev
        W_parts, V_parts, conv_sum_b, iter_sum_b = [], [], 0.0, 0.0
        for s in range(0, E, bs):
            e = min(s + bs, E)
            if s == 0 and e == E == bs:
                blk = args  # single full block: no slice/pad device copies
            else:
                blk = tuple(
                    _pad_entities(a[s:e], bs) if i < 8 else a
                    for i, a in enumerate(args)
                )
            Wb, Vb, convb, itersb = run(*blk)
            W_parts.append(np.asarray(Wb)[: e - s])
            V_parts.append(np.asarray(Vb)[: e - s] if compute_variance
                           else None)
            conv_sum_b += float(jnp.sum(convb[: e - s]))
            iter_sum_b += float(jnp.sum(itersb[: e - s]))
        W = np.concatenate(W_parts) if len(W_parts) > 1 else W_parts[0]
        V = (np.concatenate(V_parts) if len(V_parts) > 1 else V_parts[0]) \
            if compute_variance else None
        conv, iters = conv_sum_b, iter_sum_b
        if local_norm is not None:
            W = _re_to_model_space(W, *local_norm[b])
        coeffs.append(W)
        variances.append(V)
        conv_sum += conv
        iter_sum += iters
        total += E
    return RandomEffectFitResult(
        coefficients=coeffs,
        variances=variances if compute_variance else None,
        converged_fraction=conv_sum / max(total, 1),
        mean_iterations=iter_sum / max(total, 1),
    )


def score_random_effect(
    score_view: Sequence[REScoreBucket],
    coefficients: Sequence[np.ndarray],
    num_samples: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Margins of every sample under its entity's model, scattered into a
    full-dataset score vector (the reference's CoordinateDataScores role,
    SURVEY.md §3.2). Samples with no entity model score 0."""
    scores = jnp.zeros((num_samples + 1,), dtype)  # slot n swallows padding
    for view, W in zip(score_view, coefficients):
        Wd = jnp.asarray(W, dtype)
        idx = jnp.asarray(view.indices)
        val = jnp.asarray(view.values, dtype)
        sidx = jnp.asarray(view.sample_idx)

        def margins_one(w_e, idx_e, val_e):
            return jnp.sum(val_e * w_e[idx_e], axis=-1)  # [M]

        m = jax.vmap(margins_one)(Wd, idx, val)  # [E, M]
        target = jnp.where(sidx >= 0, sidx, num_samples)
        scores = scores.at[target.reshape(-1)].add(
            jnp.where(sidx >= 0, m, 0.0).reshape(-1)
        )
    return scores[:num_samples]
