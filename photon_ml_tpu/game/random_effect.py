"""Per-entity random-effect training and scoring.

Equivalent of the reference's ``RandomEffectCoordinate.trainModel`` /
``RandomEffectOptimizationProblem`` (SURVEY.md §4.3; reference mount empty):
the reference runs ``mapValues`` of local Breeze solves over an entity-keyed
RDD — thousands of small independent optimizations, executor-local. Here
each size bucket solves ALL its entities at once with ``vmap`` of the jitted
optimizer (one XLA program per bucket shape), optionally sharded over a mesh
``entity`` axis with ``shard_map`` — embarrassingly parallel, no collectives,
exactly like the reference's no-comm local solves.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.game.data import RandomEffectTrainData, REScoreBucket
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optimize import OptimizerConfig, get_optimizer
from photon_ml_tpu.types import LabeledBatch, SparseFeatures


@dataclasses.dataclass(frozen=True)
class RandomEffectFitResult:
    coefficients: List[np.ndarray]  # per bucket [E, D]
    variances: Optional[List[np.ndarray]]
    converged_fraction: float
    mean_iterations: float


def _solver_for_bucket(local_dim: int, task: str, optimizer: str,
                       config: OptimizerConfig, compute_variance: bool):
    """Build the vmapped per-bucket solve function."""
    obj = make_objective(task)
    opt = get_optimizer(optimizer)

    def solve_one(indices, values, labels, weights, offs, w0, l2, l1):
        batch = LabeledBatch(
            SparseFeatures(indices, values, dim=local_dim), labels, offs, weights
        )
        fg = lambda w: obj.value_and_grad(w, batch, l2)
        if optimizer == "owlqn":
            res = opt(fg, w0, l1, config)
        else:
            res = opt(fg, w0, config)
        var = (
            obj.coefficient_variances(res.w, batch, l2)
            if compute_variance
            else jnp.zeros((0,), res.w.dtype)
        )
        return res.w, var, res.converged, res.iterations

    return jax.vmap(solve_one, in_axes=(0, 0, 0, 0, 0, 0, None, None))


@functools.lru_cache(maxsize=256)
def _jitted_solver(local_dim, task, optimizer, config, compute_variance):
    """Cache the jitted per-bucket solver so repeated coordinate-descent
    steps with identical shapes reuse one XLA compilation."""
    return jax.jit(_solver_for_bucket(local_dim, task, optimizer, config,
                                      compute_variance))


@functools.lru_cache(maxsize=256)
def _jitted_sharded_solver(local_dim, task, optimizer, config, compute_variance,
                           mesh, axis):
    solver = _solver_for_bucket(local_dim, task, optimizer, config, compute_variance)
    spec = (P(axis),) * 6 + (P(), P())
    sharded = jax.shard_map(
        solver, mesh=mesh, in_specs=spec,
        out_specs=(P(axis), P(axis), P(axis), P(axis)),
    )
    return jax.jit(sharded)


def train_random_effect(
    data: RandomEffectTrainData,
    offsets: jax.Array,
    task: str = "logistic",
    l2=0.0,
    l1=0.0,
    optimizer: str = "lbfgs",
    config: OptimizerConfig = OptimizerConfig(max_iters=50, history=5),
    w0: Optional[List[np.ndarray]] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "entity",
    compute_variance: bool = False,
    dtype=jnp.float32,
) -> RandomEffectFitResult:
    """Solve every entity's local GLM. ``offsets`` is the full-dataset
    residual-offset vector [n] from the coordinate-descent loop. L1 weight
    requires (and auto-routes to) the OWL-QN optimizer."""
    if np.asarray(l1).item() > 0 and optimizer != "owlqn":
        optimizer = "owlqn"
    offsets = jnp.asarray(offsets, dtype)
    coeffs, variances = [], []
    conv_sum, iter_sum, total = 0.0, 0.0, 0
    for b, bucket in enumerate(data.buckets):
        E, D = bucket.num_entities, bucket.local_dim
        sidx = jnp.asarray(bucket.sample_idx)
        # padding rows (sidx == -1) carry weight 0, offset value irrelevant
        off = jnp.take(offsets, jnp.maximum(sidx, 0), axis=0) * (sidx >= 0)
        args = (
            jnp.asarray(bucket.indices),
            jnp.asarray(bucket.values, dtype),
            jnp.asarray(bucket.labels, dtype),
            jnp.asarray(bucket.weights, dtype),
            off.astype(dtype),
            jnp.asarray(w0[b], dtype) if w0 is not None else jnp.zeros((E, D), dtype),
            jnp.asarray(l2, dtype),
            jnp.asarray(l1, dtype),
        )
        if mesh is not None:
            n_dev = mesh.shape[axis]
            pad = (-E) % n_dev
            if pad:
                args = tuple(
                    jnp.concatenate([a, jnp.zeros((pad,) + a.shape[1:], a.dtype)])
                    if i < 6
                    else a
                    for i, a in enumerate(args)
                )
            run = _jitted_sharded_solver(D, task, optimizer, config,
                                         compute_variance, mesh, axis)
            W, V, conv, iters = run(*args)
            W, V, conv, iters = W[:E], V[:E], conv[:E], iters[:E]
        else:
            run = _jitted_solver(D, task, optimizer, config, compute_variance)
            W, V, conv, iters = run(*args)
        coeffs.append(np.asarray(W))
        variances.append(np.asarray(V) if compute_variance else None)
        conv_sum += float(jnp.sum(conv))
        iter_sum += float(jnp.sum(iters))
        total += E
    return RandomEffectFitResult(
        coefficients=coeffs,
        variances=variances if compute_variance else None,
        converged_fraction=conv_sum / max(total, 1),
        mean_iterations=iter_sum / max(total, 1),
    )


def score_random_effect(
    score_view: Sequence[REScoreBucket],
    coefficients: Sequence[np.ndarray],
    num_samples: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Margins of every sample under its entity's model, scattered into a
    full-dataset score vector (the reference's CoordinateDataScores role,
    SURVEY.md §3.2). Samples with no entity model score 0."""
    scores = jnp.zeros((num_samples + 1,), dtype)  # slot n swallows padding
    for view, W in zip(score_view, coefficients):
        Wd = jnp.asarray(W, dtype)
        idx = jnp.asarray(view.indices)
        val = jnp.asarray(view.values, dtype)
        sidx = jnp.asarray(view.sample_idx)

        def margins_one(w_e, idx_e, val_e):
            return jnp.sum(val_e * w_e[idx_e], axis=-1)  # [M]

        m = jax.vmap(margins_one)(Wd, idx, val)  # [E, M]
        target = jnp.where(sidx >= 0, sidx, num_samples)
        scores = scores.at[target.reshape(-1)].add(
            jnp.where(sidx >= 0, m, 0.0).reshape(-1)
        )
    return scores[:num_samples]
