"""Per-entity random-effect training and scoring.

Equivalent of the reference's ``RandomEffectCoordinate.trainModel`` /
``RandomEffectOptimizationProblem`` (SURVEY.md §4.3; reference mount empty):
the reference runs ``mapValues`` of local Breeze solves over an entity-keyed
RDD — thousands of small independent optimizations, executor-local. Here
each size bucket solves ALL its entities at once with ``vmap`` of the jitted
optimizer (one XLA program per bucket shape), optionally sharded over a mesh
``entity`` axis with ``shard_map`` — embarrassingly parallel, no collectives,
exactly like the reference's no-comm local solves.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.analysis.sanitizers import nan_guard_check
from photon_ml_tpu.compat import shard_map
from photon_ml_tpu.game.data import RandomEffectTrainData, REScoreBucket
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optimize import OptimizerConfig, get_optimizer
from photon_ml_tpu.types import LabeledBatch, SparseFeatures


@dataclasses.dataclass(frozen=True)
class RandomEffectFitResult:
    coefficients: List[np.ndarray]  # per bucket [E, D]
    variances: Optional[List[np.ndarray]]
    converged_fraction: float
    mean_iterations: float  # over the entities actually solved this call
    # per-entity detail (one array per bucket): the active-set CD loop uses
    # these to decide which entities to freeze between sweeps. Entities not
    # re-solved this call (active-set frozen) report converged=True and
    # iterations=0 — their objective was not touched.
    converged: Optional[List[np.ndarray]] = None  # bool [E] per bucket
    iterations: Optional[List[np.ndarray]] = None  # int32 [E] per bucket
    entities_solved: int = 0


def _newton_dense_solver(local_dim: int, task: str,
                         config: OptimizerConfig,
                         compute_variance: bool | str, norm_mode: int = 0):
    """Batched dense Newton (IRLS) bucket solver — the TPU-first RE path.

    Per-entity dims are small (subspace-projected, typically ≤ 64), so the
    whole bucket solves as BATCHED DENSE linear algebra instead of a
    ``vmap`` of sparse L-BFGS loops: rows densify once to ``X [E, N, D]``
    (a k-step scan, no scatter), every Newton iteration is two einsums
    (gradient ``X^T d1``, Hessian ``X^T diag(d2) X`` — MXU contractions)
    plus one batched SPD solve, and a 4-level per-entity step-halving
    safeguard keeps descent monotone. A vmapped L-BFGS executes all
    entities' line searches in lockstep on the VPU; this formulation puts
    the FLOPs where the TPU wants them (same trade the reference's local
    Breeze Newton solvers make per executor, batched instead of mapped).

    Same signature/returns as the vmapped solver: (W, variances,
    converged, iterations) per entity. L1 is not supported (the caller
    auto-routes l1 > 0 to OWL-QN).
    """
    D = local_dim
    loss = get_loss(task)
    tol = config.tolerance
    max_iters = config.max_iters

    def solve(indices, values, labels, weights, offs, w0, f_loc, s_loc,
              l2, l1):
        del l1  # caller guarantees 0 (owlqn route)
        E, N, kk = indices.shape
        dt = values.dtype

        # densify: X[e, n, idx[e, n, j]] += val[e, n, j], as a k-step scan
        # of masked adds (no scatter — TPU scatter serializes). Padding
        # slots carry value 0 and add nothing wherever they point.
        iota = jnp.arange(D, dtype=indices.dtype)

        def add_slot(X, j):
            idx_j = jnp.take(indices, j, axis=2)[..., None]  # [E, N, 1]
            val_j = jnp.take(values, j, axis=2)[..., None]
            return X + jnp.where(idx_j == iota, val_j, 0.0), None

        # match_vma: under the entity-axis shard_map the data varies over
        # the mesh axis but fresh zeros/True carries do not; align every
        # loop carry or scan/while_loop reject the carry types (no-op
        # outside shard_map)
        from photon_ml_tpu.optimize.common import match_vma, match_vma_tree

        X, _ = jax.lax.scan(add_slot,
                            match_vma(jnp.zeros((E, N, D), dt), values),
                            jnp.arange(kk))
        # normalization in data space: x' = (x - s) * f per local slot
        # (exactly the sparse path's effective-coefficient fold)
        if norm_mode == 2:
            X = (X - s_loc[:, None, :]) * f_loc[:, None, :]
        elif norm_mode == 1:
            X = X * f_loc[:, None, :]

        live = weights != 0  # [E, N]; padding rows are inert

        def margins(W):
            m = jnp.einsum("end,ed->en", X, W) + offs
            return jnp.where(live, m, 0.0)  # mask BEFORE the loss

        def fval(W):
            per = loss.loss(margins(W), labels)
            data = jnp.sum(jnp.where(live, weights * per, 0.0), axis=1)
            return data + 0.5 * l2 * jnp.sum(W * W, axis=1)

        d1_fn = jax.grad(lambda m, y: jnp.sum(loss.loss(m, y)))

        def grad_hess(W):
            m = margins(W)
            wd1 = jnp.where(live, weights * d1_fn(m, labels), 0.0)
            wd2 = jnp.where(live, weights * loss.d2(m, labels), 0.0)
            g = jnp.einsum("end,en->ed", X, wd1) + l2 * W
            H = (jnp.einsum("end,en,enf->edf", X, wd2, X)
                 + l2 * jnp.eye(D, dtype=dt))
            return g, H

        f0 = fval(w0)
        g0, _ = grad_hess(w0)
        g0n = jnp.linalg.norm(g0, axis=1)
        # converged_check semantics, batched: an explicit tol <= 0 disables
        # the tests; a positive tol is clamped to a few ulps of the dtype
        eff_tol = jnp.where(tol > 0,
                            jnp.maximum(jnp.asarray(tol, dt),
                                        4 * jnp.finfo(dt).eps),
                            jnp.asarray(0.0, dt))

        def cond(state):
            return jnp.any(state[2])  # any entity still active

        def body(state):
            W, f, active, conv_seen, iters = state
            g, H = grad_hess(W)
            step = jnp.linalg.solve(H, g[..., None])[..., 0]  # SPD batched
            # per-entity step-halving: try alpha in {1, 1/2, 1/4, 1/8},
            # keep the largest that does not increase f (batched, static)
            alphas = jnp.asarray([1.0, 0.5, 0.25, 0.125], dt)
            f_tries = jnp.stack(
                [fval(W - a * step) for a in alphas])  # [4, E]
            ok = f_tries <= f[None, :]
            first_ok = jnp.argmax(ok, axis=0)  # first True, else 0
            any_ok = jnp.any(ok, axis=0)
            a_sel = jnp.where(any_ok, alphas[first_ok], 0.0)  # 0 = stall
            f_new = jnp.where(any_ok,
                              jnp.take_along_axis(
                                  f_tries, first_ok[None, :], axis=0)[0],
                              f)
            gnorm = jnp.linalg.norm(g, axis=1)
            # converged_check semantics, batched: |f_prev - f| <= tol *
            # max(|f_prev|, 1) OR gnorm <= tol * max(||g0||, 1). The
            # relative-loss half needs an accepted step (a rejected step's
            # zero delta would pass spuriously), but the gradient half
            # fires regardless: step-halving failing AT the optimum (fp
            # noise, singular-H NaN step) is convergence, not a stall —
            # same policy as the L-BFGS paths.
            delta = jnp.abs(f - f_new)
            conv = active & (eff_tol > 0) & (
                (any_ok & (delta <= eff_tol * jnp.maximum(jnp.abs(f), 1.0)))
                | (gnorm <= eff_tol * jnp.maximum(g0n, 1.0)))
            # a rejected step must be MASKED, not zero-multiplied: with a
            # singular H (rank-deficient entity, l2=0) the solve returns
            # NaN and 0 * NaN would poison W permanently. An entity that
            # converges on its FIRST iteration also keeps its incoming
            # point (conv & first): it was already at its stopping point,
            # and taking the probed sub-tolerance step would make a
            # warm-started re-solve of a converged entity drift by one
            # noise-level step every CD sweep — defeating active-set
            # freezing (a frozen entity must be a true no-op re-solve;
            # same policy as optimize/lbfgs.py).
            first = iters == 0
            keep = conv & first
            W_new = jnp.where((active & any_ok & ~keep)[:, None],
                              W - a_sel[:, None] * step, W)
            iters_new = iters + active.astype(iters.dtype)
            active_new = active & ~conv & any_ok & (iters_new < max_iters)
            f_out = jnp.where(active & ~keep, f_new, f)
            return (W_new, f_out, active_new, conv_seen | conv, iters_new)

        state = match_vma_tree(
            (jnp.asarray(w0, dt), f0, jnp.ones((E,), bool),
             jnp.zeros((E,), bool), jnp.zeros((E,), jnp.int32)), values)
        W, f, active, conv_seen, iters = jax.lax.while_loop(cond, body,
                                                            state)
        converged = conv_seen
        _, H_fin = grad_hess(W)
        if compute_variance:
            if compute_variance == "full":
                Hinv = jnp.linalg.solve(
                    H_fin, jnp.broadcast_to(jnp.eye(D, dtype=dt),
                                            (E, D, D)))
                var = jnp.diagonal(Hinv, axis1=1, axis2=2)
            else:
                diag = jnp.einsum("end,en,end->ed", X,
                                  jnp.where(live, weights
                                            * loss.d2(margins(W), labels),
                                            0.0), X) + l2
                var = 1.0 / jnp.maximum(diag, jnp.finfo(dt).tiny)
        else:
            var = jnp.zeros((E, 0), dt)
        return W, var, converged, iters

    return solve


def _solver_for_bucket(local_dim: int, task: str, optimizer: str,
                       config: OptimizerConfig, compute_variance: bool | str,
                       norm_mode: int = 0):
    """Build the vmapped per-bucket solve function.

    ``norm_mode``: 0 = no normalization; 1 = per-entity scale factors;
    2 = factors + shifts. Each entity carries its own local factor/shift
    vectors (the global context gathered through its subspace projection,
    with the intercept slot pre-pinned to 1/0, so ``intercept_index=-1``).

    ``optimizer="newton"`` selects the batched dense-Newton solver
    (``_newton_dense_solver``) instead of a vmap of sparse optimizers."""
    if optimizer == "newton":
        return _newton_dense_solver(local_dim, task, config,
                                    compute_variance, norm_mode)
    opt = get_optimizer(optimizer)

    def solve_one(indices, values, labels, weights, offs, w0, f_loc, s_loc,
                  l2, l1):
        ctx = None
        if norm_mode == 1:
            ctx = NormalizationContext(f_loc, None, -1)
        elif norm_mode == 2:
            ctx = NormalizationContext(f_loc, s_loc, -1)
        obj = make_objective(task, normalization=ctx)
        batch = LabeledBatch(
            SparseFeatures(indices, values, dim=local_dim), labels, offs, weights
        )
        fg = lambda w: obj.value_and_grad(w, batch, l2)
        if optimizer == "owlqn":
            res = opt(fg, w0, l1, config)
        else:
            res = opt(fg, w0, config)
        # compute_variance: False | True/"diagonal" | "full" — the FULL
        # (d x d inverse) mode is feasible per entity because local dims
        # are small; vmap batches the tiny solves.
        if compute_variance:
            mode = "full" if compute_variance == "full" else "diagonal"
            var = obj.coefficient_variances(res.w, batch, l2, mode=mode)
        else:
            var = jnp.zeros((0,), res.w.dtype)
        return res.w, var, res.converged, res.iterations

    return jax.vmap(solve_one, in_axes=(0,) * 8 + (None, None))


# Every jitted bucket solver ever built (both cached builders below append
# exactly once per cache key). ``re_solver_compile_count`` sums their
# per-shape executable counts — the bench/test invariant that the active-set
# path's power-of-two sub-bucket ladder stops compiling once warmed.
_SOLVER_REGISTRY: List = []


def re_solver_compile_count() -> int:
    """Total compiled executables across all random-effect bucket solvers
    (every distinct entity-block shape is one executable)."""
    total = 0
    for fn in _SOLVER_REGISTRY:
        size = getattr(fn, "_cache_size", None)
        if callable(size):
            total += int(size())
    return total


@functools.lru_cache(maxsize=256)
def _jitted_solver(local_dim, task, optimizer, config, compute_variance,
                   norm_mode=0):
    """Cache the jitted per-bucket solver so repeated coordinate-descent
    steps with identical shapes reuse one XLA compilation."""
    fn = jax.jit(_solver_for_bucket(local_dim, task, optimizer, config,
                                    compute_variance, norm_mode))
    _SOLVER_REGISTRY.append(fn)
    return fn


@functools.lru_cache(maxsize=256)
def _jitted_sharded_solver(local_dim, task, optimizer, config, compute_variance,
                           mesh, axis, norm_mode=0):
    solver = _solver_for_bucket(local_dim, task, optimizer, config,
                                compute_variance, norm_mode)
    spec = (P(axis),) * 8 + (P(), P())
    # check_vma=False: the batched solver is per-entity independent — no
    # collective, nothing relies on vma-driven transposes — and legacy
    # check_rep has no replication rule for the optimizer's while_loop
    sharded = shard_map(
        solver, mesh=mesh, in_specs=spec,
        out_specs=(P(axis), P(axis), P(axis), P(axis)),
        check_vma=False,
    )
    fn = jax.jit(sharded)
    _SOLVER_REGISTRY.append(fn)
    return fn


def _local_normalization(buckets, norm: NormalizationContext):
    """Gather the global normalization context into per-entity local
    vectors: for each bucket, (f_loc [E,D], s_loc [E,D] | None,
    intercept_pos [E] | None). Padding slots (projection -1) get f=1, s=0;
    the global intercept slot is pinned (f=1, s=0) so the local context
    runs with ``intercept_index=-1`` and the fold-back is explicit."""
    f_g = None if norm.factors is None else np.asarray(norm.factors).copy()
    s_g = None if norm.shifts is None else np.asarray(norm.shifts).copy()
    ii = norm.intercept_index
    if f_g is not None and ii >= 0:
        f_g[ii] = 1.0
    if s_g is not None and ii >= 0:
        s_g[ii] = 0.0
    out = []
    for bucket in buckets:
        from photon_ml_tpu.game.data import SketchProjection

        if any(isinstance(lm, SketchProjection) for lm in bucket.local_maps):
            raise ValueError(
                "normalization is not supported with projection='random' "
                "(count-sketch slots mix features); use projection='subspace'")
        proj = np.asarray(bucket.projection)
        safe = np.maximum(proj, 0)
        f_loc = (np.where(proj >= 0, f_g[safe], 1.0) if f_g is not None
                 else np.ones_like(proj, np.float64))
        s_loc = None
        pos = None
        if s_g is not None:
            s_loc = np.where(proj >= 0, s_g[safe], 0.0)
            has = proj == ii
            if ii < 0 or not has.any(axis=1).all():
                raise ValueError(
                    "shift normalization requires the intercept feature in "
                    "every entity's feature subspace")
            pos = has.argmax(axis=1)
        out.append((f_loc, s_loc, pos))
    return out


def _re_to_training_space(W_raw: np.ndarray, f_loc, s_loc, pos) -> np.ndarray:
    """Per-entity inverse of the model-space fold (warm starts)."""
    W = np.array(W_raw, np.float64, copy=True)
    E = W.shape[0]
    if s_loc is not None:
        w_noint = W.copy()
        w_noint[np.arange(E), pos] = 0.0
        W[np.arange(E), pos] += np.sum(s_loc * w_noint, axis=1)
    return W / f_loc


def _re_to_model_space(W_opt: np.ndarray, f_loc, s_loc, pos) -> np.ndarray:
    """Optimizer-space bucket coefficients -> raw-feature space."""
    W = np.asarray(W_opt, np.float64) * f_loc
    if s_loc is not None:
        E = W.shape[0]
        adjust = -np.sum(s_loc * W, axis=1)  # s_loc is 0 at the intercept
        W[np.arange(E), pos] += adjust
    return W


# Per-platform random-effect solver default for ``optimizer="auto"``
# (VERDICT r3 #7). Measured by scripts/bench_game.py: on CPU the vmapped
# sparse L-BFGS wins (28.4k entities/s vs 16.6k for the batched dense
# Newton at E=2000, rows/entity=32, d_local=16). The TPU entry is
# DESIGN-PREDICTED, not yet measured (the tunnel has been wedged through
# rounds 3-5; bench_game in the armed hardware session times both solvers
# and its output names the entry to paste here): the batched dense-Newton
# IRLS was built for the MXU — per entity it is [E, d, d] einsum Hessians
# + batched Cholesky solves, systolic-array work, where the vmapped
# L-BFGS path is gather/VPU-bound. A one-line log marks the prediction
# whenever it is used, so no silent cross-platform fallback remains
# (VERDICT r4 missing #3).
_RE_SOLVER_DEFAULT = {"cpu": "lbfgs", "tpu": "newton"}
# tpu measured on the v5e (docs/tpu_r05_logs/bench_game_retry.log):
# newton 7919 entities/s vs lbfgs 2315 at E=100k, rows=64, d_local=32 —
# the 3.42x MXU prediction confirmed by hardware.
_RE_SOLVER_MEASURED = {"cpu", "tpu"}
_warned_unmeasured = set()

# Max entities per vmapped solver execution (env-overridable). 100k in one
# program exhausted v5e HBM and hard-crashed the TPU worker; 16k keeps the
# solver intermediates bounded with the per-block dispatch cost amortized
# over tens of thousands of while_loop iterations.
_RE_BLOCK_ENTITIES = int(os.environ.get("PHOTON_RE_BLOCK_ENTITIES", 16384))


def _pad_entities(a: jax.Array, width: int) -> jax.Array:
    """Zero-pad axis 0 to ``width`` (padded entities have weight-0 rows:
    their objective is constant and the solver converges immediately)."""
    pad = width - a.shape[0]
    if pad == 0:
        return a
    return jnp.concatenate([a, jnp.zeros((pad,) + a.shape[1:], a.dtype)])


def _active_width(n_active: int, block: int, n_dev: int) -> int:
    """Padded width for an active-set sub-bucket: the next power of two
    (rounded up to the device count), capped at the full block width. The
    power-of-two ladder bounds the number of distinct solver shapes at
    log2(block) — after the first couple of shrinking sweeps every width
    has been compiled and the compile counter stays flat."""
    w = 1 << max(n_active - 1, 0).bit_length()
    # floor the ladder at 32: solving 9 vs 32 entities costs the same under
    # vmap, and every distinct width below the floor would be one more XLA
    # compile for no win
    w = -(-max(w, 32) // n_dev) * n_dev
    return min(w, block)


# "auto" only picks the dense-Newton solver up to this per-entity dim:
# its [block, d, d] Hessians are 16k x d^2 x 4 B per block (1 GB at
# d=128, 8 GB at the d=351 CD bucket that crashed the Mosaic batched-
# Cholesky compile on the v5e — docs/tpu_r05_logs/bench_game_auto.log);
# the vmapped L-BFGS memory is O(d) per entity and handles wide
# subspaces fine.
_RE_NEWTON_MAX_DIM = 128


def resolve_re_optimizer(optimizer: str, local_dim: int = None) -> str:
    """Resolve ``"auto"`` to the per-platform default solver (measured
    where a measurement exists; design-predicted and logged otherwise).
    ``local_dim`` (the bucket's per-entity dimension, when known) gates
    the dense-Newton choice — see ``_RE_NEWTON_MAX_DIM``."""
    if optimizer != "auto":
        return optimizer
    platform = jax.devices()[0].platform
    choice = _RE_SOLVER_DEFAULT.get(platform, "lbfgs")
    if (choice == "newton" and local_dim is not None
            and local_dim > _RE_NEWTON_MAX_DIM):
        choice = "lbfgs"
    if platform not in _RE_SOLVER_MEASURED and platform not in _warned_unmeasured:
        _warned_unmeasured.add(platform)
        import logging

        logging.getLogger("photon_ml_tpu").info(
            "optimizer='auto' on platform %r -> %r (design-predicted "
            "default, no hardware measurement yet; run "
            "scripts/bench_game.py on this platform to measure)",
            platform, choice)
    return choice


def _run_entity_blocks(run, args, n_entities: int, bs: int,
                       compute_variance):
    """Drive the bucket solver over fixed-width entity blocks and fetch
    per-entity results. ``args`` is the 10-tuple of device arrays (8
    per-entity + 2 scalars); blocks are padded to ``bs`` with
    ``_pad_entities`` so every block shares one compiled shape."""
    W_parts, V_parts, conv_parts, iter_parts = [], [], [], []
    for s in range(0, n_entities, bs):
        e = min(s + bs, n_entities)
        if s == 0 and e == n_entities == bs:
            blk = args  # single full block: no slice/pad device copies
        else:
            blk = tuple(
                _pad_entities(a[s:e], bs) if i < 8 else a
                for i, a in enumerate(args)
            )
        Wb, Vb, convb, itersb = run(*blk)
        W_parts.append(np.asarray(Wb)[: e - s])
        V_parts.append(np.asarray(Vb)[: e - s] if compute_variance else None)
        conv_parts.append(np.asarray(convb)[: e - s])
        iter_parts.append(np.asarray(itersb)[: e - s])

    def cat(parts):
        return np.concatenate(parts) if len(parts) > 1 else parts[0]

    W = cat(W_parts)
    V = cat(V_parts) if compute_variance else None
    return W, V, cat(conv_parts).astype(bool), cat(iter_parts)


def train_random_effect(
    data: RandomEffectTrainData,
    offsets: jax.Array,
    task: str = "logistic",
    l2=0.0,
    l1=0.0,
    optimizer: str = "lbfgs",
    config: OptimizerConfig = OptimizerConfig(max_iters=50, history=5),
    w0: Optional[List[np.ndarray]] = None,
    mesh: Optional[Mesh] = None,
    axis: str = "entity",
    compute_variance: bool | str = False,  # False | "diagonal" | "full"
    dtype=jnp.float32,
    normalization: Optional[NormalizationContext] = None,
    active: Optional[Sequence[Optional[np.ndarray]]] = None,
    prev_variances: Optional[List[Optional[np.ndarray]]] = None,
) -> RandomEffectFitResult:
    """Solve every entity's local GLM. ``offsets`` is the full-dataset
    residual-offset vector [n] from the coordinate-descent loop. L1 weight
    requires (and auto-routes to) the OWL-QN optimizer.

    ``normalization`` (the shard's global context) is applied inside each
    per-entity objective via gathered local factor/shift vectors; incoming
    ``w0`` and returned coefficients stay in raw feature space (conversion
    happens here), so scoring/saving/warm-start paths are unchanged.

    ``active`` (the active-set CD path): one boolean mask [E] per bucket —
    only masked entities are re-solved. Their rows are gathered on the host
    into a power-of-two-padded sub-bucket (``_active_width``), solved with
    the same shape-bucketed jitted solver, and scattered back; frozen
    entities carry their ``w0`` coefficients (and ``prev_variances``)
    untouched and report converged=True / iterations=0. Requires ``w0``.
    A ``None`` mask entry means "solve the whole bucket"."""
    if np.asarray(l1).item() > 0 and optimizer != "owlqn":
        optimizer = "owlqn"
    if active is not None and w0 is None:
        raise ValueError("active-set training needs w0 (frozen entities "
                         "carry their previous coefficients)")
    # "auto" stays unresolved here: the per-bucket local_dim feeds the
    # dense-Newton dimension gate inside the loop
    offsets = jnp.asarray(offsets, dtype)
    local_norm = (None if normalization is None
                  else _local_normalization(data.buckets, normalization))
    norm_mode = 0
    if normalization is not None:
        norm_mode = 2 if normalization.shifts is not None else 1
    coeffs, variances = [], []
    conv_list, iter_list = [], []
    # integer accumulators (PN501): these are counts — summing them as
    # floats would be exact anyway below 2^53, but keeping them int makes
    # the order-independence self-evident to the reader and the lint
    conv_sum, iter_sum, total, solved_total = 0, 0, 0, 0
    for b, bucket in enumerate(data.buckets):
        E, D = bucket.num_entities, bucket.local_dim
        if E == 0:
            # degenerate bucket (no entities): nothing to solve — emit the
            # empty [0, D] shapes downstream consumers expect (scoring,
            # model building, warm start) and keep the convergence
            # accounting untouched rather than tripping range(step=0) /
            # W_parts[0] in the blocked loop below
            coeffs.append(np.zeros((0, D), np.dtype(dtype)))
            variances.append(np.zeros((0, D), np.dtype(dtype))
                             if compute_variance else None)
            conv_list.append(np.zeros(0, bool))
            iter_list.append(np.zeros(0, np.int32))
            continue
        mask = None if active is None else active[b]
        if mask is not None:
            mask = np.asarray(mask, bool)
            if mask.shape != (E,):
                raise ValueError(
                    f"active mask for bucket {b} has shape {mask.shape}, "
                    f"expected ({E},)")
            if mask.all():
                mask = None  # full solve — take the unsliced path
        if mask is not None and not mask.any():
            # fully frozen bucket: nothing touches the device at all
            coeffs.append(np.array(np.asarray(w0[b]), copy=True))
            variances.append(
                None if not compute_variance else
                (np.array(prev_variances[b], copy=True)
                 if prev_variances is not None and prev_variances[b]
                 is not None else np.zeros((E, D), np.dtype(dtype))))
            conv_list.append(np.ones(E, bool))
            iter_list.append(np.zeros(E, np.int32))
            conv_sum += E
            total += E
            continue
        sel = None if mask is None else np.flatnonzero(mask)
        n_solve = E if sel is None else len(sel)
        opt_b = resolve_re_optimizer(optimizer, D)
        if mesh is not None:
            n_dev = mesh.shape[axis]
            run = _jitted_sharded_solver(D, task, opt_b, config,
                                         compute_variance, mesh, axis,
                                         norm_mode)
        else:
            n_dev = 1
            run = _jitted_solver(D, task, opt_b, config, compute_variance,
                                 norm_mode)
        # Bound the vmapped width: one program over ~100k entities
        # exhausted HBM on the v5e and hard-crashed the TPU worker
        # ("kernel fault", docs/tpu_r05_logs/bench_game.log), and the
        # slowdown was superlinear well before the crash. Entities are
        # independent, so solve fixed-width blocks: every block padded to
        # one shape (single compile), results fetched per block so HBM
        # only ever holds one block's solver intermediates.
        bs = -(-min(_RE_BLOCK_ENTITIES, E) // n_dev) * n_dev
        # active-set sub-bucket: gather the unconverged entities ON THE
        # HOST (the frozen majority's arrays never transfer), pad to the
        # power-of-two ladder width, and solve that
        width = bs if sel is None else _active_width(n_solve, bs, n_dev)
        idx_np = bucket.indices if sel is None else bucket.indices[sel]
        val_np = bucket.values if sel is None else bucket.values[sel]
        lab_np = bucket.labels if sel is None else bucket.labels[sel]
        wts_np = bucket.weights if sel is None else bucket.weights[sel]
        sidx_np = (bucket.sample_idx if sel is None
                   else bucket.sample_idx[sel])
        ln_b = None
        if local_norm is not None:
            f_np, s_np, pos_np = local_norm[b]
            if sel is not None:
                f_np = f_np[sel]
                s_np = None if s_np is None else s_np[sel]
                pos_np = None if pos_np is None else pos_np[sel]
            ln_b = (f_np, s_np, pos_np)
        sidx = jnp.asarray(sidx_np)
        # padding rows (sidx == -1) carry weight 0, offset value irrelevant
        off = jnp.take(offsets, jnp.maximum(sidx, 0), axis=0) * (sidx >= 0)
        if w0 is not None:
            w_init = np.asarray(w0[b])
            if sel is not None:
                w_init = w_init[sel]
            if ln_b is not None:
                w_init = _re_to_training_space(w_init, *ln_b)
            w_init = jnp.asarray(w_init, dtype)
        else:
            w_init = jnp.zeros((n_solve, D), dtype)
        if ln_b is not None:
            f_loc = jnp.asarray(ln_b[0], dtype)
            s_loc = (jnp.zeros((n_solve, 1), dtype) if ln_b[1] is None
                     else jnp.asarray(ln_b[1], dtype))
        else:  # unused dummies (dead-code-eliminated under jit)
            f_loc = jnp.zeros((n_solve, 1), dtype)
            s_loc = jnp.zeros((n_solve, 1), dtype)
        args = (
            jnp.asarray(idx_np),
            jnp.asarray(val_np, dtype),
            jnp.asarray(lab_np, dtype),
            jnp.asarray(wts_np, dtype),
            off.astype(dtype),
            w_init,
            f_loc,
            s_loc,
            jnp.asarray(l2, dtype),
            jnp.asarray(l1, dtype),
        )
        W, V, conv, iters = _run_entity_blocks(run, args, n_solve, width,
                                               compute_variance)
        if ln_b is not None:
            W = _re_to_model_space(W, *ln_b)
        if sel is None:
            conv_arr, iter_arr = conv, iters.astype(np.int32)
        else:
            # scatter solved entities back; frozen rows carry over
            W_full = np.array(np.asarray(w0[b]), copy=True)
            W_full[sel] = W
            W = W_full
            if compute_variance:
                V_full = (np.array(prev_variances[b], copy=True)
                          if prev_variances is not None
                          and prev_variances[b] is not None
                          else np.zeros((E, np.asarray(V).shape[1]),
                                        np.asarray(V).dtype))
                V_full[sel] = V
                V = V_full
            conv_arr = np.ones(E, bool)
            conv_arr[sel] = conv
            iter_arr = np.zeros(E, np.int32)
            iter_arr[sel] = iters
        # opt-in NaN trap at the batched per-entity solver's host
        # boundary (no-op unless a NaNGuard context is armed)
        nan_guard_check(f"re_solver:bucket{b}", W)
        if compute_variance and V is not None:
            nan_guard_check(f"re_solver:bucket{b}:variances", V)
        coeffs.append(W)
        variances.append(V)
        conv_list.append(conv_arr)
        iter_list.append(iter_arr)
        conv_sum += int(conv_arr.sum())
        iter_sum += int(iter_arr.sum())
        total += E
        solved_total += n_solve
    return RandomEffectFitResult(
        coefficients=coeffs,
        variances=variances if compute_variance else None,
        converged_fraction=conv_sum / max(total, 1),
        mean_iterations=iter_sum / max(solved_total, 1),
        converged=conv_list,
        iterations=iter_list,
        entities_solved=solved_total,
    )


def _margins_one(w_e, idx_e, val_e):
    return jnp.sum(val_e * w_e[idx_e], axis=-1)  # [M]


def score_random_effect(
    score_view: Sequence[REScoreBucket],
    coefficients: Sequence[np.ndarray],
    num_samples: int,
    dtype=jnp.float32,
    prev: Optional[jax.Array] = None,
    changed: Optional[Sequence[Optional[np.ndarray]]] = None,
) -> jax.Array:
    """Margins of every sample under its entity's model, scattered into a
    full-dataset score vector (the reference's CoordinateDataScores role,
    SURVEY.md §3.2). Samples with no entity model score 0.

    Incremental mode (``prev`` + ``changed``): recompute margins only for
    the rows owned by re-solved entities and scatter-overwrite them into
    the previous score vector — every row belongs to at most one entity
    per coordinate, so a plain set is exact. ``changed`` holds one boolean
    mask [E] per bucket (None = whole bucket changed); the changed rows
    are gathered on the host and padded to a power-of-two entity width so
    the margin kernel's shape ladder stays bounded as active sets shrink."""
    if prev is None or changed is None:
        scores = jnp.zeros((num_samples + 1,), dtype)  # slot n swallows pad
        for view, W in zip(score_view, coefficients):
            Wd = jnp.asarray(W, dtype)
            idx = jnp.asarray(view.indices)
            val = jnp.asarray(view.values, dtype)
            sidx = jnp.asarray(view.sample_idx)
            m = jax.vmap(_margins_one)(Wd, idx, val)  # [E, M]
            target = jnp.where(sidx >= 0, sidx, num_samples)
            scores = scores.at[target.reshape(-1)].add(
                jnp.where(sidx >= 0, m, 0.0).reshape(-1)
            )
        return scores[:num_samples]

    scores = jnp.concatenate(
        [jnp.asarray(prev, dtype), jnp.zeros((1,), dtype)])
    for view, W, mask in zip(score_view, coefficients, changed):
        E = view.sample_idx.shape[0]
        if E == 0:
            continue
        if mask is None:
            sel = np.arange(E)
        else:
            sel = np.flatnonzero(np.asarray(mask, bool))
            if len(sel) == 0:
                continue
        width = _active_width(len(sel), E, 1)
        pad = width - len(sel)
        W_np = np.asarray(W)[sel]
        idx_np = view.indices[sel]
        val_np = view.values[sel]
        sidx_np = view.sample_idx[sel]
        if pad:
            W_np = np.concatenate([W_np, np.zeros((pad,) + W_np.shape[1:],
                                                  W_np.dtype)])
            idx_np = np.concatenate(
                [idx_np, np.zeros((pad,) + idx_np.shape[1:], idx_np.dtype)])
            val_np = np.concatenate(
                [val_np, np.zeros((pad,) + val_np.shape[1:], val_np.dtype)])
            sidx_np = np.concatenate(
                [sidx_np, np.full((pad,) + sidx_np.shape[1:], -1,
                                  sidx_np.dtype)])
        sidx = jnp.asarray(sidx_np)
        m = jax.vmap(_margins_one)(jnp.asarray(W_np, dtype),
                                   jnp.asarray(idx_np),
                                   jnp.asarray(val_np, dtype))
        target = jnp.where(sidx >= 0, sidx, num_samples)
        # overwrite, don't add: these rows' previous margins are stale
        scores = scores.at[target.reshape(-1)].set(
            jnp.where(sidx >= 0, m, 0.0).reshape(-1), mode="drop")
    return scores[:num_samples]
