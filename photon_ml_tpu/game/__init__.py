from photon_ml_tpu.game.data import (
    HostSparse,
    RandomEffectTrainData,
    build_random_effect_data,
    build_score_view,
    host_sparse_from_dense,
)
from photon_ml_tpu.game.random_effect import train_random_effect, score_random_effect
from photon_ml_tpu.game.descent import (
    CoordinateConfig,
    CoordinateDescent,
    GameDataset,
)
