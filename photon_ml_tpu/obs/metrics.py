"""Unified metrics core: counters, gauges, histograms, text exposition.

Generalized out of ``serve/metrics.py`` (which re-exports from here,
unchanged API, byte-identical ``/metrics`` render) so training records
through the same primitives: per-sweep solve/eval/comm time, chunk-cache
hits/misses, prefetch stalls, and cross-shard exchange bytes all land in
one registry with the serving series' exposition format.

Stdlib-only. The exposition format is the Prometheus text format's
subset that covers counters, gauges, and cumulative histograms; the
histogram contract (``le`` buckets cumulative, ``+Inf`` == ``_count``)
is unit-tested in ``tests/test_obs_metrics.py``.

Thread-safety: one lock per :class:`ServingMetrics` /
:class:`MetricsRegistry` instance — every recording site is a handful
of float ops, and the handler threads + batcher worker all write here.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Histogram", "ServingMetrics", "MetricsRegistry", "TrainingMetrics",
    "DEFAULT_LATENCY_BUCKETS_MS", "DEFAULT_SECONDS_BUCKETS",
    "escape_label_value", "training_metrics",
]

# Default latency buckets (milliseconds): log-ish spacing from sub-ms to
# the watchdog regime. Cumulative counts, prometheus ``le`` semantics.
DEFAULT_LATENCY_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0,
)

# Second-scale buckets for training-side phase timings (a CD coordinate
# solve spans ~ms on toy data to minutes on streamed passes).
DEFAULT_SECONDS_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
    300.0, 600.0,
)


def escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline (in that order — backslash first so the escapes
    themselves survive)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class Histogram:
    """Fixed-bucket cumulative histogram (prometheus semantics): bucket
    ``le=b`` counts observations ``<= b``, plus ``+Inf``/count/sum."""

    def __init__(self, bounds: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf overflow
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        i = len(self.bounds)
        for j, b in enumerate(self.bounds):
            if value <= b:
                i = j
                break
        self.counts[i] += 1
        self.total += 1
        self.sum += value

    def quantile(self, q: float) -> float:
        """Approximate quantile: linear interpolation within the bucket
        the rank lands in (prometheus ``histogram_quantile`` semantics —
        the old upper-bound answer overstated by up to a full bucket
        ratio, which made any policy keyed on an observed quantile, e.g.
        the front door's hedge trigger, fire a bucket late). The +Inf
        overflow bucket still reports the last finite bound."""
        if self.total == 0:
            return 0.0
        rank = q * self.total
        seen = 0
        lo = 0.0
        for j, b in enumerate(self.bounds):
            if self.counts[j] and seen + self.counts[j] >= rank:
                frac = (rank - seen) / self.counts[j]
                return lo + frac * (b - lo)
            seen += self.counts[j]
            lo = b
        return self.bounds[-1] if self.bounds else float("inf")

    def render(self, name: str, out: List[str],
               labels: str = "") -> None:
        """Emit the cumulative bucket series. ``labels`` is a pre-
        rendered ``k="v",…`` fragment (empty for the unlabeled form —
        which keeps the serving render byte-identical)."""
        if not labels:
            out.append(f"# TYPE {name} histogram")
        cum = 0
        sep = "," if labels else ""
        for j, b in enumerate(self.bounds):
            cum += self.counts[j]
            out.append(
                f'{name}_bucket{{{labels}{sep}le="{_fmt(b)}"}} {cum}')
        out.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {self.total}')
        if labels:
            out.append(f"{name}_sum{{{labels}}} {_fmt(self.sum)}")
            out.append(f"{name}_count{{{labels}}} {self.total}")
        else:
            out.append(f"{name}_sum {_fmt(self.sum)}")
            out.append(f"{name}_count {self.total}")


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    return ",".join(f'{k}="{escape_label_value(v)}"' for k, v in labels)


class _Series:
    """One named metric family in a :class:`MetricsRegistry`: a value (or
    histogram) per label set, rendered in first-seen label order."""

    def __init__(self, name: str, kind: str, help: str = "",
                 bounds: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.kind = kind  # "counter" | "gauge" | "histogram"
        self.help = help
        self.bounds = bounds
        # label tuple (sorted (k, v) pairs) -> float | Histogram
        self.values: Dict[Tuple[Tuple[str, str], ...], object] = {}

    def _key(self, labels: Dict[str, str]
             ) -> Tuple[Tuple[str, str], ...]:
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def inc(self, n: float = 1, **labels) -> None:
        key = self._key(labels)
        self.values[key] = float(self.values.get(key, 0.0)) + n

    def set(self, v: float, **labels) -> None:
        self.values[self._key(labels)] = float(v)

    def observe(self, v: float, **labels) -> None:
        key = self._key(labels)
        h = self.values.get(key)
        if h is None:
            h = self.values[key] = Histogram(
                self.bounds or DEFAULT_LATENCY_BUCKETS_MS)
        h.observe(v)

    def get(self, **labels):
        """Current value (0 / empty histogram semantics for unseen)."""
        return self.values.get(self._key(labels), 0.0)

    def render(self, out: List[str]) -> None:
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        for key, val in self.values.items():
            labels = _label_str(key)
            if self.kind == "histogram":
                val.render(self.name, out, labels)
            elif labels:
                out.append(f"{self.name}{{{labels}}} {_fmt(val)}")
            else:
                out.append(f"{self.name} {_fmt(val)}")


class MetricsRegistry:
    """Get-or-create named counters/gauges/histograms with optional
    labels, rendered in registration order. The shared substrate for
    non-serving metrics (training, front door); ``ServingMetrics`` keeps
    its hand-rolled render for byte-compatibility."""

    def __init__(self):
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}  # insertion-ordered

    def _get(self, name: str, kind: str, help: str,
             bounds: Optional[Tuple[float, ...]] = None) -> _Series:
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = _Series(name, kind, help, bounds)
            elif s.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {s.kind}")
            return s

    def counter(self, name: str, help: str = "") -> _Series:
        return self._get(name, "counter", help)

    def gauge(self, name: str, help: str = "") -> _Series:
        return self._get(name, "gauge", help)

    def histogram(self, name: str, help: str = "",
                  bounds: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_MS
                  ) -> _Series:
        return self._get(name, "histogram", help, bounds)

    def inc(self, name: str, n: float = 1, **labels) -> None:
        with self._lock:
            self._series[name].inc(n, **labels)

    def render(self) -> str:
        with self._lock:
            out: List[str] = []
            for s in self._series.values():
                s.render(out)
            return "\n".join(out) + "\n" if out else ""

    def snapshot(self) -> Dict[str, dict]:
        """Flat {name: {label_str_or_'': value}} view for tests/bench
        (histograms report their count/sum)."""
        with self._lock:
            snap: Dict[str, dict] = {}
            for s in self._series.values():
                vals = {}
                for key, val in s.values.items():
                    k = _label_str(key)
                    if isinstance(val, Histogram):
                        vals[k] = {"count": val.total, "sum": val.sum}
                    else:
                        vals[k] = val
                snap[s.name] = vals
            return snap


class TrainingMetrics:
    """The training-side series (``photon_train_`` prefix), recorded by
    descent / streaming / entity_shard / chunk_cache through one
    process-wide instance (:func:`training_metrics`):

      sweep_steps_total{coordinate} — CD coordinate steps;
      solve_seconds / eval_seconds / comm_seconds{coordinate} —
        histograms, the per-step phase split the CD history carries;
      chunk_cache_{warm,cold,fallthrough}_passes_total — decode-once
        cache effectiveness (warm == hit);
      prefetch_{stall,decode,transfer}_seconds_total — the streamed-pass
        pipeline accounting (``StreamStats``) as counters;
      exchange_{bytes_sent,bytes_gathered,rounds}_total /
        exchange_seconds_total — cross-shard score-delta traffic.
    """

    def __init__(self):
        self.registry = MetricsRegistry()
        r = self.registry
        self._steps = r.counter("photon_train_sweep_steps_total",
                                "CD coordinate steps completed")
        self._solve = r.histogram("photon_train_solve_seconds",
                                  bounds=DEFAULT_SECONDS_BUCKETS)
        self._eval = r.histogram("photon_train_eval_seconds",
                                 bounds=DEFAULT_SECONDS_BUCKETS)
        self._comm = r.histogram("photon_train_comm_seconds",
                                 bounds=DEFAULT_SECONDS_BUCKETS)
        self._cache = {
            "warm": r.counter("photon_train_chunk_cache_warm_passes_total"),
            "cold": r.counter("photon_train_chunk_cache_cold_passes_total"),
            "fallthrough": r.counter(
                "photon_train_chunk_cache_fallthrough_passes_total"),
        }
        self._stall = r.counter("photon_train_prefetch_stall_seconds_total")
        self._decode = r.counter(
            "photon_train_prefetch_decode_seconds_total")
        self._transfer = r.counter(
            "photon_train_prefetch_transfer_seconds_total")
        self._bytes_sent = r.counter("photon_train_exchange_bytes_sent_total")
        self._bytes_gathered = r.counter(
            "photon_train_exchange_bytes_gathered_total")
        self._rounds = r.counter("photon_train_exchange_rounds_total")
        self._exch_s = r.counter("photon_train_exchange_seconds_total")
        # pathwise fixed-effect screening (optimize/path.py): one
        # lambdas_total tick per solved lambda; frozen/rounds/violations
        # accumulate the screen's work split so a dashboard can tell an
        # effective screen (high frozen, rounds ~= lambdas, violations
        # ~= 0) from a thrashing one (violations and fallbacks climbing)
        self._path_lambdas = r.counter(
            "photon_train_path_lambdas_total",
            "lambdas solved by the pathwise screened solver")
        self._path_frozen = r.counter(
            "photon_train_path_features_frozen_total",
            "features frozen at zero, summed over solved lambdas")
        self._path_rounds = r.counter(
            "photon_train_path_kkt_rounds_total",
            "screen->solve->certify rounds (1 per lambda when the "
            "screen holds first try)")
        self._path_violations = r.counter(
            "photon_train_path_kkt_violations_total",
            "screened coordinates re-admitted by the KKT check")
        self._path_passes = r.counter(
            "photon_train_path_full_grad_passes_total",
            "full data-gradient passes paid for screening + certification")
        self._path_fallback = r.counter(
            "photon_train_path_fallback_total",
            "lambdas that exhausted the KKT repair budget and fell back "
            "to a full-width solve")

    def record_step(self, coordinate: str, solve_s: float, eval_s: float,
                    comm_s: float) -> None:
        self._steps.inc(1, coordinate=coordinate)
        self._solve.observe(solve_s, coordinate=coordinate)
        self._eval.observe(eval_s, coordinate=coordinate)
        self._comm.observe(comm_s, coordinate=coordinate)

    def record_chunk_cache_pass(self, kind: str) -> None:
        c = self._cache.get(kind)
        if c is not None:
            c.inc(1)

    def record_prefetch(self, stall_s: float = 0.0, decode_s: float = 0.0,
                        transfer_s: float = 0.0) -> None:
        self._stall.inc(stall_s)
        self._decode.inc(decode_s)
        self._transfer.inc(transfer_s)

    def record_path_lambda(self, frozen: int, rounds: int, violations: int,
                           full_grad_passes: int, fallback: bool) -> None:
        self._path_lambdas.inc(1)
        self._path_frozen.inc(frozen)
        self._path_rounds.inc(rounds)
        self._path_violations.inc(violations)
        self._path_passes.inc(full_grad_passes)
        if fallback:
            self._path_fallback.inc(1)

    def record_exchange(self, bytes_sent: int, bytes_gathered: int,
                        seconds: float) -> None:
        self._bytes_sent.inc(bytes_sent)
        self._bytes_gathered.inc(bytes_gathered)
        self._rounds.inc(1)
        self._exch_s.inc(seconds)

    def render(self) -> str:
        return self.registry.render()

    def snapshot(self) -> Dict[str, dict]:
        return self.registry.snapshot()


_TRAINING: Optional[TrainingMetrics] = None
_TRAINING_LOCK = threading.Lock()


def training_metrics() -> TrainingMetrics:
    """The process-wide training metrics instance (lazily created; the
    simulated harness's ranks are threads, so they share it — label
    cardinality stays per-coordinate, not per-rank)."""
    global _TRAINING
    if _TRAINING is None:
        with _TRAINING_LOCK:
            if _TRAINING is None:
                _TRAINING = TrainingMetrics()
    return _TRAINING


class ServingMetrics:
    """All serving-side instrumentation in one place.

    Exported series (``photon_serve_`` prefix):
      requests_total / rows_total / shed_total / errors_total — counters;
      shed_queue_full_total / shed_deadline_total — the load-shedding
        split by cause: admission-queue-at-capacity rejections vs
        requests whose deadline expired while still queued (shed_total
        stays the sum, for dashboards that predate the split);
      request_latency_ms / batch_latency_ms — histograms (request latency
        is admission -> response; batch latency is one scoring execution);
      queue_wait_ms / compute_ms — the request-latency split: time a
        request sat in the admission queue waiting for a batch slot vs
        the scoring execution's wall time attributed to the request, so
        the bench's stall accounting and /metrics agree on where time
        goes (queue_wait + compute ~= request_latency per request);
      queue_depth — gauge, current admission-queue occupancy;
      batch_fill_ratio — gauge, rolling mean of rows/max_batch per batch;
      compile_cache_{hits,misses}_total, coeff_cache_{hits,misses,
        evictions}_total — cache counters (hit rates derive from these);
      swaps_total / swap_latency_ms / active_version_info — the model-
        lifecycle series: hot-swap count, build-to-install latency, and
        a version-labeled info gauge (value constant 1; the label
        carries the active version, the standard prometheus idiom for
        string-valued state);
      gate_{pass,fail}_total — promotion-gate verdicts observed by this
        process (the gate tool and the reload path record here);
      degraded_total{level} — requests served below full fidelity by the
        brownout ladder (level 1 = resident-coefficients-only, level 2 =
        fixed-effect-only margin); zero whenever faults/overload are off;
      deadline_drop_total{stage} — requests dropped because their budget
        expired, labelled by the CHEAPEST stage that caught it
        (admission / queue / pre_compute — never after device compute);
      brownout_level — gauge, the controller's current DEFAULT ladder
        level (0 healthy; raised under sustained queue-wait overload);
      model_staleness_seconds — gauge, how long the live model has been
        serving without a confirmed-fresh registry poll (rises while the
        watcher pins the old version through registry failures);
      membership_epoch — gauge, the entity-affinity membership epoch the
        replica currently serves under (0 = no membership applied);
      membership_{prefetch_entities,prefetch_bytes}_total — the
        rebalance handoff: entities/bytes prefetched into this replica's
        caches+pages when an ownership delta moved them here;
      membership_non_owned_skips_total — paged installs skipped because
        the faulting entity belongs to another replica (it still scores
        correctly through the host LRU path);
      membership_evictions_total — resident paged rows dropped by a
        re-own compaction (``retain_only``) when ownership shrank.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.rows_total = 0
        self.shed_total = 0
        self.shed_queue_full_total = 0
        self.shed_deadline_total = 0
        self.errors_total = 0
        self.batches_total = 0
        self.batch_rows_sum = 0
        self.batch_fill_sum = 0.0
        self.queue_depth = 0
        self.request_latency_ms = Histogram()
        self.batch_latency_ms = Histogram()
        self.queue_wait_ms = Histogram()
        self.compute_ms = Histogram()
        # cache counters are owned here but incremented through the cache
        # objects' stat hooks so the caches stay usable standalone
        self.compile_cache_hits = 0
        self.compile_cache_misses = 0
        self.coeff_cache_hits = 0
        self.coeff_cache_misses = 0
        self.coeff_cache_evictions = 0
        # device-resident paged coefficient table (serve/paged_table.py)
        self.paged_installs = 0
        self.paged_page_evictions = 0
        self.paged_faults = 0
        # model lifecycle (registry/ + ScoringSession.swap)
        self.swaps_total = 0
        self.swap_latency_ms = Histogram()
        self.active_version = ""
        self.gate_pass_total = 0
        self.gate_fail_total = 0
        # brownout ladder + deadline budget accounting (serve/brownout.py,
        # batcher deadline propagation, watcher staleness pinning)
        self.degraded_total: Dict[int, int] = {1: 0, 2: 0}
        self.deadline_drops: Dict[str, int] = {
            "admission": 0, "queue": 0, "pre_compute": 0}
        self.brownout_level = 0
        self.model_staleness_s = 0.0
        # entity-affinity membership (serve/membership.py): the applied
        # epoch plus the rebalance-handoff accounting — prefetched
        # entities/bytes moved per re-own, installs skipped because the
        # entity belongs to another replica, and rows dropped by a
        # paged table's retain_only compaction
        self.membership_epoch = 0
        self.membership_prefetch_entities = 0
        self.membership_prefetch_bytes = 0
        self.membership_non_owned_skips = 0
        self.membership_evictions = 0

    # -- recording sites ---------------------------------------------------
    def record_request(self, rows: int, latency_ms: float,
                       queue_wait_ms: Optional[float] = None,
                       compute_ms: Optional[float] = None) -> None:
        with self._lock:
            self.requests_total += 1
            self.rows_total += rows
            self.request_latency_ms.observe(latency_ms)
            if queue_wait_ms is not None:
                self.queue_wait_ms.observe(queue_wait_ms)
            if compute_ms is not None:
                self.compute_ms.observe(compute_ms)

    def record_shed(self, cause: str = "queue_full") -> None:
        with self._lock:
            self.shed_total += 1
            if cause == "deadline":
                self.shed_deadline_total += 1
            else:
                self.shed_queue_full_total += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors_total += 1

    def record_batch(self, rows: int, max_batch: int,
                     latency_ms: float) -> None:
        with self._lock:
            self.batches_total += 1
            self.batch_rows_sum += rows
            self.batch_fill_sum += rows / max(max_batch, 1)
            self.batch_latency_ms.observe(latency_ms)

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth

    def record_compile(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.compile_cache_hits += 1
            else:
                self.compile_cache_misses += 1

    def record_coeff(self, hits: int = 0, misses: int = 0,
                     evictions: int = 0) -> None:
        with self._lock:
            self.coeff_cache_hits += hits
            self.coeff_cache_misses += misses
            self.coeff_cache_evictions += evictions

    def record_paged(self, installs: int = 0, page_evictions: int = 0,
                     faults: int = 0) -> None:
        with self._lock:
            self.paged_installs += installs
            self.paged_page_evictions += page_evictions
            self.paged_faults += faults

    def set_active_version(self, version: str) -> None:
        with self._lock:
            self.active_version = str(version)

    def record_swap(self, version: str, latency_ms: float) -> None:
        with self._lock:
            self.swaps_total += 1
            self.active_version = str(version)
            self.swap_latency_ms.observe(latency_ms)

    def record_gate(self, passed: bool) -> None:
        with self._lock:
            if passed:
                self.gate_pass_total += 1
            else:
                self.gate_fail_total += 1

    def record_degraded(self, level: int, n: int = 1) -> None:
        """A request was served below full fidelity at ladder ``level``
        (1 = resident-only, 2 = fixed-effect-only). Level 0 is a no-op so
        callers can record unconditionally."""
        if level <= 0:
            return
        with self._lock:
            self.degraded_total[int(level)] = (
                self.degraded_total.get(int(level), 0) + int(n))

    def record_deadline_drop(self, stage: str) -> None:
        """A request's deadline budget expired and it was dropped at
        ``stage`` (admission / queue / pre_compute) — always BEFORE any
        device compute was spent on it."""
        with self._lock:
            self.deadline_drops[stage] = (
                self.deadline_drops.get(stage, 0) + 1)

    def set_brownout_level(self, level: int) -> None:
        with self._lock:
            self.brownout_level = int(level)

    def set_model_staleness(self, seconds: float) -> None:
        with self._lock:
            self.model_staleness_s = float(seconds)

    def set_membership_epoch(self, epoch: int) -> None:
        with self._lock:
            self.membership_epoch = int(epoch)

    def record_membership(self, prefetch_entities: int = 0,
                          prefetch_bytes: int = 0,
                          non_owned_skips: int = 0,
                          evictions: int = 0) -> None:
        """Membership/affinity accounting: a rebalance prefetch landed
        ``prefetch_entities`` rows (``prefetch_bytes`` moved), a paged
        install was skipped for ``non_owned_skips`` entities another
        replica owns, and ``evictions`` resident rows were dropped by a
        re-own compaction."""
        with self._lock:
            self.membership_prefetch_entities += int(prefetch_entities)
            self.membership_prefetch_bytes += int(prefetch_bytes)
            self.membership_non_owned_skips += int(non_owned_skips)
            self.membership_evictions += int(evictions)

    # -- views -------------------------------------------------------------
    @staticmethod
    def _rate(hits: int, misses: int) -> float:
        total = hits + misses
        return hits / total if total else 0.0

    def snapshot(self) -> Dict[str, float]:
        """Flat dict view (tests, bench, logs)."""
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "rows_total": self.rows_total,
                "shed_total": self.shed_total,
                "shed_queue_full_total": self.shed_queue_full_total,
                "shed_deadline_total": self.shed_deadline_total,
                "errors_total": self.errors_total,
                "batches_total": self.batches_total,
                "queue_depth": self.queue_depth,
                "batch_fill_ratio": (self.batch_fill_sum
                                     / max(self.batches_total, 1)),
                "request_latency_p50_ms":
                    self.request_latency_ms.quantile(0.5),
                "request_latency_p99_ms":
                    self.request_latency_ms.quantile(0.99),
                "queue_wait_p50_ms": self.queue_wait_ms.quantile(0.5),
                "queue_wait_p99_ms": self.queue_wait_ms.quantile(0.99),
                "compute_p50_ms": self.compute_ms.quantile(0.5),
                "compute_p99_ms": self.compute_ms.quantile(0.99),
                "compile_cache_hits": self.compile_cache_hits,
                "compile_cache_misses": self.compile_cache_misses,
                "compile_cache_hit_rate": self._rate(
                    self.compile_cache_hits, self.compile_cache_misses),
                "coeff_cache_hits": self.coeff_cache_hits,
                "coeff_cache_misses": self.coeff_cache_misses,
                "coeff_cache_evictions": self.coeff_cache_evictions,
                "paged_installs": self.paged_installs,
                "paged_page_evictions": self.paged_page_evictions,
                "paged_faults": self.paged_faults,
                "coeff_cache_hit_rate": self._rate(
                    self.coeff_cache_hits, self.coeff_cache_misses),
                "swaps_total": self.swaps_total,
                "swap_latency_p50_ms": self.swap_latency_ms.quantile(0.5),
                "active_version": self.active_version,
                "gate_pass_total": self.gate_pass_total,
                "gate_fail_total": self.gate_fail_total,
                "degraded_total": sum(self.degraded_total.values()),
                "degraded_level1_total": self.degraded_total.get(1, 0),
                "degraded_level2_total": self.degraded_total.get(2, 0),
                "deadline_drops_total": sum(self.deadline_drops.values()),
                "deadline_drops_admission":
                    self.deadline_drops.get("admission", 0),
                "deadline_drops_queue": self.deadline_drops.get("queue", 0),
                "deadline_drops_pre_compute":
                    self.deadline_drops.get("pre_compute", 0),
                "brownout_level": self.brownout_level,
                "model_staleness_s": self.model_staleness_s,
                "membership_epoch": self.membership_epoch,
                "membership_prefetch_entities":
                    self.membership_prefetch_entities,
                "membership_prefetch_bytes":
                    self.membership_prefetch_bytes,
                "membership_non_owned_skips":
                    self.membership_non_owned_skips,
                "membership_evictions": self.membership_evictions,
            }

    def render(self) -> str:
        """Prometheus text exposition of every series."""
        with self._lock:
            out: List[str] = []

            def counter(name, v):
                out.append(f"# TYPE {name} counter")
                out.append(f"{name} {_fmt(v)}")

            def gauge(name, v):
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name} {_fmt(v)}")

            counter("photon_serve_requests_total", self.requests_total)
            counter("photon_serve_rows_total", self.rows_total)
            counter("photon_serve_shed_total", self.shed_total)
            counter("photon_serve_shed_queue_full_total",
                    self.shed_queue_full_total)
            counter("photon_serve_shed_deadline_total",
                    self.shed_deadline_total)
            counter("photon_serve_errors_total", self.errors_total)
            counter("photon_serve_batches_total", self.batches_total)
            gauge("photon_serve_queue_depth", self.queue_depth)
            gauge("photon_serve_batch_fill_ratio",
                  self.batch_fill_sum / max(self.batches_total, 1))
            self.request_latency_ms.render(
                "photon_serve_request_latency_ms", out)
            self.batch_latency_ms.render(
                "photon_serve_batch_latency_ms", out)
            self.queue_wait_ms.render("photon_serve_queue_wait_ms", out)
            self.compute_ms.render("photon_serve_compute_ms", out)
            counter("photon_serve_compile_cache_hits_total",
                    self.compile_cache_hits)
            counter("photon_serve_compile_cache_misses_total",
                    self.compile_cache_misses)
            gauge("photon_serve_compile_cache_hit_rate", self._rate(
                self.compile_cache_hits, self.compile_cache_misses))
            counter("photon_serve_coeff_cache_hits_total",
                    self.coeff_cache_hits)
            counter("photon_serve_coeff_cache_misses_total",
                    self.coeff_cache_misses)
            counter("photon_serve_coeff_cache_evictions_total",
                    self.coeff_cache_evictions)
            counter("photon_serve_paged_installs_total",
                    self.paged_installs)
            counter("photon_serve_paged_page_evictions_total",
                    self.paged_page_evictions)
            counter("photon_serve_paged_faults_total", self.paged_faults)
            gauge("photon_serve_coeff_cache_hit_rate", self._rate(
                self.coeff_cache_hits, self.coeff_cache_misses))
            counter("photon_serve_swaps_total", self.swaps_total)
            self.swap_latency_ms.render("photon_serve_swap_latency_ms", out)
            out.append("# TYPE photon_serve_active_version_info gauge")
            label = escape_label_value(self.active_version)
            out.append(
                f'photon_serve_active_version_info{{version="{label}"}} 1')
            counter("photon_serve_gate_pass_total", self.gate_pass_total)
            counter("photon_serve_gate_fail_total", self.gate_fail_total)
            # brownout ladder + deadline budget series: fixed label sets
            # (levels 1..2, the three pre-compute stages) so the golden-
            # fixture byte comparison stays deterministic as counts move
            out.append("# TYPE photon_serve_degraded_total counter")
            for level in sorted(set(self.degraded_total) | {1, 2}):
                out.append(
                    f'photon_serve_degraded_total{{level="{level}"}} '
                    f"{_fmt(self.degraded_total.get(level, 0))}")
            out.append("# TYPE photon_serve_deadline_drop_total counter")
            for stage in ("admission", "queue", "pre_compute"):
                out.append(
                    f'photon_serve_deadline_drop_total{{stage="{stage}"}} '
                    f"{_fmt(self.deadline_drops.get(stage, 0))}")
            for stage in sorted(set(self.deadline_drops)
                                - {"admission", "queue", "pre_compute"}):
                out.append(
                    f'photon_serve_deadline_drop_total{{stage="{stage}"}} '
                    f"{_fmt(self.deadline_drops[stage])}")
            gauge("photon_serve_brownout_level", self.brownout_level)
            gauge("photon_serve_model_staleness_seconds",
                  self.model_staleness_s)
            gauge("photon_serve_membership_epoch", self.membership_epoch)
            counter("photon_serve_membership_prefetch_entities_total",
                    self.membership_prefetch_entities)
            counter("photon_serve_membership_prefetch_bytes_total",
                    self.membership_prefetch_bytes)
            counter("photon_serve_membership_non_owned_skips_total",
                    self.membership_non_owned_skips)
            counter("photon_serve_membership_evictions_total",
                    self.membership_evictions)
            return "\n".join(out) + "\n"
