"""Structured logging: rank / trace-id / request-id on every record.

One :class:`ContextFilter` installed on the ``photon_ml_tpu`` logger
stamps three fields into every record emitted anywhere in the package:

* ``rank`` — ``resilience.current_process_index()`` resolved on the
  emitting thread (so the simulated harness's per-thread ranks come out
  right, the same rule the tracer uses);
* ``trace_id`` / ``request_id`` — the ambient
  :class:`~photon_ml_tpu.obs.trace.TraceContext`, ``-`` when absent.

This replaces ad-hoc prefixes (the old ``[CD]`` tag in descent, the
driver's hand-rolled rank prefixes): a log line's identity is carried
in record *fields*, formatted once by :func:`configure`, instead of
re-encoded in every message string. Library code never calls
``configure`` — drivers do; tests attach the filter to their own
handlers when they want the stamps.

Slow-request exemplars: :class:`SlowRequestLog` keeps the top-N
requests by latency with their span breakdown (queue-wait / compute /
rows) and logs each new entrant, so "what were the worst requests and
where did their time go" is answerable from the log stream alone.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
from typing import Dict, List, Optional

from photon_ml_tpu.obs import trace as _trace

__all__ = ["ContextFilter", "SlowRequestLog", "configure",
           "DEFAULT_FORMAT"]

DEFAULT_FORMAT = ("%(asctime)s %(levelname)s rank=%(rank)s "
                  "trace=%(trace_id)s req=%(request_id)s "
                  "%(name)s: %(message)s")


def _rank() -> int:
    try:
        from photon_ml_tpu.parallel.resilience import current_process_index
        return int(current_process_index())
    except Exception:
        return 0


class ContextFilter(logging.Filter):
    """Stamp rank/trace_id/request_id into the record (always passes).
    Safe to install on handlers or loggers; fields default to ``-`` so
    format strings never KeyError on un-traced threads."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.rank = _rank()
        ctx = _trace.current_context()
        record.trace_id = ctx.trace_id if ctx is not None else "-"
        record.request_id = (ctx.request_id
                             if ctx is not None and ctx.request_id
                             else "-")
        return True


def configure(level: int = logging.INFO,
              fmt: str = DEFAULT_FORMAT,
              logger_name: str = "photon_ml_tpu") -> logging.Logger:
    """Driver-side setup: one stream handler with the structured format
    and the context filter on the package logger. Idempotent — a second
    call reuses the installed handler (so repeated driver invocations
    in one process don't duplicate lines)."""
    logger = logging.getLogger(logger_name)
    logger.addFilter(_ensure_filter(logger))
    for h in logger.handlers:
        if getattr(h, "_photon_obs_handler", False):
            break
    else:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(fmt))
        handler._photon_obs_handler = True
        logger.addHandler(handler)
    logger.setLevel(level)
    return logger


def _ensure_filter(logger: logging.Logger) -> ContextFilter:
    for f in logger.filters:
        if isinstance(f, ContextFilter):
            return f
    return ContextFilter()


class SlowRequestLog:
    """Top-N requests by latency, with span breakdown exemplars.

    ``note()`` is called by the batcher worker once per resolved
    request; an entry that makes the top-N is logged at INFO with its
    breakdown (the log stream carries the exemplars even if nobody
    polls ``snapshot()``). Thread-safe; bounded at ``top_n`` entries."""

    def __init__(self, top_n: int = 10,
                 logger: Optional[logging.Logger] = None):
        self.top_n = int(top_n)
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._heap: List[tuple] = []  # min-heap of (latency, seq, entry)
        self._log = logger or logging.getLogger(__name__)

    def note(self, request_id: Optional[str], latency_ms: float,
             **breakdown) -> None:
        entry = {"request_id": request_id or "-",
                 "latency_ms": round(float(latency_ms), 3), **breakdown}
        item = (float(latency_ms), next(self._seq), entry)
        with self._lock:
            if len(self._heap) < self.top_n:
                heapq.heappush(self._heap, item)
            elif item[0] > self._heap[0][0]:
                heapq.heapreplace(self._heap, item)
            else:
                return
        self._log.info("slow-request exemplar %s", entry)

    def snapshot(self) -> List[Dict]:
        """Entries sorted worst-first."""
        with self._lock:
            return [e for _, _, e in
                    sorted(self._heap, key=lambda t: -t[0])]
