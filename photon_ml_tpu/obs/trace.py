"""Span tracer: nestable, thread-safe, near-zero cost when off.

Design constraints, in priority order:

1. **Off means off.** Every hot path calls ``span(...)`` unconditionally
   (descent sweeps, the streamed-pass ring, the batcher worker, the
   fused scoring dispatch). With no tracer installed the call is one
   module-global load, one ``is None`` test, and the return of a shared
   immutable null context manager — no allocation, no lock, no clock
   read. ``bench.py trace`` gates this (< 2% on the streamed-fit and
   serving closed-loop legs, ``BENCH_trace.json``).
2. **Context is explicit at thread handoffs.** A span's trace-id and
   request-id live in a :class:`TraceContext` carried in a
   ``contextvars.ContextVar`` — ambient per thread AND per asyncio
   task, so the async front end's interleaved requests don't bleed
   trace ids into each other across awaits.
   Code that hands work to another thread captures
   ``current_context()`` and the receiving thread enters
   ``use_context(ctx)`` — the batcher worker, the prefetch ring's
   transfer thread, and the session's installer thread all do this, so
   one request's spans line up under one trace-id across every thread
   that touched it.
3. **Rank is resolved per span, on the recording thread.** In the
   simulated multi-controller harness each "process" is a thread with
   an ambient per-thread transport, so the rank CANNOT be captured at
   tracer start; each span asks ``resilience.current_process_index()``
   when it closes. Real runs resolve the same call to the jax process
   index. The Chrome-trace ``pid`` field carries the rank, which is
   what lets ``photon-trace merge`` lay N ranks side by side.
4. **Crash-safe export.** Spans land in a bounded in-memory ring; a
   dedicated export thread (``photon-trace-export`` — a registered
   photon thread prefix, so the thread-leak sanitizer owns it) flushes
   a complete ``trace-rank{r}.json`` per rank via write-temp +
   ``os.replace``, the registry's atomic-publish idiom. A killed
   process leaves the last complete flush, never a torn file.

Sampling: ``PHOTON_TRACE_SAMPLE`` (or ``start(sample=…)``) decides at
trace-root creation whether the whole trace records — a sampled-out
request costs the same as tracing-off for every nested span.

Enable via ``PHOTON_TRACE=<dir>`` (any truthy non-path value uses
``./photon-trace``) or programmatically::

    tracer = trace.start("/tmp/run1-traces", sample=1.0)
    ...
    trace.stop()          # bounded join + final flush

Spans::

    with trace.span("cd.coordinate", cat="train", coordinate=cfg.name):
        ...

Collective spans carry ``cat="collective"`` and a ``site`` arg (the
``resilience.collective_site`` label); the merge tool matches the k-th
occurrence of each site across ranks to align clocks.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import os
import random
import threading
import time
import uuid
from typing import Dict, Iterator, Optional

from photon_ml_tpu.io.durable import durable_replace

__all__ = [
    "TraceContext", "Tracer", "current_context", "use_context",
    "span", "start", "stop", "enabled", "active_tracer",
    "maybe_start_from_env", "new_request_id", "current_request_id",
    "request_context",
]

# Shared clock origin: one value per process, taken at import. In the
# simulated harness every rank is a thread of this process, so per-rank
# timestamps are directly comparable; across real processes the merge
# tool re-aligns on collective sites.
_ORIGIN = time.perf_counter()

_DEFAULT_RING = 65536
_DEFAULT_FLUSH_S = 1.0


def _now_us() -> float:
    return (time.perf_counter() - _ORIGIN) * 1e6


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


class TraceContext:
    """Ambient identity for one trace: trace-id, optional request-id,
    and the per-trace sampling verdict. Immutable after creation so it
    is safe to share across threads (each thread only reads it)."""

    __slots__ = ("trace_id", "request_id", "sampled")

    def __init__(self, trace_id: Optional[str] = None,
                 request_id: Optional[str] = None, sampled: bool = True):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.request_id = request_id
        self.sampled = bool(sampled)

    def __repr__(self) -> str:
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"request_id={self.request_id!r}, sampled={self.sampled})")


_CTX: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("photon_trace_ctx", default=None)


def current_context() -> Optional[TraceContext]:
    """The ambient trace context of the calling thread / asyncio task
    (None outside any trace). Capture this before handing work to
    another thread."""
    return _CTX.get()


@contextlib.contextmanager
def use_context(ctx: Optional[TraceContext]) -> Iterator[None]:
    """Adopt a captured context on the receiving side of a thread
    handoff (batcher worker, transfer thread, installer thread).
    ``use_context(None)`` is a no-op nesting, so call sites don't need
    to branch on whether the submitter was traced."""
    token = _CTX.set(ctx if ctx is not None else _CTX.get())
    try:
        yield
    finally:
        _CTX.reset(token)


def current_request_id() -> Optional[str]:
    ctx = _CTX.get()
    return ctx.request_id if ctx is not None else None


@contextlib.contextmanager
def request_context(request_id: Optional[str] = None,
                    trace_id: Optional[str] = None) -> Iterator[None]:
    """Root context for one served request: a fresh trace carrying the
    request id, so every span under it (batcher, session, installer)
    correlates. No-op (no allocation) when tracing is off — request-id
    propagation through the serving stack rides explicit parameters,
    not this ambient context."""
    t = _TRACER
    if t is None:
        yield
        return
    ctx = TraceContext(trace_id=trace_id, request_id=request_id,
                       sampled=t.sample_decision())
    with use_context(ctx):
        yield


class _NullSpan:
    """The disabled-path span: one shared immutable instance, usable as
    a context manager any number of times concurrently."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **kwargs):  # parity with _Span.set
        return self


_NULL_SPAN = _NullSpan()


def _rank() -> int:
    try:
        from photon_ml_tpu.parallel.resilience import current_process_index
        return int(current_process_index())
    except Exception:
        return 0


class _Span:
    __slots__ = ("_tracer", "name", "cat", "args", "_t0", "_owns_ctx")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = 0.0
        self._owns_ctx = None  # a _CTX reset token when this span roots

    def set(self, **kwargs) -> "_Span":
        """Attach args discovered mid-span (batch size, fault count)."""
        self.args.update(kwargs)
        return self

    def __enter__(self) -> "_Span":
        ctx = _CTX.get()
        if ctx is None:
            ctx = TraceContext(sampled=self._tracer.sample_decision())
            # keep the reset token so __exit__ restores the outer state
            self._owns_ctx = _CTX.set(ctx)
        self._t0 = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = _now_us()
        ctx = _CTX.get()
        if self._owns_ctx is not None:
            _CTX.reset(self._owns_ctx)
        if ctx is None or not ctx.sampled:
            return False
        args = self.args
        args["trace_id"] = ctx.trace_id
        if ctx.request_id is not None:
            args["request_id"] = ctx.request_id
        if exc_type is not None:
            args["error"] = exc_type.__name__
        self._tracer.record(
            name=self.name, cat=self.cat, ts=self._t0, dur=t1 - self._t0,
            rank=_rank(), args=args)
        return False


class Tracer:
    """Bounded-ring span recorder with a periodic atomic exporter."""

    def __init__(self, trace_dir: str, *, sample: float = 1.0,
                 ring_size: int = _DEFAULT_RING,
                 flush_interval_s: float = _DEFAULT_FLUSH_S):
        self.trace_dir = str(trace_dir)
        self.sample = float(sample)
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=max(1, int(ring_size)))
        self._dropped = 0
        self._thread_names: Dict[int, str] = {}
        self._flush_interval_s = float(flush_interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(self.trace_dir, exist_ok=True)

    # -- recording (hot side) ----------------------------------------------
    def sample_decision(self) -> bool:
        return self.sample >= 1.0 or random.random() < self.sample

    def span(self, name: str, cat: str, args: dict):
        ctx = _CTX.get()
        if ctx is not None and not ctx.sampled:
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    def record(self, *, name: str, cat: str, ts: float, dur: float,
               rank: int, args: dict) -> None:
        th = threading.current_thread()
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": round(ts, 3), "dur": round(dur, 3),
              "pid": rank, "tid": th.ident, "args": args}
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)
            self._thread_names.setdefault(th.ident, th.name)

    def instant(self, name: str, cat: str = "app", **args) -> None:
        """A zero-duration marker (install drops, fault hits)."""
        ctx = _CTX.get()
        if ctx is not None and not ctx.sampled:
            return
        if ctx is not None:
            args.setdefault("trace_id", ctx.trace_id)
            if ctx.request_id is not None:
                args.setdefault("request_id", ctx.request_id)
        self.record(name=name, cat=cat, ts=_now_us(), dur=0.0,
                    rank=_rank(), args=args)

    # -- export (cold side) -------------------------------------------------
    def start_export_thread(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._export_loop, daemon=True,
            name="photon-trace-export")
        self._thread.start()

    def _export_loop(self) -> None:
        # bounded wait per cycle; stop() sets the event and joins
        while not self._stop.wait(self._flush_interval_s):
            self.flush()

    def flush(self) -> None:
        """Write one complete Chrome-trace JSON per rank seen so far —
        snapshot under the lock, serialize and write outside it (no I/O
        or callbacks run while holding the recording lock)."""
        with self._lock:
            events = list(self._events)
            names = dict(self._thread_names)
            dropped = self._dropped
        by_rank: Dict[int, list] = {}
        for ev in events:
            by_rank.setdefault(ev["pid"], []).append(ev)
        for rank, evs in by_rank.items():
            meta = [{"name": "process_name", "ph": "M", "pid": rank,
                     "tid": 0, "args": {"name": f"rank {rank}"}}]
            for tid in sorted({e["tid"] for e in evs}):
                meta.append({"name": "thread_name", "ph": "M",
                             "pid": rank, "tid": tid,
                             "args": {"name": names.get(tid, str(tid))}})
            doc = {"traceEvents": meta + evs,
                   "displayTimeUnit": "ms",
                   "metadata": {"rank": rank, "dropped_events": dropped,
                                "producer": "photon-trace"}}
            final = os.path.join(self.trace_dir, f"trace-rank{rank}.json")
            tmp = final + f".tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            durable_replace(tmp, final)

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None
        self.flush()  # final flush on the caller's thread


# -- module-global switch ----------------------------------------------------
_TRACER: Optional[Tracer] = None


def active_tracer() -> Optional[Tracer]:
    return _TRACER


def enabled() -> bool:
    return _TRACER is not None


def span(name: str, cat: str = "app", **args):
    """The one instrumentation entry point. Disabled: returns the shared
    null span (no allocation). Enabled: a recording span whose trace
    context comes from — or is installed into — the calling thread."""
    t = _TRACER
    if t is None:
        return _NULL_SPAN
    return t.span(name, cat, args)


def instant(name: str, cat: str = "app", **args) -> None:
    t = _TRACER
    if t is not None:
        t.instant(name, cat, **args)


def start(trace_dir: str, *, sample: float = 1.0,
          ring_size: int = _DEFAULT_RING,
          flush_interval_s: float = _DEFAULT_FLUSH_S,
          export_thread: bool = True) -> Tracer:
    """Install the process-wide tracer (idempotent per process: a second
    start replaces the first after stopping it)."""
    global _TRACER
    if _TRACER is not None:
        _TRACER.stop()
    t = Tracer(trace_dir, sample=sample, ring_size=ring_size,
               flush_interval_s=flush_interval_s)
    if export_thread:
        t.start_export_thread()
    _TRACER = t
    return t


def stop(timeout_s: float = 5.0) -> None:
    """Stop and uninstall the tracer: bounded export-thread join, then a
    final flush so the files on disk are complete."""
    global _TRACER
    t = _TRACER
    _TRACER = None  # flip the off switch before the (slow) join
    if t is not None:
        t.stop(timeout_s)


def maybe_start_from_env() -> Optional[Tracer]:
    """Driver hook: honor ``PHOTON_TRACE`` / ``PHOTON_TRACE_SAMPLE`` /
    ``PHOTON_TRACE_RING`` without any CLI plumbing. ``PHOTON_TRACE``
    that looks like a path (contains a separator or names an existing
    dir) is the trace dir; any other truthy value traces into
    ``./photon-trace``."""
    val = os.environ.get("PHOTON_TRACE", "").strip()
    if not val or val.lower() in ("0", "false", "off", "no"):
        return None
    if os.sep in val or os.path.isdir(val) or val.startswith("."):
        trace_dir = val
    else:
        trace_dir = "photon-trace"
    sample = float(os.environ.get("PHOTON_TRACE_SAMPLE", "1.0"))
    ring = int(os.environ.get("PHOTON_TRACE_RING", str(_DEFAULT_RING)))
    return start(trace_dir, sample=sample, ring_size=ring)
