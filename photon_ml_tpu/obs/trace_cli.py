"""``photon-trace``: merge, validate, and smoke-test per-rank traces.

``merge``: combine ``trace-rank*.json`` files (one per process, written
by :mod:`photon_ml_tpu.obs.trace`) into a single Perfetto-loadable
timeline. Ranks that ran as real processes have unrelated
``perf_counter`` origins, so the merge re-aligns clocks on the
collective spans (``cat="collective"``, ``args.site``): the k-th
occurrence of a site on rank N is the *same rendezvous* as the k-th
occurrence on rank 0 — every participant leaves an allgather/barrier
together, so their span *ends* are simultaneous up to network skew.
Rank N's shift is the median of ``end_0 - end_N`` over all matched
occurrences (median: robust to a straggler rank that entered late).
Ranks with no matching collective spans merge unshifted, with a
warning in the output metadata.

``validate``: minimal schema check for CI (exit 12 leg in
``scripts/ci_lint.sh``) — a dict with a non-empty ``traceEvents`` list
whose events carry name/ph/pid/tid and numeric ts (plus dur for
``ph="X"``).

``smoke``: end-to-end self-test — run a 2-rank simulated-process trace
through the real tracer and the real sharded exchange, merge it,
validate the merged file. Exercises exactly the path the training
driver uses, without touching jax-compiled code.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys
from typing import Dict, List, Optional, Tuple

from photon_ml_tpu.io.durable import durable_replace

__all__ = ["merge_traces", "validate_trace", "main"]


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _collective_ends(events: List[dict]) -> Dict[Tuple[str, int], float]:
    """(site, occurrence_index) -> span end µs, for clock alignment."""
    ends: Dict[Tuple[str, int], float] = {}
    seen: Dict[str, int] = {}
    for ev in events:
        if ev.get("ph") != "X" or ev.get("cat") != "collective":
            continue
        site = (ev.get("args") or {}).get("site")
        if site is None:
            continue
        k = seen.get(site, 0)
        seen[site] = k + 1
        ends[(site, k)] = float(ev["ts"]) + float(ev.get("dur", 0.0))
    return ends


def merge_traces(paths: List[str]) -> dict:
    """Merge per-rank Chrome-trace files into one document, aligning
    each rank's clock to rank 0 (lowest rank present) via matched
    collective-span end times."""
    if not paths:
        raise ValueError("no trace files to merge")
    docs = []
    for p in sorted(paths):
        doc = _load(p)
        evs = doc.get("traceEvents", [])
        spans = [e for e in evs if e.get("ph") == "X"]
        rank = (doc.get("metadata", {}).get("rank")
                if isinstance(doc.get("metadata"), dict) else None)
        if rank is None:
            rank = spans[0]["pid"] if spans else 0
        docs.append((int(rank), evs, spans, p))
    docs.sort(key=lambda d: d[0])
    base_rank, _, base_spans, _ = docs[0]
    base_ends = _collective_ends(base_spans)

    merged: List[dict] = []
    shifts: Dict[int, Optional[float]] = {}
    for rank, evs, spans, _path in docs:
        if rank == base_rank:
            shift = 0.0
        else:
            ends = _collective_ends(spans)
            deltas = [base_ends[key] - end for key, end in ends.items()
                      if key in base_ends]
            shift = statistics.median(deltas) if deltas else None
        shifts[rank] = shift
        for ev in evs:
            ev = dict(ev)
            if shift and "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift
            merged.append(ev)
    merged.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "metadata": {
            "producer": "photon-trace merge",
            "ranks": sorted(shifts),
            "clock_shifts_us": {str(r): s for r, s in shifts.items()},
            "unaligned_ranks": sorted(
                r for r, s in shifts.items() if s is None),
        },
    }


def validate_trace(doc: dict) -> List[str]:
    """Return a list of schema problems (empty == valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["top level is not an object"]
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        return ["traceEvents missing or empty"]
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        for key in ("name", "ph", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i}: missing {key!r}")
        ph = ev.get("ph")
        if ph == "X":
            for key in ("ts", "dur"):
                if not isinstance(ev.get(key), (int, float)):
                    problems.append(f"event {i}: non-numeric {key!r}")
        elif ph == "M":
            pass  # metadata events carry no timestamps
        elif "ts" in ev and not isinstance(ev["ts"], (int, float)):
            problems.append(f"event {i}: non-numeric 'ts'")
        if problems and len(problems) >= 20:
            problems.append("... (truncated)")
            break
    if not any(e.get("ph") == "X" for e in evs if isinstance(e, dict)):
        problems.append("no complete ('X') span events")
    return problems


def _cmd_merge(args) -> int:
    paths = args.files or sorted(
        glob.glob(os.path.join(args.trace_dir, "trace-rank*.json")))
    if not paths:
        print(f"photon-trace: no trace files under {args.trace_dir!r}",
              file=sys.stderr)
        return 2
    doc = merge_traces(paths)
    out = args.output or os.path.join(
        args.trace_dir or os.path.dirname(paths[0]) or ".",
        "trace-merged.json")
    tmp = out + f".tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    durable_replace(tmp, out)
    meta = doc["metadata"]
    print(f"merged {len(paths)} rank file(s) -> {out} "
          f"({len(doc['traceEvents'])} events, ranks {meta['ranks']})")
    if meta["unaligned_ranks"]:
        print(f"warning: ranks {meta['unaligned_ranks']} had no "
              "collective spans matching rank 0; merged unshifted",
              file=sys.stderr)
    return 0


def _cmd_validate(args) -> int:
    problems = validate_trace(_load(args.file))
    if problems:
        for p in problems:
            print(f"photon-trace: {args.file}: {p}", file=sys.stderr)
        return 1
    print(f"{args.file}: valid ({len(_load(args.file)['traceEvents'])} "
          "events)")
    return 0


def _smoke_rank(rank: int):
    import numpy as np

    from photon_ml_tpu.obs import trace
    from photon_ml_tpu.parallel.entity_shard import exchange_score_updates

    with trace.span("smoke.fit", cat="train", rank=rank):
        for it in range(2):
            rows = np.asarray([rank, rank + 10], np.int64)
            vals = np.asarray([0.5 * rank, 1.5], np.float64)
            exchange_score_updates(
                (rows, vals), tag=f"smoke:{it}")


def _cmd_smoke(args) -> int:
    import tempfile

    from photon_ml_tpu.obs import trace
    from photon_ml_tpu.testing import run_simulated_processes

    with tempfile.TemporaryDirectory() as td:
        trace_dir = args.trace_dir or os.path.join(td, "traces")
        trace.start(trace_dir, export_thread=False)
        try:
            outcomes = run_simulated_processes(2, _smoke_rank)
        finally:
            trace.stop()
        bad = [o for o in outcomes if isinstance(o, BaseException)]
        if bad:
            for o in bad:
                print(f"photon-trace smoke: rank failed: {o!r}",
                      file=sys.stderr)
            return 1
        paths = sorted(
            glob.glob(os.path.join(trace_dir, "trace-rank*.json")))
        if len(paths) != 2:
            print(f"photon-trace smoke: expected 2 rank files, got "
                  f"{paths}", file=sys.stderr)
            return 1
        doc = merge_traces(paths)
        problems = validate_trace(doc)
        if problems:
            for p in problems:
                print(f"photon-trace smoke: {p}", file=sys.stderr)
            return 1
        sites = {(e.get("args") or {}).get("site")
                 for e in doc["traceEvents"] if e.get("cat") == "collective"}
        if not sites & {"smoke:0", "smoke:1"}:
            print("photon-trace smoke: merged trace has no collective "
                  "spans for the smoke sites", file=sys.stderr)
            return 1
    print("photon-trace smoke: OK (2 ranks merged, schema valid, "
          f"collective sites {sorted(s for s in sites if s)})")
    return 0


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-trace",
        description="merge / validate / smoke-test photon trace files")
    sub = p.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser("merge", help="merge per-rank trace files")
    m.add_argument("trace_dir", nargs="?", default=".",
                   help="directory holding trace-rank*.json")
    m.add_argument("--files", nargs="*", default=None,
                   help="explicit trace files (overrides trace_dir glob)")
    m.add_argument("-o", "--output", default=None,
                   help="merged output path (default: "
                        "<trace_dir>/trace-merged.json)")
    m.set_defaults(fn=_cmd_merge)

    v = sub.add_parser("validate", help="schema-check one trace file")
    v.add_argument("file")
    v.set_defaults(fn=_cmd_validate)

    s = sub.add_parser("smoke", help="2-rank end-to-end self test")
    s.add_argument("--trace-dir", default=None,
                   help="keep the smoke trace files here (default: "
                        "a temp dir)")
    s.set_defaults(fn=_cmd_smoke)
    return p


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
