"""Process-wide observability core: spans, metrics, structured logging.

Three pillars, one package (docs/observability.md):

* :mod:`photon_ml_tpu.obs.trace` — nestable, thread-safe spans with
  explicit context propagation across thread handoffs and (simulated or
  real) process boundaries, exported as Perfetto/Chrome-trace JSON per
  rank. Off by default; ``span()`` is a shared null object until a
  tracer is installed (``PHOTON_TRACE=…`` or ``trace.start()``).
* :mod:`photon_ml_tpu.obs.metrics` — the Prometheus-text metrics core
  (histograms, counters, gauges) generalized out of ``serve/metrics.py``
  into a shared registry so training records per-sweep solve/eval/comm,
  chunk-cache and prefetch counters next to the serving series.
* :mod:`photon_ml_tpu.obs.logging` — rank / trace-id / request-id
  stamping for every ``photon_ml_tpu.*`` log record, plus the top-N
  slow-request exemplar log.

``photon-trace`` (:mod:`photon_ml_tpu.obs.trace_cli`) merges per-rank
trace files into one Perfetto-loadable timeline, aligning ranks on the
collective-site labels threaded through ``resilience.collective_site``.
"""

from photon_ml_tpu.obs.metrics import (  # noqa: F401
    Histogram,
    MetricsRegistry,
    ServingMetrics,
    TrainingMetrics,
    escape_label_value,
    training_metrics,
)
from photon_ml_tpu.obs.trace import (  # noqa: F401
    TraceContext,
    current_context,
    span,
    use_context,
)

__all__ = [
    "Histogram", "MetricsRegistry", "ServingMetrics", "TrainingMetrics",
    "escape_label_value", "training_metrics",
    "TraceContext", "current_context", "span", "use_context",
]
