"""Core batched data types.

TPU-native equivalent of the reference's per-example data model
(``data.LabeledPoint(label, features, offset, weight)`` — SURVEY.md §3.1;
reference mount empty, paths unverified). Instead of one object per example we
hold batched device-resident arrays: a :class:`LabeledBatch` is a pytree so it
crosses ``jit``/``shard_map`` boundaries and can be sharded over a mesh axis.

Sparse features use a row-padded ELL layout (``indices``/``values`` of shape
``[n, k]``): every row is padded to the same nnz width with ``value == 0``
entries, which contribute nothing to margins or gradients regardless of the
padding index. This gives XLA static shapes (no CSR pointer chasing) and keeps
the hot ops — margin gather and gradient scatter-add — vectorized.
"""

from __future__ import annotations

import os
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# ---------------------------------------------------------------------------
# 1-D table gather for the sparse hot path.
#
# XLA:TPU lowers a word-granular gather (slice size 1) to a serial loop —
# ~1 element/cycle. Measured on the v5e chip (docs/tpu_r05_logs/tpu_diag.log):
# the 82M-element margin gather ran at ~1 GB/s, 0.1% of HBM peak, and the
# whole L-BFGS iteration was 2x that gather. The fix is the standard TPU
# embedding-lookup shape: reshape the table to [d/128, 128] so each gathered
# element is a full 128-lane row (a vectorizable (1,128)-slice gather), then
# select the wanted lane with a one-hot multiply+reduce on the VPU. The sum
# adds exactly one real value and 127 zeros, so the result is bit-identical
# to ``table[idx]``.
#
# The row-gather materializes a [m, 128] intermediate; for large m it runs
# under ``lax.map`` over fixed-size chunks so the intermediate stays ~128 MB
# regardless of nnz (the bench shape's 82M nnz would otherwise need 42 GB).
# ---------------------------------------------------------------------------

_LANES = 128
_GATHER_CHUNK = 1 << 18  # rows per lax.map step: [2^18, 128] f32 = 128 MB
_GATHER_MIN_SIZE = 1 << 14  # below this, the serial gather costs < ~20 us
_gather_mode = os.environ.get("PHOTON_GATHER", "auto")


def set_gather_mode(mode: str) -> None:
    """'auto' (vector on TPU, scalar elsewhere), 'scalar', or 'vector'.

    The mode is read at TRACE time, so a change must invalidate every
    cached executable that baked the old mode in — otherwise an A/B
    (bench calibration, parity tests) would silently re-time the cached
    path and measure nothing. Flipping the mode is a rare, human-driven
    event; the recompile cost is accepted."""
    global _gather_mode
    if mode not in ("auto", "scalar", "vector"):
        raise ValueError(f"unknown gather mode {mode!r}")
    if mode != _gather_mode:
        _gather_mode = mode
        jax.clear_caches()


def gather_mode() -> str:
    return _gather_mode


def _vector_gather_rows(table2d: jax.Array, idx: jax.Array) -> jax.Array:
    # mode="clip": the default 'fill' pays an out-of-bounds select per
    # element (~12% of the pass on the v5e); table_gather's indices are
    # in-bounds by construction (idx < d => idx>>7 < rows), so clamping
    # is semantically a no-op and results stay bit-identical
    rows = jnp.take(table2d, jnp.right_shift(idx, 7), axis=0, mode="clip")
    lane = jnp.bitwise_and(idx, 127)
    onehot = lane[:, None] == jnp.arange(_LANES, dtype=idx.dtype)[None, :]
    return jnp.sum(jnp.where(onehot, rows, 0), axis=-1)


def table_gather(table: jax.Array, idx: jax.Array) -> jax.Array:
    """``table[idx]`` for a 1-D table, vectorized for TPU when profitable.

    Bit-identical to the serial gather on every path for normal floats
    (the lane select adds one real value and 127 zeros). The one
    exception, found by the property fuzz: SUBNORMAL table values
    (|x| < 1.2e-38 f32) flush to zero through the select-sum on
    flush-to-zero backends — the same flush every arithmetic op on TPU
    applies to them anyway, whereas the serial gather is a pure memory
    move and preserves the bits. 'auto' resolves per trace-time backend:
    the vector form pays an extra [m, 128] stream, which wins ~15x on TPU
    where the serial gather is the bottleneck but loses on CPU where the
    serial gather is already fast.
    """
    mode = _gather_mode
    if mode == "auto":
        # TPU only: the serial-gather pathology is a TPU lowering property
        # (measured docs/tpu_r05_logs/tpu_diag.log); GPUs and CPUs gather
        # words natively and would only pay the [m, 128] expansion
        mode = "vector" if jax.default_backend() == "tpu" else "scalar"
    if (mode == "scalar" or table.ndim != 1
            or idx.size < _GATHER_MIN_SIZE or table.shape[0] < _LANES):
        return table[idx]
    d = table.shape[0]
    dp = -(-d // _LANES) * _LANES
    table2d = jnp.pad(table, (0, dp - d)).reshape(dp // _LANES, _LANES)
    flat = idx.reshape(-1).astype(jnp.int32)
    m = flat.shape[0]
    if m <= _GATHER_CHUNK:
        out = _vector_gather_rows(table2d, flat)
    else:
        c = -(-m // _GATHER_CHUNK)
        flat = jnp.pad(flat, (0, c * _GATHER_CHUNK - m))  # pad idx 0: valid
        out = jax.lax.map(
            lambda ix: _vector_gather_rows(table2d, ix),
            flat.reshape(c, _GATHER_CHUNK),
        ).reshape(-1)[:m]
    return out.reshape(idx.shape)


@struct.dataclass
class SparseFeatures:
    """Row-padded sparse feature matrix (ELL layout).

    Attributes:
      indices: int32 ``[n, k]`` column ids; padding slots may hold any valid
        index (conventionally 0) because their value is 0.
      values: ``[n, k]`` feature values; 0.0 in padding slots. ``None``
        declares the implicit-ones (binary/categorical) layout: every slot
        is a real feature of value 1.0 — Criteo-style one-hot rows with a
        uniform slot count. This halves the bytes every sparse pass touches
        (the TPU hot loop is HBM-bound — docs/PERF.md) and is only valid
        when NO slot is padding (row-level padding with weight-0 rows stays
        safe: their loss/gradient contributions are weight-multiplied).
      dim: static number of feature columns (the dense width).
    """

    indices: jax.Array
    values: Optional[jax.Array]
    dim: int = struct.field(pytree_node=False)

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    def slice_rows(self, start: int, size: int) -> "SparseFeatures":
        return SparseFeatures(
            indices=jax.lax.dynamic_slice_in_dim(self.indices, start, size, 0),
            values=(None if self.values is None else
                    jax.lax.dynamic_slice_in_dim(self.values, start, size, 0)),
            dim=self.dim,
        )

    def todense(self) -> jax.Array:
        n, k = self.indices.shape
        dtype = jnp.float32 if self.values is None else self.values.dtype
        out = jnp.zeros((n, self.dim), dtype)
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, k))
        vals = (jnp.ones((n, k), dtype) if self.values is None
                else self.values)
        return out.at[rows, self.indices].add(vals)


Features = Union[jax.Array, SparseFeatures]


@struct.dataclass
class CSCTranspose:
    """Column-sorted view of a SparseFeatures batch for scatter-free
    transpose products.

    TPU rationale: XLA lowers ``.at[idx].add`` (the reference's gradient-side
    ``treeAggregate`` axpy) to a serialized scatter on TPU. Because the
    sparsity pattern is FIXED across optimizer iterations, we sort the
    nonzeros by column once (argsort + searchsorted, on device, inside the
    jitted fit) and compute ``X^T d`` as gather → cumsum → boundary
    difference: every step vectorizes on the VPU, and the result is
    deterministic (no atomics, no scatter ordering).

    Attributes:
      values: [nnz] feature values sorted by column id.
      rows: [nnz] int32 row id of each sorted nonzero.
      col_starts: [dim+1] int32; column j's nonzeros occupy
        ``values[col_starts[j]:col_starts[j+1]]``.
    """

    values: Optional[jax.Array]  # None under the implicit-ones layout
    rows: jax.Array
    col_starts: jax.Array
    # sorted column id per nonzero (== the sort key). Optional: only the
    # segment-sum apply needs it; cumsum-difference works from col_starts.
    cols: Optional[jax.Array] = None


def build_csc_transpose(indices: jax.Array, values: Optional[jax.Array],
                        dim: int, with_cols: bool = True) -> CSCTranspose:
    """Sort the padded ELL nonzeros by column (pure jax; jit/shard_map safe).
    Padding slots (value 0) are kept — they land in their index's run and
    contribute 0 to every product. ``values=None`` (implicit ones) keeps
    the sorted view value-free too. ``with_cols=False`` drops the sorted
    column-id array (+4 B/nnz) when the segment-sum apply won't be used —
    in-fit builds are dead-code-eliminated by XLA either way, but a
    precomputed view materializes every stored leaf."""
    n, k = indices.shape
    flat_idx = indices.reshape(-1)
    order = jnp.argsort(flat_idx)
    sorted_cols = flat_idx[order]
    return CSCTranspose(
        values=None if values is None else values.reshape(-1)[order],
        rows=(order // k).astype(jnp.int32),
        col_starts=jnp.searchsorted(
            sorted_cols, jnp.arange(dim + 1, dtype=jnp.int32), side="left"
        ).astype(jnp.int32),
        cols=sorted_cols.astype(jnp.int32) if with_cols else None,
    )


def csc_transpose_apply(csc: CSCTranspose, d: jax.Array,
                        precise: bool = False,
                        block: int = 1 << 16) -> jax.Array:
    """``X^T d`` from the column-sorted view, with no scatter.

    A single global prefix sum followed by boundary differencing is
    numerically unsound in f32: the difference ``prefix[b] - prefix[a]``
    cancels catastrophically once the running prefix dwarfs a column's own
    sum — ~sqrt(nnz)*eps relative error for sign-mixed gradients (~1e-3 at
    82M nnz, measured on hardware), and *unbounded* relative error for the
    all-positive ``d2`` contributions of the HVP path, where the prefix
    grows linearly.

    The default is therefore a BLOCKED two-level scheme whose error does
    not grow with nnz: contributions reshape to [B, block]; each block
    gets a local f32 cumsum (magnitudes bounded by one block); a column
    contained in one block differences only local prefixes; a column
    spanning blocks takes (suffix of its first block) + (sum of interior
    block totals) + (head of its last block). Interior sums fall back to
    a block-total prefix difference, but only columns wider than a whole
    block (>= ``block`` nonzeros) ever take it — and for those the
    interior sum *is* the dominant term, so no cancellation. Cost: the
    same one pass of cumsum traffic, plus O(dim) boundary gathers.

    ``precise=True`` keeps the old full-f64 global prefix (meaningful
    only under jax_enable_x64; without it, f64 silently degrades to f32,
    which is exactly what the blocked default repairs)."""
    dg = table_gather(d, csc.rows)
    contrib = dg if csc.values is None else csc.values * dg
    if precise:
        prefix = jnp.concatenate([
            jnp.zeros((1,), jnp.float64),
            jnp.cumsum(contrib.astype(jnp.float64)),
        ])
        out = prefix[csc.col_starts[1:]] - prefix[csc.col_starts[:-1]]
        return out.astype(d.dtype)

    nnz = contrib.shape[0]
    if nnz == 0:
        return jnp.zeros((csc.col_starts.shape[0] - 1,), d.dtype)
    T = min(block, nnz)
    B = -(-nnz // T)
    padded = jnp.pad(contrib, (0, B * T - nnz)).reshape(B, T)
    local = jnp.cumsum(padded, axis=1)  # [B, T] inclusive, block-local
    bt = local[:, -1]  # [B] block totals
    return blocked_boundary_combine(local.reshape(-1), bt, csc.col_starts,
                                    T).astype(d.dtype)


def blocked_boundary_combine(local_flat: jax.Array, bt: jax.Array,
                             col_starts: jax.Array, T: int) -> jax.Array:
    """Column sums from BLOCK-LOCAL inclusive prefixes.

    ``local_flat``: [B*T] inclusive prefix sums that restart at every block
    boundary; ``bt``: [B] block totals. Shared by the XLA cumsum path and
    the Pallas per-tile scan kernel (both produce exactly this pair).
    A column inside one block differences local prefixes only; a spanning
    column takes first-block suffix + interior block totals + last-block
    head, so no difference ever cancels against a prefix that outgrew the
    column's own sum (see ``csc_transpose_apply``)."""
    B = bt.shape[0]
    # exclusive prefix of block totals; only consulted for columns spanning
    # >= 1 full interior block
    BP = jnp.concatenate([jnp.zeros((1,), bt.dtype), jnp.cumsum(bt)])

    cs = col_starts.astype(jnp.int32)
    b, r = cs // T, cs % T
    # local exclusive prefix at each boundary: local[b, r-1], 0 at r == 0
    lp = jnp.where(r > 0, local_flat[jnp.maximum(cs - 1, 0)],
                   jnp.zeros((), local_flat.dtype))
    b0, b1 = b[:-1], b[1:]
    lp0, lp1 = lp[:-1], lp[1:]
    same = b0 == b1
    # bt[b0] is only used on the spanning branch, where b0 < B always
    suffix0 = bt[jnp.minimum(b0, B - 1)] - lp0
    mid = BP[b1] - BP[jnp.minimum(b0 + 1, B)]  # exact 0 when b1 == b0 + 1
    return jnp.where(same, lp1 - lp0, suffix0 + mid + lp1)


def csc_segment_apply(csc: CSCTranspose, d: jax.Array) -> jax.Array:
    """``X^T d`` from the column-sorted view as a SORTED segment sum: the
    scatter carries ``indices_are_sorted=True``, which XLA can lower far
    better than the unordered ELL scatter (no collision ordering to
    respect). A third strategy for the per-hardware calibration next to
    the unordered scatter and the cumsum-difference."""
    if csc.cols is None:
        raise ValueError("csc.cols missing: rebuild the CSC view "
                         "(build_csc_transpose now stores sorted cols)")
    dg = table_gather(d, csc.rows)
    contrib = dg if csc.values is None else csc.values * dg
    dim = csc.col_starts.shape[0] - 1
    return jax.ops.segment_sum(contrib, csc.cols, num_segments=dim,
                               indices_are_sorted=True)


def margins(features: Features, w: jax.Array) -> jax.Array:
    """Per-row margin ``x_i . w`` for dense ``[n, d]`` or sparse features."""
    if isinstance(features, SparseFeatures):
        if features.values is None:  # implicit ones: no value read
            return jnp.sum(table_gather(w, features.indices), axis=-1)
        return jnp.sum(features.values * table_gather(w, features.indices),
                       axis=-1)
    return features @ w


def transpose_apply(features: Features, d: jax.Array) -> jax.Array:
    """``X^T d`` — the gradient-side contraction.

    Dense path is a plain matmul (MXU); sparse path is a scatter-add over the
    padded layout (padding contributes 0 because its value is 0; the
    implicit-ones layout scatters ``d`` directly).
    """
    if isinstance(features, SparseFeatures):
        if features.values is None:
            n, k = features.indices.shape
            contrib = jnp.broadcast_to(d[:, None], (n, k))
            out = jnp.zeros((features.dim,), d.dtype)
        else:
            contrib = features.values * d[:, None]
            out = jnp.zeros((features.dim,), contrib.dtype)
        return out.at[features.indices.reshape(-1)].add(contrib.reshape(-1))
    return features.T @ d


def feature_dim(features: Features) -> int:
    if isinstance(features, SparseFeatures):
        return features.dim
    return features.shape[1]


def num_rows(features: Features) -> int:
    if isinstance(features, SparseFeatures):
        return features.num_rows
    return features.shape[0]


def row_squares_apply(features: Features, d: jax.Array) -> jax.Array:
    """``sum_i d_i * x_i^2`` (elementwise square) — used for diagonal Hessians
    and per-feature second moments (variance computation, SURVEY.md §3.2)."""
    if isinstance(features, SparseFeatures):
        if features.values is None:  # 1^2 == 1
            return transpose_apply(features, d)
        contrib = (features.values**2) * d[:, None]
        out = jnp.zeros((features.dim,), contrib.dtype)
        return out.at[features.indices.reshape(-1)].add(contrib.reshape(-1))
    return (features**2).T @ d


@struct.dataclass
class LabeledBatch:
    """A batch of weighted, offset labeled examples (the reference's
    ``LabeledPoint`` batched — SURVEY.md §3.1).

    ``offsets`` are added to margins before the loss (the residual-score /
    GAME-coordinate mechanism rides on them); ``weights`` multiply per-example
    losses. Objectives use *sum* (not mean) semantics to match the reference's
    aggregation.
    """

    features: Features
    labels: jax.Array
    offsets: jax.Array
    weights: jax.Array

    @property
    def num_examples(self) -> int:
        return self.labels.shape[0]

    @property
    def dim(self) -> int:
        return feature_dim(self.features)

    def with_offsets(self, offsets: jax.Array) -> "LabeledBatch":
        return self.replace(offsets=offsets)

    def slice_rows(self, start: int, size: int) -> "LabeledBatch":
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, start, size, 0)
        feats = (
            self.features.slice_rows(start, size)
            if isinstance(self.features, SparseFeatures)
            else sl(self.features)
        )
        return LabeledBatch(feats, sl(self.labels), sl(self.offsets), sl(self.weights))


def make_batch(
    features,
    labels,
    offsets=None,
    weights=None,
    dtype=jnp.float32,
) -> LabeledBatch:
    """Build a LabeledBatch from host data (numpy / lists / scipy.sparse)."""
    dtype = jax.dtypes.canonicalize_dtype(dtype)
    labels = jnp.asarray(labels, dtype)
    n = labels.shape[0]
    if offsets is None:
        offsets = jnp.zeros((n,), dtype)
    else:
        offsets = jnp.asarray(offsets, dtype)
    if weights is None:
        weights = jnp.ones((n,), dtype)
    else:
        weights = jnp.asarray(weights, dtype)
    if not isinstance(features, (jax.Array, SparseFeatures)):
        features = _coerce_features(features, dtype)
    return LabeledBatch(features, labels, offsets, weights)


def _coerce_features(features, dtype) -> Features:
    try:
        import scipy.sparse as sp

        if sp.issparse(features):
            return sparse_from_scipy(features, dtype=dtype)
    except ImportError:  # pragma: no cover
        pass
    return jnp.asarray(np.asarray(features), dtype)


def sparse_from_scipy(
    mat, dtype=jnp.float32, pad_to: int | None = None, allow_truncate: bool = False
) -> SparseFeatures:
    """Convert a scipy.sparse matrix to the padded ELL layout (vectorized —
    this sits on the bulk ingestion path). Raises if ``pad_to`` would drop
    nonzeros, unless ``allow_truncate`` (deliberate feature capping)."""
    import scipy.sparse as sp

    csr = sp.csr_matrix(mat)
    n, d = csr.shape
    nnz_per_row = np.diff(csr.indptr)
    max_nnz = int(nnz_per_row.max()) if n else 0
    k = int(pad_to) if pad_to is not None else max_nnz
    if k < max_nnz and not allow_truncate:
        raise ValueError(
            f"pad_to={k} < max row nnz {max_nnz}; pass allow_truncate=True to cap"
        )
    k = max(k, 1)
    indices = np.zeros((n, k), np.int32)
    values = np.zeros((n, k), np.float64)
    # position of each nonzero within its row
    rows = np.repeat(np.arange(n), nnz_per_row)
    cols = np.arange(csr.nnz) - np.repeat(csr.indptr[:-1], nnz_per_row)
    keep = cols < k
    indices[rows[keep], cols[keep]] = csr.indices[keep]
    values[rows[keep], cols[keep]] = csr.data[keep]
    return SparseFeatures(jnp.asarray(indices), jnp.asarray(values, dtype), dim=d)


def sparse_from_rows(
    rows, dim, dtype=jnp.float32, pad_to: int | None = None, allow_truncate: bool = False
) -> SparseFeatures:
    """Build padded sparse features from per-row (index, value) pair lists."""
    n = len(rows)
    max_nnz = max((len(r) for r in rows), default=0)
    k = int(pad_to) if pad_to is not None else max_nnz
    if k < max_nnz and not allow_truncate:
        raise ValueError(
            f"pad_to={k} < max row nnz {max_nnz}; pass allow_truncate=True to cap"
        )
    k = max(k, 1)
    indices = np.zeros((n, k), np.int32)
    values = np.zeros((n, k), np.float64)
    for i, row in enumerate(rows):
        for j, (idx, val) in enumerate(row[:k]):
            indices[i, j] = idx
            values[i, j] = val
    # XLA gather/scatter silently clamp out-of-range indices, which would
    # train on the wrong feature — validate on host at construction instead.
    if n and indices.max() >= dim:
        raise ValueError(f"feature index {indices.max()} out of range for dim={dim}")
    if n and indices.min() < 0:
        raise ValueError(f"negative feature index {indices.min()}")
    return SparseFeatures(jnp.asarray(indices), jnp.asarray(values, dtype), dim=dim)
