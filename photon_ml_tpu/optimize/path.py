"""Pathwise fixed-effect GLM training with KKT-certified safe screening.

The lambda grid is the last repeated cost in fixed-effect training: the
driver warm-starts coefficients across ``--reg-weights`` but every lambda
still solves over ALL features, although at the sparse (large-lambda) end
of an elastic-net path almost every coordinate of the solution is zero.
Strong-rule screening is the standard fix in distributed CD for
regularized GLMs (arxiv 1611.02101) and the core of Snap ML's
hierarchical solver (arxiv 1803.06333): walking the grid in decreasing
order, a feature whose data-gradient magnitude at the previous lambda's
solution falls below the sequential threshold
(``ops.regularization.screening_threshold``) is frozen at zero, the
restricted problem is solved over the survivors, and a full-gradient KKT
check certifies the screen — violators re-enter and the solve repeats, so
a screened fit matches the unscreened fit within solver tolerance BY
CONSTRUCTION, never by hope. This is the fixed-effect twin of the
random-effect active-set CD (``docs/descent.md``): same frozen-frontier
idea, applied across the regularization path instead of across sweeps.

Cost model per lambda (screen on, no repair round): one restricted solve
over a power-of-two bucket of the candidate width plus exactly ONE full
data pass — the certification gradient, which is then REUSED as the next
lambda's screening gradient. Compare one full-width solve (tens of full
passes) per lambda without screening.

Restriction is an ELL column remap, not a data rebuild: member columns
map through a LUT to ``[0, bucket)`` (intercept pinned to restricted
slot 0 so the restricted objective's static fields never change),
non-member slots keep index 0 with value 0 — the restricted batch has
the same ``[n, k]`` shape with only the static ``dim`` shrunk, and the
restricted margins are addend-for-addend the same sums as the full
margins at the scattered-back point. Widths ride a power-of-two bucket
ladder (``pad_to_bucket``) with ONE restricted objective shared by every
bucket, so the jit ladder stays flat as the active set shrinks: after
warm-up, new lambdas compile nothing.

Both data planes are served: in-memory (``fit_distributed`` on a mesh,
full-gradient passes through one cached ``distributed_value_and_grad``
kernel) and out-of-core (``fit_streaming`` over host chunks, with
``_RestrictedChunks`` remapping lazily per pass and
``streaming_value_and_grad`` for the certification pass) — under the
driver's chunk cache the whole 50-lambda path is ONE decode of the data.

Normalization does NOT compose with screening: normalization arrays are
pytree leaves baked into the cached restricted runners, and the virtual
shift couples every column through the margin adjustment, so a frozen
column would still move the margins. ``PathSolver`` refuses the
combination up front instead of silently mis-screening.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.obs import metrics as obs_metrics
from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.ops.objective import GLMObjective, make_objective
from photon_ml_tpu.ops.regularization import (
    RegularizationContext,
    kkt_slack,
    screening_threshold,
)
from photon_ml_tpu.optimize.common import (
    OptimizationResult,
    OptimizerConfig,
    PathConfig,
)

_log = logging.getLogger("photon_ml_tpu")

__all__ = ["PathSolver", "PathLambdaStats", "next_power_of_two",
           "pad_to_bucket"]


def next_power_of_two(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    n = int(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def pad_to_bucket(n: int, floor: int = 1) -> int:
    """Power-of-two bucket width for a candidate set of size ``n`` with a
    lower bound of ``floor`` (tiny sets must not mint single-use
    compilations). Registered with photon-check's shape-helper set, so
    shapes routed through here stay on the compiled ladder."""
    return next_power_of_two(max(int(n), int(floor)))


@dataclasses.dataclass
class PathLambdaStats:
    """Per-lambda screening record: what the lambda log line, the
    ``photon_train_path_*`` metrics, ``BENCH_path.json`` and the resume
    fingerprint all read. ``screened_dim`` is the restricted width the
    FINAL solve ran over (the bucket; ``dim`` when the solve fell
    through to full width), so artifacts assert the restricted-problem
    geometry, not just the outcome."""

    lam: float
    lam_l1: float
    lam_l2: float
    dim: int
    candidate_size: int      # candidates entering the first restricted solve
    screened_dim: int        # restricted width of the final (accepted) solve
    features_frozen: int     # dim - final candidate count (0 on full solves)
    kkt_rounds: int          # solve rounds total; 1 = screen held first try
    kkt_violations: int      # violators re-admitted across repair rounds
    solver_iterations: int   # optimizer iterations summed over rounds
    full_grad_passes: int    # full data passes paid for screen init + certs
    fallback_full: bool      # repair budget exhausted -> full-width solve
    screen_rule: str
    certified: bool          # always True on return (full solves trivially)
    solver_tolerance: float
    solve_seconds: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class _PathState:
    """One warm snapshot per solved lambda: the solution, and (lazily)
    the certified data gradient at it — the next lambda's screening
    input. ``g`` is None when the state was seeded from a resume marker
    or produced by a full-width solve; ``_ensure_grad`` computes it on
    first use, which keeps resumed runs' candidate sets IDENTICAL to
    uninterrupted runs (both screen from the data gradient at the same
    point)."""

    lam: float
    lam_l1: float
    w: np.ndarray
    g: Optional[np.ndarray]


class _RestrictedChunks:
    """Lazy LUT-remapped view of a host chunk sequence: each access
    rebuilds the chunk with member columns remapped into ``[0, bucket)``
    and non-member slots zeroed — same ``[rows, k]`` shapes, so the
    streamed kernels' fixed-shape contract holds per bucket. Implicit-
    ones chunks must materialize values here (the member mask IS the
    value), costing the value plane's transfer back; screening still
    wins because the restricted gradient/margin width shrank."""

    def __init__(self, chunks: Sequence, member: np.ndarray,
                 lut: np.ndarray, value_dtype):
        self._chunks = chunks
        self._member = member
        self._lut = lut
        self._vdtype = value_dtype

    def __len__(self) -> int:
        return len(self._chunks)

    def _remap(self, c):
        from photon_ml_tpu.parallel.streaming import HostChunk

        m = self._member[c.indices]
        idx = np.where(m, self._lut[c.indices], 0)
        ones = np.ones(c.indices.shape, self._vdtype)
        vals = ones if c.values is None else c.values
        return HostChunk(
            indices=np.ascontiguousarray(idx, np.int32),
            values=np.where(m, vals, np.zeros((), self._vdtype)),
            labels=c.labels, offsets=c.offsets, weights=c.weights)

    def __getitem__(self, i):
        return self._remap(self._chunks[i])

    def __iter__(self):
        for c in self._chunks:
            yield self._remap(c)


class PathSolver:
    """Pathwise fixed-effect solver: screen -> restricted solve -> KKT
    certify, one lambda at a time, with warm state shared across calls.

    The caller drives the grid (the driver walks it in decreasing order;
    the tuner calls out of order — any solved neighbor works as a warm/
    screening source because certification is unconditional). Exactly one
    of ``batch`` (in-memory: a LabeledBatch + mesh) or ``chunks`` (+
    ``dim``; out-of-core host chunks, ``mesh`` optional) must be given.

    ``solve(reg_weight)`` returns ``(OptimizationResult, PathLambdaStats)``
    with the result's ``w`` scattered back to full width and
    ``solver_tolerance``/``screened_dim`` attached, so every consumer can
    assert the restricted-problem geometry."""

    def __init__(
        self,
        objective: GLMObjective,
        reg: RegularizationContext,
        *,
        batch=None,
        chunks: Optional[Sequence] = None,
        dim: Optional[int] = None,
        mesh=None,
        axis: str = "data",
        optimizer: str = "lbfgs",
        config: OptimizerConfig = OptimizerConfig(),
        path_config: PathConfig = PathConfig(),
        dtype=jnp.float32,
        sparse_grad: str = "auto",
        precomputed_csc=None,
        prefetch_depth: Optional[int] = None,
        w0=None,
    ):
        if (batch is None) == (chunks is None):
            raise ValueError("pass exactly one of batch= or chunks=")
        if objective.normalization is not None \
                and path_config.screen != "off":
            raise ValueError(
                "screening does not compose with normalization (the "
                "virtual shift couples all columns through the margin "
                "adjustment and the factors bake into cached restricted "
                "runners); fit unnormalized or pass screen='off'")
        self._objective = objective
        self._reg = reg
        self._mesh = mesh
        self._axis = axis
        self._optimizer = optimizer
        self._config = config
        self._pc = path_config
        self._dtype = dtype
        self._sparse_grad = sparse_grad
        self._prefetch_depth = prefetch_depth
        self._streaming = chunks is not None
        self._states: List[_PathState] = []
        self._init_probe = None  # (w_init, g_init, lam1_max) — lazy
        self.total_iterations = 0  # across every solve (tuner accounting)

        # one restricted objective serves EVERY bucket: its static fields
        # (loss, regularize_intercept, intercept slot pinned to 0) do not
        # depend on the bucket width, so the runner/kernel caches keyed on
        # its identity hold one ladder of shape-specialized executables
        self._robj = make_objective(
            objective.loss, None, objective.regularize_intercept,
            0 if objective.intercept_index >= 0 else -1)

        if self._streaming:
            if dim is None:
                raise ValueError("chunks= mode needs dim=")
            self._chunks = chunks
            self._dim = int(dim)
            self._np_dtype = np.dtype(jnp.dtype(dtype).name)
            from photon_ml_tpu.parallel.streaming import (
                streaming_value_and_grad)

            self._stream_fg = streaming_value_and_grad(
                objective, chunks, self._dim, dtype, mesh, axis,
                prefetch_depth)
            self._pcsc = None
        else:
            if mesh is None:
                raise ValueError("batch= mode needs mesh=")
            from photon_ml_tpu.parallel.data_parallel import (
                cached_jit, distributed_value_and_grad, resolve_sparse_grad)
            from photon_ml_tpu.parallel.mesh import shard_batch
            from photon_ml_tpu.types import SparseFeatures

            self._batch = batch
            feats = batch.features
            if isinstance(feats, SparseFeatures):
                self._dim = feats.dim
                self._h_indices = np.asarray(feats.indices)
                self._h_values = (None if feats.values is None
                                  else np.asarray(feats.values))
                self._h_dense = None
            else:
                dense = np.asarray(feats)
                self._dim = dense.shape[1]
                self._h_dense = dense
                self._h_indices = self._h_values = None
                # device-resident copy with one trailing all-zero column:
                # restricted batches are built by a jitted device gather
                # (pad slots index the zero column), not a host-side
                # column copy — the host gather+pad dominated the
                # restricted solve cost at bench sizes
                self._d_dense_z = jax.device_put(
                    np.pad(dense, ((0, 0), (0, 1))))
                self._gather_k = cached_jit(
                    self._robj, ("path_gather", mesh, axis),
                    lambda: lambda x, idx: x[:, idx])
            self._h_labels = np.asarray(batch.labels)
            self._h_offsets = np.asarray(batch.offsets)
            self._h_weights = np.asarray(batch.weights)
            self._np_dtype = self._h_labels.dtype
            # the full problem's precomputed CSC serves full-width solves
            # only (restricted geometry differs); it is an error to hold
            # one when the resolved sparse-grad path would not read it
            resolved = resolve_sparse_grad(sparse_grad, feats)
            self._pcsc = precomputed_csc if resolved.startswith("csc") \
                else None
            # certification kernel: the batch is sharded ONCE and the fg
            # runner cached on the full objective, so every lambda's full-
            # gradient pass reuses one executable
            self._sbatch = shard_batch(batch, mesh, axis)
            self._full_fg = cached_jit(
                objective, ("path_full_fg", mesh, axis),
                lambda: distributed_value_and_grad(objective, mesh, axis))
        self._zero = jnp.zeros((), self._np_dtype)
        if w0 is not None:
            self._w_init = np.asarray(w0, self._np_dtype)
        else:
            self._w_init = np.zeros((self._dim,), self._np_dtype)
        self._penalized = np.ones((self._dim,), bool)
        if objective.intercept_index >= 0 \
                and not objective.regularize_intercept:
            self._penalized[objective.intercept_index] = False

    # -- full data-gradient pass (screen init + certification) -------------
    def _full_grad(self, w: np.ndarray) -> np.ndarray:
        """Data-only gradient (l2=0) at ``w`` — exactly the quantity both
        the screening rules and the zero-coordinate KKT condition are
        stated in (at a zero coordinate the ridge term contributes
        nothing)."""
        w_dev = jnp.asarray(w, self._np_dtype)
        if self._streaming:
            _f, g = self._stream_fg(w_dev, self._zero)
        else:
            _f, g = self._full_fg(w_dev, self._sbatch, self._zero)
        return np.asarray(g)

    def _ensure_grad(self, state: _PathState) -> int:
        if state.g is not None:
            return 0
        state.g = self._full_grad(state.w)
        return 1

    # -- warm/screening source ----------------------------------------------
    def _warm_source(self, lam: float) -> Optional[_PathState]:
        """Nearest solved lambda ABOVE ``lam`` (the sequential rules'
        assumption); if the caller runs out of order and none exists, the
        largest solved lambda below — over-aggressive screening there is
        repaired by the KKT loop like any other over-screen."""
        above = [s for s in self._states if s.lam >= lam]
        if above:
            return min(above, key=lambda s: s.lam)
        if self._states:
            return max(self._states, key=lambda s: s.lam)
        return None

    def _probe(self):
        """First-lambda screening source: the data gradient at the start
        point, whose max penalized magnitude is lambda_max — the smallest
        L1 weight at which every penalized coordinate is zero. Computed
        once, lazily."""
        if self._init_probe is None:
            g0 = self._full_grad(self._w_init)
            lam1_max = float(np.max(np.abs(g0) * self._penalized))
            self._init_probe = (self._w_init, g0, lam1_max)
        return self._init_probe

    def lambda_max(self) -> float:
        """Max penalized |data gradient| at the start point: the L1
        weight above which the penalized solution is all-zero (grid
        construction helper)."""
        return self._probe()[2]

    def seed_state(self, lam: float, w) -> None:
        """Install a solved lambda's solution without re-solving (lambda-
        granular resume): the gradient is computed lazily on first use,
        so replayed-path candidate sets match the uninterrupted run's."""
        w = np.asarray(w, self._np_dtype)
        self._keep(_PathState(lam=float(lam),
                              lam_l1=self._reg.l1_weight(float(lam)),
                              w=w, g=None))

    def _keep(self, state: _PathState) -> None:
        if self._pc.keep_states:
            self._states.append(state)
        else:
            self._states = [state]

    def reset_states(self) -> None:
        """Drop every warm/screening state and the lambda_max probe but
        KEEP the compiled-kernel ladder (caches key on the objective
        identities, which don't change). A re-walked grid then repeats
        the exact screen/solve trajectory on warm kernels — how the
        bench separates compile time from compute (``bench.py path``)."""
        self._states = []
        self._init_probe = None
        self.total_iterations = 0

    # -- restricted problem construction -------------------------------------
    def _selection(self, member: np.ndarray):
        """(cols, lut) for a member mask, intercept pinned to restricted
        slot 0 so the restricted objective's static intercept index is a
        constant across buckets and rounds."""
        ii = self._objective.intercept_index
        cols = np.flatnonzero(member)
        if ii >= 0:
            cols = np.concatenate(([ii], cols[cols != ii]))
        lut = np.zeros((self._dim,), np.int32)
        lut[cols] = np.arange(cols.shape[0], dtype=np.int32)
        return cols, lut

    def _restrict_batch(self, member, lut, bucket):
        from photon_ml_tpu.types import LabeledBatch, SparseFeatures

        if self._h_dense is not None:
            cols, _ = self._selection(member)
            idx = np.full((bucket,), self._dim, np.int32)
            idx[: cols.shape[0]] = cols
            feats = self._gather_k(self._d_dense_z, jnp.asarray(idx))
        else:
            m = member[self._h_indices]
            idx = np.ascontiguousarray(
                np.where(m, lut[self._h_indices], 0), np.int32)
            ones = np.ones(self._h_indices.shape, self._np_dtype)
            vals = ones if self._h_values is None else self._h_values
            feats = SparseFeatures(
                indices=idx,
                values=np.where(m, vals, np.zeros((), self._np_dtype)),
                dim=bucket)
        return LabeledBatch(feats, self._h_labels, self._h_offsets,
                            self._h_weights)

    # -- solves ---------------------------------------------------------------
    def _resolve_opt(self, lam_l1: float) -> str:
        # the smooth optimizers cannot represent the L1 subgradient;
        # mirror fit_streaming's auto-switch for the in-memory path too
        opt = "lbfgs" if self._optimizer == "auto" else self._optimizer
        return "owlqn" if lam_l1 > 0 else opt

    def _solve_restricted(self, member, lut, bucket, w_warm, lam_l1,
                          lam_l2, run_cfg) -> OptimizationResult:
        cols, _ = self._selection(member)
        w0 = np.zeros((bucket,), self._np_dtype)
        w0[: cols.shape[0]] = w_warm[cols]
        opt = self._resolve_opt(lam_l1)
        if self._streaming:
            from photon_ml_tpu.parallel.streaming import fit_streaming

            rchunks = _RestrictedChunks(self._chunks, member, lut,
                                        self._np_dtype)
            return fit_streaming(
                self._robj, rchunks, bucket, w0, l2=lam_l2, config=run_cfg,
                dtype=self._dtype, mesh=self._mesh, axis=self._axis,
                optimizer=opt, l1=lam_l1,
                prefetch_depth=self._prefetch_depth)
        from photon_ml_tpu.parallel.data_parallel import fit_distributed

        rbatch = self._restrict_batch(member, lut, bucket)
        return fit_distributed(
            self._robj, rbatch, self._mesh, jnp.asarray(w0), l2=lam_l2,
            l1=lam_l1, optimizer=opt, config=run_cfg, axis=self._axis,
            sparse_grad=self._sparse_grad)

    def _solve_full(self, w_warm, lam_l1, lam_l2,
                    run_cfg) -> OptimizationResult:
        w0 = jnp.asarray(w_warm, self._np_dtype)
        opt = self._resolve_opt(lam_l1)
        if self._streaming:
            from photon_ml_tpu.parallel.streaming import fit_streaming

            return fit_streaming(
                self._objective, self._chunks, self._dim, w0, l2=lam_l2,
                config=run_cfg, dtype=self._dtype, mesh=self._mesh,
                axis=self._axis, optimizer=opt, l1=lam_l1,
                prefetch_depth=self._prefetch_depth)
        from photon_ml_tpu.parallel.data_parallel import fit_distributed

        return fit_distributed(
            self._objective, self._batch, self._mesh, w0, l2=lam_l2,
            l1=lam_l1, optimizer=opt, config=run_cfg, axis=self._axis,
            sparse_grad=self._sparse_grad, precomputed_csc=self._pcsc)

    # -- the per-lambda walk --------------------------------------------------
    def solve(self, reg_weight: float, tolerance: Optional[float] = None
              ) -> tuple:
        """Solve one lambda: screen from the warm source's certified
        gradient, solve the restricted problem on the bucket ladder, KKT-
        certify, repair and re-solve on violations (full-width fallback
        after ``max_kkt_rounds``). Returns ``(OptimizationResult,
        PathLambdaStats)``; the result's ``w`` is full-width and carries
        ``solver_tolerance`` and ``screened_dim``."""
        lam = float(reg_weight)
        lam_l1 = self._reg.l1_weight(lam)
        lam_l2 = self._reg.l2_weight(lam)
        tol = self._config.tolerance if tolerance is None else tolerance
        run_cfg = (self._config if tolerance is None
                   else dataclasses.replace(self._config,
                                            tolerance=tolerance))
        t0 = time.perf_counter()
        with obs_trace.span("glm.path_lambda", cat="train", lam=lam,
                            l1=lam_l1, l2=lam_l2,
                            rule=self._pc.screen) as sp:
            res, stats = self._solve_one(lam, lam_l1, lam_l2, run_cfg,
                                         float(tol))
            stats.solve_seconds = time.perf_counter() - t0
            sp.set(candidates=stats.candidate_size,
                   screened_dim=stats.screened_dim,
                   frozen=stats.features_frozen,
                   kkt_rounds=stats.kkt_rounds,
                   kkt_violations=stats.kkt_violations,
                   fallback=stats.fallback_full,
                   iterations=stats.solver_iterations)
        obs_metrics.training_metrics().record_path_lambda(
            frozen=stats.features_frozen, rounds=stats.kkt_rounds,
            violations=stats.kkt_violations,
            full_grad_passes=stats.full_grad_passes,
            fallback=stats.fallback_full)
        self.total_iterations = self.total_iterations \
            + stats.solver_iterations
        _log.info(
            "path lambda=%g rule=%s: candidates=%d/%d screened_dim=%d "
            "frozen=%d kkt_rounds=%d violations=%d iters=%d tol=%g "
            "fallback=%s", lam, stats.screen_rule, stats.candidate_size,
            stats.dim, stats.screened_dim, stats.features_frozen,
            stats.kkt_rounds, stats.kkt_violations,
            stats.solver_iterations, stats.solver_tolerance,
            stats.fallback_full)
        return res, stats

    def _solve_one(self, lam, lam_l1, lam_l2, run_cfg, tol):
        stats = PathLambdaStats(
            lam=lam, lam_l1=lam_l1, lam_l2=lam_l2, dim=self._dim,
            candidate_size=self._dim, screened_dim=self._dim,
            features_frozen=0, kkt_rounds=0, kkt_violations=0,
            solver_iterations=0, full_grad_passes=0, fallback_full=False,
            screen_rule=self._pc.screen, certified=False,
            solver_tolerance=tol, solve_seconds=0.0)

        src = self._warm_source(lam)
        if self._pc.screen == "off" or lam_l1 <= 0:
            # warm-started full-width fit: the pre-path behavior (also
            # the no-L1 case, where nothing is ever exactly zero and
            # there is nothing to screen). Trivially certified: the
            # solver's own convergence test covered every coordinate.
            w_warm = src.w if src is not None else self._w_init
            res = self._solve_full(w_warm, lam_l1, lam_l2, run_cfg)
            stats.kkt_rounds = 1
            stats.solver_iterations = int(res.iterations)
            stats.certified = True
            w_full = np.asarray(res.w)
            self._keep(_PathState(lam, lam_l1, w_full, None))
            return self._finish(res, w_full, self._dim, tol), stats

        if src is not None:
            stats.full_grad_passes = stats.full_grad_passes \
                + self._ensure_grad(src)
            w_prev, g_prev, lam_l1_prev = src.w, src.g, src.lam_l1
        else:
            w_prev, g_prev, lam1_max = self._probe()
            stats.full_grad_passes = stats.full_grad_passes + 1
            lam_l1_prev = max(lam1_max, lam_l1)

        thr = screening_threshold(self._pc.screen, lam_l1,
                                  max(lam_l1_prev, lam_l1),
                                  self._pc.screen_slack)
        member = (np.abs(g_prev) >= thr) | (w_prev != 0) | ~self._penalized
        stats.candidate_size = int(np.count_nonzero(member))

        w_full = np.asarray(w_prev, self._np_dtype).copy()
        res = None
        g_cert: Optional[np.ndarray] = None
        while True:
            stats.kkt_rounds = stats.kkt_rounds + 1
            n_sel = int(np.count_nonzero(member))
            bucket = pad_to_bucket(n_sel, self._pc.min_bucket)
            over_budget = stats.kkt_rounds > self._pc.max_kkt_rounds
            if bucket >= self._dim or over_budget:
                # nothing to gain from restriction (or the repair budget
                # is spent): full-width solve, certified by construction
                stats.fallback_full = over_budget
                res = self._solve_full(w_full, lam_l1, lam_l2, run_cfg)
                stats.solver_iterations = stats.solver_iterations \
                    + int(res.iterations)
                stats.screened_dim = self._dim
                stats.features_frozen = 0
                stats.certified = True
                w_full = np.asarray(res.w)
                g_cert = None  # next lambda recomputes lazily (one pass)
                break
            cols, lut = self._selection(member)
            res = self._solve_restricted(member, lut, bucket, w_full,
                                         lam_l1, lam_l2, run_cfg)
            stats.solver_iterations = stats.solver_iterations \
                + int(res.iterations)
            w_r = np.asarray(res.w)
            w_full = np.zeros((self._dim,), self._np_dtype)
            w_full[cols] = w_r[: cols.shape[0]]
            # certification: ONE full data pass; at screened (zero)
            # coordinates the elastic-net KKT condition is |g_j| <= l1
            g_cert = self._full_grad(w_full)
            stats.full_grad_passes = stats.full_grad_passes + 1
            slack = kkt_slack(lam_l1, self._pc.kkt_tol)
            viol = (~member) & (np.abs(g_cert) > lam_l1 + slack)
            nv = int(np.count_nonzero(viol))
            if nv == 0:
                stats.screened_dim = bucket
                stats.features_frozen = self._dim - n_sel
                stats.certified = True
                break
            stats.kkt_violations = stats.kkt_violations + nv
            member = member | viol

        self._keep(_PathState(lam, lam_l1, w_full, g_cert))
        return self._finish(res, w_full, stats.screened_dim, tol), stats

    def _finish(self, res: OptimizationResult, w_full: np.ndarray,
                screened_dim: int, tol: float) -> OptimizationResult:
        return res._replace(w=jnp.asarray(w_full),
                            solver_tolerance=float(tol),
                            screened_dim=int(screened_dim))

    # -- instrumentation ------------------------------------------------------
    def compiled_kernel_count(self) -> int:
        """Compiled executables across the full objective's cached
        kernels AND the shared restricted objective's bucket ladder — the
        bench's flat-compile gate: after the ladder warms, this number
        must not move."""
        from photon_ml_tpu.parallel.data_parallel import (
            compiled_kernel_count)

        return compiled_kernel_count(self._objective) \
            + compiled_kernel_count(self._robj)
