"""Jitted TRON: trust-region Newton with a conjugate-gradient inner loop.

Equivalent of the reference's own ``optimization.TRON`` implementation (from
LIBLINEAR's algorithm, Lin & Moré — SURVEY.md §3.1; reference mount empty).
The decisive TPU difference (SURVEY.md §4.2): the reference pays one full
cluster ``treeAggregate`` per CG step for each Hessian-vector product; here an
HVP is forward-over-reverse autodiff inside the same XLA program — roughly two
fused gradient passes, with any cross-device reduction riding ICI.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    OptimizationResult,
    OptimizerConfig,
    converged_check,
    init_history,
    l2_norm,
    match_vma_tree,
)

# Lin-Moré / LIBLINEAR constants
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


class _CGState(NamedTuple):
    s: jax.Array
    r: jax.Array
    d: jax.Array
    rr: jax.Array
    i: jax.Array
    done: jax.Array


def _steihaug_cg(hvp: Callable, g: jax.Array, delta, cg_tol, max_cg: int):
    """Approximately minimize q(s) = g.s + 0.5 s.H.s within ||s|| <= delta."""

    def boundary_tau(s, d):
        sd = jnp.sum(s * d)
        dd = jnp.sum(d * d)
        ss = jnp.sum(s * s)
        disc = jnp.sqrt(jnp.maximum(sd * sd + dd * (delta * delta - ss), 0.0))
        return (-sd + disc) / jnp.maximum(dd, jnp.finfo(d.dtype).tiny)

    def body(st: _CGState) -> _CGState:
        Hd = hvp(st.d)
        dHd = jnp.sum(st.d * Hd)
        neg_curv = dHd <= 0
        alpha = st.rr / jnp.where(neg_curv, 1.0, dHd)
        outside = l2_norm(st.s + alpha * st.d) >= delta
        hit = neg_curv | outside
        # one uniform update keeps r == -(g + H s) exact even on the
        # boundary step, so the caller can form prered from (s, r) alone
        step = jnp.where(hit, boundary_tau(st.s, st.d), alpha)
        s_new = st.s + step * st.d
        r_new = st.r - step * Hd
        rr_new = jnp.sum(r_new * r_new)
        beta = rr_new / jnp.maximum(st.rr, jnp.finfo(st.rr.dtype).tiny)
        d_new = r_new + beta * st.d
        done = hit | (jnp.sqrt(rr_new) <= cg_tol)
        return _CGState(s_new, r_new, d_new, rr_new, st.i + 1, done)

    def cond(st: _CGState):
        return (~st.done) & (st.i < max_cg)

    r0 = -g
    init = _CGState(jnp.zeros_like(g), r0, r0, jnp.sum(r0 * r0), jnp.asarray(0), jnp.asarray(False))
    st = lax.while_loop(cond, body, match_vma_tree(init, g))
    return st.s, st.r, st.i


class _State(NamedTuple):
    it: jax.Array
    w: jax.Array
    f: jax.Array
    g: jax.Array
    delta: jax.Array
    converged: jax.Array
    stalled: jax.Array
    loss_hist: jax.Array
    gnorm_hist: jax.Array


def tron(
    fun_and_grad: Callable,
    w0: jax.Array,
    config: OptimizerConfig = OptimizerConfig(),
    hvp: Callable | None = None,
    max_cg_iters: int | None = None,
) -> OptimizationResult:
    """Minimize fun(w). ``hvp(w, v)`` defaults to forward-over-reverse autodiff
    of the gradient part of ``fun_and_grad``."""
    dtype = w0.dtype
    if hvp is None:
        grad_only = lambda w: fun_and_grad(w)[1]

        def hvp(w, v):
            return jax.jvp(grad_only, (w,), (v,))[1]

    max_cg = max_cg_iters if max_cg_iters is not None else max(w0.shape[0], 20)
    f0, g0 = fun_and_grad(w0)
    g0_norm = l2_norm(g0)
    loss_hist, gnorm_hist = init_history(config.max_iters, f0.dtype)

    def body(s: _State) -> _State:
        cg_tol = 0.1 * l2_norm(s.g)
        step, r, _ = _steihaug_cg(lambda v: hvp(s.w, v), s.g, s.delta, cg_tol, max_cg)
        w_try = s.w + step
        f_try, g_try = fun_and_grad(w_try)
        gs = jnp.sum(s.g * step)
        # r == -(g + H step) from CG, so s.H.s = -g.s - r.s and
        # prered = -(g.s + s.H.s/2) = 0.5*(r.s - g.s) — no extra HVP needed
        prered = 0.5 * (jnp.sum(step * r) - gs)
        actred = s.f - f_try
        snorm = l2_norm(step)

        # Lin-Moré radius update via quadratic interpolation
        denom = f_try - s.f - gs
        alpha = jnp.where(denom <= 0, _SIGMA3, jnp.maximum(_SIGMA1, -0.5 * (gs / jnp.where(denom == 0, 1.0, denom))))
        delta = jnp.where(
            actred < _ETA0 * prered,
            jnp.minimum(jnp.maximum(alpha, _SIGMA1) * snorm, _SIGMA2 * s.delta),
            jnp.where(
                actred < _ETA1 * prered,
                jnp.maximum(_SIGMA1 * s.delta, jnp.minimum(alpha * snorm, _SIGMA2 * s.delta)),
                jnp.where(
                    actred < _ETA2 * prered,
                    jnp.maximum(_SIGMA1 * s.delta, jnp.minimum(alpha * snorm, _SIGMA3 * s.delta)),
                    jnp.maximum(s.delta, jnp.minimum(alpha * snorm, _SIGMA3 * s.delta)),
                ),
            ),
        )
        accept = actred > _ETA0 * prered
        w_new = jnp.where(accept, w_try, s.w)
        f_new = jnp.where(accept, f_try, s.f)
        g_new = jnp.where(accept, g_try, s.g)
        gnorm = l2_norm(g_new)
        conv = accept & converged_check(s.f, f_new, gnorm, g0_norm, config.tolerance)
        # the quadratic model predicting no significant reduction IS
        # convergence (nothing left to gain at this dtype's resolution)
        eps = jnp.finfo(dtype).eps
        conv = conv | (prered <= eps * jnp.maximum(jnp.abs(s.f), 1.0))
        # radius below step resolution at w means further steps can't move w
        stalled = delta < eps * jnp.maximum(l2_norm(w_new), 1.0)
        return _State(
            s.it + 1, w_new, f_new, g_new, delta, conv, stalled,
            s.loss_hist.at[s.it].set(f_new),
            s.gnorm_hist.at[s.it].set(gnorm),
        )

    def cond(s: _State):
        return (~s.converged) & (~s.stalled) & (s.it < config.max_iters)

    init = _State(
        it=jnp.asarray(0), w=w0, f=f0, g=g0,
        delta=g0_norm, converged=jnp.asarray(False), stalled=jnp.asarray(False),
        loss_hist=loss_hist, gnorm_hist=gnorm_hist,
    )
    s = lax.while_loop(cond, body, match_vma_tree(init, g0))
    return OptimizationResult(
        w=s.w, value=s.f, grad_norm=l2_norm(s.g), iterations=s.it,
        converged=s.converged, loss_history=s.loss_hist, grad_norm_history=s.gnorm_hist,
    )
