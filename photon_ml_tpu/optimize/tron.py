"""Jitted TRON: trust-region Newton with a conjugate-gradient inner loop.

Equivalent of the reference's own ``optimization.TRON`` implementation (from
LIBLINEAR's algorithm, Lin & Moré — SURVEY.md §3.1; reference mount empty).
The decisive TPU difference (SURVEY.md §4.2): the reference pays one full
cluster ``treeAggregate`` per CG step for each Hessian-vector product; here an
HVP is forward-over-reverse autodiff inside the same XLA program — roughly two
fused gradient passes, with any cross-device reduction riding ICI.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    OptimizationResult,
    OptimizerConfig,
    converged_check,
    init_history,
    l2_norm,
    match_vma_tree,
)

# Lin-Moré / LIBLINEAR constants
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


class _CGState(NamedTuple):
    s: jax.Array
    r: jax.Array
    d: jax.Array
    rz: jax.Array  # r . M^{-1} r (== r.r when unpreconditioned)
    i: jax.Array
    done: jax.Array


def _steihaug_cg(hvp: Callable, g: jax.Array, delta, cg_tol, max_cg: int,
                 m_diag: jax.Array | None = None):
    """Approximately minimize q(s) = g.s + 0.5 s.H.s within a trust region.

    ``m_diag``: optional Jacobi preconditioner, the (positive) diagonal of
    an approximation to H. Each CG step costs one HVP — for the
    distributed/streamed fits that is a FULL pass over the data, so fewer
    CG steps is a direct data-pass saving on badly-scaled problems (sparse
    features with wildly different counts). Preconditioned Steihaug
    measures the trust region in the M-norm (LIBLINEAR's newer TRON does
    the same); with ``m_diag=None`` every M-product degenerates to the
    plain Euclidean form and the iteration is identical to classic
    Steihaug. The residual invariant r == -(g + H s) holds either way, so
    the caller's ``prered`` formula is unchanged."""
    if m_diag is None:
        minv = None
        mdot = lambda a, b: jnp.sum(a * b)
        prec = lambda r: r
    else:
        minv = 1.0 / m_diag
        mdot = lambda a, b: jnp.sum(a * m_diag * b)
        prec = lambda r: minv * r

    def boundary_tau(s, d):
        sd = mdot(s, d)
        dd = mdot(d, d)
        ss = mdot(s, s)
        disc = jnp.sqrt(jnp.maximum(sd * sd + dd * (delta * delta - ss), 0.0))
        return (-sd + disc) / jnp.maximum(dd, jnp.finfo(d.dtype).tiny)

    def body(st: _CGState) -> _CGState:
        Hd = hvp(st.d)
        dHd = jnp.sum(st.d * Hd)
        neg_curv = dHd <= 0
        alpha = st.rz / jnp.where(neg_curv, 1.0, dHd)
        outside = jnp.sqrt(mdot(st.s + alpha * st.d,
                                st.s + alpha * st.d)) >= delta
        hit = neg_curv | outside
        # one uniform update keeps r == -(g + H s) exact even on the
        # boundary step, so the caller can form prered from (s, r) alone
        step = jnp.where(hit, boundary_tau(st.s, st.d), alpha)
        s_new = st.s + step * st.d
        r_new = st.r - step * Hd
        z_new = prec(r_new)
        rz_new = jnp.sum(r_new * z_new)
        beta = rz_new / jnp.maximum(st.rz, jnp.finfo(st.rz.dtype).tiny)
        d_new = z_new + beta * st.d
        done = hit | (l2_norm(r_new) <= cg_tol)
        return _CGState(s_new, r_new, d_new, rz_new, st.i + 1, done)

    def cond(st: _CGState):
        return (~st.done) & (st.i < max_cg)

    r0 = -g
    z0 = prec(r0)
    init = _CGState(jnp.zeros_like(g), r0, z0, jnp.sum(r0 * z0),
                    jnp.asarray(0), jnp.asarray(False))
    st = lax.while_loop(cond, body, match_vma_tree(init, g))
    return st.s, st.r, st.i


class _State(NamedTuple):
    it: jax.Array
    w: jax.Array
    f: jax.Array
    g: jax.Array
    delta: jax.Array
    m_diag: jax.Array  # cached preconditioner diag ([0] when unused)
    converged: jax.Array
    stalled: jax.Array
    loss_hist: jax.Array
    gnorm_hist: jax.Array


def tron(
    fun_and_grad: Callable,
    w0: jax.Array,
    config: OptimizerConfig = OptimizerConfig(),
    hvp: Callable | None = None,
    max_cg_iters: int | None = None,
    precond: Callable | None = None,
) -> OptimizationResult:
    """Minimize fun(w). ``hvp(w, v)`` defaults to forward-over-reverse autodiff
    of the gradient part of ``fun_and_grad``. ``precond(w)`` optionally
    returns the Hessian diagonal at w (one extra data pass per OUTER
    iteration) for Jacobi-preconditioned CG — fewer inner HVP passes on
    badly-scaled problems."""
    dtype = w0.dtype
    if hvp is None:
        grad_only = lambda w: fun_and_grad(w)[1]

        def hvp(w, v):
            return jax.jvp(grad_only, (w,), (v,))[1]

    max_cg = max_cg_iters if max_cg_iters is not None else max(w0.shape[0], 20)
    f0, g0 = fun_and_grad(w0)
    g0_norm = l2_norm(g0)
    loss_hist, gnorm_hist = init_history(config.max_iters, f0.dtype)

    def _guard(md):
        # positivity guard: the M-norm needs a positive diagonal
        return jnp.maximum(md, jnp.finfo(dtype).eps
                           * jnp.maximum(jnp.max(md), 1.0))

    def body(s: _State) -> _State:
        cg_tol = 0.1 * l2_norm(s.g)
        m_diag = s.m_diag if precond is not None else None
        step, r, _ = _steihaug_cg(lambda v: hvp(s.w, v), s.g, s.delta,
                                  cg_tol, max_cg, m_diag=m_diag)
        w_try = s.w + step
        f_try, g_try = fun_and_grad(w_try)
        gs = jnp.sum(s.g * step)
        # r == -(g + H step) from CG, so s.H.s = -g.s - r.s and
        # prered = -(g.s + s.H.s/2) = 0.5*(r.s - g.s) — no extra HVP needed
        prered = 0.5 * (jnp.sum(step * r) - gs)
        actred = s.f - f_try
        # the radius lives in the same norm the CG boundary used
        snorm = (l2_norm(step) if m_diag is None
                 else jnp.sqrt(jnp.sum(step * m_diag * step)))

        # Lin-Moré radius update via quadratic interpolation
        denom = f_try - s.f - gs
        alpha = jnp.where(denom <= 0, _SIGMA3, jnp.maximum(_SIGMA1, -0.5 * (gs / jnp.where(denom == 0, 1.0, denom))))
        delta = jnp.where(
            actred < _ETA0 * prered,
            jnp.minimum(jnp.maximum(alpha, _SIGMA1) * snorm, _SIGMA2 * s.delta),
            jnp.where(
                actred < _ETA1 * prered,
                jnp.maximum(_SIGMA1 * s.delta, jnp.minimum(alpha * snorm, _SIGMA2 * s.delta)),
                jnp.where(
                    actred < _ETA2 * prered,
                    jnp.maximum(_SIGMA1 * s.delta, jnp.minimum(alpha * snorm, _SIGMA3 * s.delta)),
                    jnp.maximum(s.delta, jnp.minimum(alpha * snorm, _SIGMA3 * s.delta)),
                ),
            ),
        )
        accept = actred > _ETA0 * prered
        w_new = jnp.where(accept, w_try, s.w)
        f_new = jnp.where(accept, f_try, s.f)
        g_new = jnp.where(accept, g_try, s.g)
        if precond is not None:
            # the diag costs a data pass: recompute only on acceptance
            # (w unchanged on rejection -> same diagonal)
            m_new = lax.cond(accept, lambda: _guard(precond(w_new)),
                             lambda: s.m_diag)
        else:
            m_new = s.m_diag
        gnorm = l2_norm(g_new)
        conv = accept & converged_check(s.f, f_new, gnorm, g0_norm, config.tolerance)
        # the quadratic model predicting no significant reduction IS
        # convergence (nothing left to gain at this dtype's resolution)
        eps = jnp.finfo(dtype).eps
        conv = conv | (prered <= eps * jnp.maximum(jnp.abs(s.f), 1.0))
        # radius below step resolution at w means further steps can't move w
        stalled = delta < eps * jnp.maximum(l2_norm(w_new), 1.0)
        return _State(
            s.it + 1, w_new, f_new, g_new, delta, m_new, conv, stalled,
            s.loss_hist.at[s.it].set(f_new),
            s.gnorm_hist.at[s.it].set(gnorm),
        )

    def cond(s: _State):
        return (~s.converged) & (~s.stalled) & (s.it < config.max_iters)

    m0 = (_guard(precond(w0)) if precond is not None
          else jnp.zeros((0,), dtype))
    init = _State(
        it=jnp.asarray(0), w=w0, f=f0, g=g0,
        delta=g0_norm, m_diag=m0,
        converged=jnp.asarray(False), stalled=jnp.asarray(False),
        loss_hist=loss_hist, gnorm_hist=gnorm_hist,
    )
    s = lax.while_loop(cond, body, match_vma_tree(init, g0))
    return OptimizationResult(
        w=s.w, value=s.f, grad_norm=l2_norm(s.g), iterations=s.it,
        converged=s.converged, loss_history=s.loss_hist, grad_norm_history=s.gnorm_hist,
    )
