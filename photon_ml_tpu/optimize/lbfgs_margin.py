"""L-BFGS specialized for linear-margin objectives (GLMs).

The generic :func:`photon_ml_tpu.optimize.lbfgs.lbfgs` treats the objective
as a black box, so every strong-Wolfe trial point costs a full
value-and-gradient pass over the data — for the sparse hot loop that is one
O(nnz) margin gather plus one O(nnz + d) transpose scatter *per line-search
evaluation* (SURVEY.md §4.2; the reference pays the same price as one
cluster ``treeAggregate`` per evaluation).

A GLM's data term factors through the margins, and margins are linear in
the coefficients (normalization's coefficient-space map included —
``ops/normalization.py``):

    m(w + a*p) = m(w) + a * m_dir(p)

so one gather per iteration (the direction's margin) makes every
line-search trial an O(n) pointwise evaluation on cached margin vectors,
and only the *accepted* point pays the transpose for its gradient. Per
iteration the data passes drop from ``2 * (1 + line_search_evals)`` to
exactly 2 (one gather + one transpose), independent of how hard the line
search works. The L2 term is quadratic along the ray and handled in closed
form via three precomputed scalars.

The loop carries the current margins ``mw`` and updates them incrementally
(``mw += a * mp``); the accumulated f32 drift per iteration is O(eps *
|a*mp|), negligible over the tens-of-iterations fits this serves (parity
is asserted against the black-box path in tests/test_parallel.py).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    OptimizationResult,
    grad_converged,
    OptimizerConfig,
    converged_check,
    init_history,
    l2_norm,
    match_vma_tree,
)
from photon_ml_tpu.optimize.lbfgs import two_loop_direction
from photon_ml_tpu.optimize.linesearch import strong_wolfe


class _State(NamedTuple):
    it: jax.Array
    k: jax.Array
    w: jax.Array
    mw: jax.Array  # cached margins of w (incl. offsets + normalization adjust)
    f: jax.Array
    g: jax.Array
    s_hist: jax.Array
    y_hist: jax.Array
    rho: jax.Array
    converged: jax.Array
    stalled: jax.Array
    loss_hist: jax.Array
    gnorm_hist: jax.Array


def lbfgs_margin(
    dir_margin: Callable,  # p [d] -> m_p [n] (linear; no offsets)
    loss_and_dir: Callable,  # (m [n], m_p [n]) -> (sum_i w_i l(m_i),
    #                                               sum_i w_i l'(m_i) m_p_i)
    data_grad: Callable,  # m [n] -> data-term gradient [d] (chain rule incl.)
    reg_mask: Callable,  # w [d] -> w with unpenalized slots zeroed
    w0: jax.Array,
    m0: jax.Array,
    l2,
    config: OptimizerConfig = OptimizerConfig(),
    loss_delta_and_dir: Callable | None = None,
    # (m, m_p, alpha) -> (sum_i w_i (l(m_i + a m_p_i) - l(m_i)),
    #                     sum_i w_i l'(m_i + a m_p_i) m_p_i)
) -> OptimizationResult:
    """Minimize  sum_i w_i l(m_i(w)) + 0.5*l2*||reg_mask(w)||^2  where the
    margin map is affine in w. All data reductions must already be global
    (psummed) inside the supplied callables.

    When ``loss_delta_and_dir`` is given, the line search and the
    relative-loss convergence test run in DELTA space: per-row loss
    differences are summed instead of differencing two rounded totals.
    In f32 a total's resolution is eps*|f|, far coarser than late-stage
    per-iteration improvements, so total-space Wolfe tests stall the fit
    (observed on TPU: hard stop at 16/20 iterations); delta sums keep
    relative accuracy in the improvement itself."""
    m = config.history
    d = w0.shape[0]
    dtype = w0.dtype
    l2 = jnp.asarray(l2, dtype)

    def full_f(mw, w):
        f_data, _ = loss_and_dir(mw, mw)
        wr = reg_mask(w)
        return f_data + 0.5 * l2 * jnp.sum(wr * wr)

    def full_g(mw, w):
        return data_grad(mw) + l2 * reg_mask(w)

    f0 = full_f(m0, w0)
    g0 = full_g(m0, w0)
    g0_norm = l2_norm(g0)
    loss_hist, gnorm_hist = init_history(config.max_iters, f0.dtype)

    def body(s: _State) -> _State:
        p = two_loop_direction(s.g, s.s_hist, s.y_hist, s.rho, s.k, m)
        dg = jnp.sum(p * s.g)
        p = jnp.where(dg < 0, p, -s.g)

        mp = dir_margin(p)  # the iteration's ONE gather pass
        # L2 along the ray: ||reg(w) + a*reg(p)||^2 = c0 + 2*a*c1 + a^2*c2
        wr, pr = reg_mask(s.w), reg_mask(p)
        c1 = jnp.sum(wr * pr)
        c2 = jnp.sum(pr * pr)

        if loss_delta_and_dir is not None:
            # DELTA space: phi returns f(w + a p) - f(w) via summed
            # per-row differences (accurate at any |f|); strong_wolfe's
            # tests are all translation-invariant, so feeding f0 = 0
            # keeps its semantics exactly
            def phi(alpha):
                delta_data, df_data = loss_delta_and_dir(s.mw, mp, alpha)
                delta = delta_data + l2 * (alpha * c1
                                           + 0.5 * alpha * alpha * c2)
                df = df_data + l2 * (c1 + alpha * c2)
                return delta, df

            ls_f0 = jnp.zeros((), dtype)
        else:
            def phi(alpha):
                """(f(w + a p), f'(a)) as an O(n) pointwise computation;
                the scalar derivative doubles as the 1-d 'gradient' for
                strong_wolfe (direction 1.0: sum(g*p) == the derivative)."""
                f_data, df_data = loss_and_dir(s.mw + alpha * mp, mp)
                f = f_data + 0.5 * l2 * (jnp.sum(wr * wr)
                                         + 2.0 * alpha * c1
                                         + alpha * alpha * c2)
                df = df_data + l2 * (c1 + alpha * c2)
                return f, df

            ls_f0 = s.f

        # phi'(0) == p . g exactly (g is the full gradient incl. the L2
        # term): an O(d) local dot, not another distributed evaluation
        df0 = jnp.sum(p * s.g)
        alpha0 = jnp.where(s.k > 0, 1.0, 1.0 / jnp.maximum(l2_norm(s.g), 1.0))
        ls = strong_wolfe(
            phi, jnp.zeros((), dtype), jnp.ones((), dtype), ls_f0, df0,
            alpha0=alpha0, max_evals=config.max_line_search_steps,
        )
        # in delta space ls.f is the accepted IMPROVEMENT (0 on failure)
        f_new = (s.f + ls.f) if loss_delta_and_dir is not None else ls.f
        w_new = s.w + ls.alpha * p
        mw_new = s.mw + ls.alpha * mp
        g_new = full_g(mw_new, w_new)  # the iteration's ONE transpose pass

        step = ls.alpha * p
        y = g_new - s.g
        sy = jnp.sum(step * y)
        store = ls.ok & (
            sy > 1e-10 * jnp.maximum(l2_norm(step) * l2_norm(y),
                                     jnp.finfo(dtype).tiny)
        )
        slot = jnp.mod(s.k, m)
        s_hist = jnp.where(store, s.s_hist.at[slot].set(step), s.s_hist)
        y_hist = jnp.where(store, s.y_hist.at[slot].set(y), s.y_hist)
        rho = jnp.where(store,
                        s.rho.at[slot].set(1.0 / jnp.where(sy == 0, 1.0, sy)),
                        s.rho)
        # line-search failure (alpha=0, no step): RESET the history and
        # retry from steepest descent before giving up — in f32 the
        # L-BFGS metric goes stale near convergence and a restart often
        # buys several more productive iterations (observed on TPU:
        # hard stop at iteration 16/20). Stall only if the search failed
        # with an already-empty history (p was -g).
        k_new = jnp.where(store, s.k + 1, jnp.where(ls.ok, s.k, 0))
        stalled = (~ls.ok) & (s.k == 0)
        gnorm = l2_norm(g_new)
        # gate on ls.ok: a failed search leaves f unchanged, and a zero
        # loss-delta would spuriously pass the relative convergence test
        if loss_delta_and_dir is not None:
            # accurate delta: test |improvement| directly against
            # tol * max(|f|, 1) (converged_check would re-difference the
            # rounded totals and lose exactly what delta space preserves)
            full = converged_check(jnp.zeros((), dtype), -ls.f, gnorm,
                                   g0_norm, config.tolerance, f_scale=s.f)
        else:
            full = converged_check(s.f, f_new, gnorm, g0_norm,
                                   config.tolerance)
        # failed search: rel-loss half is invalid (zero delta) but the
        # gradient test must still fire — failing AT the optimum is
        # convergence, not a stall
        conv = jnp.where(ls.ok, full,
                         grad_converged(gnorm, g0_norm, config.tolerance))
        return _State(
            s.it + 1, k_new, w_new, mw_new, f_new, g_new,
            s_hist, y_hist, rho,
            conv, stalled,
            s.loss_hist.at[s.it].set(f_new),
            s.gnorm_hist.at[s.it].set(gnorm),
        )

    def cond(s: _State):
        return (~s.converged) & (~s.stalled) & (s.it < config.max_iters)

    init = _State(
        it=jnp.asarray(0), k=jnp.asarray(0), w=w0, mw=m0, f=f0, g=g0,
        s_hist=jnp.zeros((m, d), dtype), y_hist=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        converged=jnp.asarray(False), stalled=jnp.asarray(False),
        loss_hist=loss_hist, gnorm_hist=gnorm_hist,
    )
    s = lax.while_loop(cond, body, match_vma_tree(init, g0))
    return OptimizationResult(
        w=s.w, value=s.f, grad_norm=l2_norm(s.g), iterations=s.it,
        converged=s.converged, loss_history=s.loss_hist,
        grad_norm_history=s.gnorm_hist,
    )
