from photon_ml_tpu.optimize.common import (
    OptimizationResult,
    OptimizerConfig,
    ToleranceSchedule,
    parse_tolerance_schedule,
)
from photon_ml_tpu.optimize.lbfgs import lbfgs
from photon_ml_tpu.optimize.owlqn import owlqn
from photon_ml_tpu.optimize.tron import tron


OPTIMIZERS = {"lbfgs": lbfgs, "owlqn": owlqn, "tron": tron}


def get_optimizer(name: str):
    key = name.lower()
    if key not in OPTIMIZERS:
        raise ValueError(f"unknown optimizer '{name}'; known: {sorted(OPTIMIZERS)}")
    return OPTIMIZERS[key]
