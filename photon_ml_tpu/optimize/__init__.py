from photon_ml_tpu.optimize.common import (
    OptimizationResult,
    OptimizerConfig,
    PathConfig,
    ToleranceSchedule,
    parse_tolerance_schedule,
)
from photon_ml_tpu.optimize.lbfgs import lbfgs
from photon_ml_tpu.optimize.owlqn import owlqn
from photon_ml_tpu.optimize.tron import tron


OPTIMIZERS = {"lbfgs": lbfgs, "owlqn": owlqn, "tron": tron}


def get_optimizer(name: str):
    key = name.lower()
    if key not in OPTIMIZERS:
        raise ValueError(f"unknown optimizer '{name}'; known: {sorted(OPTIMIZERS)}")
    return OPTIMIZERS[key]


def __getattr__(name):
    # PathSolver lives behind a lazy hook: optimize/path.py reaches into
    # photon_ml_tpu.parallel (which itself imports this package for the
    # optimizer registry), so importing it eagerly here would be a cycle.
    if name in ("PathSolver", "PathLambdaStats"):
        from photon_ml_tpu.optimize import path

        return getattr(path, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
