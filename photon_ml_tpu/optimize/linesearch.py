"""Strong-Wolfe line search, jit-compatible (single ``lax.while_loop``).

Plays the role Breeze's ``StrongWolfeLineSearch`` plays under the reference's
``LBFGS`` (SURVEY.md §3.1; reference mount empty). Standard
bracketing + zoom (Nocedal & Wright alg. 3.5/3.6) expressed as a phase
state-machine so the whole search stays on device; zoom uses safeguarded
quadratic interpolation with bisection fallback.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import match_vma_tree

_BRACKET, _ZOOM, _DONE = 0, 1, 2


class LineSearchResult(NamedTuple):
    alpha: jax.Array
    f: jax.Array
    g: jax.Array  # gradient at w + alpha * p
    n_evals: jax.Array
    ok: jax.Array  # strong-Wolfe satisfied (else best-effort Armijo point)


class _State(NamedTuple):
    phase: jax.Array
    i: jax.Array
    alpha: jax.Array  # candidate to evaluate next / final
    f: jax.Array
    dg: jax.Array
    g: jax.Array
    a_prev: jax.Array
    f_prev: jax.Array
    dg_prev: jax.Array
    a_lo: jax.Array
    f_lo: jax.Array
    dg_lo: jax.Array
    g_lo: jax.Array
    a_hi: jax.Array
    f_hi: jax.Array
    ok: jax.Array


def strong_wolfe(
    fun_and_grad: Callable,
    w: jax.Array,
    p: jax.Array,
    f0: jax.Array,
    g0: jax.Array,
    alpha0=1.0,
    c1: float = 1e-4,
    c2: float = 0.9,
    max_evals: int = 25,
    alpha_max: float = 1e6,
) -> LineSearchResult:
    """Search along p from w. fun_and_grad(w) -> (f, g). Requires p a descent
    direction (dphi0 < 0); otherwise returns alpha=0, ok=False."""
    dtype = f0.dtype
    dphi0 = jnp.sum(g0 * p).astype(dtype)

    def phi(alpha):
        f, g = fun_and_grad(w + alpha * p)
        return f, jnp.sum(g * p), g

    def interp(a_lo, f_lo, dg_lo, a_hi, f_hi):
        # safeguarded quadratic interpolation on [lo, hi]
        denom = 2.0 * (f_hi - f_lo - dg_lo * (a_hi - a_lo))
        quad = a_lo - dg_lo * (a_hi - a_lo) ** 2 / jnp.where(denom == 0, 1.0, denom)
        mid = 0.5 * (a_lo + a_hi)
        lo, hi = jnp.minimum(a_lo, a_hi), jnp.maximum(a_lo, a_hi)
        width = hi - lo
        bad = (
            ~jnp.isfinite(quad)
            | (quad <= lo + 0.1 * width)
            | (quad >= hi - 0.1 * width)
            | (denom == 0)
        )
        return jnp.where(bad, mid, quad)

    def body(s: _State) -> _State:
        f, dg, g = phi(s.alpha)
        armijo_fail = (f > f0 + c1 * s.alpha * dphi0) | ((f >= s.f_prev) & (s.i > 0))
        curvature_ok = jnp.abs(dg) <= -c2 * dphi0

        def bracket_step():
            # cases per Nocedal & Wright alg 3.5
            to_zoom_hi = armijo_fail  # zoom(prev, cur)
            done = (~armijo_fail) & curvature_ok
            to_zoom_lo = (~armijo_fail) & (~curvature_ok) & (dg >= 0)  # zoom(cur, prev)
            next_alpha = jnp.minimum(2.0 * s.alpha, alpha_max)
            phase = jnp.where(done, _DONE, jnp.where(to_zoom_hi | to_zoom_lo, _ZOOM, _BRACKET))
            a_lo = jnp.where(to_zoom_hi, s.a_prev, s.alpha)
            f_lo = jnp.where(to_zoom_hi, s.f_prev, f)
            dg_lo = jnp.where(to_zoom_hi, s.dg_prev, dg)
            g_lo = jnp.where(to_zoom_hi, s.g, g)  # best-known g (approx for prev)
            a_hi = jnp.where(to_zoom_hi, s.alpha, s.a_prev)
            f_hi = jnp.where(to_zoom_hi, f, s.f_prev)
            alpha_next = jnp.where(
                phase == _ZOOM, interp(a_lo, f_lo, dg_lo, a_hi, f_hi),
                jnp.where(done, s.alpha, next_alpha),
            )
            return _State(
                phase, s.i + 1, alpha_next, f, dg, g,
                s.alpha, f, dg,
                a_lo, f_lo, dg_lo, g_lo, a_hi, f_hi,
                ok=done,
            )

        def zoom_step():
            hi_update = (f > f0 + c1 * s.alpha * dphi0) | (f >= s.f_lo)
            done = (~hi_update) & curvature_ok
            flip = (~hi_update) & (~curvature_ok) & (dg * (s.a_hi - s.a_lo) >= 0)
            a_hi = jnp.where(hi_update, s.alpha, jnp.where(flip, s.a_lo, s.a_hi))
            f_hi = jnp.where(hi_update, f, jnp.where(flip, s.f_lo, s.f_hi))
            a_lo = jnp.where(hi_update, s.a_lo, s.alpha)
            f_lo = jnp.where(hi_update, s.f_lo, f)
            dg_lo = jnp.where(hi_update, s.dg_lo, dg)
            g_lo = jax.tree.map(lambda old, new: jnp.where(hi_update, old, new), s.g_lo, g)
            phase = jnp.where(done, _DONE, _ZOOM)
            alpha_next = jnp.where(done, s.alpha, interp(a_lo, f_lo, dg_lo, a_hi, f_hi))
            return _State(
                phase, s.i + 1, alpha_next, f, dg, g,
                s.alpha, f, dg,
                a_lo, f_lo, dg_lo, g_lo, a_hi, f_hi,
                ok=done,
            )

        return lax.cond(s.phase == _BRACKET, bracket_step, zoom_step)

    def cond(s: _State):
        return (s.phase != _DONE) & (s.i < max_evals)

    zero = jnp.zeros((), dtype)
    init = _State(
        phase=jnp.asarray(_BRACKET),
        i=jnp.asarray(0),
        alpha=jnp.asarray(alpha0, dtype),
        f=f0, dg=dphi0, g=g0,
        a_prev=zero, f_prev=f0, dg_prev=dphi0,
        a_lo=zero, f_lo=f0, dg_lo=dphi0, g_lo=g0,
        a_hi=jnp.asarray(alpha_max, dtype), f_hi=f0,
        ok=jnp.asarray(False),
    )
    bad_direction = dphi0 >= 0
    s = lax.while_loop(cond, body, match_vma_tree(init, f0))

    # On exhaustion fall back to the best bracket point (a_lo satisfies Armijo
    # by construction once zoom is entered); if nothing worked, take no step.
    finished = s.phase == _DONE
    alpha = jnp.where(finished, s.alpha, s.a_lo)
    f = jnp.where(finished, s.f, s.f_lo)
    g = jnp.where(finished, s.g, s.g_lo)
    took_step = alpha > 0
    alpha = jnp.where(bad_direction, 0.0, alpha)
    f = jnp.where(bad_direction, f0, f)
    g = jax.tree.map(lambda a, b: jnp.where(bad_direction, a, b), g0, g)
    return LineSearchResult(alpha, f, g, s.i, (finished | took_step) & ~bad_direction)


def backtracking(
    fun: Callable,
    w: jax.Array,
    p: jax.Array,
    f0: jax.Array,
    pseudo_grad: jax.Array,
    alpha0=1.0,
    c1: float = 1e-4,
    shrink: float = 0.5,
    max_evals: int = 30,
    project: Callable | None = None,
):
    """Armijo backtracking with optional orthant projection (OWL-QN style).

    fun(w) -> f. ``project(w_trial)`` maps the trial point back to the
    feasible orthant before evaluation (identity if None). The sufficient
    decrease test uses the OWL-QN form f_new <= f0 + c1 * pseudo_grad.(w_new - w)
    which reduces to plain Armijo when project is None and pseudo_grad is the
    gradient. Returns (w_new, f_new, n_evals, ok).
    """
    proj = project if project is not None else (lambda x: x)

    def body(s):
        alpha, _, _, i, _ = s
        w_new = proj(w + alpha * p)
        f_new = fun(w_new)
        ok = f_new <= f0 + c1 * jnp.sum(pseudo_grad * (w_new - w))
        return (jnp.where(ok, alpha, alpha * shrink), w_new, f_new, i + 1, ok)

    def cond(s):
        _, _, _, i, ok = s
        return (~ok) & (i < max_evals)

    _, w_new, f_new, i, ok = lax.while_loop(
        cond, body,
        match_vma_tree(
            (jnp.asarray(alpha0, f0.dtype), w, f0, jnp.asarray(0), jnp.asarray(False)),
            f0,
        ),
    )
    w_new = jax.tree.map(lambda a, b: jnp.where(ok, b, a), w, w_new)
    f_new = jnp.where(ok, f_new, f0)
    return w_new, f_new, i, ok
