"""Jitted OWL-QN (Orthant-Wise Limited-memory Quasi-Newton) for L1 /
elastic-net objectives.

Equivalent of the reference's ``optimization.OWLQN`` (which wraps Breeze
OWLQN — SURVEY.md §3.1; reference mount empty). Minimizes
F(w) = f(w) + l1 * ||w * mask||_1 where f is smooth (the elastic net's L2 part
lives inside f, matching the reference's split — SURVEY.md §3.1
regularization row). Standard Andrew & Gao (2007) scheme: pseudo-gradient,
L-BFGS direction from smooth-gradient history, orthant projection of both the
direction and the line-search iterates.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    OptimizationResult,
    OptimizerConfig,
    converged_check,
    init_history,
    l2_norm,
    match_vma_tree,
)
from photon_ml_tpu.optimize.lbfgs import two_loop_direction
from photon_ml_tpu.optimize.linesearch import backtracking


def pseudo_gradient(w, g, l1):
    """Directional-derivative-minimizing subgradient of f + l1*|w|_1."""
    right = g + l1
    left = g - l1
    at_zero = jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0))
    return jnp.where(w > 0, right, jnp.where(w < 0, left, at_zero))


class _State(NamedTuple):
    it: jax.Array
    k: jax.Array
    w: jax.Array
    F: jax.Array  # full objective incl. L1
    g: jax.Array  # smooth gradient
    s_hist: jax.Array
    y_hist: jax.Array
    rho: jax.Array
    converged: jax.Array
    stalled: jax.Array
    loss_hist: jax.Array
    gnorm_hist: jax.Array


def owlqn(
    fun_and_grad: Callable,
    w0: jax.Array,
    l1_weight,
    config: OptimizerConfig = OptimizerConfig(),
    l1_mask: Optional[jax.Array] = None,
) -> OptimizationResult:
    """Minimize f(w) + l1_weight * ||w * l1_mask||_1; fun_and_grad is the
    smooth part. l1_mask defaults to all-ones (mask the intercept with 0)."""
    m = config.history
    d = w0.shape[0]
    dtype = w0.dtype
    mask = jnp.ones((d,), dtype) if l1_mask is None else l1_mask.astype(dtype)
    lam = jnp.asarray(l1_weight, dtype) * mask

    def full_value(w):
        f, _ = fun_and_grad(w)
        return f + jnp.sum(lam * jnp.abs(w))

    f0, g0 = fun_and_grad(w0)
    F0 = f0 + jnp.sum(lam * jnp.abs(w0))
    pg0_norm = l2_norm(pseudo_gradient(w0, g0, lam))
    loss_hist, gnorm_hist = init_history(config.max_iters, F0.dtype)

    def body(s: _State) -> _State:
        pg = pseudo_gradient(s.w, s.g, lam)
        p = two_loop_direction(pg, s.s_hist, s.y_hist, s.rho, s.k, m)
        # align the direction with -pg (orthant-wise projection of direction)
        p = jnp.where(p * (-pg) > 0, p, 0.0)
        dg = jnp.sum(p * pg)
        p = jnp.where(dg < 0, p, -pg)
        # orthant choice: sign(w), or sign(-pg) where w == 0
        xi = jnp.where(s.w != 0, jnp.sign(s.w), jnp.sign(-pg))

        def project(w_trial):
            return jnp.where(w_trial * xi > 0, w_trial, 0.0)

        alpha0 = jnp.where(s.k > 0, 1.0, 1.0 / jnp.maximum(l2_norm(pg), 1.0))
        w_new, F_new, _, ok = backtracking(
            full_value, s.w, p, s.F, pg, alpha0=alpha0,
            max_evals=config.max_line_search_steps, project=project,
        )
        _, g_new = fun_and_grad(w_new)
        step = w_new - s.w
        y = g_new - s.g
        sy = jnp.sum(step * y)
        store = ok & (sy > 1e-10 * jnp.maximum(l2_norm(step) * l2_norm(y), jnp.finfo(dtype).tiny))
        slot = jnp.mod(s.k, m)
        s_hist = jnp.where(store, s.s_hist.at[slot].set(step), s.s_hist)
        y_hist = jnp.where(store, s.y_hist.at[slot].set(y), s.y_hist)
        rho = jnp.where(store, s.rho.at[slot].set(1.0 / jnp.where(sy == 0, 1.0, sy)), s.rho)
        k_new = jnp.where(store, s.k + 1, s.k)
        pg_new_norm = l2_norm(pseudo_gradient(w_new, g_new, lam))
        conv = converged_check(s.F, F_new, pg_new_norm, pg0_norm, config.tolerance)
        return _State(
            s.it + 1, k_new, w_new, F_new, g_new,
            s_hist, y_hist, rho, conv, ~ok,
            s.loss_hist.at[s.it].set(F_new),
            s.gnorm_hist.at[s.it].set(pg_new_norm),
        )

    def cond(s: _State):
        return (~s.converged) & (~s.stalled) & (s.it < config.max_iters)

    init = _State(
        it=jnp.asarray(0), k=jnp.asarray(0), w=w0, F=F0, g=g0,
        s_hist=jnp.zeros((m, d), dtype), y_hist=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        converged=jnp.asarray(False), stalled=jnp.asarray(False),
        loss_hist=loss_hist, gnorm_hist=gnorm_hist,
    )
    s = lax.while_loop(cond, body, match_vma_tree(init, g0))
    final_pg = pseudo_gradient(s.w, s.g, lam)
    return OptimizationResult(
        w=s.w, value=s.F, grad_norm=l2_norm(final_pg), iterations=s.it,
        converged=s.converged, loss_history=s.loss_hist, grad_norm_history=s.gnorm_hist,
    )
