"""Jitted L-BFGS with strong-Wolfe line search.

Equivalent of the reference's ``optimization.LBFGS`` (which wraps Breeze
L-BFGS with a strong-Wolfe search — SURVEY.md §3.1; reference mount empty),
rebuilt as a single ``lax.while_loop`` whose carry holds the circular
(s, y) history, so the whole optimization is one XLA computation: no
per-iteration host round-trip, and under sharded batches the gradient's
all-reduce rides ICI inside the same program (the ``treeAggregate``
replacement, SURVEY.md §4.2).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optimize.common import (
    grad_converged,
    OptimizationResult,
    OptimizerConfig,
    converged_check,
    init_history,
    l2_norm,
    match_vma_tree,
)
from photon_ml_tpu.optimize.linesearch import strong_wolfe


class _State(NamedTuple):
    it: jax.Array  # iteration counter
    k: jax.Array  # number of (s,y) pairs ever stored (head of circular buffer)
    w: jax.Array
    f: jax.Array
    g: jax.Array
    s_hist: jax.Array  # [m, d]
    y_hist: jax.Array  # [m, d]
    rho: jax.Array  # [m]
    converged: jax.Array
    stalled: jax.Array
    loss_hist: jax.Array
    gnorm_hist: jax.Array


def two_loop_direction(g, s_hist, y_hist, rho, k, m):
    """Two-loop recursion over a circular buffer; slot (k-1-i) mod m is the
    i-th most recent pair, masked out when i >= min(k, m)."""
    dtype = g.dtype
    n_valid = jnp.minimum(k, m)

    def newest_to_oldest(i, carry):
        q, alphas = carry
        j = jnp.mod(k - 1 - i, m)
        valid = i < n_valid
        a = jnp.where(valid, rho[j] * jnp.sum(s_hist[j] * q), 0.0)
        q = q - a * y_hist[j]
        return q, alphas.at[j].set(a)

    q, alphas = lax.fori_loop(
        0, m, newest_to_oldest, match_vma_tree((g, jnp.zeros((m,), dtype)), g)
    )

    newest = jnp.mod(k - 1, m)
    sy = jnp.sum(s_hist[newest] * y_hist[newest])
    yy = jnp.sum(y_hist[newest] * y_hist[newest])
    gamma = jnp.where((k > 0) & (yy > 0), sy / jnp.maximum(yy, jnp.finfo(dtype).tiny), 1.0)
    r = gamma * q

    def oldest_to_newest(i, r):
        rank = n_valid - 1 - i  # recency rank, oldest first
        j = jnp.mod(k - 1 - rank, m)
        valid = rank >= 0
        beta = rho[j] * jnp.sum(y_hist[j] * r)
        upd = s_hist[j] * (alphas[j] - beta)
        return r + jnp.where(valid, upd, 0.0)

    r = lax.fori_loop(0, m, oldest_to_newest, r)
    return -r


def lbfgs(
    fun_and_grad: Callable,
    w0: jax.Array,
    config: OptimizerConfig = OptimizerConfig(),
) -> OptimizationResult:
    """Minimize fun(w); fun_and_grad(w) -> (f, g). Fully jittable."""
    m = config.history
    d = w0.shape[0]
    dtype = w0.dtype
    f0, g0 = fun_and_grad(w0)
    g0_norm = l2_norm(g0)
    loss_hist, gnorm_hist = init_history(config.max_iters, f0.dtype)

    def body(s: _State) -> _State:
        p = two_loop_direction(s.g, s.s_hist, s.y_hist, s.rho, s.k, m)
        # ensure descent; fall back to steepest descent if the metric degraded
        dg = jnp.sum(p * s.g)
        p = jnp.where(dg < 0, p, -s.g)
        alpha0 = jnp.where(s.k > 0, 1.0, 1.0 / jnp.maximum(l2_norm(s.g), 1.0))
        ls = strong_wolfe(
            fun_and_grad, s.w, p, s.f, s.g, alpha0=alpha0,
            max_evals=config.max_line_search_steps,
        )
        w_new = s.w + ls.alpha * p
        step = ls.alpha * p
        y = ls.g - s.g
        sy = jnp.sum(step * y)
        store = ls.ok & (
            sy > 1e-10 * jnp.maximum(l2_norm(step) * l2_norm(y), jnp.finfo(dtype).tiny)
        )
        slot = jnp.mod(s.k, m)
        s_hist = jnp.where(store, s.s_hist.at[slot].set(step), s.s_hist)
        y_hist = jnp.where(store, s.y_hist.at[slot].set(y), s.y_hist)
        rho = jnp.where(store, s.rho.at[slot].set(1.0 / jnp.where(sy == 0, 1.0, sy)), s.rho)
        # on line-search failure: reset the history and retry from
        # steepest descent; stall only if -g itself failed (k == 0).
        # conv is gated on ls.ok — a failed search leaves f unchanged and
        # the zero delta would spuriously pass the relative test
        # (same policy as optimize/lbfgs_margin.py)
        k_new = jnp.where(store, s.k + 1, jnp.where(ls.ok, s.k, 0))
        stalled = (~ls.ok) & (s.k == 0)
        gnorm = l2_norm(ls.g)
        # failed search: only the rel-loss half is invalid (zero delta
        # passes spuriously); the gradient test must still fire — a
        # search failing AT the optimum is convergence, not a stall
        conv = jnp.where(
            ls.ok,
            converged_check(s.f, ls.f, gnorm, g0_norm, config.tolerance),
            grad_converged(gnorm, g0_norm, config.tolerance))
        # A fit that converges on its FIRST iteration was already at its
        # stopping point: the step it just probed buys less than the
        # tolerance by definition, and taking it would make a warm-started
        # re-fit of an already-converged problem drift by one noise-level
        # step per call — coordinate descent re-fits every coordinate
        # every sweep, and that drift kept re-activating the active-set
        # frontier (game/descent.py) and prevented the sweep-level early
        # exit from ever seeing a stationary score vector. Such a re-fit
        # is now an exact no-op: it returns w0 bit-identically. Later
        # iterations keep their converging step (it carries the final
        # refinement of a genuinely-progressing fit), as before.
        take = ~(conv & (s.it == 0))
        w_out = jnp.where(take, w_new, s.w)
        f_out = jnp.where(take, ls.f, s.f)
        g_out = jnp.where(take, ls.g, s.g)
        return _State(
            s.it + 1, k_new, w_out, f_out, g_out,
            s_hist, y_hist, rho,
            conv, stalled,
            s.loss_hist.at[s.it].set(f_out),
            s.gnorm_hist.at[s.it].set(l2_norm(g_out)),
        )

    def cond(s: _State):
        return (~s.converged) & (~s.stalled) & (s.it < config.max_iters)

    init = _State(
        it=jnp.asarray(0), k=jnp.asarray(0), w=w0, f=f0, g=g0,
        s_hist=jnp.zeros((m, d), dtype), y_hist=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        converged=jnp.asarray(False), stalled=jnp.asarray(False),
        loss_hist=loss_hist, gnorm_hist=gnorm_hist,
    )
    s = lax.while_loop(cond, body, match_vma_tree(init, g0))
    return OptimizationResult(
        w=s.w, value=s.f, grad_norm=l2_norm(s.g), iterations=s.it,
        converged=s.converged, loss_history=s.loss_hist, grad_norm_history=s.gnorm_hist,
    )
