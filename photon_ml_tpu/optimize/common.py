"""Shared optimizer machinery: config, convergence, state tracking.

Equivalent of the reference's abstract ``optimization.Optimizer`` +
``OptimizationStatesTracker`` (SURVEY.md §3.1; reference mount empty):
convergence on relative-loss change and normalized gradient norm with a max
iteration cap, and a per-iteration (loss, gradient-norm) history. The tracker
here is a pair of fixed-length device arrays filled inside the jitted
``lax.while_loop`` — readable after the fact without host round-trips per
iteration.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.compat import typeof


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Mirrors the reference's per-coordinate optimizer config surface
    (optimizer type, max iters, tolerance — SURVEY.md §5.6)."""

    max_iters: int = 100
    tolerance: float = 1e-7
    # L-BFGS/OWL-QN history length (Breeze default is 10 ranks).
    history: int = 10
    # line-search evaluation cap per iteration
    max_line_search_steps: int = 25


@dataclasses.dataclass(frozen=True)
class ToleranceSchedule:
    """Inexact-outer-loop solver tolerance schedule (the standard trick in
    distributed block-coordinate methods: early sweeps don't need exact
    inner solves because the other blocks will move anyway — arxiv
    1611.02101 / 1803.06333). ``at(step, final_tol)`` starts at ``start``
    and tightens geometrically by ``decay`` per outer step, clamped from
    below at the caller's final tolerance; once the schedule reaches the
    final tolerance it stays there, so the set of distinct tolerances (and
    therefore of solver compilations keyed on them) is bounded by
    ``log(start/final) / log(1/decay)`` + 1."""

    start: float = 1e-3
    decay: float = 0.1

    def __post_init__(self):
        import math

        if not (math.isfinite(self.start) and self.start > 0):
            raise ValueError(f"schedule start must be finite and > 0, "
                             f"got {self.start}")
        if not (0 < self.decay < 1):
            raise ValueError(f"schedule decay must be in (0, 1), "
                             f"got {self.decay}")

    def at(self, step: int, final_tol: float) -> float:
        if final_tol <= 0:
            # an explicit tol <= 0 disables convergence tests entirely
            # (pinned iteration counts); a schedule must not re-enable them
            return final_tol
        return max(float(final_tol), self.start * self.decay ** max(step, 0))


@dataclasses.dataclass(frozen=True)
class PathConfig:
    """Pathwise fixed-effect solver knobs (``optimize.path.PathSolver``) —
    rides alongside :class:`OptimizerConfig` the way the reference's
    per-coordinate optimizer config rides alongside its training config.

    ``screen``: ``"strong"`` (sequential strong rule — aggressive,
    occasionally over-screens, always KKT-repaired), ``"safe"`` (double
    the strong rule's guard band — keeps marginal features on correlated
    designs, fewer repair rounds), or ``"off"`` (warm-started full-feature
    fits; the pre-path behavior). ``kkt_tol`` is the relative slack on the
    L1 weight in the violation test ``|g_j| > l1 + kkt_tol*max(l1, 1)``
    for screened coordinates. ``max_kkt_rounds`` bounds the
    screen→solve→check repair loop before falling back to a full-feature
    solve (which is trivially certified). ``min_bucket`` floors the
    power-of-two restricted width so tiny candidate sets don't mint
    single-use compilations. ``screen_slack`` inflates the screening
    threshold by ``slack * (l1_prev - l1)`` — 0 is the published rules;
    positive values deliberately over-screen (the KKT-repair adversarial
    tests and aggressiveness tuning use it). ``keep_states`` retains one
    (lambda, w, gradient) snapshot per solved lambda so out-of-order
    solves (the GP tuner) warm-start from the nearest solved neighbor;
    costs 2 * dim * 8 bytes per lambda."""

    screen: str = "strong"
    kkt_tol: float = 1e-6
    max_kkt_rounds: int = 5
    min_bucket: int = 64
    screen_slack: float = 0.0
    keep_states: bool = True

    def __post_init__(self):
        if self.screen not in ("strong", "safe", "off"):
            raise ValueError(f"screen must be strong|safe|off, "
                             f"got {self.screen!r}")
        if not (self.kkt_tol >= 0):
            raise ValueError(f"kkt_tol must be >= 0, got {self.kkt_tol}")
        if self.max_kkt_rounds < 1:
            raise ValueError(f"max_kkt_rounds must be >= 1, "
                             f"got {self.max_kkt_rounds}")
        if self.min_bucket < 1:
            raise ValueError(f"min_bucket must be >= 1, "
                             f"got {self.min_bucket}")


def parse_tolerance_schedule(spec: str) -> "ToleranceSchedule | None":
    """Parse a ``START:DECAY`` CLI spec (e.g. ``1e-3:0.1``) into a
    :class:`ToleranceSchedule`; ``off``/``none`` disable it. Raises
    ``ValueError`` with a usable message on anything malformed."""
    s = spec.strip().lower()
    if s in ("off", "none", ""):
        return None
    parts = s.split(":")
    if len(parts) != 2:
        raise ValueError(
            f"expected START:DECAY (e.g. 1e-3:0.1) or 'off', got {spec!r}")
    try:
        start, decay = float(parts[0]), float(parts[1])
    except ValueError:
        raise ValueError(
            f"expected numeric START:DECAY, got {spec!r}") from None
    return ToleranceSchedule(start, decay)


class OptimizationResult(NamedTuple):
    """Final point + convergence record (OptimizationStatesTracker role)."""

    w: jax.Array
    value: jax.Array
    grad_norm: jax.Array
    iterations: jax.Array  # i32 scalar
    converged: jax.Array  # bool scalar
    loss_history: jax.Array  # [max_iters] padded with NaN past `iterations`
    grad_norm_history: jax.Array  # [max_iters] same padding
    # streamed fits only: host-side pipeline stall accounting for the whole
    # fit (parallel/streaming.StreamStats.as_dict() — decode-wait /
    # transfer / compute-stall seconds, chunk and pass counts). None for
    # in-memory fits; never touched inside jit.
    stream_stats: "dict | None" = None
    # Restricted-problem geometry, attached HOST-SIDE after the solve
    # (never inside jit): the tolerance this fit actually converged
    # against and the width of the problem it was solved over (the
    # screened/bucketed dimension for pathwise fits, the full feature
    # dim otherwise). Logs, BENCH_path.json and the resume marker assert
    # the geometry, not just the outcome.
    solver_tolerance: "float | None" = None
    screened_dim: "int | None" = None


def converged_check(f_prev, f, g_norm, g0_norm, tol, f_scale=None):
    """Reference-style stopping rule: relative loss change below tol OR
    gradient norm below tol * max(1, ||g0||). A positive tolerance is
    clamped to a few ulps of the working dtype so a tol tuned for f64
    (e.g. 1e-9) still terminates in f32/bf16 instead of spinning to
    max_iters. An explicit tol <= 0 is honored exactly — it disables both
    tests, pinning the iteration count at max_iters (bench determinism:
    round 2's f32 run silently stopped at 15/20 "pinned" iterations
    because the clamp re-enabled the relative-loss test).

    ``f_scale``: override for the relative-test scale. Delta-space
    callers pass the accurately-summed improvement as ``f_prev=0,
    f=-delta`` (so the difference is exact, not a rounding artifact of
    two large totals) with ``f_scale`` = the current loss value."""
    dtype = jnp.asarray(f).dtype
    eps = jnp.finfo(dtype).eps
    tol = jnp.asarray(tol, dtype)
    tol = jnp.where(tol > 0, jnp.maximum(tol, 4 * eps), tol)
    scale = jnp.abs(f_prev if f_scale is None else f_scale)
    rel_loss = jnp.abs(f_prev - f) <= tol * jnp.maximum(scale, 1.0)
    grad_small = g_norm <= tol * jnp.maximum(g0_norm, 1.0)
    return (tol > 0) & (rel_loss | grad_small)


def grad_converged(g_norm, g0_norm, tol):
    """The gradient-norm half of :func:`converged_check` alone (same tol
    clamping). Used when a failed line search invalidates the relative-
    loss test (f unchanged -> zero delta would pass spuriously) but the
    gradient test remains meaningful — a search that fails AT the optimum
    must still report convergence."""
    dtype = jnp.asarray(g_norm).dtype
    eps = jnp.finfo(dtype).eps
    tol = jnp.asarray(tol, dtype)
    tol = jnp.where(tol > 0, jnp.maximum(tol, 4 * eps), tol)
    return (tol > 0) & (g_norm <= tol * jnp.maximum(g0_norm, 1.0))


def init_history(max_iters: int, dtype) -> tuple[jax.Array, jax.Array]:
    nan = jnp.full((max_iters,), jnp.nan, dtype)
    return nan, nan


def l2_norm(a):
    return jnp.sqrt(jnp.sum(a * a))


def match_vma(x, ref):
    """Give ``x`` the varying-manual-axes (vma) type of ``ref``.

    Inside ``shard_map`` (manual mode), freshly created constants (zeros,
    counters, False flags) are "unvarying" while values derived from sharded
    inputs are "varying over the mesh axis"; ``lax.while_loop`` requires carry
    input/output types to match exactly, so optimizer loop state initialized
    from constants must be cast to the gradient's vma. Outside shard_map this
    is a no-op."""
    vma = frozenset(getattr(typeof(ref), "vma", frozenset()))
    cur = frozenset(getattr(typeof(x), "vma", frozenset()))
    missing = tuple(sorted(vma - cur))
    if missing:
        x = jax.lax.pcast(x, missing, to="varying")
    return x


def match_vma_tree(tree, ref):
    return jax.tree.map(lambda x: match_vma(x, ref), tree)
