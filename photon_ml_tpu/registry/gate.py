"""Promotion gate: a candidate version earns ``LATEST`` on held-out data.

The gate scores a held-out Avro shard through the EXISTING batch path —
``io/data_reader`` -> ``game/scoring.score_game_model`` -> the
``evaluation/`` metric registry — for the candidate AND the live
version, then refuses to move the pointer when any metric regresses
beyond the configured tolerance. The verdict (both metric dicts, the
per-metric deltas, pass/fail) is recorded in the candidate's manifest
either way, so a refused version carries its own audit trail.

No live version (bootstrap registry) passes trivially: there is nothing
to regress against.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_ml_tpu.evaluation import get_evaluator, is_regression
from photon_ml_tpu.evaluation.evaluators import TASK_DEFAULT_EVALUATOR
from photon_ml_tpu.registry.delta import materialize
from photon_ml_tpu.registry.store import ModelRegistry, RegistryError

__all__ = ["GateVerdict", "evaluate_model_dir", "run_gate"]


@dataclasses.dataclass
class GateVerdict:
    """Outcome of one gate run (also serialized into the manifest)."""

    candidate: str
    live: Optional[str]
    passed: bool
    promoted: bool
    candidate_metrics: Dict[str, float]
    live_metrics: Dict[str, float]
    regressions: Dict[str, dict]
    tolerance: float

    def to_manifest(self) -> dict:
        return {
            "against": self.live,
            "passed": self.passed,
            "promoted": self.promoted,
            "candidate_metrics": self.candidate_metrics,
            "live_metrics": self.live_metrics,
            "regressions": self.regressions,
            "tolerance": self.tolerance,
            "at": time.time(),
        }


def evaluate_model_dir(model_dir: str, data_paths: Sequence[str],
                       evaluators: Sequence[str],
                       group_column: Optional[str] = None,
                       dtype=None) -> Dict[str, float]:
    """Score a labeled Avro shard with a saved model and compute the
    named metrics — the scoring driver's evaluate leg as a library call
    (one scoring code path for batch, gate, and serving parity)."""
    import jax.numpy as jnp

    from photon_ml_tpu.game.scoring import score_game_model
    from photon_ml_tpu.io.data_reader import read_training_examples
    from photon_ml_tpu.io.model_io import load_game_model, load_model_metadata
    from photon_ml_tpu.io.paldb import load_index_map
    from photon_ml_tpu.models import RandomEffectModel
    import os

    dtype = dtype or jnp.float64
    model = load_game_model(model_dir)
    meta = load_model_metadata(model_dir)
    shards = sorted({c["feature_shard"] for c in meta["coordinates"]})
    index_maps = {
        s: load_index_map(os.path.join(model_dir, f"index-map.{s}.json"))
        for s in shards}
    entity_columns = [c.entity_column for c in model.coordinates.values()
                      if isinstance(c, RandomEffectModel) and c.entity_column]
    if group_column and group_column not in entity_columns:
        entity_columns = entity_columns + [group_column]
    feats, labels, offsets, weights, ents, _uids = read_training_examples(
        data_paths, index_maps, entity_columns=entity_columns,
        require_response=True)
    scores = np.asarray(score_game_model(model, feats, ents,
                                         offsets=offsets, dtype=dtype))
    labeled = ~np.isnan(labels)
    group_ids = ents[group_column][labeled] if group_column else None
    out = {}
    for name in evaluators:
        ev = get_evaluator(name)
        out[name] = ev.evaluate(scores[labeled], labels[labeled],
                                weights[labeled], group_ids)
    return out


def run_gate(registry: ModelRegistry, candidate: str,
             data_paths: Sequence[str], *,
             evaluators: Optional[Sequence[str]] = None,
             tolerance: float = 0.0,
             group_column: Optional[str] = None,
             promote: bool = True,
             metrics_sink=None) -> GateVerdict:
    """Gate ``candidate`` against the live version on ``data_paths``.

    ``evaluators`` defaults to the candidate task's default metric.
    ``tolerance`` is the largest acceptable regression in a metric's own
    units (AUC points, RMSE units, ...) — strictly-worse-by-more-than-
    tolerance on ANY metric refuses promotion. ``metrics_sink`` (a
    ``serve.ServingMetrics``) gets the verdict counted when provided."""
    from photon_ml_tpu.io.model_io import load_model_metadata

    live = registry.read_latest()
    if live == candidate:
        raise RegistryError(f"candidate {candidate!r} is already live")
    candidate_dir = materialize(registry, candidate)
    if not evaluators:
        task = load_model_metadata(candidate_dir)["task"]
        evaluators = [TASK_DEFAULT_EVALUATOR[task]]
    candidate_metrics = evaluate_model_dir(
        candidate_dir, data_paths, evaluators, group_column)
    live_metrics: Dict[str, float] = {}
    regressions: Dict[str, dict] = {}
    if live is not None:
        live_metrics = evaluate_model_dir(
            materialize(registry, live), data_paths, evaluators,
            group_column)
        for name in evaluators:
            ev = get_evaluator(name)
            cand, base = candidate_metrics[name], live_metrics[name]
            if is_regression(ev, cand, base, tolerance):
                regressions[name] = {
                    "candidate": _jsonable(cand), "live": _jsonable(base),
                    "higher_is_better": ev.higher_is_better,
                }
    passed = not regressions
    verdict = GateVerdict(
        candidate=candidate, live=live, passed=passed,
        promoted=passed and promote,
        candidate_metrics={k: _jsonable(v)
                           for k, v in candidate_metrics.items()},
        live_metrics={k: _jsonable(v) for k, v in live_metrics.items()},
        regressions=regressions, tolerance=float(tolerance))
    registry.update_manifest(candidate, gate=verdict.to_manifest())
    if metrics_sink is not None:
        metrics_sink.record_gate(passed)
    if verdict.promoted:
        registry.set_latest(candidate)
    return verdict


def _jsonable(v: float):
    v = float(v)
    return None if math.isnan(v) else v
