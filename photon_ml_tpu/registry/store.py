"""Append-only versioned model registry rooted at a directory.

Layout::

    <root>/
      LATEST                      # JSON {"version": "v000003"}; atomic
      versions/
        v000001/
          MANIFEST.json           # payload + per-artifact fingerprints
          model/...               # io/model_io layout (full or delta)
        .tmp-<pid>-<n>/           # in-flight publish (ignored by readers)
      .resolved/
        v000003/                  # materialized delta cache (delta.py)

Invariants the serving/GC sides program against:

* a ``versions/<v>`` directory is COMPLETE the instant it exists — the
  whole tree (payload + manifest) is staged in a sibling ``.tmp-`` dir
  and renamed into place in one ``os.rename``;
* ``LATEST`` is written last (after the version rename) via temp file +
  ``os.replace``, so a reader can never see a pointer to a version that
  is not fully on disk;
* readers tolerate a concurrent publish: ``.tmp-`` dirs are ignored
  everywhere, and a ``LATEST`` read retries briefly on ENOENT (a
  registry being bootstrapped) before reporting "no live version";
* GC never collects the live version or ANY ancestor in its delta
  chain — collecting a delta's parent would orphan the live model.

Manifests are written through :class:`parallel.resilience.ResumeManager`
so the per-artifact content fingerprints ride the SAME embedded-
fingerprint + verify contract as the training resume markers: tampered
or truncated artifacts surface as a ``ResumeMismatch`` naming the exact
file, not as silently wrong scores.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import time
from typing import Dict, List, Optional

from photon_ml_tpu.io.durable import durable_dir_rename, durable_replace
from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.parallel.resilience import ResumeManager

__all__ = ["ModelRegistry", "RegistryError", "ResolvedVersion",
           "SCHEMA_VERSION"]

SCHEMA_VERSION = 1
_VERSION_RE = re.compile(r"^v(\d{6})$")
_MANIFEST = "MANIFEST.json"
_MODEL = "model"


class RegistryError(RuntimeError):
    """A registry operation failed (missing version, corrupt pointer,
    exhausted publish retries)."""


class ResolvedVersion:
    """A version resolved to its model-directory chain, topmost first
    (``chain[0]`` is the version's own payload, later entries its delta
    ancestry ending at a full publish). ``ScoringSession`` and the
    materializer consume this; a plain full version has a 1-dir chain."""

    __slots__ = ("version", "chain")

    def __init__(self, version: str, chain: List[str]):
        self.version = version
        self.chain = list(chain)

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"ResolvedVersion({self.version!r}, {len(self.chain)} layers)"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def artifact_fingerprints(version_dir: str) -> Dict[str, str]:
    """relpath -> sha256 for every file under ``<version_dir>/model`` —
    the manifest's embedded fingerprint dict."""
    root = os.path.join(version_dir, _MODEL)
    out: Dict[str, str] = {}
    for dirpath, _dirs, files in os.walk(root):
        for name in sorted(files):
            full = os.path.join(dirpath, name)
            out[os.path.relpath(full, root)] = _sha256_file(full)
    return out


class ModelRegistry:
    """One registry root. Construction is cheap and touches nothing;
    directories are created lazily on first publish."""

    def __init__(self, root: str):
        self.root = str(root)
        self.versions_root = os.path.join(self.root, "versions")
        self.latest_path = os.path.join(self.root, "LATEST")
        self.resolved_root = os.path.join(self.root, ".resolved")
        self._tmp_seq = 0

    # -- read side ---------------------------------------------------------
    def list_versions(self) -> List[str]:
        """Complete versions, oldest first. ``.tmp-`` staging dirs from
        in-flight (or crashed) publishes are ignored — a version exists
        only once its atomic rename landed."""
        if not os.path.isdir(self.versions_root):
            return []
        return sorted(d for d in os.listdir(self.versions_root)
                      if _VERSION_RE.match(d)
                      and os.path.isdir(os.path.join(self.versions_root, d)))

    def version_dir(self, version: str) -> str:
        return os.path.join(self.versions_root, version)

    def model_dir(self, version: str) -> str:
        """The version's own payload dir (a delta version's payload is
        PARTIAL — use :meth:`open_version` / ``delta.materialize`` for a
        loadable view)."""
        return os.path.join(self.version_dir(version), _MODEL)

    def manifest_path(self, version: str) -> str:
        return os.path.join(self.version_dir(version), _MANIFEST)

    def manifest(self, version: str) -> dict:
        path = self.manifest_path(version)
        if not os.path.exists(path):
            raise RegistryError(f"no version {version!r} in {self.root} "
                                f"(known: {self.list_versions()})")
        return ResumeManager(path).load(verify=False)

    def read_latest(self, retries: int = 3, delay_s: float = 0.02
                    ) -> Optional[str]:
        """The live version name, or None when nothing was promoted yet.

        ``LATEST`` is replaced atomically, so a missing file normally
        means "never promoted" — but a reader racing the very first
        promotion (or a registry on a filesystem replaying a rename) can
        transiently see ENOENT, so the read retries briefly before
        concluding the registry has no live version. Persistent garbage
        (a hand-edited pointer) raises instead of silently serving
        nothing."""
        err: Optional[Exception] = None
        for attempt in range(max(1, int(retries))):
            if attempt:
                time.sleep(delay_s)
            try:
                with open(self.latest_path) as f:
                    record = json.load(f)
                version = record["version"]
            except FileNotFoundError:
                err = None
                continue
            except (json.JSONDecodeError, KeyError, TypeError) as e:
                err = e  # partial/hand-mangled pointer: retry then raise
                continue
            if not self._exists(version):
                # pointer ahead of a publish we cannot see yet (or to a
                # GC'd version — operator error): retry, then raise
                err = RegistryError(
                    f"LATEST points at missing version {version!r}")
                continue
            return version
        if err is not None:
            raise RegistryError(f"unreadable LATEST pointer at "
                                f"{self.latest_path}: {err}")
        return None

    def _exists(self, version: str) -> bool:
        return os.path.exists(self.manifest_path(version))

    def parent_chain(self, version: str) -> List[str]:
        """``[version, parent, grandparent, ...]`` ending at the full
        publish a delta chain resolves against."""
        chain, seen = [], set()
        v: Optional[str] = version
        while v is not None:
            if v in seen:
                raise RegistryError(f"parent cycle at {v!r}")
            seen.add(v)
            chain.append(v)
            v = self.manifest(v).get("parent")
        return chain

    def open_version(self, version: str) -> ResolvedVersion:
        """Resolve a version to its model-dir chain (topmost first) —
        the object ``ScoringSession`` loads and swaps to."""
        return ResolvedVersion(
            version, [self.model_dir(v) for v in self.parent_chain(version)])

    def verify(self, version: str) -> dict:
        """Recompute every artifact fingerprint and check it against the
        manifest (the ResumeManager embedded-fingerprint contract);
        raises ``ResumeMismatch`` naming the diverging file(s)."""
        path = self.manifest_path(version)
        current = artifact_fingerprints(self.version_dir(version))
        return ResumeManager(path, fingerprint=current).load()

    # -- write side --------------------------------------------------------
    def _staging_dir(self) -> str:
        self._tmp_seq += 1
        return os.path.join(self.versions_root,
                            f".tmp-{os.getpid()}-{self._tmp_seq}")

    def _next_version(self) -> str:
        versions = self.list_versions()
        n = int(_VERSION_RE.match(versions[-1]).group(1)) if versions else 0
        return f"v{n + 1:06d}"

    def publish(self, source_model_dir: Optional[str] = None, *,
                writer=None, metrics: Optional[dict] = None,
                parent: Optional[str] = None, delta: bool = False,
                extra: Optional[dict] = None,
                set_latest: bool = False) -> str:
        """Publish one immutable version; returns its name.

        The payload comes from copying ``source_model_dir`` or from
        ``writer(dst_dir)`` (the delta publisher). The whole version —
        payload plus fingerprinted manifest — is staged under a
        ``.tmp-`` sibling and renamed into ``versions/<v>`` in one
        ``os.rename``; a concurrent publisher losing the race for ``<v>``
        simply retries under the next number. ``LATEST`` moves only when
        ``set_latest`` (normally the gate's job)."""
        if (source_model_dir is None) == (writer is None):
            raise ValueError("publish needs exactly one of "
                             "source_model_dir or writer")
        if parent is not None and not self._exists(parent):
            raise RegistryError(f"parent version {parent!r} not in registry")
        os.makedirs(self.versions_root, exist_ok=True)
        staging = self._staging_dir()
        try:
            if source_model_dir is not None:
                if not os.path.exists(
                        os.path.join(source_model_dir, "metadata.json")):
                    raise RegistryError(
                        f"{source_model_dir} is not a saved model dir "
                        "(no metadata.json)")
                shutil.copytree(source_model_dir,
                                os.path.join(staging, _MODEL))
            else:
                os.makedirs(os.path.join(staging, _MODEL))
                writer(os.path.join(staging, _MODEL))
            fingerprints = artifact_fingerprints(staging)
            # crash window A: payload staged, nothing renamed — readers
            # and GC must ignore the leftover .tmp- dir
            fault_injection.check("registry.publish_prepared")
            version = None
            for _ in range(100):
                candidate = self._next_version()
                payload = {
                    "schema_version": SCHEMA_VERSION,
                    "version": candidate,
                    "parent": parent,
                    "delta": bool(delta),
                    "created_at": time.time(),
                    "metrics": dict(metrics or {}),
                    "gate": None,
                }
                payload.update(extra or {})
                ResumeManager(os.path.join(staging, _MANIFEST),
                              fingerprint=fingerprints).save(payload)
                try:
                    # durable: fsync staging + parent around the rename so
                    # a power loss can't surface a "complete" version dir
                    # whose entries never reached disk (io/durable.py)
                    durable_dir_rename(staging, self.version_dir(candidate))
                except OSError:
                    continue  # lost the number to a concurrent publish
                version = candidate
                break
            if version is None:
                raise RegistryError(
                    "publish retries exhausted (100 concurrent-publish "
                    f"collisions under {self.versions_root})")
        except BaseException:
            # an EXCEPTION unwinds the staging dir; a crash (SIGKILL,
            # injected at the sites above) leaves it for readers to
            # ignore and a later `gc(clean_staging=True)` to sweep
            if os.path.isdir(staging):
                shutil.rmtree(staging, ignore_errors=True)
            raise
        # crash window B: version landed but LATEST not moved — the
        # version is visible/garbage-collectable, pointer still old
        fault_injection.check("registry.published")
        if set_latest:
            self.set_latest(version)
        return version

    def set_latest(self, version: str) -> None:
        """Atomically AND durably repoint ``LATEST`` (temp file + fsync +
        ``os.replace`` + parent-dir fsync, same discipline as every
        marker in this repo — io/durable.py). Also the rollback
        primitive: point it back at any retained version."""
        if not self._exists(version):
            raise RegistryError(f"cannot promote missing version "
                                f"{version!r} (known: {self.list_versions()})")
        os.makedirs(self.root, exist_ok=True)
        tmp = f"{self.latest_path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": version, "promoted_at": time.time()}, f)
        durable_replace(tmp, self.latest_path)

    def update_manifest(self, version: str, **fields) -> dict:
        """Rewrite a version's manifest payload with ``fields`` merged in
        (atomic; artifact fingerprints preserved). Used by the gate to
        record its verdict — the ONLY sanctioned post-publish mutation."""
        path = self.manifest_path(version)
        mgr = ResumeManager(path)
        payload = mgr.load(verify=False)
        if payload is None:
            raise RegistryError(f"no version {version!r} in {self.root}")
        stored_fp = payload.pop(ResumeManager._FP_KEY, None)
        payload.update(fields)
        ResumeManager(path, fingerprint=stored_fp).save(payload)
        return payload

    # -- retention ---------------------------------------------------------
    def protected_versions(self) -> List[str]:
        """The live version plus its whole delta ancestry — the set GC
        must never touch (collecting a delta's parent orphans the live
        model)."""
        live = self.read_latest(retries=1)
        if live is None:
            return []
        return self.parent_chain(live)

    def gc(self, keep: int = 2, clean_staging: bool = False,
           staging_grace_s: float = 3600.0) -> List[str]:
        """Collect old versions, keeping the newest ``keep`` plus the
        live version's full parent chain. Returns the removed names.

        Concurrent-publish tolerance: ``.tmp-`` staging dirs are never
        counted as versions and are left alone unless ``clean_staging``
        — and even then only when older than ``staging_grace_s``, so a
        publish in flight on another process is never swept out from
        under its rename."""
        versions = self.list_versions()
        protected = set(self.protected_versions())
        protected.update(versions[-max(0, int(keep)):] if keep else [])
        removed = []
        for v in versions:
            if v in protected:
                continue
            shutil.rmtree(self.version_dir(v), ignore_errors=True)
            shutil.rmtree(os.path.join(self.resolved_root, v),
                          ignore_errors=True)
            removed.append(v)
        if clean_staging and os.path.isdir(self.versions_root):
            now = time.time()
            for d in sorted(os.listdir(self.versions_root)):
                if not d.startswith(".tmp-"):
                    continue
                full = os.path.join(self.versions_root, d)
                try:
                    if now - os.path.getmtime(full) > staging_grace_s:
                        shutil.rmtree(full, ignore_errors=True)
                except OSError:  # pragma: no cover - raced the publisher
                    pass
        return removed
