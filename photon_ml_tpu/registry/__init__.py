"""Model lifecycle: versioned registry, delta publish, promotion gate.

The training drivers produce model directories; the serving stack keeps
one resident. This package is the seam between them — the Snap ML lesson
(PAPERS.md, arXiv:1803.06333) that the train->deploy pipeline is a
first-class hierarchical system, not a "restart the server at a new
path" afterthought:

* :class:`~photon_ml_tpu.registry.store.ModelRegistry` — append-only
  versioned store: every publish lands an immutable ``versions/<v>/``
  via temp-dir + atomic rename, with a manifest carrying per-artifact
  content fingerprints (the PR-1 resilience fingerprint contract) and a
  ``LATEST`` pointer written last; retention GC never collects the live
  version or its delta ancestry.
* :mod:`~photon_ml_tpu.registry.delta` — incremental publish: a version
  may carry only the CHANGED per-entity random-effect records (plus
  optional replacement fixed-effect coordinates), resolved against its
  parent chain at load time — a retrain that touched 1% of entities
  publishes 1% of the bytes.
* :mod:`~photon_ml_tpu.registry.gate` — promotion gate: score a
  held-out Avro shard through ``game/scoring.py``, compare
  ``evaluation/`` metrics against the live version, refuse to move
  ``LATEST`` on regression beyond tolerance, and record the verdict in
  the manifest.

Serving-side hot swap lives in ``serve/`` (``ScoringSession.swap``,
``/admin/reload``, ``serve/watcher.py``). See docs/lifecycle.md.
"""

from photon_ml_tpu.registry.store import (
    ModelRegistry,
    RegistryError,
    ResolvedVersion,
)
from photon_ml_tpu.registry.delta import (
    compute_delta,
    materialize,
    publish_delta,
)
from photon_ml_tpu.registry.gate import GateVerdict, run_gate

__all__ = [
    "ModelRegistry", "RegistryError", "ResolvedVersion",
    "compute_delta", "materialize", "publish_delta",
    "GateVerdict", "run_gate",
]
