"""Incremental (delta) publish and parent-chain resolution.

A full version's ``model/`` dir is a complete ``io/model_io`` tree. A
DELTA version's ``model/`` dir is the same layout, but its random-effect
``coefficients.avro`` files hold ONLY the entities whose records changed
against the parent (and its ``fixed-effect/`` subtree holds only
replaced coordinates). ``metadata.json`` and the index maps are always
copied in full (they are tiny and make every version self-describing);
a delta REFUSES to publish when the index maps differ from the parent's
— a changed feature space silently remapping the parent's untouched
coefficients is exactly the corruption a delta must never introduce.

Resolution is layered, topmost first: the serving coefficient cache
checks the delta layer before falling back down the chain
(``serve/coeff_cache.LayeredCoefficientStore``), so a hot-swap to a
delta touches only the changed bytes; batch consumers (the gate, the
scoring driver) call :func:`materialize` to merge the chain into one
complete, loadable model dir (cached under ``<root>/.resolved/<v>``,
built atomically)."""

from __future__ import annotations

import dataclasses
import os
import shutil
from typing import Dict, List, Optional

from photon_ml_tpu.io.avro import read_avro_file, write_avro_file
from photon_ml_tpu.io.model_io import load_model_metadata
from photon_ml_tpu.io.schemas import BAYESIAN_LINEAR_MODEL_SCHEMA
from photon_ml_tpu.registry.store import ModelRegistry, RegistryError

__all__ = ["DeltaSpec", "compute_delta", "publish_delta", "materialize"]

_RE_FILE = os.path.join("random-effect", "{name}", "coefficients.avro")
_FE_FILE = os.path.join("fixed-effect", "{name}", "coefficients.avro")


@dataclasses.dataclass
class DeltaSpec:
    """What changed between a new model dir and its parent."""

    changed_fixed: List[str]
    # coordinate -> changed/added RandomEffectModel records (parent order
    # is irrelevant: records are keyed by modelId at every consumer)
    random_effect_updates: Dict[str, List[dict]]
    unchanged_entities: Dict[str, int]

    @property
    def empty(self) -> bool:
        return not self.changed_fixed and not any(
            self.random_effect_updates.values())


def _file_bytes_equal(a: str, b: str) -> bool:
    try:
        if os.path.getsize(a) != os.path.getsize(b):
            return False
        with open(a, "rb") as fa, open(b, "rb") as fb:
            while True:
                ba, bb = fa.read(1 << 20), fb.read(1 << 20)
                if ba != bb:
                    return False
                if not ba:
                    return True
    except OSError:
        return False


def _records_by_id(path: str) -> Dict[str, dict]:
    if not os.path.exists(path):
        return {}
    records, _ = read_avro_file(path)
    return {str(r["modelId"]): r for r in records}


def compute_delta(new_model_dir: str, parent_model_dir: str) -> DeltaSpec:
    """Diff two COMPLETE model dirs (the parent side is the materialized
    parent). Coordinate structure and index maps must match — anything
    else needs a full publish."""
    meta_new = load_model_metadata(new_model_dir)
    meta_par = load_model_metadata(parent_model_dir)
    key = lambda m: [(c["name"], c["type"], c["feature_shard"])
                     for c in m["coordinates"]]
    if meta_new["task"] != meta_par["task"] or key(meta_new) != key(meta_par):
        raise ValueError(
            "delta publish needs an identical coordinate structure "
            f"(new={key(meta_new)} task={meta_new['task']!r}, "
            f"parent={key(meta_par)} task={meta_par['task']!r}); "
            "publish a full version instead")
    shards = {c["feature_shard"] for c in meta_new["coordinates"]}
    for shard in shards:
        name = f"index-map.{shard}.json"
        if not _file_bytes_equal(os.path.join(new_model_dir, name),
                                 os.path.join(parent_model_dir, name)):
            raise ValueError(
                f"index map for shard {shard!r} differs from the "
                "parent's — a delta cannot remap the parent's feature "
                "space; publish a full version instead")
    changed_fixed, re_updates, unchanged = [], {}, {}
    for c in meta_new["coordinates"]:
        if c["type"] == "fixed":
            rel = _FE_FILE.format(name=c["name"])
            if not _file_bytes_equal(os.path.join(new_model_dir, rel),
                                     os.path.join(parent_model_dir, rel)):
                changed_fixed.append(c["name"])
        else:
            rel = _RE_FILE.format(name=c["name"])
            new = _records_by_id(os.path.join(new_model_dir, rel))
            par = _records_by_id(os.path.join(parent_model_dir, rel))
            removed = sorted(set(par) - set(new))
            if removed:
                raise ValueError(
                    f"random effect {c['name']!r} dropped entities "
                    f"{removed[:5]}{'...' if len(removed) > 5 else ''} — "
                    "deltas are additive (layered lookup cannot express "
                    "a removal); publish a full version instead")
            changed = [rec for eid, rec in new.items()
                       if par.get(eid) != rec]
            re_updates[c["name"]] = changed
            unchanged[c["name"]] = len(new) - len(changed)
    return DeltaSpec(changed_fixed, re_updates, unchanged)


def _write_delta_tree(dst: str, new_model_dir: str, meta: dict,
                      spec: DeltaSpec) -> None:
    shutil.copy2(os.path.join(new_model_dir, "metadata.json"),
                 os.path.join(dst, "metadata.json"))
    for shard in sorted({c["feature_shard"] for c in meta["coordinates"]}):
        name = f"index-map.{shard}.json"
        shutil.copy2(os.path.join(new_model_dir, name),
                     os.path.join(dst, name))
    for name in spec.changed_fixed:
        rel = _FE_FILE.format(name=name)
        os.makedirs(os.path.dirname(os.path.join(dst, rel)), exist_ok=True)
        shutil.copy2(os.path.join(new_model_dir, rel),
                     os.path.join(dst, rel))
    for name, records in spec.random_effect_updates.items():
        if not records:
            continue  # untouched coordinate: resolved from the parent
        rel = _RE_FILE.format(name=name)
        os.makedirs(os.path.dirname(os.path.join(dst, rel)), exist_ok=True)
        write_avro_file(os.path.join(dst, rel),
                        sorted(records, key=lambda r: str(r["modelId"])),
                        BAYESIAN_LINEAR_MODEL_SCHEMA)


def publish_delta(registry: ModelRegistry, new_model_dir: str, *,
                  parent: Optional[str] = None,
                  metrics: Optional[dict] = None,
                  set_latest: bool = False) -> str:
    """Publish ``new_model_dir`` as a delta against ``parent`` (default:
    the live version). The delta is computed against the parent's
    MATERIALIZED view, so chaining deltas on deltas stays correct.
    Returns the new version name."""
    parent = parent or registry.read_latest()
    if parent is None:
        raise RegistryError(
            "delta publish needs a parent version and the registry has "
            "no LATEST; publish a full version first")
    parent_dir = materialize(registry, parent)
    spec = compute_delta(new_model_dir, parent_dir)
    meta = load_model_metadata(new_model_dir)
    version = registry.publish(
        writer=lambda dst: _write_delta_tree(dst, new_model_dir, meta, spec),
        metrics=metrics, parent=parent, delta=True,
        extra={"delta_summary": {
            "changed_fixed": spec.changed_fixed,
            "changed_entities": {k: len(v) for k, v
                                 in spec.random_effect_updates.items()},
            "unchanged_entities": spec.unchanged_entities,
        }},
        set_latest=set_latest)
    return version


def materialize(registry: ModelRegistry, version: str,
                dest: Optional[str] = None) -> str:
    """A COMPLETE model dir for ``version``: the version's own payload
    when it is a full publish, else the parent chain merged (topmost
    record wins) into ``dest`` (default ``<root>/.resolved/<version>``,
    built in a temp dir and renamed atomically; an existing resolved
    cache is reused — versions are immutable, so it can never be
    stale)."""
    chain = registry.parent_chain(version)
    if len(chain) == 1 and not registry.manifest(version).get("delta"):
        return registry.model_dir(version)
    dirs = [registry.model_dir(v) for v in chain]  # topmost first
    dest = dest or os.path.join(registry.resolved_root, version)
    if os.path.exists(os.path.join(dest, "metadata.json")):
        return dest
    tmp = f"{dest}.tmp-{os.getpid()}"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        meta = load_model_metadata(dirs[0])
        shutil.copy2(os.path.join(dirs[0], "metadata.json"),
                     os.path.join(tmp, "metadata.json"))
        for shard in sorted({c["feature_shard"] for c in meta["coordinates"]}):
            name = f"index-map.{shard}.json"
            shutil.copy2(_topmost(dirs, name), os.path.join(tmp, name))
        for c in meta["coordinates"]:
            rel = (_FE_FILE if c["type"] == "fixed" else _RE_FILE).format(
                name=c["name"])
            os.makedirs(os.path.dirname(os.path.join(tmp, rel)),
                        exist_ok=True)
            if c["type"] == "fixed":
                shutil.copy2(_topmost(dirs, rel), os.path.join(tmp, rel))
                continue
            merged: Dict[str, dict] = {}
            for layer in reversed(dirs):  # oldest first: topmost wins
                merged.update(_records_by_id(os.path.join(layer, rel)))
            write_avro_file(
                os.path.join(tmp, rel),
                [merged[k] for k in sorted(merged)],
                BAYESIAN_LINEAR_MODEL_SCHEMA)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        try:
            os.rename(tmp, dest)
        except OSError:
            # a concurrent materialize won the rename; its result is
            # byte-identical (deterministic writer over immutable inputs)
            shutil.rmtree(tmp, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return dest


def _topmost(dirs: List[str], rel: str) -> str:
    for d in dirs:
        path = os.path.join(d, rel)
        if os.path.exists(path):
            return path
    raise RegistryError(f"artifact {rel!r} missing from every layer of "
                        f"the chain ({dirs})")
