"""Auto-tune GAME regularization weights after the explicit grid.

Equivalent of the reference's GAME Bayesian-tuning path (SURVEY.md §4.5:
GameTrainingDriver seeds a GaussianProcessSearch with the evaluated grid
points, then runs fit→evaluate rounds; best model across grid + tuned
points wins). The tunable surface is each coordinate's ``reg_weight`` on a
log scale — the same surface the reference tunes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from photon_ml_tpu.estimators import GameEstimator, GameFitResult
from photon_ml_tpu.evaluation import get_evaluator
from photon_ml_tpu.game.descent import CoordinateConfig, GameDataset
from photon_ml_tpu.tuning.search import (
    GaussianProcessSearch,
    ParamRange,
    RandomSearch,
)


def resolve_tuned_coordinates(
    base_configs: Sequence[CoordinateConfig],
    tuned_coordinates: Optional[Sequence[str]],
    locked: Sequence[str] = (),
) -> List[str]:
    """Which coordinates' reg weights move during tuning: the explicit list,
    else every unlocked coordinate. Shared by the driver's fail-fast check
    and ``tune_game`` so the two can't disagree."""
    tuned = list(tuned_coordinates
                 if tuned_coordinates is not None
                 else [c.name for c in base_configs
                       if c.name not in set(locked)])
    unknown = set(tuned) - {c.name for c in base_configs}
    if unknown:
        raise ValueError(f"tuned coordinates not in configs: {sorted(unknown)}")
    if not tuned:
        raise ValueError("no coordinates to tune")
    return tuned


def tune_glm_path(
    estimator,
    n_iterations: int,
    batch=None,
    chunks=None,
    dim=None,
    validation_batch=None,
    mode: str = "bayesian",
    reg_range: Tuple[float, float] = (1e-4, 1e4),
    prior_results: Sequence = (),
    seed: int = 0,
    round_size: int = 1,
    fit_callback=None,
):
    """Tune the fixed-effect regularization weight over a SHARED pathwise
    solver (``estimators.GlmPathEstimator`` / ``optimize.path``): every
    trial's solve screens and warm-starts from the nearest already-solved
    lambda, so the union of all trials is one incrementally-extended
    regularization path — trials sharing a lambda prefix pay only their
    new tail, not a cold full-feature fit each. ``round_size > 1``
    proposes that many lambdas per round and walks them in decreasing
    order (``search.find(batch=, eval_order=)``), the screening-friendly
    direction. ``prior_results`` (e.g. the driver grid's
    ``GlmPathFitResult`` list) seed the surrogate. Returns one
    ``GlmPathFitResult`` per trial; ``estimator.select_best`` over
    grid + tuned picks the winner. Total solver work is visible as
    ``estimator.solver().total_iterations`` — the tuner test asserts it
    beats independent cold fits."""
    if not estimator.evaluator_names:
        raise ValueError("tuning needs at least one evaluator on the estimator")
    if mode not in ("random", "bayesian"):
        raise ValueError(f"tuning mode must be random|bayesian, got {mode}")
    if validation_batch is None:
        raise ValueError("tune_glm_path needs a validation batch to score")
    primary = estimator.evaluator_names[0]
    evaluator = get_evaluator(primary)
    ranges = [ParamRange("reg_weight", reg_range[0], reg_range[1], log=True)]

    results = []

    def evaluate(params: Dict[str, float]) -> float:
        fit = estimator.fit([params["reg_weight"]], batch=batch,
                            chunks=chunks, dim=dim,
                            validation_batch=validation_batch)[0]
        results.append(fit)
        if fit_callback is not None:
            fit_callback(len(results) - 1, fit)
        return fit.metrics[primary]

    search_cls = GaussianProcessSearch if mode == "bayesian" else RandomSearch
    search = search_cls(ranges, evaluate, seed=seed,
                        maximize=evaluator.higher_is_better)
    for prior in prior_results:
        if primary not in prior.metrics:
            continue
        if reg_range[0] <= prior.reg_weight <= reg_range[1]:
            search.on_prior_observation({"reg_weight": prior.reg_weight},
                                        prior.metrics[primary])
    search.find(n_iterations, batch=round_size,
                eval_order=lambda p: -p["reg_weight"])
    return results


def tune_game(
    estimator: GameEstimator,
    train: GameDataset,
    validation: GameDataset,
    base_configs: Sequence[CoordinateConfig],
    n_iterations: int,
    mode: str = "bayesian",
    reg_range: Tuple[float, float] = (1e-4, 1e4),
    prior_results: Sequence[GameFitResult] = (),
    seed: int = 0,
    tuned_coordinates: Optional[Sequence[str]] = None,
    fit_callback=None,
    warm_start=None,
    locked: Sequence[str] = (),
) -> List[GameFitResult]:
    """Run ``n_iterations`` tuning rounds; returns one GameFitResult per
    round. ``prior_results`` (e.g. the evaluated grid) seed the surrogate.
    ``tuned_coordinates`` restricts which coordinates' reg_weights move
    (default: all). ``fit_callback(round_index, result)`` fires per round.
    """
    if not estimator.evaluator_names:
        raise ValueError("tuning needs at least one evaluator on the estimator")
    if mode not in ("random", "bayesian"):
        raise ValueError(f"tuning mode must be random|bayesian, got {mode}")
    tuned = resolve_tuned_coordinates(base_configs, tuned_coordinates, locked)

    primary = estimator.evaluator_names[0]
    evaluator = get_evaluator(primary)
    ranges = [ParamRange(name, reg_range[0], reg_range[1], log=True)
              for name in tuned]

    results: List[GameFitResult] = []
    dataset_cache: dict = {}  # per-entity bucketing built once, not per round

    def evaluate(params: Dict[str, float]) -> float:
        configs = [
            dataclasses.replace(c, reg_weight=params[c.name])
            if c.name in params else c
            for c in base_configs
        ]
        fits = estimator.fit(train, validation, config_grid=[configs],
                             warm_start=warm_start, locked=locked,
                             dataset_cache=dataset_cache)
        result = fits[0]
        results.append(result)
        if fit_callback is not None:
            fit_callback(len(results) - 1, result)
        return result.evaluation.metrics[primary]

    search_cls = GaussianProcessSearch if mode == "bayesian" else RandomSearch
    search = search_cls(ranges, evaluate, seed=seed,
                        maximize=evaluator.higher_is_better)
    for prior in prior_results:
        if prior.evaluation is None or primary not in prior.evaluation.metrics:
            continue
        by_name = {c.name: c for c in prior.configs}
        if not all(name in by_name for name in tuned):
            continue
        params = {}
        in_range = True
        for name in tuned:
            w = by_name[name].reg_weight
            if not (reg_range[0] <= w <= reg_range[1]):
                in_range = False
                break
            params[name] = w
        if in_range:
            search.on_prior_observation(params,
                                        prior.evaluation.metrics[primary])
    search.find(n_iterations)
    return results
