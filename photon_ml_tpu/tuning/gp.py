"""Gaussian-process regression with a Matérn-5/2 kernel.

Equivalent of the reference's ``hyperparameter.estimators.{GaussianProcess-
Estimator, GaussianProcessModel}`` (SURVEY.md §3.1; reference mount empty —
upstream linkedin/photon-ml uses a Matérn-5/2 GP surrogate for GAME
regularization-weight auto-tuning). Plain NumPy: the observation sets are
tiny (tens of points), so a jitted path would be all compile time.

Inputs are expected in the unit hypercube (the search layer normalizes);
targets are standardized internally. Kernel length-scale and noise are
chosen by log-marginal-likelihood over a small grid — the same "fit the
surrogate each round" role as the reference's estimator, without an external
optimizer dependency.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np


def matern52(x1: np.ndarray, x2: np.ndarray, lengthscale: float,
             amplitude: float = 1.0) -> np.ndarray:
    """Matérn-5/2 kernel matrix between row-stacked points."""
    x1 = np.atleast_2d(np.asarray(x1, np.float64))
    x2 = np.atleast_2d(np.asarray(x2, np.float64))
    d2 = ((x1[:, None, :] - x2[None, :, :]) ** 2).sum(-1)
    r = np.sqrt(np.maximum(d2, 0.0)) / max(lengthscale, 1e-12)
    s5r = np.sqrt(5.0) * r
    return amplitude * (1.0 + s5r + 5.0 / 3.0 * r * r) * np.exp(-s5r)


@dataclasses.dataclass(frozen=True)
class GaussianProcessModel:
    """Posterior GP over standardized targets; ``predict`` de-standardizes."""

    x_train: np.ndarray
    alpha: np.ndarray          # K⁻¹ y (via Cholesky solves)
    chol: np.ndarray           # lower Cholesky factor of K + σ²I
    lengthscale: float
    amplitude: float
    noise: float
    y_mean: float
    y_std: float

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at query points (original target scale)."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        k_star = matern52(x, self.x_train, self.lengthscale, self.amplitude)
        mean = k_star @ self.alpha
        v = np.linalg.solve(self.chol, k_star.T)
        var = self.amplitude - (v * v).sum(axis=0)
        var = np.maximum(var, 1e-12)
        return (mean * self.y_std + self.y_mean, np.sqrt(var) * self.y_std)


def _log_marginal_likelihood(y: np.ndarray, chol: np.ndarray,
                             alpha: np.ndarray) -> float:
    n = len(y)
    return float(
        -0.5 * y @ alpha
        - np.log(np.diag(chol)).sum()
        - 0.5 * n * np.log(2.0 * np.pi)
    )


def fit_gp(
    x: np.ndarray,
    y: np.ndarray,
    lengthscales: Optional[np.ndarray] = None,
    noises: Optional[np.ndarray] = None,
) -> GaussianProcessModel:
    """Fit hyperparameters by exact log-marginal-likelihood over a grid.

    ``x``: (n, d) in the unit hypercube; ``y``: (n,) raw metric values.
    """
    x = np.atleast_2d(np.asarray(x, np.float64))
    y = np.asarray(y, np.float64)
    y_mean = float(y.mean())
    y_std = float(y.std())
    if y_std < 1e-12:
        y_std = 1.0
    ys = (y - y_mean) / y_std

    if lengthscales is None:
        lengthscales = np.geomspace(0.05, 2.0, 8)
    if noises is None:
        noises = np.array([1e-6, 1e-4, 1e-2])

    best = None
    n = len(ys)
    for ls in lengthscales:
        k_base = matern52(x, x, float(ls), 1.0)
        for noise in noises:
            try:
                chol = np.linalg.cholesky(k_base + noise * np.eye(n))
            except np.linalg.LinAlgError:
                continue
            alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, ys))
            lml = _log_marginal_likelihood(ys, chol, alpha)
            if best is None or lml > best[0]:
                best = (lml, float(ls), float(noise), chol, alpha)
    if best is None:  # pathological inputs: fall back to heavy jitter
        noise = 1.0
        chol = np.linalg.cholesky(matern52(x, x, 1.0, 1.0) + noise * np.eye(n))
        alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, ys))
        best = (0.0, 1.0, noise, chol, alpha)
    _, ls, noise, chol, alpha = best
    return GaussianProcessModel(
        x_train=x, alpha=alpha, chol=chol, lengthscale=ls, amplitude=1.0,
        noise=noise, y_mean=y_mean, y_std=y_std,
    )
