"""Hyperparameter search strategies: random and GP-guided (Bayesian).

Equivalent of the reference's ``hyperparameter.search.{RandomSearch,
GaussianProcessSearch}`` + ``EvaluationFunction`` (SURVEY.md §3.1/§4.5;
reference mount empty). The evaluation function is any callable
``params_dict -> float``; search keeps (vector, value) observations, may be
seeded with prior observations (the reference seeds from the evaluated
grid points), and proposes the next configuration either uniformly at
random or by maximizing expected improvement under a Matérn-5/2 GP
surrogate over a random candidate pool.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Sequence

import numpy as np
from scipy.special import erf as _erf

from photon_ml_tpu.tuning.gp import fit_gp


@dataclasses.dataclass(frozen=True)
class ParamRange:
    """One tunable parameter: bounds plus scale. ``log=True`` searches in
    log-space (the natural scale for regularization weights)."""

    name: str
    low: float
    high: float
    log: bool = False
    integer: bool = False

    def __post_init__(self):
        if not (self.high > self.low):
            raise ValueError(f"{self.name}: need high > low, got "
                             f"[{self.low}, {self.high}]")
        if self.log and self.low <= 0:
            raise ValueError(f"{self.name}: log-scale range needs low > 0")

    def to_unit(self, value: float) -> float:
        if self.log:
            u = (math.log(value) - math.log(self.low)) / (
                math.log(self.high) - math.log(self.low))
        else:
            u = (value - self.low) / (self.high - self.low)
        return min(max(u, 0.0), 1.0)

    def from_unit(self, u: float) -> float:
        u = min(max(float(u), 0.0), 1.0)
        if self.log:
            value = math.exp(
                math.log(self.low)
                + u * (math.log(self.high) - math.log(self.low))
            )
        else:
            value = self.low + u * (self.high - self.low)
        if self.integer:
            value = round(value)
        return value


@dataclasses.dataclass(frozen=True)
class Observation:
    params: Dict[str, float]
    value: float


class RandomSearch:
    """Uniform search over the unit hypercube (log-warped per ParamRange)."""

    def __init__(
        self,
        ranges: Sequence[ParamRange],
        evaluation_function: Callable[[Dict[str, float]], float],
        seed: int = 0,
        maximize: bool = True,
    ):
        if not ranges:
            raise ValueError("need at least one ParamRange")
        names = [r.name for r in ranges]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate parameter names: {names}")
        self.ranges = list(ranges)
        self.evaluation_function = evaluation_function
        self.maximize = maximize
        self.rng = np.random.default_rng(seed)
        self.observations: List[Observation] = []

    # -- observation bookkeeping ----------------------------------------
    def on_prior_observation(self, params: Dict[str, float], value: float):
        """Seed the search with an already-evaluated configuration (the
        reference seeds from the explicit grid — SURVEY.md §4.5)."""
        self.observations.append(Observation(dict(params), float(value)))

    def _vectorize(self, params: Dict[str, float]) -> np.ndarray:
        return np.array([r.to_unit(params[r.name]) for r in self.ranges])

    def _devectorize(self, u: np.ndarray) -> Dict[str, float]:
        return {r.name: r.from_unit(u[i]) for i, r in enumerate(self.ranges)}

    def best(self) -> Observation:
        if not self.observations:
            raise ValueError("no observations yet")
        key = (max if self.maximize else min)
        return key(self.observations, key=lambda o: o.value)

    # -- proposal --------------------------------------------------------
    def propose(self) -> Dict[str, float]:
        return self._devectorize(self.rng.random(len(self.ranges)))

    def propose_batch(self, k: int) -> List[Dict[str, float]]:
        """``k`` proposals from the CURRENT posterior/state, before any of
        them is evaluated (random search: independent draws)."""
        return [self.propose() for _ in range(k)]

    def find(self, n: int, batch: int = 1,
             eval_order: Callable[[Dict[str, float]], float] | None = None,
             ) -> List[Observation]:
        """Run ``n`` propose→evaluate rounds; returns the new observations.

        With ``batch > 1`` each round proposes ``batch`` configurations
        up front and evaluates them all before re-fitting, in ascending
        ``eval_order(params)`` order when given. The GLM path tuner
        (``tuning.game_tuner.tune_glm_path``) orders each round by
        DESCENDING reg weight so the round walks the regularization path
        downward, reusing the shared path solver's warm states
        sequentially instead of cold-starting every trial."""
        new: List[Observation] = []
        for _ in range(n):
            proposals = self.propose_batch(batch)
            if eval_order is not None:
                proposals = sorted(proposals, key=eval_order)
            for params in proposals:
                value = float(self.evaluation_function(params))
                obs = Observation(params, value)
                self.observations.append(obs)
                new.append(obs)
        return new


class GaussianProcessSearch(RandomSearch):
    """Bayesian search: fit a GP to observations each round, propose the
    candidate maximizing expected improvement over a random pool."""

    def __init__(
        self,
        ranges: Sequence[ParamRange],
        evaluation_function: Callable[[Dict[str, float]], float],
        seed: int = 0,
        maximize: bool = True,
        candidate_pool: int = 512,
        exploration: float = 0.01,
    ):
        super().__init__(ranges, evaluation_function, seed, maximize)
        self.candidate_pool = candidate_pool
        self.exploration = exploration

    def _expected_improvement(self, mean, std, best_value) -> np.ndarray:
        # maximize-form EI; minimize flips signs
        if self.maximize:
            improve = mean - best_value - self.exploration
        else:
            improve = best_value - mean - self.exploration
        z = improve / std
        cdf = 0.5 * (1.0 + _erf(z / math.sqrt(2.0)))
        pdf = np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
        return improve * cdf + std * pdf

    def propose(self) -> Dict[str, float]:
        return self.propose_batch(1)[0]

    def propose_batch(self, k: int):
        if len(self.observations) < 2:
            return [super(GaussianProcessSearch, self).propose()
                    for _ in range(k)]
        x = np.stack([self._vectorize(o.params) for o in self.observations])
        y = np.array([o.value for o in self.observations])
        gp = fit_gp(x, y)
        candidates = self.rng.random((self.candidate_pool, len(self.ranges)))
        mean, std = gp.predict(candidates)
        ei = self._expected_improvement(mean, std, self.best().value)
        # batched rounds take the k best-EI pool members (distinct by
        # construction: the pool is k >> batch random candidates) from
        # ONE posterior — a cheap q-EI stand-in that keeps each GLM-path
        # tuning round a single downward walk of the lambda path
        top = np.argsort(ei)[::-1][:k]
        return [self._devectorize(candidates[int(i)]) for i in top]
