"""Bayesian hyperparameter tuning (SURVEY.md §3.1 "Hyperparameter tuning",
§4.5 call stack; reference mount empty).

Equivalent of the reference's ``hyperparameter.estimators.{GaussianProcess-
Estimator, GaussianProcessModel}`` and ``hyperparameter.search.{RandomSearch,
GaussianProcessSearch}``: a Gaussian-process surrogate with a Matérn-5/2
kernel fit to (hyperparameter-vector, metric) observations, maximizing
expected improvement to propose the next configuration; random search as the
baseline strategy. Used by the GAME training driver to auto-tune
regularization weights after the explicit grid is evaluated.
"""

from photon_ml_tpu.tuning.gp import GaussianProcessModel, fit_gp, matern52
from photon_ml_tpu.tuning.search import (
    GaussianProcessSearch,
    ParamRange,
    RandomSearch,
)
from photon_ml_tpu.tuning.game_tuner import (
    resolve_tuned_coordinates,
    tune_game,
    tune_glm_path,
)

__all__ = [
    "GaussianProcessModel",
    "GaussianProcessSearch",
    "ParamRange",
    "RandomSearch",
    "fit_gp",
    "matern52",
    "resolve_tuned_coordinates",
    "tune_game",
    "tune_glm_path",
]
