"""Model diagnostics — the classic driver's diagnostic stage.

The reference's legacy ``Driver`` ends with a diagnostics stage (SURVEY.md
§3.3: "staged pipeline (... → validate → diagnostics)"): goodness-of-fit
and model-quality reports alongside the trained models. TPU-native
equivalents here:

* ``hosmer_lemeshow``: decile goodness-of-fit test for binary models.
* ``bootstrap_coefficients``: coefficient confidence intervals via
  multinomial-weight bootstrap, run as a **vmap of the jitted L-BFGS fit**
  — R replicate fits execute as one batched XLA program instead of R
  cluster jobs (the TPU answer to the reference's driver-side bootstrap).
* ``feature_importance``: |w_j| * std_j ranking (scale-adjusted weight
  magnitude).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.optimize.lbfgs import lbfgs
from photon_ml_tpu.types import LabeledBatch


def hosmer_lemeshow(
    probabilities: np.ndarray,
    labels: np.ndarray,
    n_bins: int = 10,
) -> Dict[str, float]:
    """Hosmer–Lemeshow chi-square over probability deciles. Returns the
    statistic, degrees of freedom, and p-value (chi2 survival function)."""
    probabilities = np.asarray(probabilities, np.float64)
    labels = np.asarray(labels, np.float64)
    order = np.argsort(probabilities)
    p_sorted = probabilities[order]
    y_sorted = labels[order]
    bins = np.array_split(np.arange(len(p_sorted)), n_bins)
    stat = 0.0
    used = 0
    for idx in bins:
        if len(idx) == 0:
            continue
        exp = float(p_sorted[idx].sum())
        obs = float(y_sorted[idx].sum())
        n = len(idx)
        denom = exp * (1.0 - exp / n)
        if denom <= 0:
            continue
        stat += (obs - exp) ** 2 / denom
        used += 1
    dof = max(used - 2, 1)
    from scipy.stats import chi2

    return {"statistic": stat, "dof": dof, "p_value": float(chi2.sf(stat, dof))}


def bootstrap_coefficients(
    objective: GLMObjective,
    batch: LabeledBatch,
    w_hat: jax.Array,
    l2: float = 0.0,
    n_replicates: int = 32,
    seed: int = 0,
    config: Optional[OptimizerConfig] = None,
    ci: float = 0.95,
) -> Dict[str, np.ndarray]:
    """Percentile confidence intervals for coefficients.

    Bootstrap resampling is expressed as multinomial example weights (the
    weight-space formulation — no data copy), and every replicate warm-starts
    from ``w_hat``; ``vmap`` batches all replicate L-BFGS fits into one XLA
    program."""
    if config is None:
        config = OptimizerConfig(max_iters=50)
    n = batch.num_examples

    @jax.jit
    def run_all(key):
        from photon_ml_tpu.compat import random_multinomial

        counts = random_multinomial(
            key, n, jnp.full((n,), 1.0 / n), shape=(n_replicates, n)
        ).astype(batch.weights.dtype)

        def one(boot_counts):
            b = batch.replace(weights=batch.weights * boot_counts)
            res = lbfgs(lambda w: objective.value_and_grad(w, b, l2),
                        w_hat, config)
            return res.w

        return jax.vmap(one)(counts)

    ws = np.asarray(run_all(jax.random.key(seed)))  # [R, d]
    alpha = (1.0 - ci) / 2.0
    return {
        "mean": ws.mean(axis=0),
        "std": ws.std(axis=0, ddof=1),
        "lower": np.quantile(ws, alpha, axis=0),
        "upper": np.quantile(ws, 1.0 - alpha, axis=0),
        "replicates": ws,
    }


def feature_importance(
    w: np.ndarray,
    feature_std: Optional[np.ndarray] = None,
    top_k: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """Rank features by scale-adjusted coefficient magnitude
    ``|w_j| * std_j`` (plain ``|w_j|`` when no summary is available)."""
    w = np.asarray(w)
    score = np.abs(w) * (np.asarray(feature_std) if feature_std is not None
                         else 1.0)
    order = np.argsort(-score)
    if top_k is not None:
        order = order[:top_k]
    return {"index": order, "score": score[order]}
