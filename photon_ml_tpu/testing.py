"""Test scaffolding: synthetic datasets and fixtures.

Equivalent of the reference's ``photon-test-utils`` module
(``SparkTestUtils``/``GameTestUtils``/``CommonTestUtils`` — SURVEY.md §3.5;
reference mount empty, paths unverified). The local-mode-Spark role is played
by the virtual CPU device mesh (``tests/conftest.py`` sets
``--xla_force_host_platform_device_count``); this module supplies the
deterministic synthetic data generators: plain GLM problems, mixed-effect
(GAME) datasets with known fixed/random-effect structure, and Avro fixture
writers for driver-level integration tests.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class SyntheticGLM:
    X: np.ndarray  # [n, d] dense
    y: np.ndarray  # [n]
    w_true: np.ndarray  # [d]
    offsets: np.ndarray
    weights: np.ndarray


def synthetic_glm_data(
    n: int = 500,
    d: int = 10,
    task: str = "logistic",
    seed: int = 0,
    density: float = 1.0,
    with_offsets: bool = False,
    with_weights: bool = False,
) -> SyntheticGLM:
    """A well-specified GLM problem with known coefficients."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    if density < 1.0:
        X *= rng.random((n, d)) < density
    w = rng.normal(size=d)
    offsets = rng.normal(size=n) * 0.1 if with_offsets else np.zeros(n)
    weights = rng.uniform(0.5, 2.0, size=n) if with_weights else np.ones(n)
    m = X @ w + offsets
    if task == "logistic" or task == "smoothed_hinge":
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-m))).astype(float)
    elif task == "poisson":
        y = rng.poisson(np.exp(np.clip(m, None, 5.0))).astype(float)
    else:  # squared / linear
        y = m + rng.normal(size=n) * 0.1
    return SyntheticGLM(X, y, w, offsets, weights)


@dataclasses.dataclass(frozen=True)
class SyntheticGame:
    """Mixed-effect data with known structure: global fixed effect plus one
    coefficient vector per entity per random effect."""

    features: Dict[str, np.ndarray]  # shard -> [n, d_shard]
    labels: np.ndarray
    entity_ids: Dict[str, np.ndarray]  # column -> [n]
    w_fixed: np.ndarray
    random_effects: Dict[str, np.ndarray]  # column -> [n_entities, d_shard]


def synthetic_game_data(
    n_entities: Dict[str, int] = None,
    d_fixed: int = 6,
    d_random: int = 3,
    rows_per_entity: Tuple[int, int] = (15, 45),
    task: str = "logistic",
    seed: int = 0,
) -> SyntheticGame:
    """Generate GAME data: every row belongs to one entity per random-effect
    column; margins sum the fixed effect and each entity's effect (the model
    ``CoordinateDescent`` should recover — SURVEY.md §4.1)."""
    if n_entities is None:
        n_entities = {"userId": 20}
    rng = np.random.default_rng(seed)
    w_fixed = rng.normal(size=d_fixed)
    effects = {
        col: rng.normal(size=(count, d_random)) * 1.5
        for col, count in n_entities.items()
    }
    # rows are grouped by the FIRST entity column; other columns get random
    # entity assignments (crossed random effects)
    first = next(iter(n_entities))
    Xg_parts, Xr_parts, y_parts, ids = [], [], [], {c: [] for c in n_entities}
    for e in range(n_entities[first]):
        m_rows = int(rng.integers(*rows_per_entity))
        xg = rng.normal(size=(m_rows, d_fixed))
        xr = rng.normal(size=(m_rows, d_random))
        margin = xg @ w_fixed + xr @ effects[first][e]
        ids[first].append(np.full(m_rows, e))
        for col in list(n_entities)[1:]:
            assign = rng.integers(0, n_entities[col], size=m_rows)
            ids[col].append(assign)
            margin = margin + np.sum(xr * effects[col][assign], axis=1)
        if task == "logistic":
            y = (rng.random(m_rows) < 1 / (1 + np.exp(-margin))).astype(float)
        else:
            y = margin + rng.normal(size=m_rows) * 0.1
        Xg_parts.append(xg)
        Xr_parts.append(xr)
        y_parts.append(y)
    features = {
        "global": np.concatenate(Xg_parts),
        "entity": np.concatenate(Xr_parts),
    }
    return SyntheticGame(
        features=features,
        labels=np.concatenate(y_parts),
        entity_ids={c: np.concatenate(v) for c, v in ids.items()},
        w_fixed=w_fixed,
        random_effects=effects,
    )


def game_dataset_from_synthetic(data: SyntheticGame, share_features: bool = False):
    """Build a GameDataset (both shards, entity ids) from synthetic data.
    ``share_features=True`` exposes only the 'global' shard (fixed-effect-
    only tests)."""
    from photon_ml_tpu.game.descent import make_game_dataset

    feats = ({"global": data.features["global"]} if share_features
             else dict(data.features))
    return make_game_dataset(feats, labels=data.labels,
                             entity_ids=dict(data.entity_ids))


def write_game_avro_fixture(
    path: str,
    data: SyntheticGame,
    rows: Optional[np.ndarray] = None,
    feature_prefixes: Dict[str, str] = None,
) -> None:
    """Write synthetic GAME rows as TrainingExampleAvro for driver tests.
    Feature names are ``<prefix><j>`` per shard (prefix defaults: 'g' for
    global, 'u' for entity), so shard configs can select by prefix."""
    from photon_ml_tpu.io.data_reader import write_training_examples

    if feature_prefixes is None:
        feature_prefixes = {"global": "g", "entity": "u"}
    if rows is None:
        rows = np.arange(len(data.labels))

    def tuples():
        for i in rows:
            row = []
            for shard, prefix in feature_prefixes.items():
                X = data.features[shard]
                row += [(f"{prefix}{j}", "", float(X[i, j]))
                        for j in range(X.shape[1])]
            yield row

    write_training_examples(
        path, tuples(), data.labels[rows],
        entity_ids={c: v[rows] for c, v in data.entity_ids.items()},
        uids=[str(i) for i in rows],
    )


# -- simulated multi-controller runtime ------------------------------------
# The moral equivalent of local-mode Spark for FAILURE paths: N "processes"
# are N threads sharing one interpreter, each with its own resilience
# transport endpoint, so every coordinated-abort path (health barriers,
# guards, watchdog) runs the production code against deterministic injected
# faults (parallel/fault_injection.py) without real OS processes or a real
# coordinator. jax itself stays single-process (collectives reduce over the
# virtual CPU device mesh), which is exactly what makes the harness cheap
# enough for tier-1.

class Dropped:
    """Outcome sentinel: the simulated process died silently (fail-stop
    without a report — fault kind 'drop') or never finished in time."""

    def __repr__(self):  # pragma: no cover - debugging aid
        return "<Dropped>"


class _SimGroup:
    """Shared N-way status-exchange rendezvous (generation-counted so
    consecutive barriers don't mix). A participant that never arrives
    starves the round; waiters raise WatchdogTimeout — the simulated
    equivalent of a dead peer wedging a real allgather. Deaths are
    DECLARED by the runner supervisor when a simulated process's thread
    exits (for any reason), so waiters fail a starved round immediately
    instead of sitting out the full watchdog, and the elastic-recovery
    rendezvous (:meth:`recover`) knows which peers can still arrive."""

    def __init__(self, n: int):
        self.n = n
        self.cond = threading.Condition()
        self.gen = 0
        self.slots: Dict[int, Dict[int, int]] = {}
        self.results: Dict[int, List[int]] = {}
        # ranks whose thread has exited (cleanly, dropped, or crashed);
        # under fail-stop an exited rank can never deposit again
        self.deaths: set = set()
        # elastic recovery state: per-epoch survivor registration and the
        # shrunk child group each completed epoch produced
        self.recovery_epoch = 0
        self.recovery_reg: Dict[int, dict] = {}
        self.recovery_done: Dict[int, tuple] = {}
        # (child_group, {parent_rank: child_rank}) per completed recovery
        # — death declarations cascade into live children, and the runner
        # verifies child traces at join
        self.children: List[tuple] = []
        # per-rank collective event sequences, recorded at CALL time (a
        # process that dies inside a rendezvous still recorded its
        # intent) and verified at join by the collective-trace sanitizer
        self.traces: Dict[int, list] = {i: [] for i in range(n)}

    def record(self, rank: int, op: str, payload) -> None:
        from photon_ml_tpu.analysis.sanitizers import describe_payload
        from photon_ml_tpu.parallel.resilience import current_collective_site

        self.traces[rank].append(
            (op, current_collective_site(), describe_payload(payload)))

    def declare_dead(self, rank: int) -> None:
        """Mark ``rank``'s simulated process as gone (its thread exited).
        Wakes every waiter — a round the dead rank never joined fails
        immediately — and cascades into shrunk child groups so
        post-recovery collectives learn about it too."""
        with self.cond:
            self.deaths.add(rank)
            self.cond.notify_all()
            children = list(self.children)
        for child, rank_map in children:
            if rank in rank_map:
                child.declare_dead(rank_map[rank])

    def exchange(self, rank: int, code: int, timeout: float) -> List[int]:
        from photon_ml_tpu.parallel.resilience import CODE_ERROR, WatchdogTimeout

        deadline = time.monotonic() + timeout
        with self.cond:
            gen = self.gen
            slot = self.slots.setdefault(gen, {})
            slot[rank] = code
            if len(slot) == self.n:
                self.results[gen] = [slot[i] for i in range(self.n)]
                self.gen += 1
                self.cond.notify_all()
                return list(self.results[gen])
            while gen not in self.results:
                # a declared-dead peer that never deposited can never
                # complete this round: fail fast with the same taxonomy
                # the watchdog would use, naming the dead ranks
                dead_missing = sorted(self.deaths - set(slot))
                if dead_missing:
                    raise WatchdogTimeout(
                        f"simulated peer process(es) {dead_missing} died "
                        "before joining this collective round (fail-stop)",
                        failed={r: CODE_ERROR for r in dead_missing})
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    missing = sorted(set(range(self.n)) - set(slot))
                    raise WatchdogTimeout(
                        f"simulated health barrier timed out after "
                        f"{timeout:.1f}s: processes {missing} never "
                        "reported (fail-stop)",
                        failed={r: CODE_ERROR for r in missing})
                self.cond.wait(remaining)
            return list(self.results[gen])

    def recover(self, rank: int, payload, timeout: float):
        """Surviving-set recovery rendezvous: every LIVE rank registers a
        payload; when the registered set covers every not-declared-dead
        rank, a shrunk child :class:`_SimGroup` is created once and every
        survivor returns ``(survivor_ranks, payloads, child_group)`` —
        survivor ranks sorted, payloads in that order, and each
        survivor's child rank is its index in the sorted list. A live
        rank that never registers starves the rendezvous; waiters raise
        WatchdogTimeout (recovery itself is bounded, never a hang)."""
        from photon_ml_tpu.parallel.resilience import CODE_ERROR, WatchdogTimeout

        deadline = time.monotonic() + timeout
        with self.cond:
            epoch = self.recovery_epoch
            reg = self.recovery_reg.setdefault(epoch, {})
            reg[rank] = payload
            self.cond.notify_all()
            while epoch not in self.recovery_done:
                live = set(range(self.n)) - self.deaths
                if set(reg) >= live:
                    survivors = sorted(reg)
                    child = _SimGroup(len(survivors))
                    rank_map = {r: i for i, r in enumerate(survivors)}
                    # a survivor that registered and then died before the
                    # group formed is already gone: seed the child's
                    # deaths so its first round fails fast
                    child.deaths = {rank_map[r] for r in survivors
                                    if r in self.deaths}
                    self.children.append((child, rank_map))
                    self.recovery_done[epoch] = (
                        survivors, [reg[r] for r in survivors], child)
                    self.recovery_epoch = epoch + 1
                    self.cond.notify_all()
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    missing = sorted(live - set(reg))
                    raise WatchdogTimeout(
                        f"recovery rendezvous timed out after "
                        f"{timeout:.1f}s: live processes {missing} never "
                        "joined recovery",
                        failed={r: CODE_ERROR for r in missing})
                self.cond.wait(remaining)
            return self.recovery_done[epoch]


class ThreadTransport:
    """One simulated process's endpoint onto a :class:`_SimGroup`.

    Both allgather legs pass the ``transport.allgather`` fault site on
    the way in — a crash schedule can kill a rank MID-COLLECTIVE (after
    peers committed to the round, before this rank deposits), the
    nastiest point in the fail-stop state space."""

    def __init__(self, group: _SimGroup, rank: int):
        self._group = group
        self._rank = rank

    def process_index(self) -> int:
        return self._rank

    def process_count(self) -> int:
        return self._group.n

    def allgather_status(self, code: int, timeout: float) -> List[int]:
        from photon_ml_tpu.parallel import fault_injection

        fault_injection.check("transport.allgather")
        self._group.record(self._rank, "status", code)
        return self._group.exchange(self._rank, code, timeout)

    def allgather_payload(self, payload, timeout: float) -> list:
        """Generation-counted N-way PAYLOAD exchange (the data leg of the
        simulated transport, used by the entity-shard score exchange):
        returns every process's payload in rank order. It shares the
        status-exchange rendezvous, so payload and status collectives
        stay SPMD-ordered exactly like the real runtime's in-order
        collective stream — and a peer that never arrives surfaces as
        WatchdogTimeout here too."""
        from photon_ml_tpu.parallel import fault_injection

        fault_injection.check("transport.allgather")
        self._group.record(self._rank, "payload", payload)
        return self._group.exchange(self._rank, payload, timeout)

    def recover(self, payload, timeout: float):
        """Elastic-recovery rendezvous over the surviving set: block until
        every live rank registers, then return ``(survivor_ranks,
        payloads, new_transport)`` where the new transport is this
        process's endpoint onto the SHRUNK group (its rank is its index
        in the sorted survivor list). Only the simulated transport
        supports shrink — the production jax runtime cannot resize a
        running job, which is why ``recovery.recovery_supported``
        capability-gates on this method."""
        survivors, payloads, child = self._group.recover(
            self._rank, payload, timeout)
        return (survivors, payloads,
                ThreadTransport(child, survivors.index(self._rank)))


def run_simulated_processes(n: int, fn: Callable, *,
                            join_timeout: float = 120.0,
                            verify_collectives: bool = True,
                            verify_lock_order: bool = True,
                            verify_thread_leaks: bool = True,
                            verify_determinism: bool = True) -> list:
    """Run ``fn(process_index)`` on ``n`` simulated processes (threads,
    each under its own resilience transport + fault-injection process
    context) and return the per-process OUTCOMES: the return value,
    the raised exception object, or :class:`Dropped` for a process that
    died silently / never finished. Exceptions are captured, not raised —
    fault tests assert on the whole outcome vector.

    ``verify_collectives`` (default on) runs the collective-trace
    sanitizer at join: every process's recorded collective sequence
    (op, site, payload kind) must be a prefix of the longest one —
    fail-stop processes stop early, but a process must never issue a
    DIFFERENT collective. Divergence raises
    :class:`~photon_ml_tpu.analysis.sanitizers.CollectiveTraceMismatch`
    naming the step, sites, and ranks. Skipped when a thread is still
    alive at ``join_timeout`` (its trace is still moving).

    ``verify_lock_order`` (default on) arms the lock-order sanitizer
    over the run: locks CREATED by ``fn`` (or anything it builds) are
    instrumented, and an acquisition-order cycle across the simulated
    processes raises
    :class:`~photon_ml_tpu.analysis.sanitizers.LockOrderViolation` with
    both stacks — after the outcomes are collected (deferred mode), so
    a violation never corrupts the outcome vector itself.

    ``verify_thread_leaks`` (default on) asserts no new live
    photon-named thread outlives the run (after a bounded grace):
    :class:`~photon_ml_tpu.analysis.sanitizers.ThreadLeakError` names
    the survivors. Skipped when a sim thread itself is still alive at
    ``join_timeout`` — the timeout is the finding there, and fault
    tests that interrogate it opt out explicitly.

    ``verify_determinism`` (default on) arms the determinism sanitizer
    over the run: every block the stack marks with
    ``sanitizers.deterministic_replay`` (delta computation, payload
    pack/unpack, gather reassembly, sweep resyncs) executes twice, and
    a bitwise divergence raises
    :class:`~photon_ml_tpu.analysis.sanitizers.DeterminismViolation`
    in the offending simulated process, naming the block and the first
    differing array index — the PN5xx lint's runtime twin, proving the
    parity-bearing blocks are pure functions of their inputs on every
    harness run."""
    from photon_ml_tpu.analysis.sanitizers import (
        DeterminismSanitizer,
        LockOrderSanitizer,
        ThreadLeakSanitizer,
    )
    from photon_ml_tpu.parallel import fault_injection, resilience

    group = _SimGroup(n)
    outcomes: list = [Dropped() for _ in range(n)]

    def run(rank: int):
        transport = ThreadTransport(group, rank)
        try:
            with resilience.use_transport(transport), \
                    fault_injection.process_context(rank):
                outcomes[rank] = fn(rank)
        except fault_injection.DroppedProcess:
            pass  # stays Dropped: this process reports nothing to anyone
        except BaseException as e:
            outcomes[rank] = e
        finally:
            # fail-stop bookkeeping: however this process ended, it will
            # never deposit into another round — peers stuck waiting on
            # it fail their round immediately instead of eating the full
            # watchdog, and the recovery rendezvous stops expecting it
            group.declare_dead(rank)

    leak_san = ThreadLeakSanitizer() if verify_thread_leaks else None
    if leak_san is not None:
        leak_san.__enter__()
    lock_san = (LockOrderSanitizer(immediate=False)
                if verify_lock_order else None)
    if lock_san is not None:
        lock_san.__enter__()
    # armed across the whole run so replay hooks fire inside every sim
    # process; a violation raises in the offending thread and lands in
    # its outcome slot like any other exception
    det_san = DeterminismSanitizer() if verify_determinism else None
    if det_san is not None:
        det_san.__enter__()
    try:
        threads = [threading.Thread(target=run, args=(i,), daemon=True,
                                    name=f"sim-process-{i}")
                   for i in range(n)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + join_timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
    finally:
        if det_san is not None:
            det_san.__exit__(None, None, None)
        if lock_san is not None:
            lock_san.__exit__(None, None, None)
    any_alive = any(t.is_alive() for t in threads)
    if verify_collectives and not any_alive:
        from photon_ml_tpu.analysis.sanitizers import (
            CollectiveTraceSanitizer,
        )

        # Site labels are compared strictly only on CLEAN runs: a
        # guard reporting a local failure pairs its barrier with
        # whatever barrier the healthy peers reach next (tags differ
        # by design there), but op/payload-kind streams must align
        # regardless. A run that RECOVERED from an injected fault ends
        # with clean outcomes while its traces contain such a pairing,
        # so an armed fault plan also disables strict sites.
        clean = (not any(isinstance(o, (BaseException, Dropped))
                         for o in outcomes)
                 and not fault_injection.installed())
        CollectiveTraceSanitizer.verify(
            group.traces, context=f"{n} simulated processes",
            strict_sites=clean)
        # shrunk post-recovery groups carry their own collective streams;
        # the prefix discipline (a dead rank stops early, never diverges)
        # applies to each of them too
        pending = list(group.children)
        depth = 0
        while pending:
            child, _ = pending.pop()
            depth += 1
            CollectiveTraceSanitizer.verify(
                child.traces,
                context=f"recovery child group {depth} of {n} simulated "
                        "processes",
                strict_sites=False)
            pending.extend(child.children)
    if lock_san is not None:
        lock_san.check()
    if leak_san is not None and not any_alive:
        leak_san.check()
    return outcomes


def run_supervised_processes(n: int, fn: Callable, *,
                             max_restarts: int = 2,
                             backoff_s: float = 0.05,
                             backoff_factor: float = 2.0,
                             jitter: float = 0.1,
                             sleep: Callable = time.sleep,
                             **sim_kwargs) -> Tuple[list, int]:
    """Whole-job respawn-with-backoff supervision over
    :func:`run_simulated_processes` — the simulated equivalent of a pod
    scheduler relaunching a failed multi-controller job. Each attempt
    runs on a FRESH rendezvous group (the production jax runtime cannot
    rejoin a single rank into a live SPMD job; restart granularity is
    the job, which is exactly the drivers' resume-marker/exit-75
    contract). A failed attempt (any exception or Dropped outcome)
    respawns after a jittered exponential backoff, up to
    ``max_restarts`` restarts.

    ``fn`` may accept ``(rank)`` or ``(rank, attempt)`` — the attempt
    index lets a driver-style body enable ``--auto-resume`` behavior on
    respawns. Returns ``(outcomes, attempts)`` where ``outcomes`` is the
    LAST attempt's outcome vector."""
    import inspect

    from photon_ml_tpu.parallel.resilience import Backoff

    try:
        params = inspect.signature(fn).parameters
        wants_attempt = len(params) >= 2
    except (TypeError, ValueError):
        wants_attempt = False
    backoff = Backoff(base_s=backoff_s, factor=backoff_factor,
                      max_s=60.0, jitter=jitter)
    attempts = 0
    while True:
        a = attempts
        call = (lambda rank: fn(rank, a)) if wants_attempt else fn
        outcomes = run_simulated_processes(n, call, **sim_kwargs)
        attempts += 1
        failed = any(isinstance(o, (BaseException, Dropped))
                     for o in outcomes)
        if not failed or attempts > max_restarts:
            return outcomes, attempts
        sleep(backoff.next_delay())
