"""Test scaffolding: synthetic datasets and fixtures.

Equivalent of the reference's ``photon-test-utils`` module
(``SparkTestUtils``/``GameTestUtils``/``CommonTestUtils`` — SURVEY.md §3.5;
reference mount empty, paths unverified). The local-mode-Spark role is played
by the virtual CPU device mesh (``tests/conftest.py`` sets
``--xla_force_host_platform_device_count``); this module supplies the
deterministic synthetic data generators: plain GLM problems, mixed-effect
(GAME) datasets with known fixed/random-effect structure, and Avro fixture
writers for driver-level integration tests.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class SyntheticGLM:
    X: np.ndarray  # [n, d] dense
    y: np.ndarray  # [n]
    w_true: np.ndarray  # [d]
    offsets: np.ndarray
    weights: np.ndarray


def synthetic_glm_data(
    n: int = 500,
    d: int = 10,
    task: str = "logistic",
    seed: int = 0,
    density: float = 1.0,
    with_offsets: bool = False,
    with_weights: bool = False,
) -> SyntheticGLM:
    """A well-specified GLM problem with known coefficients."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    if density < 1.0:
        X *= rng.random((n, d)) < density
    w = rng.normal(size=d)
    offsets = rng.normal(size=n) * 0.1 if with_offsets else np.zeros(n)
    weights = rng.uniform(0.5, 2.0, size=n) if with_weights else np.ones(n)
    m = X @ w + offsets
    if task == "logistic" or task == "smoothed_hinge":
        y = (rng.random(n) < 1.0 / (1.0 + np.exp(-m))).astype(float)
    elif task == "poisson":
        y = rng.poisson(np.exp(np.clip(m, None, 5.0))).astype(float)
    else:  # squared / linear
        y = m + rng.normal(size=n) * 0.1
    return SyntheticGLM(X, y, w, offsets, weights)


@dataclasses.dataclass(frozen=True)
class SyntheticGame:
    """Mixed-effect data with known structure: global fixed effect plus one
    coefficient vector per entity per random effect."""

    features: Dict[str, np.ndarray]  # shard -> [n, d_shard]
    labels: np.ndarray
    entity_ids: Dict[str, np.ndarray]  # column -> [n]
    w_fixed: np.ndarray
    random_effects: Dict[str, np.ndarray]  # column -> [n_entities, d_shard]


def synthetic_game_data(
    n_entities: Dict[str, int] = None,
    d_fixed: int = 6,
    d_random: int = 3,
    rows_per_entity: Tuple[int, int] = (15, 45),
    task: str = "logistic",
    seed: int = 0,
) -> SyntheticGame:
    """Generate GAME data: every row belongs to one entity per random-effect
    column; margins sum the fixed effect and each entity's effect (the model
    ``CoordinateDescent`` should recover — SURVEY.md §4.1)."""
    if n_entities is None:
        n_entities = {"userId": 20}
    rng = np.random.default_rng(seed)
    w_fixed = rng.normal(size=d_fixed)
    effects = {
        col: rng.normal(size=(count, d_random)) * 1.5
        for col, count in n_entities.items()
    }
    # rows are grouped by the FIRST entity column; other columns get random
    # entity assignments (crossed random effects)
    first = next(iter(n_entities))
    Xg_parts, Xr_parts, y_parts, ids = [], [], [], {c: [] for c in n_entities}
    for e in range(n_entities[first]):
        m_rows = int(rng.integers(*rows_per_entity))
        xg = rng.normal(size=(m_rows, d_fixed))
        xr = rng.normal(size=(m_rows, d_random))
        margin = xg @ w_fixed + xr @ effects[first][e]
        ids[first].append(np.full(m_rows, e))
        for col in list(n_entities)[1:]:
            assign = rng.integers(0, n_entities[col], size=m_rows)
            ids[col].append(assign)
            margin = margin + np.sum(xr * effects[col][assign], axis=1)
        if task == "logistic":
            y = (rng.random(m_rows) < 1 / (1 + np.exp(-margin))).astype(float)
        else:
            y = margin + rng.normal(size=m_rows) * 0.1
        Xg_parts.append(xg)
        Xr_parts.append(xr)
        y_parts.append(y)
    features = {
        "global": np.concatenate(Xg_parts),
        "entity": np.concatenate(Xr_parts),
    }
    return SyntheticGame(
        features=features,
        labels=np.concatenate(y_parts),
        entity_ids={c: np.concatenate(v) for c, v in ids.items()},
        w_fixed=w_fixed,
        random_effects=effects,
    )


def game_dataset_from_synthetic(data: SyntheticGame, share_features: bool = False):
    """Build a GameDataset (both shards, entity ids) from synthetic data.
    ``share_features=True`` exposes only the 'global' shard (fixed-effect-
    only tests)."""
    from photon_ml_tpu.game.descent import make_game_dataset

    feats = ({"global": data.features["global"]} if share_features
             else dict(data.features))
    return make_game_dataset(feats, labels=data.labels,
                             entity_ids=dict(data.entity_ids))


def write_game_avro_fixture(
    path: str,
    data: SyntheticGame,
    rows: Optional[np.ndarray] = None,
    feature_prefixes: Dict[str, str] = None,
) -> None:
    """Write synthetic GAME rows as TrainingExampleAvro for driver tests.
    Feature names are ``<prefix><j>`` per shard (prefix defaults: 'g' for
    global, 'u' for entity), so shard configs can select by prefix."""
    from photon_ml_tpu.io.data_reader import write_training_examples

    if feature_prefixes is None:
        feature_prefixes = {"global": "g", "entity": "u"}
    if rows is None:
        rows = np.arange(len(data.labels))

    def tuples():
        for i in rows:
            row = []
            for shard, prefix in feature_prefixes.items():
                X = data.features[shard]
                row += [(f"{prefix}{j}", "", float(X[i, j]))
                        for j in range(X.shape[1])]
            yield row

    write_training_examples(
        path, tuples(), data.labels[rows],
        entity_ids={c: v[rows] for c, v in data.entity_ids.items()},
        uids=[str(i) for i in rows],
    )
