from photon_ml_tpu.evaluation.evaluators import (
    Evaluator,
    EvaluationResults,
    get_evaluator,
    is_regression,
    auc,
    rmse,
    logistic_loss_metric,
    poisson_loss_metric,
    squared_loss_metric,
    precision_at_k,
)
