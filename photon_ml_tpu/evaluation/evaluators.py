"""Evaluation metrics on scored data.

Equivalent of the reference's ``evaluation.{Evaluator, EvaluatorType,
AreaUnderROCCurveEvaluator, RMSEEvaluator, MultiEvaluator, ...}``
(SURVEY.md §3.2; reference mount empty). Pointwise metrics (AUC, RMSE,
logistic/Poisson/squared loss) plus grouped "Multi" variants that compute the
metric per group (e.g. per-query AUC) and average — the reference's
MultiEvaluator family. Metrics are computed on host in f64: they sit outside
the jitted training loop and parity (tie handling in AUC especially —
SURVEY.md §7 "hard parts") matters more than speed here.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Evaluator:
    name: str
    fn: Callable  # (scores, labels, weights) -> float
    higher_is_better: bool
    grouped: bool = False  # average the metric over groups (Multi- variant)
    # vectorized grouped implementation: (scores, labels, weights,
    # inverse_group_indices, n_groups) -> per-group value array (nan = skip).
    # Grouped evaluation is segment-op based — a Python loop over np.unique
    # groups walls at 1e5+ query groups (SURVEY.md §3.2) — with the loop
    # kept only for fns without a registered vectorized form.
    grouped_fn: Optional[Callable] = None

    def evaluate(self, scores, labels, weights=None, group_ids=None) -> float:
        scores = np.asarray(scores, np.float64)
        labels = np.asarray(labels, np.float64)
        weights = (
            np.ones_like(labels) if weights is None else np.asarray(weights, np.float64)
        )
        if not self.grouped:
            v = self.fn(scores, labels, weights)
            return float("nan") if v is None else float(v)
        if group_ids is None:
            raise ValueError(f"evaluator '{self.name}' needs group_ids")
        group_ids = np.asarray(group_ids)
        _, inv = np.unique(group_ids, return_inverse=True)
        n_groups = int(inv.max()) + 1 if len(inv) else 0
        if self.grouped_fn is not None:
            vals = self.grouped_fn(scores, labels, weights, inv, n_groups)
            vals = vals[np.isfinite(vals)]
            return float(np.mean(vals)) if len(vals) else float("nan")
        vals = []
        for g in range(n_groups):
            m = inv == g
            v = self.fn(scores[m], labels[m], weights[m])
            if v is not None and np.isfinite(v):
                vals.append(v)
        return float(np.mean(vals)) if vals else float("nan")

    def better(self, a: float, b: float) -> bool:
        """True if metric value a is better than b."""
        return a > b if self.higher_is_better else a < b


@dataclasses.dataclass(frozen=True)
class EvaluationResults:
    """Per-evaluator metric values; first evaluator is primary for model
    selection (the reference's EvaluationResults — SURVEY.md §3.2)."""

    metrics: Dict[str, float]
    primary: str

    @property
    def primary_value(self) -> float:
        return self.metrics[self.primary]


def auc(scores, labels, weights):
    """Area under the ROC curve with average-rank tie handling (matches
    sklearn.roc_auc_score on unweighted data); weighted generalization uses
    weighted ranks. Returns None for degenerate single-class groups."""
    pos = labels > 0.5
    w_pos = weights[pos].sum()
    w_neg = weights[~pos].sum()
    if w_pos == 0 or w_neg == 0:
        return None
    order = np.argsort(scores, kind="mergesort")
    s, w, p = scores[order], weights[order], pos[order]
    # weighted mid-ranks with ties sharing the average rank
    cw = np.cumsum(w)
    ranks = cw - w / 2.0  # midpoint rank of each item
    # collapse ties: average rank within each tied score block
    block_start = np.concatenate(([True], s[1:] != s[:-1]))
    block_id = np.cumsum(block_start) - 1
    block_w = np.zeros(block_id[-1] + 1)
    block_rw = np.zeros_like(block_w)
    np.add.at(block_w, block_id, w)
    np.add.at(block_rw, block_id, ranks * w)
    ranks = (block_rw / block_w)[block_id]
    r_pos = np.sum(w[p] * ranks[p])
    return (r_pos - w_pos * w_pos / 2.0) / (w_pos * w_neg)


def grouped_auc(scores, labels, weights, inv, n_groups):
    """Per-group weighted mid-rank AUC, fully vectorized: one lexsort by
    (group, score) then segment ops — no per-group Python. Exactly matches
    ``auc`` applied per group (ties share the weighted average rank within
    a group's tied-score block); single-class groups come back nan."""
    if n_groups == 0:
        return np.empty(0)
    pos = labels > 0.5
    order = np.lexsort((scores, inv))
    g, s, w, p = inv[order], scores[order], weights[order], pos[order]
    counts = np.bincount(g, minlength=n_groups)
    cw = np.cumsum(w)
    starts = np.concatenate(([0], np.cumsum(counts[:-1])))
    # cumulative weight before each group, broadcast to its rows
    group_offset = np.repeat(np.concatenate(([0.0], cw[starts[1:] - 1]))
                             if n_groups > 1 else np.zeros(1), counts)
    ranks = cw - group_offset - w / 2.0
    # collapse ties within a group: same average rank per tied-score block
    block_start = np.concatenate(
        ([True], (g[1:] != g[:-1]) | (s[1:] != s[:-1])))
    block_id = np.cumsum(block_start) - 1
    block_w = np.bincount(block_id, w)
    block_rw = np.bincount(block_id, ranks * w)
    ranks = (block_rw / block_w)[block_id]
    w_pos = np.bincount(g, w * p, minlength=n_groups)
    w_neg = np.bincount(g, w * ~p, minlength=n_groups)
    r_pos = np.bincount(g, w * p * ranks, minlength=n_groups)
    with np.errstate(divide="ignore", invalid="ignore"):
        out = (r_pos - w_pos * w_pos / 2.0) / (w_pos * w_neg)
    out[(w_pos == 0) | (w_neg == 0)] = np.nan
    return out


def _grouped_weighted_mean(pointwise, post=None):
    """Lift a pointwise loss row->value into a vectorized per-group
    weighted-mean implementation (segment sums via bincount); ``post``
    maps the per-group mean (e.g. sqrt for RMSE)."""

    def fn(scores, labels, weights, inv, n_groups):
        loss = pointwise(scores, labels)
        num = np.bincount(inv, weights * loss, minlength=n_groups)
        den = np.bincount(inv, weights, minlength=n_groups)
        with np.errstate(divide="ignore", invalid="ignore"):
            out = num / den
        return out if post is None else post(out)

    return fn


def grouped_precision_at_k(k: int):
    """Vectorized per-group precision@k: one stable lexsort by
    (group, -score), rank-within-group via segment offsets."""

    def fn(scores, labels, weights, inv, n_groups):
        if n_groups == 0:
            return np.empty(0)
        order = np.lexsort((-scores, inv))
        g, lab = inv[order], labels[order]
        counts = np.bincount(g, minlength=n_groups)
        starts = np.concatenate(([0], np.cumsum(counts[:-1])))
        rank = np.arange(len(g)) - np.repeat(starts, counts)
        top = rank < k
        hits = np.bincount(g[top], lab[top] > 0.5, minlength=n_groups)
        return hits / np.minimum(counts, k)

    return fn


def rmse(scores, labels, weights):
    return np.sqrt(np.sum(weights * (scores - labels) ** 2) / weights.sum())


def logistic_loss_metric(scores, labels, weights):
    """Mean weighted logistic loss of raw margins."""
    return np.sum(weights * (np.logaddexp(0.0, scores) - labels * scores)) / weights.sum()


def poisson_loss_metric(scores, labels, weights):
    return np.sum(weights * (np.exp(scores) - labels * scores)) / weights.sum()


def squared_loss_metric(scores, labels, weights):
    return np.sum(weights * 0.5 * (scores - labels) ** 2) / weights.sum()


def _smoothed_hinge_pointwise(scores, labels):
    z = (2.0 * labels - 1.0) * scores
    return np.where(z <= 0, 0.5 - z, np.where(z < 1, 0.5 * (1 - z) ** 2, 0.0))


def smoothed_hinge_loss_metric(scores, labels, weights):
    return np.sum(weights * _smoothed_hinge_pointwise(scores, labels)) / weights.sum()


def precision_at_k(k: int):
    def fn(scores, labels, weights):
        if len(scores) == 0:
            return None
        top = np.argsort(-scores, kind="mergesort")[:k]
        return float(np.mean(labels[top] > 0.5))

    return fn


def is_regression(evaluator: Evaluator, candidate: float, live: float,
                  tolerance: float = 0.0) -> bool:
    """True when ``candidate`` is worse than ``live`` by more than
    ``tolerance`` in the metric's own units — the promotion gate's
    refusal predicate (registry/gate.py). Fails safe on NaN: a candidate
    that could not be evaluated regresses; a live side that could not be
    evaluated cannot block the candidate."""
    import math

    if math.isnan(candidate):
        return not math.isnan(live)
    if math.isnan(live):
        return False
    delta = (live - candidate) if evaluator.higher_is_better else (
        candidate - live)
    return delta > tolerance


_BASE = {
    "auc": Evaluator("auc", auc, higher_is_better=True,
                     grouped_fn=grouped_auc),
    "rmse": Evaluator(
        "rmse", rmse, higher_is_better=False,
        grouped_fn=_grouped_weighted_mean(
            lambda s, l: (s - l) ** 2, post=np.sqrt)),
    "logistic_loss": Evaluator(
        "logistic_loss", logistic_loss_metric, higher_is_better=False,
        grouped_fn=_grouped_weighted_mean(
            lambda s, l: np.logaddexp(0.0, s) - l * s)),
    "poisson_loss": Evaluator(
        "poisson_loss", poisson_loss_metric, higher_is_better=False,
        grouped_fn=_grouped_weighted_mean(lambda s, l: np.exp(s) - l * s)),
    "squared_loss": Evaluator(
        "squared_loss", squared_loss_metric, higher_is_better=False,
        grouped_fn=_grouped_weighted_mean(lambda s, l: 0.5 * (s - l) ** 2)),
    "smoothed_hinge_loss": Evaluator(
        "smoothed_hinge_loss", smoothed_hinge_loss_metric,
        higher_is_better=False,
        grouped_fn=_grouped_weighted_mean(_smoothed_hinge_pointwise)),
}

# default evaluator per task (the reference ties it to TaskType)
TASK_DEFAULT_EVALUATOR = {
    "logistic": "auc",
    "squared": "rmse",
    "linear": "rmse",
    "poisson": "poisson_loss",
    "smoothed_hinge": "auc",
}


def get_evaluator(name: str) -> Evaluator:
    """Resolve an evaluator by name. Grouped variants: "per_group_auc" (the
    reference's MultiAUCEvaluator), "precision_at_K" / "per_group_precision_at_K"."""
    key = name.lower()
    if key in _BASE:
        return _BASE[key]
    if key.startswith("per_group_"):
        inner = get_evaluator(key[len("per_group_") :])
        return dataclasses.replace(inner, name=key, grouped=True)
    if key.startswith("precision_at_"):
        k = int(key[len("precision_at_") :])
        return Evaluator(key, precision_at_k(k), higher_is_better=True,
                         grouped_fn=grouped_precision_at_k(k))
    raise ValueError(f"unknown evaluator '{name}'; known: {sorted(_BASE)}, "
                     "per_group_<name>, precision_at_<k>")
