"""Evaluation metrics on scored data.

Equivalent of the reference's ``evaluation.{Evaluator, EvaluatorType,
AreaUnderROCCurveEvaluator, RMSEEvaluator, MultiEvaluator, ...}``
(SURVEY.md §3.2; reference mount empty). Pointwise metrics (AUC, RMSE,
logistic/Poisson/squared loss) plus grouped "Multi" variants that compute the
metric per group (e.g. per-query AUC) and average — the reference's
MultiEvaluator family. Metrics are computed on host in f64: they sit outside
the jitted training loop and parity (tie handling in AUC especially —
SURVEY.md §7 "hard parts") matters more than speed here.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Evaluator:
    name: str
    fn: Callable  # (scores, labels, weights) -> float
    higher_is_better: bool
    grouped: bool = False  # average the metric over groups (Multi- variant)

    def evaluate(self, scores, labels, weights=None, group_ids=None) -> float:
        scores = np.asarray(scores, np.float64)
        labels = np.asarray(labels, np.float64)
        weights = (
            np.ones_like(labels) if weights is None else np.asarray(weights, np.float64)
        )
        if not self.grouped:
            v = self.fn(scores, labels, weights)
            return float("nan") if v is None else float(v)
        if group_ids is None:
            raise ValueError(f"evaluator '{self.name}' needs group_ids")
        group_ids = np.asarray(group_ids)
        vals = []
        for g in np.unique(group_ids):
            m = group_ids == g
            v = self.fn(scores[m], labels[m], weights[m])
            if v is not None and np.isfinite(v):
                vals.append(v)
        return float(np.mean(vals)) if vals else float("nan")

    def better(self, a: float, b: float) -> bool:
        """True if metric value a is better than b."""
        return a > b if self.higher_is_better else a < b


@dataclasses.dataclass(frozen=True)
class EvaluationResults:
    """Per-evaluator metric values; first evaluator is primary for model
    selection (the reference's EvaluationResults — SURVEY.md §3.2)."""

    metrics: Dict[str, float]
    primary: str

    @property
    def primary_value(self) -> float:
        return self.metrics[self.primary]


def auc(scores, labels, weights):
    """Area under the ROC curve with average-rank tie handling (matches
    sklearn.roc_auc_score on unweighted data); weighted generalization uses
    weighted ranks. Returns None for degenerate single-class groups."""
    pos = labels > 0.5
    w_pos = weights[pos].sum()
    w_neg = weights[~pos].sum()
    if w_pos == 0 or w_neg == 0:
        return None
    order = np.argsort(scores, kind="mergesort")
    s, w, p = scores[order], weights[order], pos[order]
    # weighted mid-ranks with ties sharing the average rank
    cw = np.cumsum(w)
    ranks = cw - w / 2.0  # midpoint rank of each item
    # collapse ties: average rank within each tied score block
    block_start = np.concatenate(([True], s[1:] != s[:-1]))
    block_id = np.cumsum(block_start) - 1
    block_w = np.zeros(block_id[-1] + 1)
    block_rw = np.zeros_like(block_w)
    np.add.at(block_w, block_id, w)
    np.add.at(block_rw, block_id, ranks * w)
    ranks = (block_rw / block_w)[block_id]
    r_pos = np.sum(w[p] * ranks[p])
    return (r_pos - w_pos * w_pos / 2.0) / (w_pos * w_neg)


def rmse(scores, labels, weights):
    return np.sqrt(np.sum(weights * (scores - labels) ** 2) / weights.sum())


def logistic_loss_metric(scores, labels, weights):
    """Mean weighted logistic loss of raw margins."""
    return np.sum(weights * (np.logaddexp(0.0, scores) - labels * scores)) / weights.sum()


def poisson_loss_metric(scores, labels, weights):
    return np.sum(weights * (np.exp(scores) - labels * scores)) / weights.sum()


def squared_loss_metric(scores, labels, weights):
    return np.sum(weights * 0.5 * (scores - labels) ** 2) / weights.sum()


def smoothed_hinge_loss_metric(scores, labels, weights):
    z = (2.0 * labels - 1.0) * scores
    loss = np.where(z <= 0, 0.5 - z, np.where(z < 1, 0.5 * (1 - z) ** 2, 0.0))
    return np.sum(weights * loss) / weights.sum()


def precision_at_k(k: int):
    def fn(scores, labels, weights):
        if len(scores) == 0:
            return None
        top = np.argsort(-scores, kind="mergesort")[:k]
        return float(np.mean(labels[top] > 0.5))

    return fn


_BASE = {
    "auc": Evaluator("auc", auc, higher_is_better=True),
    "rmse": Evaluator("rmse", rmse, higher_is_better=False),
    "logistic_loss": Evaluator("logistic_loss", logistic_loss_metric, higher_is_better=False),
    "poisson_loss": Evaluator("poisson_loss", poisson_loss_metric, higher_is_better=False),
    "squared_loss": Evaluator("squared_loss", squared_loss_metric, higher_is_better=False),
    "smoothed_hinge_loss": Evaluator(
        "smoothed_hinge_loss", smoothed_hinge_loss_metric, higher_is_better=False
    ),
}

# default evaluator per task (the reference ties it to TaskType)
TASK_DEFAULT_EVALUATOR = {
    "logistic": "auc",
    "squared": "rmse",
    "linear": "rmse",
    "poisson": "poisson_loss",
    "smoothed_hinge": "auc",
}


def get_evaluator(name: str) -> Evaluator:
    """Resolve an evaluator by name. Grouped variants: "per_group_auc" (the
    reference's MultiAUCEvaluator), "precision_at_K" / "per_group_precision_at_K"."""
    key = name.lower()
    if key in _BASE:
        return _BASE[key]
    if key.startswith("per_group_"):
        inner = get_evaluator(key[len("per_group_") :])
        return dataclasses.replace(inner, name=key, grouped=True)
    if key.startswith("precision_at_"):
        k = int(key[len("precision_at_") :])
        return Evaluator(key, precision_at_k(k), higher_is_better=True)
    raise ValueError(f"unknown evaluator '{name}'; known: {sorted(_BASE)}, "
                     "per_group_<name>, precision_at_<k>")
