"""On-device (jit/shard_map-friendly) evaluation metrics.

The host evaluators (``evaluators.py``) are exact f64 references, but at
10⁹ scored rows a single-threaded host mergesort is a wall (SURVEY.md
§3.2: the reference evaluates with Spark jobs). Device-side equivalents:

- ``device_auc``: exact weighted mid-rank AUC as one jitted XLA program
  (device sort + segment ops). Single-device; use for up to ~10⁸ rows
  resident in HBM.
- ``histogram_auc_contrib`` / ``histogram_auc``: sharded AUC by weighted
  score histograms. The per-shard contribution is two fixed-width
  histograms (positives / negatives), which are ``psum``-reducible over
  the mesh — the `treeAggregate`-replacement pattern (SURVEY.md §5.8) —
  after which the AUC follows from cumulative sums with the standard
  within-bin tie (trapezoid) correction. Exact when every tied-score pair
  lands in one bin (in particular for discrete/quantized scores); error is
  otherwise O(within-bin mass²). Use ``device_auc`` when exactness
  matters and the data fits on one device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def device_auc(scores, labels, weights):
    """Exact weighted AUC with average-rank tie handling, on device.

    Matches ``evaluators.auc`` (the host f64 reference) up to dtype: ties
    share the weighted mid-rank of their tied-score block. Returns nan for
    single-class inputs.
    """
    pos = labels > 0.5
    w_pos = jnp.sum(jnp.where(pos, weights, 0.0))
    w_neg = jnp.sum(jnp.where(pos, 0.0, weights))
    order = jnp.argsort(scores, stable=True)
    s, w, p = scores[order], weights[order], pos[order]
    cw = jnp.cumsum(w)
    ranks = cw - w / 2.0
    block_start = jnp.concatenate(
        (jnp.ones((1,), bool), s[1:] != s[:-1]))
    block_id = jnp.cumsum(block_start) - 1
    n = s.shape[0]
    block_w = jnp.zeros(n, w.dtype).at[block_id].add(w)
    block_rw = jnp.zeros(n, w.dtype).at[block_id].add(ranks * w)
    ranks = (block_rw / block_w)[block_id]
    r_pos = jnp.sum(jnp.where(p, w * ranks, 0.0))
    out = (r_pos - w_pos * w_pos / 2.0) / (w_pos * w_neg)
    return jnp.where((w_pos > 0) & (w_neg > 0), out, jnp.nan)


@partial(jax.jit, static_argnames=("n_bins",))
def histogram_auc_contrib(scores, labels, weights, lo, hi, n_bins=4096):
    """Per-shard AUC contribution: weighted histograms of positive and
    negative scores over [lo, hi] with ``n_bins`` equal bins. The outputs
    are elementwise-additive across shards — reduce with ``psum`` (inside
    shard_map) or plain ``+`` (host), then finish with
    ``histogram_auc_from_hists``. Rows may carry weight 0 (padding)."""
    pos = labels > 0.5
    width = (hi - lo) / n_bins
    bins = jnp.clip(((scores - lo) / width).astype(jnp.int32), 0, n_bins - 1)
    pos_hist = jnp.zeros(n_bins, weights.dtype).at[bins].add(
        jnp.where(pos, weights, 0.0))
    neg_hist = jnp.zeros(n_bins, weights.dtype).at[bins].add(
        jnp.where(pos, 0.0, weights))
    return pos_hist, neg_hist


@jax.jit
def histogram_auc_from_hists(pos_hist, neg_hist):
    """AUC from reduced histograms: P(score⁺ > score⁻) + ½P(tie), treating
    all mass within one bin as tied (trapezoid / mid-rank rule)."""
    w_pos = jnp.sum(pos_hist)
    w_neg = jnp.sum(neg_hist)
    neg_below = jnp.concatenate(
        (jnp.zeros((1,), neg_hist.dtype), jnp.cumsum(neg_hist)[:-1]))
    pairs = jnp.sum(pos_hist * (neg_below + neg_hist / 2.0))
    return jnp.where((w_pos > 0) & (w_neg > 0),
                     pairs / (w_pos * w_neg), jnp.nan)


def make_device_evaluator(name: str, mesh=None):
    """Device-side form of a host evaluator for per-iteration CD-loop
    validation (VERDICT r2 #9: per-iteration metrics must not round-trip
    full score vectors through host numpy at scale). Returns a callable
    ``(scores, labels, weights) -> device scalar`` or None when the metric
    has no device form (grouped / precision@k variants fall back to host).

    AUC uses the exact ``device_auc`` on a single device and the psum-able
    ``histogram_auc`` when scores are sharded over a >1-device mesh. The
    pointwise losses mirror ``evaluators.py`` definitions exactly. Final
    reported metrics should still come from the host f64 evaluators (the
    CD loop recomputes its last record with them)."""
    key = name.lower()
    multi = mesh is not None and mesh.devices.size > 1

    if key == "auc":
        if multi:
            axis = ("data" if "data" in mesh.shape else mesh.axis_names[0])
            return lambda s, l, w: histogram_auc(s, l, w, mesh=mesh,
                                                 axis=axis)
        return device_auc

    def wmean(point):
        @jax.jit
        def f(scores, labels, weights):
            return (jnp.sum(weights * point(scores, labels))
                    / jnp.sum(weights))
        return f

    if key == "rmse":
        f = wmean(lambda s, l: (s - l) ** 2)
        return lambda s, l, w: jnp.sqrt(f(s, l, w))
    if key == "logistic_loss":
        return wmean(lambda s, l: jnp.logaddexp(0.0, s) - l * s)
    if key == "poisson_loss":
        return wmean(lambda s, l: jnp.exp(s) - l * s)
    if key == "squared_loss":
        return wmean(lambda s, l: 0.5 * (s - l) ** 2)
    if key == "smoothed_hinge_loss":
        def point(s, l):
            z = (2.0 * l - 1.0) * s
            return jnp.where(z <= 0, 0.5 - z,
                             jnp.where(z < 1, 0.5 * (1 - z) ** 2, 0.0))
        return wmean(point)
    return None


def histogram_auc(scores, labels, weights=None, n_bins=4096, mesh=None,
                  axis=None):
    """Sharded/histogram AUC driver. With a mesh, the histogram reduction
    rides the mesh's collectives via inputs sharded over ``axis`` (default:
    the mesh's first axis); XLA turns the segment-sum over sharded rows
    into per-shard sums + all-reduce."""
    scores = jnp.asarray(scores)
    labels = jnp.asarray(labels)
    weights = (jnp.ones_like(scores) if weights is None
               else jnp.asarray(weights))
    lo = jnp.min(scores)
    hi = jnp.max(scores)
    hi = jnp.where(hi > lo, hi, lo + 1.0)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        data_axis = axis or mesh.axis_names[0]
        sharding = NamedSharding(mesh, P(data_axis))
        n_dev = mesh.shape[data_axis]
        pad = (-len(scores)) % n_dev
        if pad:
            scores = jnp.concatenate((scores, jnp.zeros(pad, scores.dtype)))
            labels = jnp.concatenate((labels, jnp.zeros(pad, labels.dtype)))
            weights = jnp.concatenate((weights, jnp.zeros(pad, weights.dtype)))
        scores = jax.device_put(scores, sharding)
        labels = jax.device_put(labels, sharding)
        weights = jax.device_put(weights, sharding)
    ph, nh = histogram_auc_contrib(scores, labels, weights, lo, hi,
                                   n_bins=n_bins)
    return histogram_auc_from_hists(ph, nh)
