"""On-device (jit/shard_map-friendly) evaluation metrics.

The host evaluators (``evaluators.py``) are exact f64 references, but at
10⁹ scored rows a single-threaded host mergesort is a wall (SURVEY.md
§3.2: the reference evaluates with Spark jobs). Device-side equivalents:

- ``device_auc``: exact weighted mid-rank AUC as one jitted XLA program
  (device sort + segment ops). Single-device; use for up to ~10⁸ rows
  resident in HBM.
- ``histogram_auc_contrib`` / ``histogram_auc``: sharded AUC by weighted
  score histograms. The per-shard contribution is two fixed-width
  histograms (positives / negatives), which are ``psum``-reducible over
  the mesh — the `treeAggregate`-replacement pattern (SURVEY.md §5.8) —
  after which the AUC follows from cumulative sums with the standard
  within-bin tie (trapezoid) correction. Exact when every tied-score pair
  lands in one bin (in particular for discrete/quantized scores); error is
  otherwise O(within-bin mass²). Use ``device_auc`` when exactness
  matters and the data fits on one device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def device_auc(scores, labels, weights):
    """Exact weighted AUC with average-rank tie handling, on device.

    Matches ``evaluators.auc`` (the host f64 reference) up to dtype: ties
    share the weighted mid-rank of their tied-score block. Returns nan for
    single-class inputs.
    """
    pos = labels > 0.5
    w_pos = jnp.sum(jnp.where(pos, weights, 0.0))
    w_neg = jnp.sum(jnp.where(pos, 0.0, weights))
    order = jnp.argsort(scores, stable=True)
    s, w, p = scores[order], weights[order], pos[order]
    cw = jnp.cumsum(w)
    ranks = cw - w / 2.0
    block_start = jnp.concatenate(
        (jnp.ones((1,), bool), s[1:] != s[:-1]))
    block_id = jnp.cumsum(block_start) - 1
    n = s.shape[0]
    block_w = jnp.zeros(n, w.dtype).at[block_id].add(w)
    block_rw = jnp.zeros(n, w.dtype).at[block_id].add(ranks * w)
    ranks = (block_rw / block_w)[block_id]
    r_pos = jnp.sum(jnp.where(p, w * ranks, 0.0))
    out = (r_pos - w_pos * w_pos / 2.0) / (w_pos * w_neg)
    return jnp.where((w_pos > 0) & (w_neg > 0), out, jnp.nan)


@partial(jax.jit, static_argnames=("n_bins",))
def histogram_auc_contrib(scores, labels, weights, lo, hi, n_bins=4096):
    """Per-shard AUC contribution: weighted histograms of positive and
    negative scores over [lo, hi] with ``n_bins`` equal bins. The outputs
    are elementwise-additive across shards — reduce with ``psum`` (inside
    shard_map) or plain ``+`` (host), then finish with
    ``histogram_auc_from_hists``. Rows may carry weight 0 (padding)."""
    pos = labels > 0.5
    width = (hi - lo) / n_bins
    bins = jnp.clip(((scores - lo) / width).astype(jnp.int32), 0, n_bins - 1)
    pos_hist = jnp.zeros(n_bins, weights.dtype).at[bins].add(
        jnp.where(pos, weights, 0.0))
    neg_hist = jnp.zeros(n_bins, weights.dtype).at[bins].add(
        jnp.where(pos, 0.0, weights))
    return pos_hist, neg_hist


@jax.jit
def histogram_auc_from_hists(pos_hist, neg_hist):
    """AUC from reduced histograms: P(score⁺ > score⁻) + ½P(tie), treating
    all mass within one bin as tied (trapezoid / mid-rank rule)."""
    w_pos = jnp.sum(pos_hist)
    w_neg = jnp.sum(neg_hist)
    neg_below = jnp.concatenate(
        (jnp.zeros((1,), neg_hist.dtype), jnp.cumsum(neg_hist)[:-1]))
    pairs = jnp.sum(pos_hist * (neg_below + neg_hist / 2.0))
    return jnp.where((w_pos > 0) & (w_neg > 0),
                     pairs / (w_pos * w_neg), jnp.nan)


def make_device_evaluator(name: str, mesh=None):
    """Device-side form of a host evaluator for per-iteration CD-loop
    validation (VERDICT r2 #9: per-iteration metrics must not round-trip
    full score vectors through host numpy at scale). Returns a callable
    ``(scores, labels, weights) -> device scalar`` or None when the metric
    has no device form (grouped / precision@k variants fall back to host).

    AUC uses the exact ``device_auc`` on a single device and the psum-able
    ``histogram_auc`` when scores are sharded over a >1-device mesh. The
    pointwise losses mirror ``evaluators.py`` definitions exactly. Final
    reported metrics should still come from the host f64 evaluators (the
    CD loop recomputes its last record with them)."""
    key = name.lower()
    multi = mesh is not None and mesh.devices.size > 1

    if key == "auc":
        if multi:
            axis = ("data" if "data" in mesh.shape else mesh.axis_names[0])
            return lambda s, l, w: histogram_auc(s, l, w, mesh=mesh,
                                                 axis=axis)
        return device_auc

    def wmean(point):
        @jax.jit
        def f(scores, labels, weights):
            return (jnp.sum(weights * point(scores, labels))
                    / jnp.sum(weights))
        return f

    if key == "rmse":
        f = wmean(lambda s, l: (s - l) ** 2)
        return lambda s, l, w: jnp.sqrt(f(s, l, w))
    if key == "logistic_loss":
        return wmean(lambda s, l: jnp.logaddexp(0.0, s) - l * s)
    if key == "poisson_loss":
        return wmean(lambda s, l: jnp.exp(s) - l * s)
    if key == "squared_loss":
        return wmean(lambda s, l: 0.5 * (s - l) ** 2)
    if key == "smoothed_hinge_loss":
        def point(s, l):
            z = (2.0 * l - 1.0) * s
            return jnp.where(z <= 0, 0.5 - z,
                             jnp.where(z < 1, 0.5 * (1 - z) ** 2, 0.0))
        return wmean(point)
    if key.startswith("precision_at_"):
        k = int(key[len("precision_at_"):])

        def pk(scores, labels, weights):
            kk = min(k, scores.shape[0])  # static at trace time
            _, idx = jax.lax.top_k(scores, kk)
            # ties at the k boundary may resolve differently than the host
            # mergesort — monitoring only; finals are host f64
            return jnp.mean((labels[idx] > 0.5).astype(scores.dtype))
        return jax.jit(pk)
    return None


def _finite_mean(vals):
    """Mean over finite entries (nan when none) — the grouped evaluators'
    aggregation rule (``evaluators.Evaluator.evaluate``)."""
    ok = jnp.isfinite(vals)
    cnt = jnp.sum(ok)
    return jnp.where(cnt > 0,
                     jnp.sum(jnp.where(ok, vals, 0.0)) / cnt, jnp.nan)


def make_grouped_device_evaluator(name: str, group_ids, mesh=None):
    """Device form of a ``per_group_*`` evaluator, closed over the
    factorized group ids (static per validation set — factorization
    happens ONCE on host; every CD iteration then runs segment ops on
    device with no score-vector round trip, VERDICT r4 #8). Returns
    ``(scores, labels, weights) -> device scalar`` mirroring the host
    ``grouped_fn`` + finite-mean aggregation exactly, or None when the
    metric has no grouped device form."""
    import numpy as np

    key = name.lower()
    if not key.startswith("per_group_"):
        return None
    inner = key[len("per_group_"):]
    _, inv_np = np.unique(np.asarray(group_ids), return_inverse=True)
    G = int(inv_np.max()) + 1 if len(inv_np) else 0
    if G == 0:
        return None
    inv = jnp.asarray(inv_np, jnp.int32)
    seg = partial(jax.ops.segment_sum, segment_ids=inv, num_segments=G)

    if inner == "auc":
        @jax.jit
        def grouped_auc_dev(scores, labels, weights):
            # one lexsort by (group, score) then segment ops — the exact
            # device mirror of evaluators.grouped_auc
            order = jnp.lexsort((scores, inv))
            g, s, w = inv[order], scores[order], weights[order]
            p = labels[order] > 0.5
            w_grp = jax.ops.segment_sum(w, g, num_segments=G)
            before = jnp.concatenate(
                (jnp.zeros((1,), w.dtype), jnp.cumsum(w_grp)[:-1]))
            ranks = jnp.cumsum(w) - before[g] - w / 2.0
            n = s.shape[0]
            block_start = jnp.concatenate(
                (jnp.ones((1,), bool), (g[1:] != g[:-1]) | (s[1:] != s[:-1])))
            block_id = jnp.cumsum(block_start) - 1
            block_w = jnp.zeros(n, w.dtype).at[block_id].add(w)
            block_rw = jnp.zeros(n, w.dtype).at[block_id].add(ranks * w)
            ranks = (block_rw / block_w)[block_id]
            w_pos = jax.ops.segment_sum(jnp.where(p, w, 0.0), g,
                                        num_segments=G)
            w_neg = jax.ops.segment_sum(jnp.where(p, 0.0, w), g,
                                        num_segments=G)
            r_pos = jax.ops.segment_sum(jnp.where(p, w * ranks, 0.0), g,
                                        num_segments=G)
            vals = (r_pos - w_pos * w_pos / 2.0) / (w_pos * w_neg)
            vals = jnp.where((w_pos > 0) & (w_neg > 0), vals, jnp.nan)
            return _finite_mean(vals)
        return grouped_auc_dev

    if inner.startswith("precision_at_"):
        k = int(inner[len("precision_at_"):])

        @jax.jit
        def grouped_pk_dev(scores, labels, weights):
            order = jnp.lexsort((-scores, inv))
            g, lab = inv[order], labels[order]
            counts = jax.ops.segment_sum(jnp.ones_like(scores), g,
                                         num_segments=G)
            starts = jnp.concatenate(
                (jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]))
            rank = jnp.arange(g.shape[0]) - starts[g]
            top = rank < k
            hits = jax.ops.segment_sum(
                jnp.where(top, (lab > 0.5).astype(scores.dtype), 0.0), g,
                num_segments=G)
            return _finite_mean(hits / jnp.minimum(counts, float(k)))
        return grouped_pk_dev

    pointwise = {
        "rmse": lambda s, l: (s - l) ** 2,
        "logistic_loss": lambda s, l: jnp.logaddexp(0.0, s) - l * s,
        "poisson_loss": lambda s, l: jnp.exp(s) - l * s,
        "squared_loss": lambda s, l: 0.5 * (s - l) ** 2,
        "smoothed_hinge_loss": lambda s, l: jnp.where(
            (z := (2.0 * l - 1.0) * s) <= 0, 0.5 - z,
            jnp.where(z < 1, 0.5 * (1 - z) ** 2, 0.0)),
    }.get(inner)
    if pointwise is None:
        return None
    post = jnp.sqrt if inner == "rmse" else (lambda x: x)

    @jax.jit
    def grouped_mean_dev(scores, labels, weights):
        num = seg(weights * pointwise(scores, labels))
        den = seg(weights)
        vals = post(num / den)
        return _finite_mean(jnp.where(den > 0, vals, jnp.nan))

    return grouped_mean_dev


def histogram_auc(scores, labels, weights=None, n_bins=4096, mesh=None,
                  axis=None):
    """Sharded/histogram AUC driver. With a mesh, the histogram reduction
    rides the mesh's collectives via inputs sharded over ``axis`` (default:
    the mesh's first axis); XLA turns the segment-sum over sharded rows
    into per-shard sums + all-reduce."""
    scores = jnp.asarray(scores)
    labels = jnp.asarray(labels)
    weights = (jnp.ones_like(scores) if weights is None
               else jnp.asarray(weights))
    lo = jnp.min(scores)
    hi = jnp.max(scores)
    hi = jnp.where(hi > lo, hi, lo + 1.0)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        data_axis = axis or mesh.axis_names[0]
        sharding = NamedSharding(mesh, P(data_axis))
        n_dev = mesh.shape[data_axis]
        pad = (-len(scores)) % n_dev
        if pad:
            scores = jnp.concatenate((scores, jnp.zeros(pad, scores.dtype)))
            labels = jnp.concatenate((labels, jnp.zeros(pad, labels.dtype)))
            weights = jnp.concatenate((weights, jnp.zeros(pad, weights.dtype)))
        scores = jax.device_put(scores, sharding)
        labels = jax.device_put(labels, sharding)
        weights = jax.device_put(weights, sharding)
    ph, nh = histogram_auc_contrib(scores, labels, weights, lo, hi,
                                   n_bins=n_bins)
    return histogram_auc_from_hists(ph, nh)
