"""Streaming (larger-than-HBM) fixed-effect training.

The in-memory path (``fit_distributed``) holds the whole batch in device
memory and runs the optimizer as one XLA program. At Criteo-1TB scale the
dataset doesn't fit in HBM; the reference streams partitions through
executors on every ``treeAggregate`` pass (SURVEY.md §4.2 — one cluster pass
per optimizer iteration). The TPU-native equivalent here: the dataset lives
in host RAM as fixed-shape chunks, each optimizer iteration streams chunks
through the device accumulating (loss, gradient) partials with a jitted
per-chunk kernel (one compilation, static shapes), and the L-BFGS direction
/ update math stays on device via the same jitted two-loop recursion the
in-memory optimizer uses. Transfers overlap compute via one-chunk lookahead
(JAX async dispatch).

Cost model matches the reference: each L-BFGS iteration (plus each extra
line-search evaluation) is one full pass over the data.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optimize.common import OptimizationResult, OptimizerConfig
from photon_ml_tpu.optimize.lbfgs import two_loop_direction
from photon_ml_tpu.types import LabeledBatch, SparseFeatures


@dataclasses.dataclass(frozen=True)
class HostChunk:
    """One fixed-shape chunk resident in host RAM (numpy)."""

    indices: np.ndarray  # [rows, k] int32
    values: np.ndarray  # [rows, k]
    labels: np.ndarray  # [rows]
    offsets: np.ndarray  # [rows]
    weights: np.ndarray  # [rows]; padding rows have weight 0


def make_host_chunks(
    features,
    labels,
    offsets=None,
    weights=None,
    chunk_rows: int = 1 << 16,
    pad_nnz: Optional[int] = None,
) -> tuple[List[HostChunk], int]:
    """Slice a host dataset into uniform chunks (last chunk padded with
    zero-weight rows so every chunk compiles to the same shapes).

    ``features``: HostSparse-like (``indices``/``values``/``dim``) or dense
    [n, d] numpy. Returns (chunks, dim)."""
    labels = np.asarray(labels)
    n = labels.shape[0]
    if offsets is None:
        offsets = np.zeros(n)
    if weights is None:
        weights = np.ones(n)
    offsets = np.asarray(offsets)
    weights = np.asarray(weights)

    if hasattr(features, "indices"):
        indices = np.asarray(features.indices)
        values = np.asarray(features.values)
        dim = features.dim
    else:
        dense = np.asarray(features)
        dim = dense.shape[1]
        indices = np.broadcast_to(np.arange(dim, dtype=np.int32),
                                  dense.shape).copy()
        values = dense
    k = indices.shape[1]
    if pad_nnz is not None:
        if pad_nnz < k:
            raise ValueError(f"pad_nnz={pad_nnz} < chunk nnz width {k}")
        pad = pad_nnz - k
        indices = np.pad(indices, ((0, 0), (0, pad)))
        values = np.pad(values, ((0, 0), (0, pad)))
        k = pad_nnz

    chunks: List[HostChunk] = []
    for start in range(0, max(n, 1), chunk_rows):
        stop = min(start + chunk_rows, n)
        rows = stop - start
        pad = chunk_rows - rows
        chunks.append(HostChunk(
            indices=np.pad(indices[start:stop], ((0, pad), (0, 0))),
            values=np.pad(values[start:stop], ((0, pad), (0, 0))),
            labels=np.pad(labels[start:stop], (0, pad)),
            offsets=np.pad(offsets[start:stop], (0, pad)),
            weights=np.pad(weights[start:stop], (0, pad)),  # pad weight = 0
        ))
    return chunks, dim


def _chunk_to_device(chunk: HostChunk, dim: int, dtype, sharding) -> LabeledBatch:
    put = (lambda a: jax.device_put(a, sharding)) if sharding else jax.device_put
    return LabeledBatch(
        SparseFeatures(put(chunk.indices.astype(np.int32)),
                       put(chunk.values.astype(dtype)), dim=dim),
        put(chunk.labels.astype(dtype)),
        put(chunk.offsets.astype(dtype)),
        put(chunk.weights.astype(dtype)),
    )


def streaming_value_and_grad(
    objective: GLMObjective,
    chunks: Sequence[HostChunk],
    dim: int,
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
) -> Callable:
    """Returns fg(w, l2) -> (value, grad) computed in ONE streamed pass over
    the chunks: per-chunk partials accumulate on device, the next chunk's
    host->device transfer overlaps the current chunk's compute (async
    dispatch + one-chunk lookahead). L2 is added once at the end."""
    sharding = None
    if mesh is not None:
        sharding = NamedSharding(mesh, P(axis))

    @jax.jit
    def chunk_fg(w, batch, f_acc, g_acc):
        f, g = objective.value_and_grad(w, batch, 0.0)
        return f_acc + f, g_acc + g

    def fg(w, l2=0.0):
        w = jnp.asarray(w, dtype)
        f_acc = jnp.zeros((), dtype)
        g_acc = jnp.zeros((dim,), dtype)
        # one-chunk lookahead: transfer chunk i+1 while chunk i computes
        pending = None
        for chunk in chunks:
            dev = _chunk_to_device(chunk, dim, dtype, sharding)
            if pending is not None:
                f_acc, g_acc = chunk_fg(w, pending, f_acc, g_acc)
            pending = dev
        if pending is not None:
            f_acc, g_acc = chunk_fg(w, pending, f_acc, g_acc)
        wr = objective._reg_mask(w)
        l2 = jnp.asarray(l2, dtype)
        return f_acc + 0.5 * l2 * jnp.sum(wr * wr), g_acc + l2 * wr

    return fg


def streaming_coefficient_variances(
    objective: GLMObjective,
    chunks: Sequence[HostChunk],
    dim: int,
    w: jax.Array,
    l2=0.0,
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
) -> jax.Array:
    """Diagonal-inverse-Hessian coefficient variances over a streamed pass
    (the in-memory ``GLMObjective.coefficient_variances``, chunked). The
    data term accumulates per chunk (l2=0 adds nothing); the regularization
    diagonal is added once at the end."""
    sharding = NamedSharding(mesh, P(axis)) if mesh is not None else None

    @jax.jit
    def chunk_diag(w, batch, acc):
        return acc + objective.diagonal_hessian(w, batch, 0.0)

    w = jnp.asarray(w, dtype)
    acc = jnp.zeros((dim,), dtype)
    for chunk in chunks:
        acc = chunk_diag(w, _chunk_to_device(chunk, dim, dtype, sharding), acc)
    reg = jnp.full((dim,), jnp.asarray(l2, dtype))
    if not objective.regularize_intercept and objective.intercept_index >= 0:
        reg = reg.at[objective.intercept_index].set(0.0)
    diag = acc + reg
    return 1.0 / jnp.maximum(diag, jnp.finfo(dtype).tiny)


def fit_streaming(
    objective: GLMObjective,
    chunks: Sequence[HostChunk],
    dim: int,
    w0: Optional[jax.Array] = None,
    l2=0.0,
    config: OptimizerConfig = OptimizerConfig(),
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
) -> OptimizationResult:
    """L-BFGS over a streamed full-batch objective.

    The direction (two-loop recursion over the device-resident (s, y)
    history) and the vector updates stay on device; only the line-search
    control flow runs on host, because each function evaluation is a full
    streamed pass (exactly the reference's driver-side Breeze loop with one
    ``treeAggregate`` per evaluation — SURVEY.md §4.2). Line search is
    backtracking Armijo; pairs are stored only under a curvature guard, which
    keeps the inverse-Hessian metric positive definite without paying extra
    full passes for the Wolfe curvature condition."""
    m = config.history
    if w0 is None:
        w0 = jnp.zeros((dim,), dtype)
    w = jnp.asarray(w0, dtype)
    fg = streaming_value_and_grad(objective, chunks, dim, dtype, mesh, axis)

    direction = jax.jit(functools.partial(two_loop_direction, m=m))

    @jax.jit
    def store_pair(s_hist, y_hist, rho, k, step, y):
        sy = jnp.sum(step * y)
        slot = jnp.mod(k, m)
        return (s_hist.at[slot].set(step), y_hist.at[slot].set(y),
                rho.at[slot].set(1.0 / sy))

    f, g = fg(w, l2)
    f0 = float(f)
    g0_norm = float(jnp.linalg.norm(g))
    s_hist = jnp.zeros((m, dim), dtype)
    y_hist = jnp.zeros((m, dim), dtype)
    rho = jnp.zeros((m,), dtype)
    k = 0
    eps = float(jnp.finfo(dtype).eps)
    tol = max(config.tolerance, eps)
    loss_hist = np.full((config.max_iters,), np.nan)
    gnorm_hist = np.full((config.max_iters,), np.nan)

    it = 0
    converged = False
    for it in range(config.max_iters):
        p = direction(g, s_hist, y_hist, rho, jnp.asarray(k))
        dg = float(jnp.sum(p * g))
        if dg >= 0:  # degraded metric: steepest descent restart
            p = -g
            dg = -float(jnp.sum(g * g))
        alpha = 1.0 if k > 0 else 1.0 / max(g0_norm, 1.0)
        f_cur = float(f)
        accepted = False
        for _ in range(config.max_line_search_steps):
            w_try = w + alpha * p
            f_try, g_try = fg(w_try, l2)
            if float(f_try) <= f_cur + 1e-4 * alpha * dg and np.isfinite(
                float(f_try)
            ):
                accepted = True
                break
            alpha *= 0.5
        if not accepted:
            break
        step = w_try - w
        yv = g_try - g
        sy = float(jnp.sum(step * yv))
        if sy > 1e-10 * max(
            float(jnp.linalg.norm(step)) * float(jnp.linalg.norm(yv)), eps
        ):
            s_hist, y_hist, rho = store_pair(s_hist, y_hist, rho,
                                             jnp.asarray(k), step, yv)
            k += 1
        w, f, g = w_try, f_try, g_try
        gnorm = float(jnp.linalg.norm(g))
        loss_hist[it] = float(f)
        gnorm_hist[it] = gnorm
        rel = abs(f_cur - float(f)) / max(abs(f_cur), eps)
        if rel < tol or gnorm < tol * max(g0_norm, eps):
            converged = True
            it += 1
            break
    else:
        it = config.max_iters

    return OptimizationResult(
        w=w, value=f, grad_norm=jnp.linalg.norm(g),
        iterations=jnp.asarray(it), converged=jnp.asarray(converged),
        loss_history=jnp.asarray(loss_hist),
        grad_norm_history=jnp.asarray(gnorm_hist),
    )
