"""Streaming (larger-than-HBM) fixed-effect training.

The in-memory path (``fit_distributed``) holds the whole batch in device
memory and runs the optimizer as one XLA program. At Criteo-1TB scale the
dataset doesn't fit in HBM; the reference streams partitions through
executors on every ``treeAggregate`` pass (SURVEY.md §4.2 — one cluster pass
per optimizer iteration). The TPU-native equivalent here: the dataset lives
in host RAM as fixed-shape chunks, each optimizer iteration streams chunks
through the device accumulating (loss, gradient) partials with a jitted
per-chunk kernel (one compilation, static shapes), and the L-BFGS direction
/ update math stays on device via the same jitted two-loop recursion the
in-memory optimizer uses. Transfers overlap compute via a depth-K device
prefetch ring (:func:`iter_device_chunks`): a dedicated transfer thread
stages the next K chunks' host->device uploads while this thread dispatches
compute, and per-pass stall accounting (decode-wait / transfer /
compute-stall seconds, :class:`StreamStats`) rides the fit result so an
epoch-rate gap is attributable to a pipeline stage, not guessed at.

Cost model: the default margin-space L-BFGS pays exactly two sparse
passes per iteration (direction margins + accepted-point gradient) with
line-search trials streaming only cached margin vectors; the black-box
loops (``lbfgs_blackbox``, TRON, OWL-QN) match the reference's model of
one full pass per evaluation.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import logging
import os
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.compat import shard_map
from photon_ml_tpu.obs import metrics as obs_metrics
from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.parallel.resilience import (
    CollectiveGuard,
    current_transport,
    use_transport,
)
from photon_ml_tpu.parallel.data_parallel import cached_jit
from photon_ml_tpu.optimize.common import OptimizationResult, OptimizerConfig
from photon_ml_tpu.optimize.lbfgs import two_loop_direction
from photon_ml_tpu.types import LabeledBatch, SparseFeatures
from photon_ml_tpu.utils import transfer_budget

_log = logging.getLogger("photon_ml_tpu")

# Device-side prefetch depth of the streamed transfer ring: how many chunks
# the transfer thread may stage on device ahead of the chunk the consumer
# is dispatching. Depth 2 covers decode/transfer jitter without holding
# more than ~4 chunks of HBM (staged + in-flight + current); raise it when
# decode latency is spiky (cold page cache), lower to 0 for a synchronous
# single-thread loop (debugging).
DEFAULT_PREFETCH_DEPTH = 2


def resolve_prefetch_depth(depth: Optional[int] = None) -> int:
    """Explicit depth, else ``PHOTON_PREFETCH_DEPTH``, else the default."""
    if depth is None:
        env = os.environ.get("PHOTON_PREFETCH_DEPTH", "")
        depth = int(env) if env else DEFAULT_PREFETCH_DEPTH
    return max(int(depth), 0)


@dataclasses.dataclass
class StreamStats:
    """Host-side pipeline stall accounting for streamed passes.

    ``decode_s``: transfer-thread seconds blocked waiting on the chunk
    source (disk decode or the source's own producer queue);
    ``transfer_s``: seconds issuing budget-accounted host->device puts;
    ``stall_s``: consumer seconds blocked on an empty ring — the compute
    dispatcher starved of staged data; ``comm_s``: seconds in the
    once-per-pass cross-process reduction of the streamed partials (0 in
    single-process runs) — the stall-accounting leg the entity-sharded
    CD's ``comm_seconds`` mirrors. All accumulate across every pass of a
    fit; ``passes``/``chunks`` normalize them."""

    decode_s: float = 0.0
    transfer_s: float = 0.0
    stall_s: float = 0.0
    comm_s: float = 0.0
    chunks: int = 0
    passes: int = 0

    def as_dict(self) -> dict:
        return {"decode_s": round(self.decode_s, 6),
                "transfer_s": round(self.transfer_s, 6),
                "stall_s": round(self.stall_s, 6),
                "comm_s": round(self.comm_s, 6),
                "chunks": self.chunks, "passes": self.passes}


# consumer-side ring poll (seconds): each expiry rechecks transfer-thread
# liveness so a producer that dies without relaying its sentinel fails
# the pass instead of hanging the consumer forever
_RING_POLL_S = 0.5


def _ring_put(q: queue.Queue, stop: threading.Event, item) -> bool:
    """Stop-aware bounded put (chunks, sentinel and errors alike) so an
    abandoned consumer can never wedge the transfer thread — same contract
    as ``AvroChunkSource._put_or_stop``."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.2)
            return True
        except queue.Full:
            continue
    return False


def iter_device_chunks(chunks, to_device: Callable, depth: Optional[int] = None,
                       stats: Optional[StreamStats] = None):
    """Yield ``(host_chunk, device_batch)`` with the device batches staged
    ``depth`` chunks ahead by a dedicated transfer thread.

    This generalizes the old one-chunk lookahead: the transfer thread pulls
    from the (possibly disk-backed) chunk source and issues the
    budget-accounted uploads, so decode AND transfer of chunks i+1..i+K
    overlap the consumer's compute dispatch of chunk i. Exceptions from
    either the source or the upload are re-raised in the consumer (inside
    its CollectiveGuard, preserving coordinated-abort semantics), and the
    consumer's ambient process context (fault-injection identity, simulated
    transport) is propagated into the transfer thread so per-process fault
    plans still address decode faults deterministically.

    ``depth=0`` is a synchronous single-thread fallback (JAX async dispatch
    still overlaps transfer with compute one chunk at a time)."""
    depth = resolve_prefetch_depth(depth)
    if stats is not None:
        stats.passes += 1
    if depth == 0:
        t_wait = time.perf_counter()
        for chunk in chunks:
            now = time.perf_counter()
            dev = to_device(chunk)
            if stats is not None:
                stats.decode_s += now - t_wait
                stats.transfer_s += time.perf_counter() - now
                stats.chunks += 1
            yield chunk, dev
            t_wait = time.perf_counter()
        return

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    tp = current_transport()
    try:
        fault_proc = tp.process_index()
    except Exception:
        fault_proc = None

    tctx = obs_trace.current_context()  # handed off to the ring thread

    def produce():
        it = iter(chunks)
        ctx = (fault_injection.process_context(fault_proc)
               if fault_proc is not None else contextlib.nullcontext())
        try:
            with use_transport(tp), ctx, obs_trace.use_context(tctx):
                t_wait = time.perf_counter()
                while True:
                    try:
                        chunk = next(it)
                    except StopIteration:
                        break
                    now = time.perf_counter()
                    if stop.is_set():
                        return
                    with obs_trace.span("stream.upload", cat="stream"):
                        dev = to_device(chunk)
                    if stats is not None:
                        stats.decode_s += now - t_wait
                        stats.transfer_s += time.perf_counter() - now
                    if not _ring_put(q, stop, (chunk, dev)):
                        return
                    t_wait = time.perf_counter()
                _ring_put(q, stop, None)  # end-of-pass sentinel
        except BaseException as e:  # surfaced in the consumer
            _ring_put(q, stop, e)
        finally:
            # deterministically close a generator-backed source so ITS
            # producer thread (AvroChunkSource) winds down with this pass
            close = getattr(it, "close", None)
            if close is not None:
                close()

    t = threading.Thread(target=produce, daemon=True,
                         name="stream-transfer")
    t.start()
    try:
        while True:
            t0 = time.perf_counter()
            try:
                item = q.get(timeout=_RING_POLL_S)
            except queue.Empty:
                if t.is_alive():
                    if stats is not None:
                        stats.stall_s += time.perf_counter() - t0
                    continue
                try:
                    # the thread may have parked its last item/sentinel
                    # between our timeout and its exit
                    item = q.get_nowait()
                except queue.Empty:
                    raise RuntimeError(
                        "stream-transfer thread died without delivering "
                        "its end-of-pass sentinel") from None
            if stats is not None:
                stats.stall_s += time.perf_counter() - t0
            if item is None:
                break
            if isinstance(item, BaseException):
                raise item
            if stats is not None:
                stats.chunks += 1
            yield item
    finally:
        stop.set()
        t.join(timeout=30)
        if t.is_alive():
            _log.warning(
                "transfer thread %s still alive 30s after the pass ended "
                "(wedged source or upload); leaking it as a daemon",
                t.name)


@dataclasses.dataclass(frozen=True)
class HostChunk:
    """One fixed-shape chunk resident in host RAM (numpy)."""

    indices: np.ndarray  # [rows, k] int32
    values: Optional[np.ndarray]  # [rows, k]; None = implicit-ones layout
    labels: np.ndarray  # [rows]
    offsets: np.ndarray  # [rows]
    weights: np.ndarray  # [rows]; padding rows have weight 0


def make_host_chunks(
    features,
    labels,
    offsets=None,
    weights=None,
    chunk_rows: int = 1 << 16,
    pad_nnz: Optional[int] = None,
) -> tuple[List[HostChunk], int]:
    """Slice a host dataset into uniform chunks (last chunk padded with
    zero-weight rows so every chunk compiles to the same shapes).

    ``features``: HostSparse-like (``indices``/``values``/``dim``) or dense
    [n, d] numpy. Returns (chunks, dim)."""
    labels = np.asarray(labels)
    n = labels.shape[0]
    if offsets is None:
        offsets = np.zeros(n)
    if weights is None:
        weights = np.ones(n)
    offsets = np.asarray(offsets)
    weights = np.asarray(weights)

    if hasattr(features, "indices"):
        indices = np.asarray(features.indices)
        # implicit-ones layout flows value-free all the way to the device:
        # at streamed scale the halved chunk transfer is the whole point
        values = (None if features.values is None
                  else np.asarray(features.values))
        dim = features.dim
    else:
        dense = np.asarray(features)
        dim = dense.shape[1]
        indices = np.broadcast_to(np.arange(dim, dtype=np.int32),
                                  dense.shape).copy()
        values = dense
    k = indices.shape[1]
    if pad_nnz is not None:
        if pad_nnz < k:
            raise ValueError(f"pad_nnz={pad_nnz} < chunk nnz width {k}")
        if values is None and pad_nnz > k:
            raise ValueError(
                "pad_nnz slot padding is invalid for the implicit-ones "
                "layout (every slot is a real 1.0 feature)")
        pad = pad_nnz - k
        indices = np.pad(indices, ((0, 0), (0, pad)))
        if values is not None:
            values = np.pad(values, ((0, 0), (0, pad)))
        k = pad_nnz

    chunks: List[HostChunk] = []
    for start in range(0, max(n, 1), chunk_rows):
        stop = min(start + chunk_rows, n)
        rows = stop - start
        pad = chunk_rows - rows
        chunks.append(HostChunk(
            indices=np.pad(indices[start:stop], ((0, pad), (0, 0))),
            values=(None if values is None
                    else np.pad(values[start:stop], ((0, pad), (0, 0)))),
            labels=np.pad(labels[start:stop], (0, pad)),
            offsets=np.pad(offsets[start:stop], (0, pad)),
            weights=np.pad(weights[start:stop], (0, pad)),  # pad weight = 0
        ))
    return chunks, dim


def _cross_process_sum(tree, stats: Optional[StreamStats] = None):
    """Sum accumulator pytrees across processes (multi-controller runtime).

    Single-process: identity. Multi-process: each process streams only its
    own row span (``multihost.process_span``), then the per-process partials
    are reduced here — the DCN leg of the reference's ``treeAggregate``
    (SURVEY.md §5.8). Uses allgather+sum of [d]-sized partials, negligible
    next to the per-chunk compute; the time still lands in
    ``StreamStats.comm_s`` so a multi-host stall is attributable."""
    if jax.process_count() == 1:
        return tree
    t0 = time.perf_counter()
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(tree)
    out = jax.tree.map(lambda a: jnp.asarray(a).sum(axis=0), gathered)
    if stats is not None:
        stats.comm_s += time.perf_counter() - t0
    return out


def _chunk_to_device(chunk: HostChunk, dim: int, dtype, sharding) -> LabeledBatch:
    # every streamed upload is budget-accounted (utils.transfer_budget):
    # chunk-sized pieces are tunnel-safe, but a session budget catches a
    # misconfigured chunk_rows before it can wedge the TPU worker. The
    # .astype happens first so the charged bytes are the bytes moved.
    def put(a):
        return transfer_budget.device_put(a, sharding, what="stream chunk")
    return LabeledBatch(
        SparseFeatures(put(chunk.indices.astype(np.int32)),
                       (None if chunk.values is None
                        else put(chunk.values.astype(dtype))), dim=dim),
        put(chunk.labels.astype(dtype)),
        put(chunk.offsets.astype(dtype)),
        put(chunk.weights.astype(dtype)),
    )



def _host_tol(tolerance, dtype) -> float:
    """Mirror :func:`optimize.common.converged_check` tolerance semantics
    for the streamed HOST loops: an explicit tol <= 0 disables the
    convergence tests entirely (exact iteration counts — bench determinism),
    while a positive tol is clamped to a few ulps of the working dtype so an
    f64-tuned tolerance still terminates in f32. Round 3 clamped
    ``max(tol, eps)`` unconditionally, silently re-enabling the tests that
    ``tolerance=0`` callers (scripts/bench_streaming.py) rely on being off."""
    t = float(np.asarray(tolerance))
    if t <= 0:
        return 0.0
    return max(t, 4 * float(jnp.finfo(dtype).eps))


def _kahan_add(acc, comp, x):
    """One compensated (Kahan) accumulation step: returns (acc', comp')
    with acc' - comp' == (acc - comp) + x to ~f32-exact (``comp`` holds
    the running EXCESS of ``acc`` over the true sum, so fold with
    ``acc - comp``). Streamed fits sum
    thousands of per-chunk partials — at the 1TB north star (~15k chunks)
    naive f32 accumulation drifts by ~n_chunks * eps (~2e-3 relative on
    biased sums), which this removes without f64 (unavailable on TPU
    without x64). XLA is IEEE-strict by default, so the cancellation
    sequence below is not reassociated away."""
    y = x - comp
    t = acc + y
    comp = (t - acc) - y
    return t, comp


def _shard_width(mesh: Optional[Mesh], axis: str) -> int:
    return 1 if mesh is None else int(mesh.shape[axis])


def _partial_sharding(mesh, axis):
    """Sharding for per-device partial accumulators ([S] / [S, d] arrays
    whose leading axis is the device axis)."""
    return NamedSharding(mesh, P(axis)) if mesh is not None else None


def _sharded_zeros(shape, dtype, mesh, axis):
    z = jnp.zeros(shape, dtype)
    sh = _partial_sharding(mesh, axis)
    return jax.device_put(z, sh) if sh is not None else z


def _shard_map_chunk(fn, mesh, axis, n_batch_args, acc_ndims):
    """Wrap a per-shard chunk kernel in ``shard_map`` with NO collective:
    batch args shard on ``axis`` (rows), accumulators carry a leading
    device axis ([S, ...], sharded on it), ``w``-like leading args
    replicate.

    WHY: jit-over-sharded-inputs lets GSPMD insert an all-reduce into
    every per-chunk program, and the streamed loops dispatch chunks
    asynchronously (host syncs only at pass end). XLA:CPU's in-process
    rendezvous deadlocks when ~64+ collective executions queue unsynced
    (scripts/repro_cpu_collective_deadlock.py — 7 of 8 participants
    arrive, SIGABRT; r4 contingency). Per-device partials make the
    per-chunk program collective-free on EVERY backend; the single
    cross-shard reduction happens once per pass in a reduce kernel whose
    result the host consumes (and therefore syncs) immediately. On real
    meshes this is also strictly less ICI traffic: one [d] all-reduce per
    PASS instead of per chunk.

    ``check_vma=False`` is load-bearing: under vma tracking the AD
    transpose of "replicated w touches sharded rows" auto-inserts the
    gradient's all-reduce inside the kernel (see
    ``data_parallel.distributed_value_and_grad``'s comment), which would
    put the per-chunk collective right back."""
    in_specs = ((P(),)                      # w (or other replicated lead)
                + (P(axis),) * n_batch_args
                + tuple(P(axis, *([None] * (nd - 1)))
                        for nd in acc_ndims))
    out_specs = tuple(P(axis, *([None] * (nd - 1))) for nd in acc_ndims)
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


def _rebuild_batch(dim, indices, values, labels, offsets, weights
                   ) -> LabeledBatch:
    """Rebuild the chunk batch from flat leaves inside a kernel.
    Implicit-ones chunks pass ``values=()`` — an EMPTY pytree, part of the
    jit signature, so the two layouts never retrace each other; shard_map
    specs stay simplest over flat array arguments."""
    return LabeledBatch(
        SparseFeatures(indices,
                       None if isinstance(values, tuple) else values,
                       dim=dim),
        labels, offsets, weights)


def _batch_args(dev: LabeledBatch):
    """Flatten a device batch into the kernel's leaf arguments (the
    inverse of :func:`_rebuild_batch`)."""
    vals = dev.features.values
    return (dev.features.indices, () if vals is None else vals,
            dev.labels, dev.offsets, dev.weights)


def _make_kahan_reduce():
    """The once-per-pass cross-shard fold of [S, ...] Kahan partials —
    the ONLY collective the sharded streamed paths ever run."""
    return lambda acc, comp: jnp.sum(acc - comp, axis=0)


def streaming_value_and_grad(
    objective: GLMObjective,
    chunks: Sequence[HostChunk],
    dim: int,
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    prefetch_depth: Optional[int] = None,
    stats: Optional[StreamStats] = None,
) -> Callable:
    """Returns fg(w, l2) -> (value, grad) computed in ONE streamed pass over
    the chunks: per-chunk partials accumulate on device, the transfer
    thread stages the next ``prefetch_depth`` chunks while the current one
    computes (:func:`iter_device_chunks`). L2 is added once at the end.

    Distributed (``mesh``): the per-chunk kernel is COLLECTIVE-FREE — each
    device accumulates its own Kahan partial under ``shard_map``; one
    reduction per pass folds the [S]/[S, d] partials (see
    ``_shard_map_chunk`` for why this matters on XLA:CPU and saves ICI
    bandwidth on real meshes)."""
    sharding = None
    if mesh is not None:
        sharding = NamedSharding(mesh, P(axis))
    S = _shard_width(mesh, axis)

    # cached per objective: a GAME CD loop re-enters fit_streaming every
    # iteration — a fresh jit here would recompile the chunk kernel each
    # time (same failure mode the fit_distributed runner cache fixes)

    def _make_chunk_fg():
        def chunk_fg(w, indices, values, labels, offsets, weights,
                     f_acc, f_comp, g_acc, g_comp):
            batch = _rebuild_batch(dim, indices, values, labels, offsets,
                                   weights)
            f, g = objective.value_and_grad(w, batch, 0.0)
            f_acc, f_comp = _kahan_add(f_acc, f_comp,
                                       jnp.reshape(f, f_acc.shape))
            g_acc, g_comp = _kahan_add(g_acc, g_comp,
                                       jnp.reshape(g, g_acc.shape))
            return f_acc, f_comp, g_acc, g_comp

        if mesh is None:
            return chunk_fg
        return _shard_map_chunk(chunk_fg, mesh, axis, n_batch_args=5,
                                acc_ndims=(1, 1, 2, 2))

    def _make_reduce():
        fold = _make_kahan_reduce()

        def reduce_fg(f_acc, f_comp, g_acc, g_comp):
            return fold(f_acc, f_comp), fold(g_acc, g_comp)
        return reduce_fg

    # dim is baked into the kernel closure (the batch rebuild), so it must
    # be part of the cache key: same objective at a different width must
    # not reuse a kernel with a stale dim. The Kahan accumulators are
    # DONATED: each chunk's call reuses the previous (loss, grad, comp)
    # buffers in place instead of allocating a fresh [S, d] pair per chunk.
    chunk_fg_k = cached_jit(objective, ("stream_fg", mesh, axis, dim),
                            _make_chunk_fg, donate_argnums=(6, 7, 8, 9))
    reduce_k = cached_jit(objective, ("stream_fg_reduce", mesh, axis, dim),
                          _make_reduce)

    def fg(w, l2=0.0):
        w = jnp.asarray(w, dtype)
        acc = (_sharded_zeros((S,), dtype, mesh, axis),
               _sharded_zeros((S,), dtype, mesh, axis),
               _sharded_zeros((S, dim), dtype, mesh, axis),
               _sharded_zeros((S, dim), dtype, mesh, axis))
        # the whole local pass runs under the health guard: a process that
        # fails mid-stream (bad block, decode error, injected fault) is
        # converted into PeerFailure on EVERY process at the pass boundary
        # instead of wedging its peers inside _cross_process_sum
        with CollectiveGuard("stream.fg"):
            for _hc, dev in iter_device_chunks(
                    chunks,
                    lambda c: _chunk_to_device(c, dim, dtype, sharding),
                    prefetch_depth, stats):
                acc = chunk_fg_k(w, *_batch_args(dev), *acc)
            # ONE cross-shard reduction per pass; its output is consumed by
            # the host right away, so at most one collective is in flight
            f_acc, g_acc = reduce_k(*acc)
        f_acc, g_acc = _cross_process_sum((f_acc, g_acc), stats)
        wr = objective._reg_mask(w)
        l2 = jnp.asarray(l2, dtype)
        return f_acc + 0.5 * l2 * jnp.sum(wr * wr), g_acc + l2 * wr

    return fg


def streaming_hvp(
    objective: GLMObjective,
    chunks: Sequence[HostChunk],
    dim: int,
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    prefetch_depth: Optional[int] = None,
    stats: Optional[StreamStats] = None,
) -> Callable:
    """Returns hvp(w, v, l2) computed in one streamed pass — the cost model
    of the reference's HessianVectorAggregator treeAggregate per CG step
    (SURVEY.md §4.2), with chunks instead of cluster partitions. Sharded:
    collective-free per-device partials, one reduction per pass
    (``_shard_map_chunk``)."""
    sharding = NamedSharding(mesh, P(axis)) if mesh is not None else None
    S = _shard_width(mesh, axis)

    def _make_chunk_hvp():
        def chunk_hvp(wv, indices, values, labels, offsets, weights,
                      acc, comp):
            w, v = wv
            batch = _rebuild_batch(dim, indices, values, labels, offsets,
                                   weights)
            hv = objective.hvp(w, v, batch, 0.0)
            return _kahan_add(acc, comp, jnp.reshape(hv, acc.shape))

        if mesh is None:
            return chunk_hvp
        return _shard_map_chunk(chunk_hvp, mesh, axis, n_batch_args=5,
                                acc_ndims=(2, 2))

    chunk_hvp_k = cached_jit(objective, ("stream_hvp", mesh, axis, dim),
                             _make_chunk_hvp, donate_argnums=(6, 7))
    reduce_k = cached_jit(objective, ("stream_hvp_reduce", mesh, axis, dim),
                          _make_kahan_reduce)

    def hvp(w, v, l2=0.0):
        w = jnp.asarray(w, dtype)
        v = jnp.asarray(v, dtype)
        acc = _sharded_zeros((S, dim), dtype, mesh, axis)
        comp = _sharded_zeros((S, dim), dtype, mesh, axis)
        with CollectiveGuard("stream.hvp"):  # see streaming_value_and_grad
            for _hc, dev in iter_device_chunks(
                    chunks,
                    lambda c: _chunk_to_device(c, dim, dtype, sharding),
                    prefetch_depth, stats):
                acc, comp = chunk_hvp_k((w, v), *_batch_args(dev), acc,
                                        comp)
            total = reduce_k(acc, comp)
        total = _cross_process_sum(total, stats)
        return total + jnp.asarray(l2, dtype) * objective._reg_mask(v)

    return hvp


def streaming_coefficient_variances(
    objective: GLMObjective,
    chunks: Sequence[HostChunk],
    dim: int,
    w: jax.Array,
    l2=0.0,
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    prefetch_depth: Optional[int] = None,
    stats: Optional[StreamStats] = None,
) -> jax.Array:
    """Diagonal-inverse-Hessian coefficient variances over a streamed pass
    (the in-memory ``GLMObjective.coefficient_variances``, chunked). The
    data term accumulates per chunk (l2=0 adds nothing); the regularization
    diagonal is added once at the end."""
    diag = streaming_hessian_diagonal(objective, chunks, dim, w, l2,
                                      dtype, mesh, axis, prefetch_depth,
                                      stats)
    return 1.0 / jnp.maximum(diag, jnp.finfo(dtype).tiny)


def streaming_hessian_diagonal(
    objective: GLMObjective,
    chunks: Sequence[HostChunk],
    dim: int,
    w: jax.Array,
    l2=0.0,
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    prefetch_depth: Optional[int] = None,
    stats: Optional[StreamStats] = None,
) -> jax.Array:
    """Exact Hessian diagonal over one streamed (Kahan-compensated) pass —
    shared by coefficient variances and TRON's Jacobi preconditioner.
    Sharded: collective-free per-device partials (``_shard_map_chunk``)."""
    sharding = NamedSharding(mesh, P(axis)) if mesh is not None else None
    S = _shard_width(mesh, axis)

    def _make_chunk_diag():
        def chunk_diag(w, indices, values, labels, offsets, weights,
                       acc, comp):
            batch = _rebuild_batch(dim, indices, values, labels, offsets,
                                   weights)
            d = objective.diagonal_hessian(w, batch, 0.0)
            return _kahan_add(acc, comp, jnp.reshape(d, acc.shape))

        if mesh is None:
            return chunk_diag
        return _shard_map_chunk(chunk_diag, mesh, axis, n_batch_args=5,
                                acc_ndims=(2, 2))

    chunk_diag_k = cached_jit(objective, ("stream_diag", mesh, axis, dim),
                              _make_chunk_diag, donate_argnums=(6, 7))
    reduce_k = cached_jit(objective, ("stream_diag_reduce", mesh, axis, dim),
                          _make_kahan_reduce)

    w = jnp.asarray(w, dtype)
    acc = _sharded_zeros((S, dim), dtype, mesh, axis)
    comp = _sharded_zeros((S, dim), dtype, mesh, axis)
    with CollectiveGuard("stream.diag"):  # see streaming_value_and_grad
        for _hc, dev in iter_device_chunks(
                chunks, lambda c: _chunk_to_device(c, dim, dtype, sharding),
                prefetch_depth, stats):
            acc, comp = chunk_diag_k(w, *_batch_args(dev), acc, comp)
        total = reduce_k(acc, comp)
    total = _cross_process_sum(total, stats)
    reg = jnp.full((dim,), jnp.asarray(l2, dtype))
    if not objective.regularize_intercept and objective.intercept_index >= 0:
        reg = reg.at[objective.intercept_index].set(0.0)
    return total + reg


def fit_streaming(
    objective: GLMObjective,
    chunks: Sequence[HostChunk],
    dim: int,
    w0: Optional[jax.Array] = None,
    l2=0.0,
    config: OptimizerConfig = OptimizerConfig(),
    dtype=jnp.float32,
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    optimizer: str = "lbfgs",
    l1=0.0,
    progress_callback: Optional[Callable] = None,
    prefetch_depth: Optional[int] = None,
) -> OptimizationResult:
    """Streamed (larger-than-HBM) full-batch fit.

    ``progress_callback(iteration, w)``, when given, fires with the
    0-based loop index and the current point — measurement harnesses use
    it for per-iteration progress logging and host-side checkpoints so a
    tunnel stall loses an iteration, not the run (VERDICT r3 #5). The
    L-BFGS/OWL-QN loops fire only on iterations that accepted a step
    (line-search-failure retries are counted in ``iterations`` but fire
    no callback, so indices can skip); TRON fires every outer iteration
    — a rejected trust-region step still paid a full CG pass sequence,
    and ``w`` is simply unchanged.

    ``optimizer``: "lbfgs" (default — margin-space line search: trials
    stream cached margin vectors instead of paying a sparse pass each,
    see ``_fit_streaming_lbfgs_margin``), "lbfgs_blackbox" (one full
    streamed fg pass per Armijo trial — the reference's cost model),
    "tron" (trust-region Newton — each CG step is one streamed HVP
    pass), or "owlqn" (L1; auto-selected when ``l1`` > 0). Only the
    outer control flow runs on host; direction/update vector math stays
    on device. Line search is backtracking Armijo; pairs are stored only
    under a curvature guard, which keeps the inverse-Hessian metric
    positive definite without paying extra full passes for the Wolfe
    curvature condition (a weaker (s,y) filter than the in-memory
    strong-Wolfe optimizer — convergence contract in docs/PERF.md)."""
    if optimizer == "auto":
        # measured default: the margin L-BFGS streams 2 sparse passes per
        # iteration — the fewest of any streamed optimizer
        optimizer = "lbfgs"
    if np.asarray(l1).item() > 0 and optimizer != "owlqn":
        optimizer = "owlqn"
    stats = StreamStats()
    if optimizer == "tron":
        res = _fit_streaming_tron(objective, chunks, dim, w0, l2, config,
                                  dtype, mesh, axis, progress_callback,
                                  prefetch_depth, stats)
        return _finish_stream_result(res, stats, "tron")
    if optimizer == "owlqn":
        res = _fit_streaming_owlqn(objective, chunks, dim, w0, l2, l1,
                                   config, dtype, mesh, axis,
                                   progress_callback, prefetch_depth, stats)
        return _finish_stream_result(res, stats, "owlqn")
    if optimizer == "lbfgs":
        res = _fit_streaming_lbfgs_margin(objective, chunks, dim, w0, l2,
                                          config, dtype, mesh, axis,
                                          progress_callback, prefetch_depth,
                                          stats)
        return _finish_stream_result(res, stats, "lbfgs")
    if optimizer != "lbfgs_blackbox":
        raise ValueError(f"unknown streaming optimizer '{optimizer}'")
    m = config.history
    if w0 is None:
        w0 = jnp.zeros((dim,), dtype)
    w = jnp.asarray(w0, dtype)
    fg = streaming_value_and_grad(objective, chunks, dim, dtype, mesh, axis,
                                  prefetch_depth, stats)

    direction, store_pair = _lbfgs_stream_kernels(objective, mesh, axis, m)

    f, g = fg(w, l2)
    g0_norm = float(jnp.linalg.norm(g))
    s_hist = jnp.zeros((m, dim), dtype)
    y_hist = jnp.zeros((m, dim), dtype)
    rho = jnp.zeros((m,), dtype)
    k = 0
    eps = float(jnp.finfo(dtype).eps)
    tol = _host_tol(config.tolerance, dtype)
    loss_hist = np.full((config.max_iters,), np.nan)
    gnorm_hist = np.full((config.max_iters,), np.nan)

    it = 0
    converged = False
    for it in range(config.max_iters):
        p = direction(g, s_hist, y_hist, rho, jnp.asarray(k))
        dg = float(jnp.sum(p * g))
        if dg >= 0:  # degraded metric: steepest descent restart
            p = -g
            dg = -float(jnp.sum(g * g))
        alpha = 1.0 if k > 0 else 1.0 / max(g0_norm, 1.0)
        f_cur = float(f)
        accepted = False
        for _ in range(config.max_line_search_steps):
            w_try = w + alpha * p
            f_try, g_try = fg(w_try, l2)
            if float(f_try) <= f_cur + 1e-4 * alpha * dg and np.isfinite(
                float(f_try)
            ):
                accepted = True
                break
            alpha *= 0.5
        if not accepted:
            # mirror optimize/lbfgs.py: failing AT the optimum is
            # convergence, not a stall — and with a stale f32 metric a
            # history reset + steepest-descent retry often buys more
            # productive iterations before giving up. The attempted
            # iteration is counted and recorded (f unchanged), matching
            # the in-memory loop's unconditional it+1.
            gnorm = float(jnp.linalg.norm(g))
            loss_hist[it] = float(f)
            gnorm_hist[it] = gnorm
            if tol > 0 and gnorm <= tol * max(g0_norm, 1.0):
                converged = True
                it += 1
                break
            if k > 0:
                s_hist = jnp.zeros((m, dim), dtype)
                y_hist = jnp.zeros((m, dim), dtype)
                rho = jnp.zeros((m,), dtype)
                k = 0
                continue
            it += 1  # the attempted iteration counts: histories[:iterations]
            break    # must include the record written above

        step = w_try - w
        yv = g_try - g
        sy = float(jnp.sum(step * yv))
        if sy > 1e-10 * max(
            float(jnp.linalg.norm(step)) * float(jnp.linalg.norm(yv)), eps
        ):
            s_hist, y_hist, rho = store_pair(s_hist, y_hist, rho,
                                             jnp.asarray(k), step, yv)
            k += 1
        w, f, g = w_try, f_try, g_try
        gnorm = float(jnp.linalg.norm(g))
        loss_hist[it] = float(f)
        gnorm_hist[it] = gnorm
        if progress_callback is not None:
            progress_callback(it, w)
        rel = abs(f_cur - float(f)) / max(abs(f_cur), 1.0)
        if tol > 0 and (rel <= tol or gnorm <= tol * max(g0_norm, 1.0)):
            converged = True
            it += 1
            break
    else:
        it = config.max_iters

    return _finish_stream_result(OptimizationResult(
        w=w, value=f, grad_norm=jnp.linalg.norm(g),
        iterations=jnp.asarray(it), converged=jnp.asarray(converged),
        loss_history=jnp.asarray(loss_hist),
        grad_norm_history=jnp.asarray(gnorm_hist),
    ), stats, "lbfgs_blackbox")


def _finish_stream_result(res: OptimizationResult, stats: StreamStats,
                          optimizer: str) -> OptimizationResult:
    """Attach the fit-wide pipeline stall accounting to the result and log
    the one-line breakdown measurement harnesses grep for."""
    _log.info(
        "streamed %s fit: %d passes / %d chunk transfers; decode-wait "
        "%.3fs, transfer %.3fs, compute-stall %.3fs",
        optimizer, stats.passes, stats.chunks, stats.decode_s,
        stats.transfer_s, stats.stall_s)
    # one StreamStats per fit, so the totals ARE this fit's delta
    obs_metrics.training_metrics().record_prefetch(
        stall_s=stats.stall_s, decode_s=stats.decode_s,
        transfer_s=stats.transfer_s)
    return res._replace(stream_stats=stats.as_dict())


def _lbfgs_stream_kernels(objective, mesh, axis, m):
    """Jitted direction/store-pair kernels, cached per (objective, m) so a
    GAME CD loop re-entering fit_streaming every iteration reuses the
    compiled executables (the same failure mode the chunk-kernel cache
    exists for)."""
    direction = cached_jit(
        objective, ("stream_dir", mesh, axis, m),
        lambda: functools.partial(two_loop_direction, m=m))

    def _make_store():
        def store_pair(s_hist, y_hist, rho, k, step, y):
            sy = jnp.sum(step * y)
            slot = jnp.mod(k, m)
            return (s_hist.at[slot].set(step), y_hist.at[slot].set(y),
                    rho.at[slot].set(1.0 / sy))
        return store_pair

    store_pair = cached_jit(objective, ("stream_store", mesh, axis, m),
                            _make_store)
    return direction, store_pair


def _fit_streaming_lbfgs_margin(objective, chunks, dim, w0, l2, config,
                                dtype, mesh, axis, progress_callback=None,
                                prefetch_depth=None,
                                stats=None) -> OptimizationResult:
    """Streamed L-BFGS with margin-space line search (the default).

    The black-box streamed loop pays one FULL sparse pass (index gather +
    transpose) per Armijo trial. GLM margins are affine in w (offsets and
    the normalization adjust are the constant/linear parts —
    ``ops/objective.margins``), so this loop instead caches the per-chunk
    margin vectors ``mw`` in HOST RAM and evaluates the backtracking
    ladder in GROUPS of 8 candidate steps per stream of (mw, mp, labels,
    weights) — 16 bytes/row per group against the hundreds of bytes/row
    of a sparse pass per trial; the first group almost always decides, so
    the typical iteration is one gather pass (the direction's margins),
    one margin-only ladder stream (worst case
    ceil(max_line_search_steps/8)), and one
    gather+transpose pass for the accepted point's gradient — the same
    2-sparse-pass cost as the in-memory margin optimizer
    (``optimize/lbfgs_margin.py``), where the black-box loop paid
    ``1 + n_trials`` full passes. The L2 term is closed-form along the ray
    (three O(d) scalars). Accumulations are Kahan-compensated; Armijo
    semantics and the (s, y) curvature guard match the black-box loop.

    Drift consistency: ``mw`` is updated incrementally and in f32 slowly
    drifts from the exact margins of ``w``, so the Armijo test compares
    the trial against ``phi(0)`` — the margin-space value of the CURRENT
    point under the same drift — never against the exact ``f`` from the
    sparse pass (mixing the two reference frames would make the shrinking
    Armijo allowance a coin flip near convergence). Exact (f, g) from the
    accepted-point sparse pass still drive convergence tests and the
    returned histories."""
    m = config.history
    if w0 is None:
        w0 = jnp.zeros((dim,), dtype)
    w = jnp.asarray(w0, dtype)
    sharding = NamedSharding(mesh, P(axis)) if mesh is not None else None
    fg = streaming_value_and_grad(objective, chunks, dim, dtype, mesh, axis,
                                  prefetch_depth, stats)

    margin_k = cached_jit(
        objective, ("stream_margin", mesh, axis),
        lambda: lambda w, batch: objective.margins(w, batch))
    # per-chunk trial: masked margins -> weighted loss partial (Kahan)
    from photon_ml_tpu.ops.losses import apply_weights, mask_margins

    # Ladder GROUP width: per streamed pass, this many candidate steps are
    # evaluated together (G x the pointwise math per chunk — nearly free on
    # device, noticeable on a 1-core CPU host, hence not the full 25-step
    # ladder). Backtracking rarely goes past the first few halvings, so one
    # group usually decides; worst case ceil(max_line_search_steps / G)
    # passes instead of one pass per trial.
    L = min(max(int(config.max_line_search_steps), 1), 8)

    S = _shard_width(mesh, axis)

    def _make_trial():
        def trial(alphas, mw, mp, labels, weights, f_acc, f_comp):
            # DELTA space: per-row loss DIFFERENCES l(mw + a*mp) - l(mw).
            # In f32 a loss total's resolution is eps*|f|, far coarser
            # than late-stage improvements, so Armijo on totals stalls;
            # the difference keeps relative accuracy in the improvement
            # itself (same scheme as the in-memory lbfgs_margin delta
            # path). Also removes the need for a separate phi(0) stream:
            # the trial compares against 0.
            #
            # LADDER: ``alphas`` is the whole [L] backtracking ladder and
            # f_acc/f_comp are [L] Kahan accumulators — the streamed
            # search is transfer-bound, so every candidate step is
            # evaluated in the SAME streamed visit of the chunk (L x the
            # pointwise math, ~free on device) instead of one 16B/row
            # stream per trial.
            mm0 = mask_margins(weights, mw)
            l0 = apply_weights(weights, objective.loss.loss(mm0, labels))

            def per_alpha(a):
                mm1 = mask_margins(weights, mw + a * mp)
                return jnp.sum(apply_weights(
                    weights, objective.loss.loss(mm1, labels)) - l0)

            return _kahan_add(f_acc, f_comp,
                              jnp.reshape(jax.vmap(per_alpha)(alphas),
                                          f_acc.shape))

        if mesh is None:
            return trial
        # collective-free per-device [1, L] partials (_shard_map_chunk:
        # the async ladder loop must queue no rendezvous)
        return _shard_map_chunk(trial, mesh, axis, n_batch_args=4,
                                acc_ndims=(2, 2))

    trial_k = cached_jit(objective,
                         ("stream_trial_delta_ladder", mesh, axis, L),
                         _make_trial, donate_argnums=(5, 6))
    trial_reduce_k = cached_jit(
        objective, ("stream_trial_reduce", mesh, axis, L),
        _make_kahan_reduce)

    def _put(a):
        if not isinstance(a, jax.Array):
            # charge the bytes actually moved (post-cast width); any
            # host array-protocol object counts, not only np.ndarray —
            # same gate as transfer_budget.device_put (ADVICE r4), and
            # the charge doubles as the stall-watchdog liveness signal
            transfer_budget.charge(
                int(np.size(a)) * jnp.dtype(dtype).itemsize,
                "margin trial chunk")
        dev = jnp.asarray(a, dtype)
        return jax.device_put(dev, sharding) if sharding else dev

    # Host scalar cache: per-chunk labels/weights/offsets, captured during
    # the first streamed pass. For in-RAM chunk lists these are references
    # (zero copy); for a disk-backed source (io/stream_source.py) this is
    # the 12B/row cache that makes every margin-ladder trial DECODE-FREE —
    # without it each ladder group would re-decode full chunks from disk
    # just to read two scalar columns, turning the 2-pass/iteration cost
    # model into ~(2 + groups) full decodes. Same order of host state as
    # the mw/mp margin caches below (8B/row).
    n_chunks = len(chunks)
    labels_h = [None] * n_chunks
    weights_h = [None] * n_chunks
    offsets_h = [None] * n_chunks

    def margins_of(vec, out):
        """One streamed gather pass: per-chunk margins of ``vec`` (offsets
        included), stored to host numpy in ``out``. The transfer ring
        stages chunk i+1..i+K while chunk i's margins compute, and the
        device->host fetch of chunk i-1 overlaps chunk i's dispatch."""
        # guarded even though this pass itself has no collective: in SPMD
        # lockstep the peers run this same pass, and a process failing
        # here would otherwise strand them at the NEXT phase's barrier
        # until the watchdog instead of aborting promptly
        with CollectiveGuard("stream.margins"):
            pending = None
            for i, (chunk, dev) in enumerate(iter_device_chunks(
                    chunks,
                    lambda c: _chunk_to_device(c, dim, dtype, sharding),
                    prefetch_depth, stats)):
                if labels_h[i] is None:
                    labels_h[i] = chunk.labels
                    weights_h[i] = chunk.weights
                    offsets_h[i] = chunk.offsets
                res = margin_k(vec, dev)
                if pending is not None:
                    out[pending[0]] = np.asarray(pending[1])
                pending = (i, res)
            if pending is not None:
                out[pending[0]] = np.asarray(pending[1])
        return out

    def phi_delta_ladder(mw_h, mp_h, alphas):
        """[L] data-term deltas f(w + a p) - f(w) for the whole
        backtracking ladder, in ONE margin-only streamed pass over the
        HOST caches — no chunk (re-)decode, no sparse data, and (sharded)
        no per-chunk collective: per-device [S, L] partials reduce once
        at the end, synced by the host fetch below."""
        f_acc = _sharded_zeros((S, L), dtype, mesh, axis)
        f_comp = _sharded_zeros((S, L), dtype, mesh, axis)
        a = jnp.asarray(alphas, dtype)
        with CollectiveGuard("stream.ladder"):  # see streaming_value_and_grad
            for i in range(n_chunks):
                f_acc, f_comp = trial_k(
                    a, _put(mw_h[i]), _put(mp_h[i]),
                    _put(labels_h[i]), _put(weights_h[i]),
                    f_acc, f_comp)
            total = trial_reduce_k(f_acc, f_comp)
        (d,) = _cross_process_sum((total,), stats)
        return np.asarray(d, np.float64)

    direction, store_pair = _lbfgs_stream_kernels(objective, mesh, axis, m)

    f, g = fg(w, l2)
    g0_norm = float(jnp.linalg.norm(g))
    mw_h = margins_of(w, [None] * len(chunks))
    mp_h = [None] * len(chunks)
    s_hist = jnp.zeros((m, dim), dtype)
    y_hist = jnp.zeros((m, dim), dtype)
    rho = jnp.zeros((m,), dtype)
    k = 0
    eps = float(jnp.finfo(dtype).eps)
    tol = _host_tol(config.tolerance, dtype)
    loss_hist = np.full((config.max_iters,), np.nan)
    gnorm_hist = np.full((config.max_iters,), np.nan)

    it = 0
    converged = False
    for it in range(config.max_iters):
        p = direction(g, s_hist, y_hist, rho, jnp.asarray(k))
        dg = float(jnp.sum(p * g))
        if dg >= 0:  # degraded metric: steepest descent restart
            p = -g
            dg = -float(jnp.sum(g * g))
        # ONE gather pass: the direction's margins (offsets subtracted:
        # margins() adds them and they are the affine constant)
        mp_h = margins_of(p, mp_h)
        for i in range(n_chunks):
            mp_h[i] = mp_h[i] - np.asarray(offsets_h[i], mp_h[i].dtype)
        # L2 delta along the ray: l2 * (a c1 + a^2/2 c2)
        wr = np.asarray(objective._reg_mask(w), np.float64)
        pr = np.asarray(objective._reg_mask(p), np.float64)
        l2f = float(np.asarray(l2))
        c1, c2 = wr @ pr, pr @ pr

        alpha0 = 1.0 if k > 0 else 1.0 / max(g0_norm, 1.0)
        f_cur = float(f)  # exact value (fg pass) — drives convergence only
        # delta-space Armijo over ladder GROUPS, each group one streamed
        # pass: improvement vs 0, accurate at any |f| (and
        # drift-consistent — both sides live on the cached mw). First
        # (largest) passing alpha == what sequential backtracking would
        # have taken.
        full = alpha0 * 0.5 ** np.arange(config.max_line_search_steps)
        accepted = False
        alpha = 0.0
        for g0 in range(0, len(full), L):
            grp = full[g0:g0 + L]
            if len(grp) < L:  # pad: duplicates of the last alpha are inert
                grp = np.concatenate([grp, np.full(L - len(grp), grp[-1])])
            deltas = (phi_delta_ladder(mw_h, mp_h, grp)
                      + l2f * (grp * c1 + 0.5 * grp * grp * c2))
            armijo = (deltas <= 1e-4 * grp * dg) & np.isfinite(deltas)
            if armijo.any():
                accepted = True
                alpha = float(grp[int(np.argmax(armijo))])
                break
        if not accepted:
            # mirror optimize/lbfgs_margin.py: a search failing AT the
            # optimum is convergence, not a stall; otherwise reset the
            # (stale-in-f32) history and retry once from steepest descent
            # before reporting not-converged. The attempted iteration is
            # counted and recorded (f unchanged), matching the in-memory
            # loop's unconditional it+1.
            gnorm = float(jnp.linalg.norm(g))
            loss_hist[it] = float(f)
            gnorm_hist[it] = gnorm
            if tol > 0 and gnorm <= tol * max(g0_norm, 1.0):
                converged = True
                it += 1
                break
            if k > 0:
                s_hist = jnp.zeros((m, dim), dtype)
                y_hist = jnp.zeros((m, dim), dtype)
                rho = jnp.zeros((m,), dtype)
                k = 0
                continue
            it += 1  # the attempted iteration counts: histories[:iterations]
            break    # must include the record written above

        w_try = w + jnp.asarray(alpha, dtype) * p
        # accepted point: ONE gather+transpose pass for the exact (f, g)
        f_try_x, g_try = fg(w_try, l2)
        for i in range(len(chunks)):
            mw_h[i] = mw_h[i] + mw_h[i].dtype.type(alpha) * mp_h[i]
        step = w_try - w
        yv = g_try - g
        sy = float(jnp.sum(step * yv))
        if sy > 1e-10 * max(
            float(jnp.linalg.norm(step)) * float(jnp.linalg.norm(yv)), eps
        ):
            s_hist, y_hist, rho = store_pair(s_hist, y_hist, rho,
                                             jnp.asarray(k), step, yv)
            k += 1
        w, f, g = w_try, f_try_x, g_try
        gnorm = float(jnp.linalg.norm(g))
        loss_hist[it] = float(f)
        gnorm_hist[it] = gnorm
        if progress_callback is not None:
            progress_callback(it, w)
        rel = abs(f_cur - float(f)) / max(abs(f_cur), 1.0)
        if tol > 0 and (rel <= tol or gnorm <= tol * max(g0_norm, 1.0)):
            converged = True
            it += 1
            break
    else:
        it = config.max_iters

    return OptimizationResult(
        w=w, value=f, grad_norm=jnp.linalg.norm(g),
        iterations=jnp.asarray(it), converged=jnp.asarray(converged),
        loss_history=jnp.asarray(loss_hist),
        grad_norm_history=jnp.asarray(gnorm_hist),
    )


# Lin-Moré / LIBLINEAR constants (same as optimize/tron.py)
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


def _fit_streaming_tron(objective, chunks, dim, w0, l2, config, dtype, mesh,
                        axis, progress_callback=None, prefetch_depth=None,
                        stats=None) -> OptimizationResult:
    """Host-loop TRON mirroring ``optimize.tron``: Steihaug CG inner loop
    where every Hessian-vector product is one streamed pass over the data —
    the reference's one-treeAggregate-per-CG-step cost model (SURVEY.md
    §4.2) with host chunks in place of cluster partitions."""
    if w0 is None:
        w0 = jnp.zeros((dim,), dtype)
    w = jnp.asarray(w0, dtype)
    fg = streaming_value_and_grad(objective, chunks, dim, dtype, mesh, axis,
                                  prefetch_depth, stats)
    hvp = streaming_hvp(objective, chunks, dim, dtype, mesh, axis,
                        prefetch_depth, stats)
    max_cg = max(dim, 20)
    eps = float(jnp.finfo(dtype).eps)

    def cg(wc, g, delta, cg_tol, m_diag):
        """Jacobi-preconditioned Steihaug CG; each hvp call is a full
        streamed pass, so the preconditioner (one extra streamed diag
        pass per OUTER iteration) buys the expensive thing: fewer inner
        passes. Trust region measured in the M-norm (mirrors
        optimize.tron)."""
        minv = 1.0 / m_diag
        mdot = lambda a, b: float(jnp.sum(a * m_diag * b))
        s = jnp.zeros_like(g)
        r = -g
        d = minv * r
        rz = float(jnp.sum(r * d))
        for _ in range(max_cg):
            Hd = hvp(wc, d, l2)
            dHd = float(jnp.sum(d * Hd))
            neg_curv = dHd <= 0
            alpha = rz / (1.0 if neg_curv else dHd)
            outside = np.sqrt(mdot(s + alpha * d, s + alpha * d)) >= delta
            if neg_curv or outside:
                sd = mdot(s, d)
                dd = mdot(d, d)
                ss = mdot(s, s)
                disc = np.sqrt(max(sd * sd + dd * (delta * delta - ss), 0.0))
                tau = (-sd + disc) / max(dd, eps)
                s = s + tau * d
                r = r - tau * Hd
                break
            s = s + alpha * d
            r = r - alpha * Hd
            if float(jnp.linalg.norm(r)) <= cg_tol:
                break
            z = minv * r
            rz_new = float(jnp.sum(r * z))
            d = z + (rz_new / max(rz, eps)) * d
            rz = rz_new
        return s, r

    f, g = fg(w, l2)
    f = float(f)
    g0_norm = float(jnp.linalg.norm(g))
    delta = g0_norm
    tol = _host_tol(config.tolerance, dtype)
    loss_hist = np.full((config.max_iters,), np.nan)
    gnorm_hist = np.full((config.max_iters,), np.nan)
    it = 0
    converged = False
    m_diag = None
    for it in range(config.max_iters):
        gnorm = float(jnp.linalg.norm(g))
        if m_diag is None:  # recomputed only after an ACCEPTED step
            md = streaming_hessian_diagonal(objective, chunks, dim, w, l2,
                                            dtype, mesh, axis,
                                            prefetch_depth, stats)
            # same relative positivity floor as optimize.tron
            m_diag = jnp.maximum(md, eps * jnp.maximum(float(jnp.max(md)),
                                                       1.0))
        step, r = cg(w, g, delta, 0.1 * gnorm, m_diag)
        w_try = w + step
        f_try_j, g_try = fg(w_try, l2)
        f_try = float(f_try_j)
        gs = float(jnp.sum(g * step))
        prered = 0.5 * (float(jnp.sum(step * r)) - gs)
        actred = f - f_try
        # radius lives in the CG's M-norm
        snorm = float(jnp.sqrt(jnp.sum(step * m_diag * step)))

        denom = f_try - f - gs
        alpha = _SIGMA3 if denom <= 0 else max(_SIGMA1, -0.5 * (gs / denom))
        if actred < _ETA0 * prered:
            delta = min(max(alpha, _SIGMA1) * snorm, _SIGMA2 * delta)
        elif actred < _ETA1 * prered:
            delta = max(_SIGMA1 * delta, min(alpha * snorm, _SIGMA2 * delta))
        elif actred < _ETA2 * prered:
            delta = max(_SIGMA1 * delta, min(alpha * snorm, _SIGMA3 * delta))
        else:
            delta = max(delta, min(alpha * snorm, _SIGMA3 * delta))

        accept = actred > _ETA0 * prered
        if accept:
            m_diag = None  # w moved: the cached diagonal is stale
            prev_f = f
            w, f, g = w_try, f_try, g_try
            gnorm = float(jnp.linalg.norm(g))
            rel = abs(prev_f - f) / max(abs(prev_f), 1.0)
            if tol > 0 and (rel <= tol or gnorm <= tol * max(g0_norm, 1.0)):
                converged = True
        loss_hist[it] = f
        gnorm_hist[it] = gnorm
        if progress_callback is not None:
            # TRON fires every OUTER iteration, accepted or not: a
            # rejected step still paid a full Steihaug-CG sequence of
            # streamed passes (minutes on a slow tunnel), and the stall
            # watchdog must see that heartbeat. ``w`` is the current
            # (possibly unmoved) point, so checkpoints stay valid, and
            # TRON's own ``iterations`` counts rejected outer iterations
            # the same way.
            progress_callback(it, w)
        if prered <= eps * max(abs(f), 1.0):  # model predicts no gain left
            converged = True
        if converged or delta < eps * max(float(jnp.linalg.norm(w)), 1.0):
            it += 1
            break
    else:
        it = config.max_iters

    return OptimizationResult(
        w=w, value=jnp.asarray(f, dtype), grad_norm=jnp.linalg.norm(g),
        iterations=jnp.asarray(it), converged=jnp.asarray(converged),
        loss_history=jnp.asarray(loss_hist),
        grad_norm_history=jnp.asarray(gnorm_hist),
    )


def _fit_streaming_owlqn(objective, chunks, dim, w0, l2, l1, config, dtype,
                         mesh, axis, progress_callback=None,
                         prefetch_depth=None, stats=None
                         ) -> OptimizationResult:
    """Host-loop OWL-QN mirroring ``optimize.owlqn`` (Andrew & Gao 2007):
    pseudo-gradient from the streamed smooth gradient, L-BFGS direction on
    device, orthant projection of direction and iterates; every line-search
    evaluation is one streamed pass."""
    from photon_ml_tpu.optimize.owlqn import pseudo_gradient

    m = config.history
    if w0 is None:
        w0 = jnp.zeros((dim,), dtype)
    w = jnp.asarray(w0, dtype)
    fg = streaming_value_and_grad(objective, chunks, dim, dtype, mesh, axis,
                                  prefetch_depth, stats)
    mask = jnp.ones((dim,), dtype)
    if objective.intercept_index >= 0 and not objective.regularize_intercept:
        mask = mask.at[objective.intercept_index].set(0.0)
    lam = jnp.asarray(l1, dtype) * mask

    direction = jax.jit(functools.partial(two_loop_direction, m=m))

    @jax.jit
    def project_direction(p, pg):
        p = jnp.where(p * (-pg) > 0, p, 0.0)
        dg = jnp.sum(p * pg)
        return jnp.where(dg < 0, p, -pg), jnp.minimum(dg, jnp.sum(-pg * pg))

    @jax.jit
    def project_point(w_trial, xi):
        return jnp.where(w_trial * xi > 0, w_trial, 0.0)

    def full_F(f_smooth, w_at):
        return float(f_smooth) + float(jnp.sum(lam * jnp.abs(w_at)))

    f, g = fg(w, l2)
    F = full_F(f, w)
    pg = pseudo_gradient(w, g, lam)
    pg0_norm = float(jnp.linalg.norm(pg))
    eps = float(jnp.finfo(dtype).eps)
    tol = _host_tol(config.tolerance, dtype)
    s_hist = jnp.zeros((m, dim), dtype)
    y_hist = jnp.zeros((m, dim), dtype)
    rho = jnp.zeros((m,), dtype)
    k = 0
    loss_hist = np.full((config.max_iters,), np.nan)
    gnorm_hist = np.full((config.max_iters,), np.nan)
    it = 0
    converged = False
    for it in range(config.max_iters):
        pg = pseudo_gradient(w, g, lam)
        p = direction(pg, s_hist, y_hist, rho, jnp.asarray(k))
        p, _ = project_direction(p, pg)
        xi = jnp.where(w != 0, jnp.sign(w), jnp.sign(-pg))
        alpha = 1.0 if k > 0 else 1.0 / max(float(jnp.linalg.norm(pg)), 1.0)
        accepted = False
        for _ in range(config.max_line_search_steps):
            w_try = project_point(w + alpha * p, xi)
            f_try, g_try = fg(w_try, l2)
            F_try = full_F(f_try, w_try)
            dgtest = float(jnp.sum(pg * (w_try - w)))
            if F_try <= F + 1e-4 * dgtest and np.isfinite(F_try):
                accepted = True
                break
            alpha *= 0.5
        if not accepted:
            break
        step = w_try - w
        yv = g_try - g
        sy = float(jnp.sum(step * yv))
        if sy > 1e-10 * max(
            float(jnp.linalg.norm(step)) * float(jnp.linalg.norm(yv)), eps
        ):
            slot = k % m
            s_hist = s_hist.at[slot].set(step)
            y_hist = y_hist.at[slot].set(yv)
            rho = rho.at[slot].set(1.0 / sy)
            k += 1
        F_prev = F
        w, g, F = w_try, g_try, F_try
        pg_norm = float(jnp.linalg.norm(pseudo_gradient(w, g, lam)))
        loss_hist[it] = F
        gnorm_hist[it] = pg_norm
        if progress_callback is not None:
            progress_callback(it, w)
        rel = abs(F_prev - F) / max(abs(F_prev), 1.0)
        if tol > 0 and (rel <= tol or pg_norm <= tol * max(pg0_norm, 1.0)):
            converged = True
            it += 1
            break
    else:
        it = config.max_iters

    final_pg = pseudo_gradient(w, g, lam)
    return OptimizationResult(
        w=w, value=jnp.asarray(F, dtype),
        grad_norm=jnp.linalg.norm(final_pg),
        iterations=jnp.asarray(it), converged=jnp.asarray(converged),
        loss_history=jnp.asarray(loss_hist),
        grad_norm_history=jnp.asarray(gnorm_hist),
    )
